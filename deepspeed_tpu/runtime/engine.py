"""DeepSpeedEngine — the training runtime.

TPU-native analog of the reference's ``deepspeed/runtime/engine.py:96``.
Same facade (``forward`` :729 / ``backward`` :767 / ``step`` :903,
``save_checkpoint`` :1329 / ``load_checkpoint`` :1173, gradient-accumulation
boundary logic :843), completely different execution model:

- The reference is eager: backward hooks bucket per-param grads onto side
  CUDA streams (stage2.py:591), allreduce is hand-bucketed (engine.py:1013),
  overlap is hand-scheduled. Here one **compiled micro-step** holds forward,
  backward, gradient accumulation, and the (conditional) optimizer update;
  XLA schedules all collectives (psum/reduce-scatter/all-gather over the
  ``data`` mesh axis) with overlap.
- ZeRO stages are *sharding assignments* on the master/optimizer pytrees
  (see runtime/zero/sharding.py), not separate optimizer classes.
- fp16 dynamic loss scaling runs inside jit via ``lax.cond`` — no host
  round-trip per step (loss_scaler.py). bf16 is the TPU-native default.

Model contract: ``model`` is a pure loss function
``loss_fn(params, batch [, rng]) -> loss | (loss, aux)``; ``model_parameters``
is the initial fp32 pytree. (The reference wrapped an nn.Module; in JAX the
trainable object *is* (fn, params). ``deepspeed_tpu.flax_loss_fn`` adapts a
flax module + criterion to this contract.)
"""

import inspect
import time
import os
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu import distributed as dist
from deepspeed_tpu.ops.optimizers import Optimizer, build_optimizer
from deepspeed_tpu.parallel.mesh import (axis_size, build_mesh,
                                         data_axis_names, data_axis_size,
                                         split_data_axis)
from deepspeed_tpu.parallel.topology import ParallelGrid
from deepspeed_tpu.runtime import checkpoint as ckpt
from deepspeed_tpu.runtime import elastic
from deepspeed_tpu.runtime import fault
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import (
    DeepSpeedDataLoader, PrefetchLoader, RepeatingLoader,
    normalize_eval_input, stack_micro_batches)
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, LossScaleState, StaticLossScaler, has_overflow)
from deepspeed_tpu.runtime.lr_schedules import build_lr_schedule
from deepspeed_tpu.runtime.zero.sharding import (
    replicated_shardings, zero_shardings)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

MEMORY_OPT_ALLREDUCE_SIZE = 500000000


class TrainState(NamedTuple):
    """All device-resident training state; a pure pytree so the whole step
    is functional (and shardable leaf-by-leaf)."""
    params: Any            # fp32 master params
    opt_state: Any
    accum_grads: Any       # () when gradient_accumulation_steps == 1
    loss_scale: LossScaleState
    global_step: jnp.ndarray    # optimizer steps taken
    micro_step: jnp.ndarray     # micro batches seen since last boundary
    skipped_steps: jnp.ndarray  # overflow-skipped optimizer steps
    rng: jnp.ndarray            # PRNG key threaded through the model


def _tree_cast(tree, dtype):
    if dtype is None:
        return tree
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating)
        else x, tree)


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx",
                                                  getattr(p, "name", p))))
                    for p in path)


_EMBEDDING_NAME_RE = None


def _detect_embedding_paths(params) -> set:
    """Leaf paths that look like lookup embeddings: 2-D float leaves whose
    name contains emb/embed/embedding/wte/word_embeddings (reference
    converts grads of ``nn.Embedding`` modules, engine.py:181-187)."""
    global _EMBEDDING_NAME_RE
    if _EMBEDDING_NAME_RE is None:
        import re
        _EMBEDDING_NAME_RE = re.compile(
            r"(^|[/_.])(emb|embed|embedding|embeddings|wte|word_embeddings)"
            r"($|[/_.])", re.IGNORECASE)
    out = set()
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = _path_key(path)
        if (hasattr(leaf, "ndim") and leaf.ndim == 2
                and jnp.issubdtype(leaf.dtype, jnp.floating)
                and _EMBEDDING_NAME_RE.search(key)):
            out.add(key)
    return out


from deepspeed_tpu.runtime.utils import global_norm as _global_norm


class DeepSpeedEngine:

    def __init__(self,
                 args=None,
                 model: Callable = None,
                 optimizer: Optional[Optimizer] = None,
                 model_parameters: Any = None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu: Optional[ParallelGrid] = None,
                 param_specs: Any = None,
                 collate_fn=None,
                 config: Any = None,
                 config_params: Any = None,
                 dont_change_device: bool = False,
                 seed: int = 0):
        assert model is not None, "deepspeed_tpu.initialize requires a model (loss fn)"
        assert model_parameters is not None, \
            "deepspeed_tpu.initialize requires model_parameters (init pytree)"

        dist.init_distributed()

        # -- config + mesh (mesh decides the dp world size for the batch
        #    triangle, so it is built first) --
        raw = config if config is not None else config_params
        if raw is None and args is not None and \
                getattr(args, "deepspeed_config", None):
            raw = args.deepspeed_config
        assert raw is not None, "a DeepSpeed config (dict or path) is required"
        if isinstance(raw, str):
            import json as _json
            with open(raw) as f:
                raw = _json.load(f)

        mesh_axes = raw.get("mesh", {}).get("axes") if isinstance(raw, dict) else None
        # hierarchical quantized comm (ZeRO++ 2D shapes) splits the data
        # axis into data_inter x data_intra BEFORE the mesh is built, so
        # every downstream sharding sees the 2D form
        _qc_hier = 0
        self._comm_plan = None
        if isinstance(raw, dict):
            from deepspeed_tpu.runtime.config import (
                get_comm_autotune_config, get_quantized_comm_config)
            _qc_raw = get_quantized_comm_config(raw)
            # the split is gated on enabled: a disabled quantized_comm
            # block must leave the mesh (and every 'data'-keyed path)
            # exactly as before
            if _qc_raw["enabled"]:
                _qc_hier = int(_qc_raw["hierarchical"])
                if get_comm_autotune_config(raw)["enabled"]:
                    # topology-aware autotuner: picks algo/block AND the
                    # hierarchy split, which must be known pre-mesh
                    self._comm_plan = self._plan_comm_autotune(
                        raw, _qc_raw, mesh_axes, model_parameters)
                if self._comm_plan is not None:
                    _qc_hier = self._comm_plan.hierarchical
                    if _qc_hier >= 2:
                        from deepspeed_tpu.parallel.mesh import \
                            resolve_axis_sizes
                        # the split below needs concrete sizes, not -1
                        mesh_axes = resolve_axis_sizes(
                            mesh_axes, len(jax.devices()))
        if _qc_hier >= 2:
            if mesh_axes is None:
                mesh_axes = {"data": len(jax.devices())}
            mesh_axes = split_data_axis(mesh_axes, _qc_hier)
        self.mesh = build_mesh(mesh_axes)
        # dp axes: ("data",), or ("data_inter", "data_intra") on a
        # hierarchical mesh; dp_world_size is their product
        self.dp_axes = data_axis_names(self.mesh) or ("data",)
        self._dp_hierarchical = len(self.dp_axes) > 1
        # the PartitionSpec dim entry that shards over the full dp degree
        self._dp_axis_entry = (self.dp_axes if self._dp_hierarchical
                               else self.dp_axes[0])
        self.dp_world_size = data_axis_size(self.mesh)
        self.mp_world_size = axis_size(self.mesh, "model")
        # make the mesh known to the activation-checkpointing subsystem so
        # partition_activations can shard the stash (the reference threads
        # mpu into deepspeed.checkpointing.configure; here the mesh is it)
        from deepspeed_tpu.runtime.activation_checkpointing import (
            checkpointing as _ds_ckpt)
        _ds_ckpt.set_mesh(self.mesh)

        self._config = DeepSpeedConfig(raw, world_size=self.dp_world_size)
        self.mpu = mpu

        # -- precision policy --
        self.fp16_enabled = self._config.fp16_enabled
        self.bf16_enabled = self._config.bf16_enabled
        if self.fp16_enabled:
            self.compute_dtype = jnp.float16
        elif self.bf16_enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = None  # fp32 end to end
        # Master-weight-free bf16 (TPU-native analog of the reference's
        # __STOCHASTIC_MODE__ kernels, setup.py:211-242 there): params are
        # held in bf16 end-to-end — no fp32 master copy, saving 4
        # bytes/param of HBM (and, at stage 3, halving the param
        # all-gather bytes) — and the optimizer casts its fp32 update
        # result back with stochastic rounding so sub-ulp steps
        # accumulate in expectation instead of RNE-truncating to zero.
        self.bf16_master_weights = self._config.bf16_master_weights
        self.bf16_stochastic_rounding = self._config.bf16_stochastic_rounding

        if self.fp16_enabled:
            if self._config.loss_scale == 0:
                ls_args = self._config.dynamic_loss_scale_args or {}
                self.loss_scaler = DynamicLossScaler(
                    init_scale=ls_args.get("init_scale",
                                           self._config.initial_dynamic_scale),
                    scale_window=ls_args.get("scale_window", 1000),
                    min_scale=ls_args.get("min_scale", 1.0),
                    delayed_shift=ls_args.get("delayed_shift", 1))
            else:
                self.loss_scaler = StaticLossScaler(self._config.loss_scale)
        else:
            self.loss_scaler = StaticLossScaler(1.0)

        # -- model / loss fn --
        self._loss_fn = model
        sig_params = None
        try:
            sig_params = len(inspect.signature(model).parameters)
        except (TypeError, ValueError):
            pass
        self._loss_takes_rng = (sig_params == 3)

        # -- optimizer --
        self.client_optimizer = optimizer
        # ZeRO-Offload (reference zero/stage2.py:334-350 cpu_offload path):
        # fp32 master + moments live on the host, updated by the native
        # C++ SIMD Adam (csrc/adam/cpu_adam.cpp); the device holds only
        # compute-dtype params and grads.
        self.zero_cpu_offload = bool(
            self._config.zero_config.stage >= 1 and
            self._config.zero_config.cpu_offload)
        # overlap_comm + cpu_offload: host Adam overlaps the next window's
        # device compute (one-window-delayed updates; reference overlaps
        # D2H/H2D on side streams, stage2.py:291-294)
        self._offload_overlap = bool(
            self.zero_cpu_offload and self._config.zero_config.overlap_comm)
        self._offload_pending = None
        self._offload_pool = None
        if self._offload_overlap:
            from concurrent.futures import ThreadPoolExecutor
            self._offload_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ds-offload")
        if self.zero_cpu_offload:
            assert optimizer is None, \
                "client optimizers are unsupported with cpu_offload"
            name = (self._config.optimizer_name or "adam").lower()
            assert "adam" in name and "onebit" not in name, \
                "ZeRO-Offload requires a plain Adam-family optimizer (the " \
                "reference drives DeepSpeedCPUAdam, stage2.py:1418); " \
                "OnebitAdam does not compose with ZeRO/offload"
            assert "8bit" not in name and "8_bit" not in name, \
                "Adam8bit does not compose with cpu_offload: offload " \
                "keeps fp32 moments in HOST memory (the native CPU Adam " \
                "owns them), so quantized device states would be " \
                "silently replaced — drop cpu_offload to use 8-bit " \
                "states, or keep offload with the host fp32 states"
            self.optimizer = None  # built below, once master params exist
        elif optimizer is not None:
            self.optimizer = optimizer
        else:
            self.optimizer = build_optimizer(self._config.optimizer_name,
                                             self._config.optimizer_params)
        self.base_lr = getattr(self.optimizer, "lr", 1e-3)

        # 1-bit Adam phase tracking (reference onebit_adam.py:369-372 flips
        # adam_freeze_key python-side; here the phase is a static compile
        # flag so XLA gets two clean programs). With dp > 1 the engine runs
        # the WHOLE grad+update under shard_map over 'data' so each rank
        # holds a local gradient and the compressed allreduce is the only
        # cross-rank traffic in the compression phase (the reference
        # disables dense backward allreduce at :369-372 for the same
        # reason).
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
        self._onebit = isinstance(self.optimizer, OnebitAdam)
        self._onebit_compression = False
        self._onebit_dist = False

        # -- lr scheduler --
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        else:
            self.lr_scheduler = build_lr_schedule(self._config.scheduler_name,
                                                  self._config.scheduler_params)

        # -- zero stage / shardings --
        self.zero_stage = self._config.zero_optimization_stage
        if self._onebit:
            # reference parity: OnebitAdam is not a ZeRO-supported optimizer
            # (zero/utils.py is_zero_supported_optimizer lists only
            # Adam-family fused/CPU optimizers)
            assert self.zero_stage == 0, \
                "OneBitAdam does not compose with ZeRO (reference " \
                "zero/utils.py is_zero_supported_optimizer); use stage 0"
            if self.dp_world_size > 1:
                self._onebit_dist = True
                self.optimizer.axis_name = "data"
                self.optimizer.world_size = self.dp_world_size
        self.param_specs = param_specs  # tensor-parallel PartitionSpecs
        master_params = _tree_cast(model_parameters, jnp.float32)
        if self.zero_stage >= 1:
            self._param_shardings = zero_shardings(
                master_params, self.mesh, stage=self.zero_stage,
                axis_name=self._dp_axis_entry, model_specs=param_specs)
        else:
            self._param_shardings = replicated_shardings(
                master_params, self.mesh, model_specs=param_specs)

        if self.zero_cpu_offload:
            # (master_weights=false x cpu_offload is refused earlier, in
            # DeepSpeedConfig._do_error_check)
            from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
            p = dict(self._config.optimizer_params or {})
            self.optimizer = DeepSpeedCPUAdam(
                master_params,
                lr=p.get("lr", 1e-3),
                betas=tuple(p.get("betas", (0.9, 0.999))),
                eps=p.get("eps", 1e-8),
                weight_decay=p.get("weight_decay", 0.0),
                adamw_mode=p.get("adam_w_mode", True),
                bias_correction=p.get("bias_correction", True))
            self.base_lr = self.optimizer.lr
            # device params in compute dtype only — the HBM saving that IS
            # ZeRO-Offload; fp32 master stays host-side in the optimizer
            params = _tree_cast(master_params,
                                self.compute_dtype or jnp.float32)
            opt_state = ()
            self._opt_shardings = ()
        else:
            if self.bf16_enabled and not self.bf16_master_weights:
                assert not self._onebit, \
                    "bf16.master_weights=false does not compose with " \
                    "OnebitAdam (its error-feedback state assumes an " \
                    "fp32-precision param target)"
                try:
                    accepts_sr = "sr_key" in inspect.signature(
                        self.optimizer.update).parameters
                except (TypeError, ValueError):
                    accepts_sr = False
                assert accepts_sr, \
                    "bf16.master_weights=false needs an optimizer whose " \
                    "update() accepts sr_key (the built-in Adam/SGD/Lamb " \
                    "do); this one would silently RNE-truncate bf16 updates"
                # params live in bf16; moments stay fp32 (Optimizer.init
                # allocates them fp32 regardless of param dtype)
                params = _tree_cast(master_params, jnp.bfloat16)
            else:
                params = master_params
            opt_state = self.optimizer.init(params)
            if self.zero_stage >= 1:
                self._opt_shardings = zero_shardings(
                    opt_state, self.mesh, stage=self.zero_stage,
                    axis_name=self._dp_axis_entry, model_specs=None)
            else:
                self._opt_shardings = replicated_shardings(opt_state,
                                                           self.mesh)
        if self._onebit_dist:
            # per-rank error-feedback state: leading (dp,) dim sharded over
            # 'data' — each shard owns its own worker/server error
            dp = self.dp_world_size
            data_shd = NamedSharding(self.mesh, PartitionSpec("data"))
            opt_state = opt_state._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda e: jnp.zeros((dp,) + e.shape, e.dtype),
                    opt_state.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda e: jnp.zeros((dp,) + e.shape, e.dtype),
                    opt_state.server_error))
            self._opt_shardings = self._opt_shardings._replace(
                worker_error=jax.tree_util.tree_map(
                    lambda _: data_shd, opt_state.worker_error),
                server_error=jax.tree_util.tree_map(
                    lambda _: data_shd, opt_state.server_error))

        self.gradient_accumulation_steps = self._config.gradient_accumulation_steps
        # With real accumulation (ga>1) grads sum on device in fp32 and
        # apply at the boundary (offload: one D2H of the summed grads).
        # cpu_offload at ga=1 allocates NO accumulator at all: the grads
        # leave the micro step as a compute-dtype OUTPUT and the host
        # snapshots them right after the dispatch — the reference's
        # transfer-grads-as-produced design (zero/stage2.py cpu_offload
        # 16-bit grad buckets) without a params-sized staging buffer
        # resident in HBM (the saving that lets a 2.5B model fit v5e,
        # test_offload_memory.py).
        if self.gradient_accumulation_steps > 1:
            if self._onebit_dist:
                # stacked per-rank local-grad accumulators
                dp = self.dp_world_size
                accum = jax.tree_util.tree_map(
                    lambda p: jnp.zeros((dp,) + p.shape, jnp.float32), params)
                accum_shardings = jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, PartitionSpec("data")),
                    accum)
            else:
                accum = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params)
                if self.zero_stage >= 2:
                    accum_shardings = zero_shardings(
                        accum, self.mesh, stage=self.zero_stage,
                        axis_name=self._dp_axis_entry,
                        model_specs=param_specs)
                else:
                    accum_shardings = replicated_shardings(accum, self.mesh)
        else:
            accum, accum_shardings = (), ()
        self._offload_grads_device = None   # offload ga=1 grad output

        state = TrainState(
            params=params,
            opt_state=opt_state,
            accum_grads=accum,
            loss_scale=self.loss_scaler.init(),
            global_step=jnp.zeros((), jnp.int32),
            micro_step=jnp.zeros((), jnp.int32),
            skipped_steps=jnp.zeros((), jnp.int32),
            rng=jax.random.PRNGKey(seed),
        )
        # Every leaf gets an explicit mesh placement (replicated unless a
        # ZeRO/TP rule shards it) so jit never sees mixed device sets.
        repl = NamedSharding(self.mesh, PartitionSpec())
        self._state_shardings = TrainState(
            params=self._param_shardings,
            opt_state=self._opt_shardings,
            accum_grads=accum_shardings,
            loss_scale=jax.tree_util.tree_map(lambda _: repl, state.loss_scale),
            global_step=repl, micro_step=repl, skipped_steps=repl, rng=repl,
        )
        placed = jax.device_put(state, self._state_shardings)

        # device_put can alias the source buffers (same-device shards) —
        # but the compiled step DONATES the state, which would delete the
        # caller's model_parameters out from under them. One explicit copy
        # at init decouples the engine state from user arrays.
        self.state = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, placed)

        self.gradient_clipping = self._config.gradient_clipping

        # -- data --
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(
                training_data, collate_fn=collate_fn)

        # -- misc bookkeeping --
        # tensorboard (reference engine.py:151-156; rank-0 only)
        from deepspeed_tpu.utils.monitor import TensorBoardMonitor
        self.monitor = TensorBoardMonitor(
            enabled=self._config.tensorboard_enabled,
            output_path=self._config.tensorboard_output_path,
            job_name=self._config.tensorboard_job_name,
            rank=jax.process_index())
        self.summary_writer = self.monitor.writer  # reference attr name

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu() *
            self.gradient_accumulation_steps,
            num_workers=self.dp_world_size,
            steps_per_output=self._config.steps_per_print)
        self.wall_clock_breakdown_enabled = self._config.wall_clock_breakdown
        # jax.profiler trace window ('observability.trace', legacy
        # 'profiler' section aliased; the reference's analog is the
        # wall_clock_breakdown timer ladder — on TPU the XLA trace is
        # the actionable artifact, SURVEY.md §5)
        self._profiler_cfg = self._config.profiler_config
        self._profiler_active = False
        # unified profiling & telemetry ('observability' config section):
        # FLOPs/MFU cost profiler, recompile tracking, memory watermarks,
        # trace spans, JSONL event log (deepspeed_tpu/profiling/)
        from deepspeed_tpu.profiling import Observer
        self.observability = Observer(
            self._config.observability_config, monitor=self.monitor,
            rank=jax.process_index(), device=jax.local_devices()[0],
            num_devices=len(jax.devices()))
        self.observability.set_step_provider(
            lambda: self._host_global_step)
        # postmortem health plane ('observability.health' section):
        # flight-recorder ring tapping the monitor mirror, stall
        # watchdog fed heartbeats at dispatch boundaries, numeric
        # anomaly detectors over the deferred-telemetry flush values
        # (utils/health.py — host-side only, pinned zero-perturbation)
        from ..utils.health import HealthPlane
        self.health = HealthPlane(
            self._config.observability_config.get("health"),
            monitor=self.monitor, rank=jax.process_index(),
            component="train",
            events_dir=self._config.observability_config.get(
                "events_dir"))
        # fault-tolerant checkpointing knobs ('checkpoint' config section):
        # CRC verification on load, retention, transient-I/O retry policy
        self._ckpt_cfg = self._config.checkpoint_config
        ckpt.set_retry_policy(self._ckpt_cfg["io_retries"],
                              self._ckpt_cfg["io_retry_backoff"])
        # elastic resilience (runtime/elastic.py; docs/checkpointing.md
        # "Surviving TPU preemption"): env-armed fault injections so a
        # supervisor-relaunched child can be faulted, the async-save
        # writer slot, and the opt-in preemption guard. The guard only
        # FLAGS a signal; the drain runs at the next train_batch
        # boundary (_elastic_boundary) where the window has committed.
        fault.arm_from_env()
        self._ckpt_writer = None         # lazy AsyncCheckpointWriter
        self._last_ckpt_dir = None       # fallback preemption save_dir
        self._restart_count = elastic.restart_count()
        self._elastic = None
        if self._ckpt_cfg["drain_on_preemption"]:
            self._elastic = elastic.PreemptionGuard()
            if self._elastic.install():
                log_dist(
                    "elastic: draining on SIGTERM/SIGINT (resumable exit "
                    f"code {elastic.RESUMABLE_EXIT_CODE})", ranks=[0])
            else:
                logger.warning(
                    "elastic: drain_on_preemption set but signal handlers "
                    "are main-thread-only; software trigger still active")
        if self._restart_count:
            # a supervisor relaunch: make the restart count visible on
            # the same x-axis as everything else
            self.monitor.write_elastic_metrics(
                restarts=self._restart_count)
        cc = self._config.compile_cache_config
        if cc["enabled"]:
            from ..utils.platform import enable_compile_cache
            if not enable_compile_cache(cc["dir"], cc["min_compile_secs"]):
                logger.warning(
                    "compile_cache: could not activate %r (another dir "
                    "already active, unwritable path, or older jax); "
                    "running uncached", cc["dir"])
        self._last_step_time_ms = None

        # -- sparse (CSR) embedding gradients (reference engine.py:181-187
        # converts nn.Embedding grads; exchange at :1088-1139). With no
        # module types in the functional contract, embedding leaves are
        # detected by name (emb*/wte/word_embeddings) + 2-D shape. Active
        # only with dp > 1 (single shard has no exchange to compress) and
        # without 1-bit Adam (which owns its own grad path).
        self._sparse_grad_paths = set()
        if (self.sparse_gradients_enabled() and self.dp_world_size > 1
                and not self._onebit):
            explicit = getattr(self._config, "sparse_gradients_params",
                               None)
            if explicit:
                # explicit opt-in (safer than the name heuristic: a
                # tied-head "embedding" is NOT a pure lookup table and
                # must stay dense — the heuristic can only catch that at
                # runtime via the overflow flag)
                eligible = {
                    _path_key(p): leaf for p, leaf in
                    jax.tree_util.tree_flatten_with_path(params)[0]
                    if hasattr(leaf, "ndim") and leaf.ndim == 2
                    and jnp.issubdtype(leaf.dtype, jnp.floating)}
                resolved = set()
                for entry in explicit:
                    hits = {p for p in eligible
                            if p == entry or entry in p}
                    if not hits:
                        raise ValueError(
                            f"sparse_gradients_params entry {entry!r} "
                            f"matches no 2-D float leaf; eligible: "
                            f"{sorted(eligible)}")
                    resolved |= hits
                self._sparse_grad_paths = resolved
            else:
                self._sparse_grad_paths = _detect_embedding_paths(params)
            if self._sparse_grad_paths:
                log_dist("sparse_gradients: CSR allreduce for "
                         f"{sorted(self._sparse_grad_paths)}"
                         + ("" if explicit else " (name heuristic; set "
                            "sparse_gradients_params to pin)"), ranks=[0])
            else:
                logger.warning(
                    "sparse_gradients enabled but no embedding-named 2-D "
                    "leaves found; all grads exchanged dense")
        self._csr_overflow = None     # device flag from the last micro step
        self._csr_overflow_logged = False

        # Hierarchical quantized collectives (TPU-native extension; ZeRO++
        # qgZ/qwZ/hpZ shapes — runtime/quantized_collectives.py). The
        # gradient path is exclusive with the 1-bit and CSR manual paths.
        qc = self._config.quantized_comm_config
        self._quant_cfg = qc
        self._quant_allreduce = bool(
            qc["enabled"] and self.dp_world_size > 1
            and not self._onebit and not self._sparse_grad_paths)
        self._quant_block = int(qc["block"])
        self._quant_algo = qc["algo"]
        if qc["enabled"] and not self._quant_allreduce:
            logger.warning(
                "quantized_comm gradient exchange ignored (needs dp > 1 "
                "and no 1-bit/sparse gradient path)")
        if self._dp_hierarchical:
            assert not self._onebit and not self._sparse_grad_paths, \
                "quantized_comm.hierarchical does not compose with " \
                "OnebitAdam or sparse_gradients (their manual shard_map " \
                "paths are written against the flat 'data' axis)"
            assert self._quant_algo == "twohop", \
                "quantized_comm.hierarchical requires algo='twohop' " \
                "(the legacy allgather exchange has no 2D form)"
        # qwZ: int8 block-quantized ZeRO param all-gather. Only on the
        # GSPMD (non-shard_map) path where the gather exists, with a
        # compute-dtype cast to ride (stage 3 skips the up-front cast —
        # its per-use-site gathers are already the lean shape).
        # comm_autotune: the plan (computed pre-mesh) now overrides the
        # static algo/block; hierarchy already shaped the mesh above
        self._autotune_cfg = self._config.comm_autotune_config
        if self._comm_plan is not None and self._quant_allreduce:
            if self._comm_plan.world != self.dp_world_size:
                logger.warning(
                    "comm_autotune: planned against dp=%d but the mesh "
                    "built dp=%d — plan dropped, static quantized_comm "
                    "config in effect", self._comm_plan.world,
                    self.dp_world_size)
                self._comm_plan = None
            else:
                self._quant_algo = self._comm_plan.algo
                self._quant_block = int(self._comm_plan.block)
        if self._comm_plan is not None and self._quant_allreduce and \
                self._autotune_cfg["calibrate"]:
            # opt-in drift check of the wire model against the compiled
            # exchange — best-effort: a dead device must never fail init
            try:
                from deepspeed_tpu.runtime.comm_autotune import \
                    calibrate_wire_model
                cal = calibrate_wire_model(
                    world=self.dp_world_size, algo=self._quant_algo,
                    block=self._quant_block,
                    hierarchical=self._comm_plan.hierarchical, n=1 << 14)
                self._comm_plan = self._comm_plan._replace(calibration=cal)
                if abs(cal["drift"]) > 0.05:
                    logger.warning(
                        "comm_autotune: wire model drifts %.1f%% from "
                        "the compiled HLO byte accounting — the cost "
                        "model's inputs may have rotted",
                        cal["drift"] * 100.0)
                # on real hardware, also TIME the exchange and persist
                # the measured link constants: the next run's LinkModel
                # then plans against the fabric as measured, not the
                # nominal round numbers (explicit config keys still win).
                # KNOWN-uniform fabric only (unknown topology counts as
                # split): the flat probe's slowest hop on a split fabric
                # is the DCN, and persisting that as the INTRA constants
                # would collapse the planner's fast/slow-wire
                # distinction for every later run
                import jax as _jax
                from deepspeed_tpu.runtime.comm_autotune import \
                    uniform_fabric
                uniform = uniform_fabric(self._comm_plan.topo_intra,
                                         self.dp_world_size)
                if _jax.default_backend() == "tpu" and uniform:
                    from deepspeed_tpu.runtime.comm_autotune import (
                        measure_link_constants, save_wire_calibration)
                    measured = measure_link_constants(
                        world=self.dp_world_size, algo=self._quant_algo,
                        block=self._quant_block)
                    path = save_wire_calibration(measured)
                    logger.info(
                        "comm_autotune: measured link constants "
                        f"({measured['intra_gbps']:.1f} gbps, "
                        f"{measured['intra_latency_us']:.1f} us) saved "
                        f"to {path}")
            except Exception as e:
                logger.warning(f"comm_autotune: calibration skipped "
                               f"({e!r})")
        self._qwz = bool(qc["enabled"] and qc["quantize_weights"]
                         and 1 <= self.zero_stage <= 2
                         and self.compute_dtype is not None
                         and self.dp_world_size > 1)
        if qc["quantize_weights"] and qc["enabled"] and not self._qwz:
            logger.warning(
                "quantized_comm.quantize_weights ignored (needs ZeRO "
                "stage 1-2, a compute dtype, and dp > 1)")
        # hpZ: keep the compute-dtype params sharded over the intra axis
        # only, so backward re-gathers never cross the slow inter axis
        self._hpz = bool(qc["enabled"] and qc["secondary_partition"]
                         and self._dp_hierarchical
                         and 1 <= self.zero_stage <= 2
                         and self.compute_dtype is not None)
        if qc["secondary_partition"] and qc["enabled"] and not self._hpz:
            logger.warning(
                "quantized_comm.secondary_partition ignored (needs "
                "hierarchical mode, ZeRO stage 1-2, and a compute dtype)")

        self._compiled_micro_step = None
        self._compiled_batch_step = None
        self._compiled_grad = None
        self._compiled_apply = None
        self._cached_grads = None
        self._cached_loss = None

        # Async step pipeline ('async_pipeline' config section,
        # docs/performance.md "Async step pipeline"): scan-fused
        # accumulation (one dispatch per train_batch), background
        # prefetch, and deferred loss telemetry so steady-state steps
        # never force a device round-trip.
        ap = self._config.async_pipeline_config
        self._async_cfg = ap
        self._sync_loss_every_step = bool(ap["sync_loss_every_step"])
        self._prefetch_depth = int(ap["prefetch_depth"])
        self._use_fused_batch = None     # decided once, at first train_batch
        self._use_overlap = None         # comm_autotune exchange overlap
        self._prefetcher = None
        self._train_iter = None
        self._stacked_shd = None
        self._micro_shd = None
        self._monitor_ring = []          # deferred loss/lr/scale records
        self._last_loss_device = None    # device scalar; last_loss() syncs
        self._host_sync_count = 0        # forced device syncs (telemetry)
        self._host_gap_ms = None         # per-step host time outside dispatch
        # only a dynamic fp16 scaler's per-step scale must be snapshot
        # into the ring; static scales are exact at flush time
        self._dynamic_scale_telemetry = bool(
            self.fp16_enabled and isinstance(self.loss_scaler,
                                             DynamicLossScaler))
        self._window_anchor = None       # flush-to-flush wall-clock base
        # scripts predating close() must not lose the tail of the ring
        # at process exit; registered AFTER the Observer's own atexit
        # hook so (LIFO) the flush still finds an open event log. The
        # hook holds only a WEAKREF — the registry must not pin the
        # engine (and its device state) for process life when the
        # caller simply drops it; close() unregisters explicitly.
        import atexit
        import weakref
        self_ref = weakref.ref(self)

        def _exit_flush(ref=self_ref):
            eng = ref()
            if eng is not None:
                eng._flush_monitor_atexit()

        self._atexit_flush_hook = _exit_flush
        atexit.register(_exit_flush)
        # Host mirrors of the device counters, used for boundary checks and
        # print gating WITHOUT a device->host sync per step (the device is
        # potentially across a network tunnel; a sync per step destroys
        # throughput). _host_micro_step counts completed micro fwd/bwd/step
        # cycles (reference engine.py micro_steps); exact. _host_global_step
        # ignores overflow skips (the device value, via .global_steps, is
        # authoritative).
        self._host_micro_step = 0
        self._host_global_step = 0

        # the one-line which-exchange log (mirrors the which-path-
        # compiled log of the async pipeline): chosen algo/block/
        # hierarchy and why — plus the comm_plan event obs_report shows
        if self._quant_allreduce:
            from deepspeed_tpu.runtime.comm_autotune import candidate_label
            hier = (axis_size(self.mesh, "data_intra")
                    if self._dp_hierarchical else 0)
            label = candidate_label(self._quant_algo, self._quant_block,
                                    hier)
            why = (self._comm_plan.reason if self._comm_plan is not None
                   else "static quantized_comm config")
            log_dist(f"quantized_comm exchange = {label} "
                     f"[{'autotuned' if self._comm_plan is not None else 'static'}] "
                     f"({why})", ranks=[0])
            if self._comm_plan is not None:
                p = self._comm_plan
                self.observability.record_comm_plan(
                    algo=p.algo, block=p.block,
                    hierarchical=p.hierarchical, world=p.world,
                    topo_intra=p.topo_intra, reason=p.reason,
                    overridden=p.overridden, modeled_us=p.modeled_us,
                    calibration=p.calibration)

        # per-step DP comm-bytes model (host math on leaf shapes; the
        # wire shape itself is pinned by the HLO audits) — written to the
        # monitor each step and logged once here
        self._comm_stats = self._estimate_step_comm_bytes()
        if self._comm_stats is not None:
            log_dist(
                "dp grad exchange: ~{:.2f} MB/step/rank ({}), dense fp32 "
                "ring would be ~{:.2f} MB (ratio {:.2f}x)".format(
                    self._comm_stats["bytes_per_step"] / 2**20,
                    self._comm_stats["mode"],
                    self._comm_stats["dense_bytes_per_step"] / 2**20,
                    self._comm_stats["compression_ratio"] or 1.0),
                ranks=[0])

        log_dist(
            f"DeepSpeedEngine initialized: mesh={dict(self.mesh.shape)} "
            f"zero_stage={self.zero_stage} dtype="
            f"{self.compute_dtype or jnp.float32} "
            f"grad_acc={self.gradient_accumulation_steps}", ranks=[0])

    # ------------------------------------------------------------------ #
    # config accessors (reference engine.py:255-370)
    # ------------------------------------------------------------------ #
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def steps_per_print(self):
        return self._config.steps_per_print

    def zero_optimization(self):
        return self.zero_stage > 0

    def sparse_gradients_enabled(self):
        """(reference engine.py:269) When enabled, embedding-style grads can
        be exchanged in CSR form — see runtime/csr_tensor.csr_allreduce for
        the shard_map collective; under plain GSPMD XLA already moves only
        live shards."""
        return self._config.sparse_gradients_enabled

    def loss_scale(self):
        return float(self.state.loss_scale.scale)

    def get_lr(self):
        return [float(self._lr_at(self.state.global_step))]

    def get_global_step(self):
        return int(self.state.global_step)

    @property
    def global_steps(self):
        return int(self.state.global_step)

    @property
    def skipped_steps(self):
        return int(self.state.skipped_steps)

    @property
    def module_params(self):
        """Current master params (host view on demand).

        With ``zero_optimization.overlap_comm`` offload, an update may
        still be in flight — reads here would see the previous window's
        params. Warn once rather than silently returning stale weights
        (call :meth:`synchronize` first, as save/eval do)."""
        if getattr(self, "_offload_pending", None) is not None and \
                not getattr(self, "_warned_stale_params", False):
            self._warned_stale_params = True
            logger.warning(
                "module_params read with an overlapped ZeRO-Offload "
                "update still in flight — values are one window stale; "
                "call engine.synchronize() first for settled weights")
        return self.state.params

    def is_gradient_accumulation_boundary(self):
        """True while processing the LAST micro batch of the accumulation
        window (reference engine.py:843: (micro_steps+1) % gas == 0)."""
        return ((self._host_micro_step + 1) %
                self.gradient_accumulation_steps == 0)

    # -- remaining config-accessor facade (reference engine.py:255-370;
    #    fp16_enabled/gradient_accumulation_steps/gradient_clipping/
    #    zero_cpu_offload exist as engine ATTRIBUTES here — a documented
    #    deviation, the values are identical) --
    def optimizer_name(self):
        return self._config.optimizer_name

    def optimizer_params(self):
        return self._config.optimizer_params

    def optimizer_legacy_fusion(self):
        return self._config.optimizer_legacy_fusion

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def dynamic_loss_scale(self):
        return self.fp16_enabled and self._config.loss_scale == 0

    def initial_dynamic_scale(self):
        return self._config.initial_dynamic_scale

    def dynamic_loss_scale_args(self):
        return self._config.dynamic_loss_scale_args

    def amp_enabled(self):
        return False                     # no apex/amp on TPU

    def amp_params(self):
        return None

    def postscale_gradients(self):
        return not self._config.prescale_gradients

    def gradient_predivide_factor(self):
        return self._config.gradient_predivide_factor

    def wall_clock_breakdown(self):
        return self._config.wall_clock_breakdown

    def memory_breakdown(self):
        return self._config.memory_breakdown

    def tensorboard_enabled(self):
        return self._config.tensorboard_enabled

    def tensorboard_output_path(self):
        return self._config.tensorboard_output_path

    def tensorboard_job_name(self):
        return self._config.tensorboard_job_name

    def get_summary_writer(self):
        mon = getattr(self, "monitor", None)
        return getattr(mon, "writer", None)

    def zero_optimization_stage(self):
        return self._config.zero_optimization_stage

    def zero_reduce_scatter(self):
        return self._config.zero_config.reduce_scatter

    def zero_overlap_comm(self):
        return self._config.zero_config.overlap_comm

    def zero_reduce_bucket_size(self):
        return self._config.zero_config.reduce_bucket_size

    def zero_allgather_partitions(self):
        return self._config.zero_config.allgather_partitions

    def zero_allgather_bucket_size(self):
        return self._config.zero_config.allgather_bucket_size

    def zero_contiguous_gradients(self):
        return self._config.zero_config.contiguous_gradients

    def zero_load_from_fp32_weights(self):
        return self._config.zero_config.load_from_fp32_weights

    def zero_optimization_partition_gradients(self):
        return self.zero_optimization_stage() >= 2

    def get_mom(self):
        """Current scheduled momentum, mirroring :meth:`get_lr`
        (reference engine.py get_mom)."""
        mom = self._mom_at(self.state.global_step)
        if mom is not None:
            return [float(mom)]
        betas = (self._config.optimizer_params or {}).get("betas")
        if betas:
            return [float(betas[0])]
        return [float((self._config.optimizer_params or {})
                      .get("momentum", 0.0))]

    def train(self, mode: bool = True):
        """Training-mode flag for API parity (reference calls
        module.train()); determinism here is owned by the loss fn's
        ``deterministic`` knob, so this only records intent."""
        self._train_mode = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def zero_grad(self):
        """Clear the gradient-accumulation buffer (the analog of zeroing
        module grads; reference engine.py zero_grad)."""
        zeros = jax.tree_util.tree_map(jnp.zeros_like,
                                       self.state.accum_grads)
        self.state = self.state._replace(
            accum_grads=zeros, micro_step=jnp.zeros((), jnp.int32))

    def allreduce_gradients(self, bucket_size=MEMORY_OPT_ALLREDUCE_SIZE):
        """No-op by design: gradient reduction happens INSIDE the
        compiled step (GSPMD psum/reduce-scatter over 'data'), not as a
        separate host-driven pass (reference engine.py:751). Kept so
        reference-style training scripts port unchanged."""
        del bucket_size

    def module_state_dict(self):
        """Host copy of the model params (reference engine.py:1370).

        Must be a REAL copy: np.asarray of a CPU-backed jax array is
        zero-copy, and the compiled step donates the old param buffer —
        a view would silently morph into the post-update values."""
        from deepspeed_tpu.runtime.checkpoint import _to_host_global
        return jax.tree_util.tree_map(
            lambda x: np.array(_to_host_global(x), copy=True),
            self.state.params)

    def load_module_state_dict(self, state_dict, strict: bool = True):
        """Replace model params from a host pytree (reference
        engine.py:1342); shapes must match the current params."""
        cur = self.state.params
        if strict:
            cur_leaves = jax.tree_util.tree_leaves(cur)
            new_leaves = jax.tree_util.tree_leaves(state_dict)
            assert len(cur_leaves) == len(new_leaves), \
                (len(cur_leaves), len(new_leaves))
            for a, b in zip(cur_leaves, new_leaves):
                assert a.shape == np.shape(b), (a.shape, np.shape(b))
        new = jax.tree_util.tree_map(
            lambda tmpl, v: jnp.asarray(v, tmpl.dtype), cur, state_dict)
        self.state = self.state._replace(params=jax.device_put(
            new, self._state_shardings.params))

    def dump_state(self):
        """Readable engine-state summary (reference engine.py dump_state
        prints its internals; ours is the compiled-step equivalent)."""
        lines = [
            f"world: dp={self.dp_world_size} mp={self.mp_world_size} "
            f"mesh={dict(self.mesh.shape)}",
            f"precision: fp16={self.fp16_enabled} "
            f"bf16={self.bf16_enabled} loss_scale={self.loss_scale()}",
            f"zero: stage={self.zero_optimization_stage()} "
            f"cpu_offload={self.zero_cpu_offload}",
            f"batch: micro={self.train_micro_batch_size_per_gpu()} "
            f"gas={self.gradient_accumulation_steps} "
            f"global={self.train_batch_size()}",
            f"progress: step={self.global_steps} "
            f"skipped={self.skipped_steps} lr={self.get_lr()[0]:.3e}",
        ]
        logger.info("engine state:\n  " + "\n  ".join(lines))
        return lines

    # ------------------------------------------------------------------ #
    # data
    # ------------------------------------------------------------------ #
    def deepspeed_io(self, dataset, batch_size=None, collate_fn=None,
                     data_sampler=None):
        """(reference engine.py:652) Build a sharded loader over the global
        micro batch (micro_batch_per_chip × dp_world)."""
        if batch_size is None:
            batch_size = (self.train_micro_batch_size_per_gpu() *
                          self.dp_world_size)
        return DeepSpeedDataLoader(dataset, batch_size=batch_size,
                                   mesh=self.mesh, collate_fn=collate_fn,
                                   data_sampler=data_sampler)

    # ------------------------------------------------------------------ #
    # compiled step construction
    # ------------------------------------------------------------------ #
    def _lr_at(self, step):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.lr_at(step)
        return jnp.asarray(self.base_lr, jnp.float32)

    def _mom_at(self, step):
        """Scheduled momentum (OneCycle cycle_momentum, reference
        lr_schedules.py:518), or None when the schedule doesn't cycle it.
        Flows into the compiled optimizer update as a beta1/mu override,
        the same way _lr_at flows as the lr."""
        sch = self.lr_scheduler
        if (sch is not None and getattr(sch, "cycle_momentum", False)
                and hasattr(sch, "mom_at")):
            if getattr(self, "_onebit", False) or \
                    getattr(self, "_onebit_dist", False):
                # 1-bit Adam's error-feedback state is calibrated against
                # a FIXED beta1 during compression (its update does not
                # take a momentum override); cycling it silently would be
                # worse than not cycling — warn once and keep beta1 fixed
                if not getattr(self, "_warned_onebit_mom", False):
                    self._warned_onebit_mom = True
                    logger.warning(
                        "OneCycle cycle_momentum is ignored with "
                        "OnebitAdam: beta1 stays at its configured value "
                        "(set cycle_momentum=false to silence this)")
                return None
            return sch.mom_at(step)
        return None

    def _plan_comm_autotune(self, raw, qc, mesh_axes, model_parameters):
        """Run the topology-aware exchange autotuner
        (runtime/comm_autotune.py) BEFORE the mesh exists: the plan's
        hierarchy split shapes the mesh itself. Pure host math over the
        gradient-size histogram; returns a CommPlan or None (config the
        quantized exchange refuses, or nothing to tune). Called only
        from __init__ — must not touch engine attributes."""
        opt_name = ((raw.get("optimizer", {}) or {}).get("type") or "")
        if "onebit" in opt_name.lower().replace("_", ""):
            logger.warning("comm_autotune: skipped (OnebitAdam owns its "
                           "own compressed exchange)")
            return None
        if raw.get("sparse_gradients"):
            logger.warning("comm_autotune: skipped (sparse_gradients "
                           "owns the CSR exchange)")
            return None
        from deepspeed_tpu.parallel.mesh import (natural_intra_size,
                                                 resolve_axis_sizes)
        from deepspeed_tpu.runtime.comm_autotune import plan_comm
        try:
            axes = resolve_axis_sizes(mesh_axes, len(jax.devices()))
        except ValueError:
            return None          # build_mesh will raise the real error
        if all(a in axes for a in ("data_inter", "data_intra")):
            # an explicitly 2D mesh IS a topology statement: the split
            # is pinned, the autotuner still prices algo/block
            world = axes["data_inter"] * axes["data_intra"]
            qc = dict(qc, hierarchical=axes["data_intra"],
                      explicit=dict(qc["explicit"], hierarchical=True))
            intra_hint = axes["data_intra"]
        elif "data" in axes:
            world = axes["data"]
            # physical fallback hint (no comm_autotune.intra_size):
            # devices-per-process is the fast-wire island, but the data
            # axis only spans it at a stride of the MINOR axes' product
            # (model/seq/expert sit after 'data' in the canonical
            # device-mesh order) — a {'data': 4, 'model': 2} mesh on
            # 4-device hosts has data extent 2 per host, not 4.
            # Approximate (create_device_mesh may reorder devices for
            # ICI contiguity); comm_autotune.intra_size overrides.
            stride = 1
            past_data = False
            for name, size in axes.items():
                if name == "data":
                    past_data = True
                elif past_data:
                    stride *= size
            local = natural_intra_size()
            intra_hint = (local // stride
                          if local and local % stride == 0 else 0)
            if intra_hint < 2 or world % intra_hint:
                intra_hint = 0
        else:
            return None          # no data axis: no gradient exchange
        if world <= 1:
            return None
        sizes = [leaf.size for leaf in
                 jax.tree_util.tree_leaves(model_parameters)
                 if hasattr(leaf, "dtype")
                 and jnp.issubdtype(leaf.dtype, jnp.floating)]
        if not sizes:
            return None
        from deepspeed_tpu.runtime.config import get_comm_autotune_config
        try:
            return plan_comm(sizes, world, qc,
                             get_comm_autotune_config(raw),
                             intra_hint=intra_hint)
        except Exception as e:
            # planning runs BEFORE DeepSpeedConfig validation: an
            # invalid quantized_comm combo (pinned hierarchy with a
            # pinned non-twohop algo, typo'd algo, ...) must surface
            # the config layer's curated error a few lines later, not
            # a raw planner exception here
            logger.warning(f"comm_autotune: planning skipped ({e!r}); "
                           "static quantized_comm config in effect")
            return None

    def _cast_for_loss(self, params, constrain=True):
        """fp32 master -> compute dtype, unless the loss fn owns the cast
        (pipeline loss fns cast inside shard_map so grad psums stay fp32).

        ZeRO stage 3: no up-front cast at all — materializing the full
        compute-dtype copy would be the replicated-parameter transient
        stage 3 exists to eliminate. The data-sharded fp32 master flows in
        directly and each weight is gathered + cast AT ITS USE SITE (our
        model families cast per-weight: models/gpt2.py gpt2_block
        ``.astype(dtype)``), so GSPMD schedules per-layer all-gathers
        just-in-time and ``jax.checkpoint``ed blocks re-gather in backward
        — the reference stage-3 gather/partition lifecycle as a compiler
        schedule. Measured on the 8-dev mesh: ~34% lower XLA temp memory
        on a param-dominated GPT-2 vs the stage-2 pre-cast.
        """
        if getattr(self._loss_fn, "owns_cast", False):
            return params
        if self.zero_stage >= 3:
            return params
        if constrain and self._qwz:
            # qwZ: the ZeRO param all-gather moves int8 + per-slice fp32
            # scales instead of bf16 (ZeRO++ arXiv:2306.10209 §quantized
            # weights) — see _quantized_weight_cast
            return self._quantized_weight_cast(params)
        cast = _tree_cast(params, self.compute_dtype)
        if constrain and self.compute_dtype is not None \
                and self.zero_stage >= 1:
            # Pin the compute-dtype copy to the MASTER's sharded layout so
            # the cast runs shard-local and the forward's param all-gather
            # moves compute-dtype (bf16) elements. Without this GSPMD may
            # gather the f32 masters and cast downstream — 2x wire traffic
            # on the per-micro gather (the docs/performance.md caveat,
            # now asserted in test_hlo_collectives.py).
            # hpZ (secondary_partition): constrain to the intra-sharded
            # secondary layout instead — the inter hop happens here once,
            # and every use-site (re-)gather stays on the fast intra axis.
            target = (self._secondary_shardings() if self._hpz
                      else self._param_shardings)
            cast = jax.lax.with_sharding_constraint(cast, target)
        return cast

    # -- qwZ / hpZ: quantized + secondary-sharded ZeRO weight gather ------
    def _leaf_dp_dim(self, spec) -> Optional[int]:
        """Index of the PartitionSpec dim sharded over the dp axes, or
        None (replicated / model-only leaf)."""
        dp = set(self.dp_axes)
        for i, entry in enumerate(spec):
            names = entry if isinstance(entry, tuple) else (entry,)
            if any(a in dp for a in names if a is not None):
                return i
        return None

    def _secondary_shardings(self):
        """hpZ target layout: each leaf's dp-sharded dim re-sharded over
        the intra sub-axis ONLY (replicated across data_inter) — the
        ZeRO++ secondary partition, as a sharding assignment."""
        def one(shd):
            spec = shd.spec
            k = self._leaf_dp_dim(spec)
            if k is None:
                return shd
            entries = list(spec)
            entries[k] = "data_intra"
            return NamedSharding(self.mesh, PartitionSpec(*entries))
        return jax.tree_util.tree_map(one, self._param_shardings)

    def _quantized_weight_cast(self, params):
        """qwZ (+ optional hpZ): per-leaf int8 block-quantized ZeRO param
        gather.

        For each dp-sharded leaf: symmetric int8 quantization per slice
        along the sharded dim (absmax over the other dims — shard-local
        math), both q and scales pinned to the master's sharded layout,
        then resharded to the gather target (replicated, or the
        intra-sharded secondary layout under hpZ) BEFORE dequantization —
        so the partitioner's all-gather moves int8 elements + fp32
        scales, ~2x less wire than the bf16 gather and ~4x less than a
        naive f32 one. Dequant + compute-dtype cast run on the gathered
        values (elementwise, negligible). Leaves with no dp sharding or
        tiny per-slice extents ship as plain compute-dtype casts.

        MUST be applied OUTSIDE autodiff (every caller pre-casts before
        value_and_grad / before entering shard_map): round() has a zero
        derivative and the int8 wire carries no cotangents, so
        differentiating through this cast would zero the master
        gradients.
        """
        mesh = self.mesh
        hpz = self._hpz
        dtype = self.compute_dtype

        def one(leaf, shd):
            spec = shd.spec
            k = self._leaf_dp_dim(spec)
            plain_ok = (k is None or leaf.ndim == 0
                        or not jnp.issubdtype(leaf.dtype, jnp.floating)
                        or leaf.size // leaf.shape[k] < 16)
            if plain_ok:
                cast = (leaf.astype(dtype)
                        if jnp.issubdtype(leaf.dtype, jnp.floating)
                        else leaf)
                if k is not None:
                    cast = jax.lax.with_sharding_constraint(cast, shd)
                return cast
            # per-slice symmetric int8: one fp32 scale per index along
            # the sharded dim (reduction is over unsharded dims only)
            other = tuple(i for i in range(leaf.ndim) if i != k)
            absmax = jnp.max(jnp.abs(leaf.astype(jnp.float32)),
                             axis=other, keepdims=True)
            s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
            q = jnp.clip(jnp.round(leaf.astype(jnp.float32) / s),
                         -127, 127).astype(jnp.int8)
            # scales: same rank, size-1 dims except k -> only dim k's
            # entry of the leaf spec survives
            s_spec = PartitionSpec(*[spec[i] if i == k else None
                                     for i in range(leaf.ndim)])
            q = jax.lax.with_sharding_constraint(q, shd)
            s = jax.lax.with_sharding_constraint(
                s, NamedSharding(mesh, s_spec))
            # the gather: reshard the int8 payload (this is what crosses
            # the wire). hpZ keeps the intra shard; otherwise replicate.
            tgt_entry = "data_intra" if hpz else None
            t_spec = list(spec)
            t_spec[k] = tgt_entry
            q = jax.lax.with_sharding_constraint(
                q, NamedSharding(mesh, PartitionSpec(*t_spec)))
            ts_spec = [None] * leaf.ndim
            ts_spec[k] = tgt_entry
            s = jax.lax.with_sharding_constraint(
                s, NamedSharding(mesh, PartitionSpec(*ts_spec)))
            return (q.astype(jnp.float32) * s).astype(dtype)

        return jax.tree_util.tree_map(one, params, self._param_shardings)

    def _compute_loss_and_grads(self, params, batch, rng, scale,
                                constrain_cast=True):
        """value_and_grad of the (scaled) loss in the compute dtype.

        Pipelined models bypass autodiff: the 1F1B executor
        (runtime/pipe/spmd.py build_pipeline_grad_fn) returns explicit
        fp32 grads with the loss-scale folded in, attached as
        ``loss_fn.grad_fn``.

        ``constrain_cast=False`` is passed by the shard_map gradient
        paths (CSR / quantized / 1-bit): there 'data' is a manual axis,
        params are replicated per rank, and the cast's sharding
        constraint would be both illegal and meaningless."""
        explicit_grad = getattr(self._loss_fn, "grad_fn", None)
        if explicit_grad is not None:
            loss, grads = explicit_grad(
                params, batch, rng,
                scale / self.gradient_accumulation_steps)
            return loss, None, grads

        def scaled_loss_fn(p):
            cp = self._cast_for_loss(p, constrain=constrain_cast)
            if self._loss_takes_rng:
                out = self._loss_fn(cp, batch, rng)
            else:
                out = self._loss_fn(cp, batch)
            if isinstance(out, tuple):
                loss, aux = out[0], out[1]
            else:
                loss, aux = out, None
            scaled = (loss.astype(jnp.float32) * scale /
                      self.gradient_accumulation_steps)
            return scaled, (loss, aux)

        (_, (loss, aux)), grads = jax.value_and_grad(
            scaled_loss_fn, has_aux=True)(params)
        grads = _tree_cast(grads, jnp.float32)
        return loss, aux, grads

    # -- sparse (CSR) embedding-gradient path -----------------------------
    def _compute_sparse_grads(self, params, batch, rng, scale):
        """Grad exchange with CSR compression for embedding leaves
        (reference engine.py:1088-1139 csr_allreduce_no_retain).

        The whole backward runs under shard_map over 'data' so each rank
        holds a LOCAL gradient; embedding leaves are compacted to
        (capacity, dim+1) and exchanged via all_gather + local scatter-add
        (runtime/csr_tensor.csr_allreduce) — payload world x cap x (dim+1)
        instead of world x vocab x dim — while every other leaf takes a
        plain pmean. Returns an extra in-jit overflow flag: the capacity
        bound (tokens in the local batch) is provably safe for pure lookup
        embeddings but NOT for tied heads; a True flag means dropped rows
        and is surfaced loudly by the engine at the boundary.
        """
        from deepspeed_tpu.runtime.csr_tensor import (
            csr_allreduce, dense_to_csr)
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        sparse_paths = self._sparse_grad_paths
        dp = self.dp_world_size

        def inner(p, b, r, s):
            r = jax.random.fold_in(r, jax.lax.axis_index("data"))
            loss, aux, g = self._compute_loss_and_grads(
                p, b, r, s, constrain_cast=False)
            loss = jax.lax.pmean(loss, "data")
            # capacity: one grad row per token index in the local batch
            tokens = sum(int(np.prod(x.shape))
                         for x in jax.tree_util.tree_leaves(b)
                         if jnp.issubdtype(x.dtype, jnp.integer))
            overflow = jnp.zeros((), bool)

            def exchange(path, grad):
                nonlocal overflow
                key = _path_key(path)
                if key in sparse_paths and tokens > 0 \
                        and tokens < grad.shape[0]:
                    idx, vals, ovf = dense_to_csr(grad, tokens,
                                                  with_overflow=True)
                    overflow = jnp.logical_or(
                        overflow, jax.lax.pmax(ovf, "data"))
                    return csr_allreduce(idx, vals, grad.shape[0],
                                         "data") / dp
                return jax.lax.pmean(grad, "data")

            g = jax.tree_util.tree_map_with_path(exchange, g)
            return loss, overflow, g

        loss, overflow, grads = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(repl(params),
                      jax.tree_util.tree_map(lambda _: P("data"), batch),
                      P(), P()),
            out_specs=(P(), P(), repl(params)),
            check_vma=False)(params, batch, rng, scale)
        return loss, overflow, grads

    def _check_csr_overflow(self):
        """Surface a CSR capacity violation (dropped gradient rows) loudly,
        once; gated to boundary syncs so it costs nothing per-step."""
        if self._csr_overflow is None or self._csr_overflow_logged:
            return
        if bool(self._csr_overflow):
            self._csr_overflow_logged = True
            logger.error(
                "sparse_gradients: an embedding gradient had more nonzero "
                "rows than the token-count capacity — rows were DROPPED "
                "(gradient is wrong). This happens when a detected "
                "'embedding' leaf also receives dense gradients (e.g. a "
                "tied LM head). Disable sparse_gradients for this model.")

    # -- int8 quantized allreduce path ------------------------------------
    def _quant_exchange_parts(self):
        """``(detect_ovf, exchange_tree)`` closures over the engine's
        quantized-comm config — the ONE copy of the per-leaf exchange
        and the fp16 nonfinite sentinel, shared by the serial
        in-shard_map exchange and the overlapped deferred one
        (:meth:`_quant_exchange_stacked`), so the bitwise-parity
        contract between the two paths cannot drift across hand-kept
        copies. Both closures must run INSIDE shard_map over the data
        axes.

        ``detect_ovf``: fp16 overflow sentinel — quantization destroys
        inf/nan (the absmax scale goes inf -> q garbage), so nonfinite
        is detected BEFORE the exchange and ``exchange_tree`` re-poisons
        the result, keeping the engine's has_overflow skip-step
        machinery working. ``exchange_tree``: leaves smaller than one
        quantization block ship dense (pmean); the rest take the
        flat/hierarchical quantized mean."""
        from deepspeed_tpu.runtime.quantized_collectives import (
            hierarchical_quantized_allreduce_mean, quantized_allreduce_mean)
        block = self._quant_block
        algo = self._quant_algo
        dp_axes = self.dp_axes
        hierarchical = self._dp_hierarchical
        if hierarchical:
            inter_size = axis_size(self.mesh, "data_inter")
            intra_size = axis_size(self.mesh, "data_intra")
        world = self.dp_world_size
        fp16 = self.fp16_enabled

        def detect_ovf(g):
            ovf = jnp.zeros((), bool)
            if fp16:
                for leaf in jax.tree_util.tree_leaves(g):
                    ovf = jnp.logical_or(
                        ovf, jnp.any(~jnp.isfinite(leaf)))
                ovf = jax.lax.pmax(ovf.astype(jnp.int32),
                                   dp_axes).astype(bool)
            return ovf

        def exchange_tree(g, ovf):
            def exchange(grad):
                if grad.size < block:
                    return jax.lax.pmean(grad, dp_axes)
                if hierarchical:
                    out = hierarchical_quantized_allreduce_mean(
                        grad, "data_intra", "data_inter",
                        intra_size, inter_size, block)
                else:
                    out = quantized_allreduce_mean(
                        grad, dp_axes[0], block, algo=algo,
                        world_size=world)
                if fp16:
                    out = jnp.where(ovf, jnp.nan, out)
                return out

            return jax.tree_util.tree_map(exchange, g)

        return detect_ovf, exchange_tree

    def _compute_quantized_grads(self, params, batch, rng, scale):
        """Backward under shard_map over the data axes with the int8
        block-quantized gradient exchange
        (runtime/quantized_collectives.py).

        algo='twohop' (default) is the qgZ shape: per-rank wire ~2n int8
        bytes independent of dp degree. algo='allgather' is the legacy
        O(W*n) exchange (only sane at dp=2). With
        quantized_comm.hierarchical the bandwidth-heavy hops run over
        'data_intra' and only the reduced 1/W_intra chunk crosses
        'data_inter'."""
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        # Gather + cast ONCE in GSPMD land before entering shard_map:
        # in_specs=repl would otherwise coerce the ZeRO-sharded fp32
        # masters to replicated — an f32 all-gather on the wire where a
        # compute-dtype (or, under qwZ, int8) gather would do. The cast
        # rides qwZ/hpZ when enabled; inside the shard_map the re-cast
        # is a no-op.
        params = self._cast_for_loss(params, constrain=True)
        dp_axes = self.dp_axes
        batch_entry = self._dp_axis_entry
        detect_ovf, exchange_tree = self._quant_exchange_parts()

        def inner(p, b, r, s):
            idx = jax.lax.axis_index(dp_axes[0])
            for ax in dp_axes[1:]:
                idx = idx * axis_size(self.mesh, ax) + \
                    jax.lax.axis_index(ax)
            r = jax.random.fold_in(r, idx)
            loss, _aux, g = self._compute_loss_and_grads(
                p, b, r, s, constrain_cast=False)
            loss = jax.lax.pmean(loss, dp_axes)
            g = exchange_tree(g, detect_ovf(g))
            return loss, g

        loss, grads = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(repl(params),
                      jax.tree_util.tree_map(lambda _: P(batch_entry),
                                             batch),
                      P(), P()),
            out_specs=(P(), repl(params)),
            check_vma=False)(params, batch, rng, scale)
        return loss, None, grads

    # -- 1-bit Adam distributed path --------------------------------------
    def _compute_local_grads(self, params, batch, rng, scale):
        """Per-data-shard gradients, stacked on a leading (dp,) axis sharded
        over 'data'. Under shard_map XLA does NOT insert the dense grad
        allreduce — each rank keeps its local gradient, which is what the
        1-bit compressed momentum exchange needs (reference disables
        enable_backward_allreduce, onebit_adam.py:369-372)."""
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)

        def inner(p, b, r, s):
            r = jax.random.fold_in(r, jax.lax.axis_index("data"))
            loss, _aux, g = self._compute_loss_and_grads(
                p, b, r, s, constrain_cast=False)
            loss = jax.lax.pmean(loss, "data")
            return loss, jax.tree_util.tree_map(lambda x: x[None], g)

        loss, grads = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(repl(params),
                      jax.tree_util.tree_map(lambda _: P("data"), batch),
                      P(), P()),
            out_specs=(P(),
                       jax.tree_util.tree_map(lambda _: P("data"), params)),
            check_vma=False)(params, batch, rng, scale)
        return loss, None, grads

    def _onebit_shard_update(self, params, opt_state, grads_stacked, lr):
        """Run the OnebitAdam update inside shard_map over 'data': each rank
        updates momentum with its local grad, then the compressed allreduce
        (or warmup pmean) is the only cross-rank communication."""
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        data = lambda tree: jax.tree_util.tree_map(lambda _: P("data"), tree)
        from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdamState

        def upd(p, m, v, step, we, se, g, lr_):
            take0 = lambda tree: jax.tree_util.tree_map(
                lambda x: x[0], tree)
            st = OnebitAdamState(step=step, exp_avg=m, exp_avg_sq=v,
                                 worker_error=take0(we),
                                 server_error=take0(se))
            new_p, new_st = self.optimizer.update(
                take0(g), st, p, lr=lr_,
                compression=self._onebit_compression)
            lead = lambda tree: jax.tree_util.tree_map(
                lambda x: x[None], tree)
            return (new_p, new_st.exp_avg, new_st.exp_avg_sq, new_st.step,
                    lead(new_st.worker_error), lead(new_st.server_error))

        outs = jax.shard_map(
            upd, mesh=self.mesh,
            in_specs=(repl(params), repl(opt_state.exp_avg),
                      repl(opt_state.exp_avg_sq), P(),
                      data(opt_state.worker_error),
                      data(opt_state.server_error),
                      data(grads_stacked), P()),
            out_specs=(repl(params), repl(opt_state.exp_avg),
                       repl(opt_state.exp_avg_sq), P(),
                       data(opt_state.worker_error),
                       data(opt_state.server_error)),
            check_vma=False)(
            params, opt_state.exp_avg, opt_state.exp_avg_sq,
            opt_state.step, opt_state.worker_error,
            opt_state.server_error, grads_stacked, lr)
        new_params, m, v, step, we, se = outs
        return new_params, OnebitAdamState(
            step=step, exp_avg=m, exp_avg_sq=v,
            worker_error=we, server_error=se)

    def _apply_update(self, state: TrainState, grads) -> TrainState:
        """Optimizer boundary: unscale, clip, update, loss-scale bookkeeping.
        (reference stage2.py:1331 step / engine.py:865 _take_model_step)"""
        inv_scale = 1.0 / state.loss_scale.scale
        grads = jax.tree_util.tree_map(lambda g: g * inv_scale, grads)

        if self.fp16_enabled:
            overflow = has_overflow(grads)
        else:
            overflow = jnp.zeros((), bool)

        if self.gradient_clipping > 0:
            if self._onebit_dist:
                # stacked local grads: clip by the norm of the averaged
                # gradient (what the dense path would see)
                norm = _global_norm(jax.tree_util.tree_map(
                    lambda g: g.mean(axis=0), grads))
            else:
                norm = _global_norm(grads)
            clip = jnp.minimum(1.0, self.gradient_clipping /
                               (norm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * clip, grads)

        lr = self._lr_at(state.global_step)
        mom = self._mom_at(state.global_step)
        # master-weight-free bf16: per-step PRNG key for the stochastic
        # rounding of the fp32 update result back into the bf16 params
        sr_key = None
        if self.bf16_enabled and not self.bf16_master_weights:
            sr_key = jax.random.fold_in(
                jax.random.PRNGKey(self._config.bf16_sr_seed),
                state.global_step)

        def do_update(operand):
            params, opt_state, g = operand
            if self._onebit_dist:
                return self._onebit_shard_update(params, opt_state, g, lr)
            if self._onebit:
                return self.optimizer.update(
                    g, opt_state, params, lr=lr,
                    compression=self._onebit_compression)
            kw = {} if sr_key is None else {"sr_key": sr_key}
            if mom is not None:
                return self.optimizer.update(g, opt_state, params, lr=lr,
                                             momentum=mom, **kw)
            return self.optimizer.update(g, opt_state, params, lr=lr, **kw)

        def skip_update(operand):
            params, opt_state, _ = operand
            return params, opt_state

        if self.fp16_enabled:
            new_params, new_opt = jax.lax.cond(
                overflow, skip_update, do_update,
                (state.params, state.opt_state, grads))
        else:
            # overflow is statically False (bf16/fp32): no cond — keeps
            # collectives (1-bit allreduce) out of conditional branches
            new_params, new_opt = do_update(
                (state.params, state.opt_state, grads))

        new_scale = self.loss_scaler.update(state.loss_scale, overflow)
        zero_accum = jax.tree_util.tree_map(jnp.zeros_like,
                                            state.accum_grads)
        return state._replace(
            params=new_params,
            opt_state=new_opt,
            accum_grads=zero_accum,
            loss_scale=new_scale,
            global_step=state.global_step + (1 - overflow.astype(jnp.int32)),
            micro_step=jnp.zeros((), jnp.int32),
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
        )

    def _grads_for_micro(self, state: TrainState, batch, sub):
        """One micro batch's fwd+bwd, dispatched to the configured
        gradient-exchange path. Returns ``(loss, csr_overflow|None,
        grads)`` — shared by the per-micro step, the facade
        ``forward()``, and the fused batch step's scan body."""
        scale = state.loss_scale.scale
        if self._onebit_dist:
            loss, _aux, grads = self._compute_local_grads(
                state.params, batch, sub, scale)
        elif self._sparse_grad_paths:
            return self._compute_sparse_grads(state.params, batch, sub,
                                              scale)
        elif self._quant_allreduce:
            loss, _aux, grads = self._compute_quantized_grads(
                state.params, batch, sub, scale)
        else:
            loss, _aux, grads = self._compute_loss_and_grads(
                state.params, batch, sub, scale)
        return loss, None, grads

    def _micro_step(self, state: TrainState, batch) -> Tuple[TrainState, Any]:
        """One fused micro-batch step: fwd + bwd + accumulate + maybe-apply.
        Returns ``(state, loss)`` — or ``(state, (loss, csr_overflow))``
        when the CSR sparse-gradient path is active."""
        rng, sub = jax.random.split(state.rng)
        loss, csr_ovf, grads = self._grads_for_micro(state, batch, sub)

        out = loss if csr_ovf is None else (loss, csr_ovf)
        if self.zero_cpu_offload and self.gradient_accumulation_steps == 1:
            # no accumulator: the compute-dtype grads are an OUTPUT of
            # the dispatch (half the D2H bytes of fp32 — the
            # reference's 16-bit grad transfer to the host optimizer);
            # train_batch/backward stash them for _host_grad_snapshot
            state = state._replace(rng=rng,
                                   micro_step=state.micro_step + 1)
            return state, (out, _tree_cast(grads, self.compute_dtype))
        if self.zero_cpu_offload or self.gradient_accumulation_steps > 1:
            accum = jax.tree_util.tree_map(jnp.add, state.accum_grads, grads)
            state = state._replace(accum_grads=accum, rng=rng,
                                   micro_step=state.micro_step + 1)
            if not self.zero_cpu_offload:
                # offload applies host-side in _host_apply_update instead
                boundary = (state.micro_step %
                            self.gradient_accumulation_steps == 0)
                state = jax.lax.cond(
                    boundary,
                    lambda s: self._apply_update(s, s.accum_grads),
                    lambda s: s,
                    state)
        else:
            state = state._replace(rng=rng,
                                   micro_step=state.micro_step + 1)
            state = self._apply_update(state, grads)
        return state, out

    def _get_compiled_micro_step(self):
        if self._compiled_micro_step is None:
            # wrap_jit is identity with observability off; on, it counts
            # compiles + wall time and flags steady-state recompiles
            self._compiled_micro_step = self.observability.wrap_jit(
                jax.jit(self._micro_step, donate_argnums=(0,)),
                "micro_step")
        return self._compiled_micro_step

    # ------------------------------------------------------------------ #
    # async step pipeline: scan-fused accumulation
    # ------------------------------------------------------------------ #
    def _batch_step(self, state: TrainState, stacked) -> Tuple[TrainState,
                                                               Any]:
        """The WHOLE accumulation window as ONE compiled program
        (``async_pipeline.fused_accumulation``): a ``lax.scan`` of the
        micro fwd+bwd+accumulate body over the stacked ``(gas, ...)``
        batch, then the boundary apply — same rng stream, same
        accumulation order, same loss-scale/overflow semantics as
        ``gas`` separate micro dispatches, so losses and updates are
        bit-identical to the per-micro loop
        (tests/unit/test_async_pipeline.py pins this). One dispatch per
        ``train_batch`` instead of ``gas``: the host never sits between
        two micro steps. State/accumulator shardings are the micro
        step's own (the ZeRO ``zero_shardings`` placements ride the
        donated carry); the quantized/hierarchical DP exchange runs
        unchanged inside the scan body."""
        gas = self.gradient_accumulation_steps
        # the scan body IS the micro step (same accumulate + boundary
        # cond + apply graph per iteration) — parity with the per-micro
        # loop is structural, not re-derived
        state, losses = jax.lax.scan(self._micro_step, state, stacked)
        # left-fold mean in the loss dtype, matching the per-micro
        # loop's python-side accumulation
        total = losses[0]
        for i in range(1, gas):
            total = total + losses[i]
        return state, total / gas

    # -- comm_autotune: compute/comm overlap inside the fused window ------
    #
    # The serial scan body computes micro-step i's gradients AND
    # exchanges them in the same iteration — the exchange collectives
    # depend on that iteration's backward dots, so the ICI idles during
    # compute and the MXU idles during the exchange. The overlapped
    # shape double-buffers: iteration i carries micro-step i-1's LOCAL
    # (unexchanged) gradients and issues their exchange alongside
    # micro-step i's forward/backward — the exchange reads only the
    # loop carry, making it data-independent of the iteration's compute
    # (pinned structurally by the HLO operand-cone audit in
    # tests/unit/test_hlo_quantized_comm.py), so XLA's scheduler can
    # run the two concurrently. The last window's exchange flushes
    # after the scan, then the boundary apply runs. Exchange inputs,
    # math, and accumulation order are IDENTICAL to the serial path —
    # losses and updates are bitwise-equal (tier-1 pinned).

    def _quant_local_grads(self, params, batch, rng, scale):
        """One micro-step's loss + LOCAL (pre-exchange) gradients under
        shard_map over the data axes, stacked on a leading dp-sharded
        axis — the double-buffered carry of the overlapped scan.
        ``params`` are already cast/gathered by the caller (the qwZ
        weight gather is hoisted out of the scan: params are constant
        within the window, so one gather serves all ``gas`` micros)."""
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        dp_axes = self.dp_axes
        batch_entry = self._dp_axis_entry
        stacked = lambda tree: jax.tree_util.tree_map(
            lambda _: P(batch_entry), tree)

        def inner(p, b, r, s):
            idx = jax.lax.axis_index(dp_axes[0])
            for ax in dp_axes[1:]:
                idx = idx * axis_size(self.mesh, ax) + \
                    jax.lax.axis_index(ax)
            r = jax.random.fold_in(r, idx)
            loss, _aux, g = self._compute_loss_and_grads(
                p, b, r, s, constrain_cast=False)
            loss = jax.lax.pmean(loss, dp_axes)
            return loss, jax.tree_util.tree_map(lambda x: x[None], g)

        loss, local = jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(repl(params),
                      jax.tree_util.tree_map(lambda _: P(batch_entry),
                                             batch),
                      P(), P()),
            out_specs=(P(), stacked(params)),
            check_vma=False)(params, batch, rng, scale)
        return loss, local

    def _quant_exchange_stacked(self, local):
        """The deferred half of the quantized exchange: stacked local
        gradients in, replicated fp32 mean out. Shares the per-leaf
        exchange (and fp16 nonfinite-poisoning) closures with the
        serial :meth:`_compute_quantized_grads` via
        :meth:`_quant_exchange_parts` — only the issue POINT moved, so
        the result is bitwise what the serial path produces for the
        same local gradients."""
        P = PartitionSpec
        repl = lambda tree: jax.tree_util.tree_map(lambda _: P(), tree)
        batch_entry = self._dp_axis_entry
        detect_ovf, exchange_tree = self._quant_exchange_parts()

        def inner(stacked):
            g = jax.tree_util.tree_map(lambda x: x[0], stacked)
            return exchange_tree(g, detect_ovf(g))

        return jax.shard_map(
            inner, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(batch_entry),
                                             local),),
            out_specs=repl(local),
            check_vma=False)(local)

    def _batch_step_overlapped(self, state: TrainState, stacked
                               ) -> Tuple[TrainState, Any]:
        """The fused window with the exchange double-buffered: micro 0
        computes outside the scan, each scan iteration exchanges the
        PREVIOUS micro's gradients while computing its own, the last
        exchange flushes after the scan, then the boundary apply runs.
        Same rng stream, same exchange math, same accumulation order as
        the serial :meth:`_batch_step` — bitwise-equal losses/params
        (tests/unit/test_comm_autotune.py pins this)."""
        gas = self.gradient_accumulation_steps
        # hoisted weight gather: params are constant within the window,
        # so the (qwZ/hpZ-riding) cast+gather runs once per window, not
        # once per micro — the prefetched next-step weights of the
        # ZeRO++ playbook, as a loop-invariant the partitioner can
        # schedule ahead of the first micro's compute
        cast = self._cast_for_loss(state.params, constrain=True)
        scale = state.loss_scale.scale
        rng, sub = jax.random.split(state.rng)
        micro0 = jax.tree_util.tree_map(lambda x: x[0], stacked)
        loss0, pending = self._quant_local_grads(cast, micro0, sub, scale)

        def body(carry, batch):
            rng, accum, pending = carry
            rng, sub = jax.random.split(rng)
            loss, local = self._quant_local_grads(cast, batch, sub, scale)
            exchanged = self._quant_exchange_stacked(pending)
            accum = jax.tree_util.tree_map(jnp.add, accum, exchanged)
            return (rng, accum, local), loss

        rest = jax.tree_util.tree_map(lambda x: x[1:], stacked)
        (rng, accum, pending), losses = jax.lax.scan(
            body, (rng, state.accum_grads, pending), rest)
        # flush: the last micro's exchange has no next compute to hide
        # under (the NEXT window's first micro would — across-dispatch
        # overlap is the async dispatch queue's job)
        exchanged = self._quant_exchange_stacked(pending)
        accum = jax.tree_util.tree_map(jnp.add, accum, exchanged)
        state = state._replace(rng=rng,
                               micro_step=state.micro_step + gas)
        state = self._apply_update(state, accum)
        total = loss0
        for i in range(gas - 1):
            total = total + losses[i]
        return state, total / gas

    def _select_overlap_path(self):
        """(overlap?, why) — the exchange-overlap analog of
        :meth:`_select_batch_path`; only consulted on the fused path."""
        ca = self._autotune_cfg
        if not ca["enabled"]:
            return False, "comm_autotune disabled"
        if ca["overlap"] is False:
            return False, "comm_autotune.overlap=false"
        if self.gradient_accumulation_steps < 2:
            return False, ("gas=1: no next micro-step to hide the "
                           "exchange under")
        if not self._quant_allreduce:
            return False, ("no explicit exchange to defer (dense GSPMD "
                           "/ CSR / 1-bit paths own their schedules)")
        return True, ("grad exchange of micro-step i issued alongside "
                      "micro-step i+1's compute (double-buffered carry, "
                      "post-scan flush)")

    def _overlap_path(self) -> bool:
        """Decide once which fused-step body compiles (overlapped or
        serial exchange), with its own one-line log."""
        if self._use_overlap is None:
            ov, why = self._select_overlap_path()
            self._use_overlap = ov
            if self._autotune_cfg["enabled"]:
                log_dist("comm_autotune: exchange overlap = "
                         + ("on" if ov else "off") + f" ({why})",
                         ranks=[0])
        return self._use_overlap

    def _select_batch_path(self):
        """(fused?, why) for this engine's configuration. The fused path
        covers the default configs (bf16/fp16/fp32 x ZeRO 0-2 x dense or
        quantized/hierarchical collectives); paths that genuinely need
        the host between micro steps keep the per-micro loop."""
        if not self._async_cfg["fused_accumulation"]:
            return False, "async_pipeline.fused_accumulation=false"
        if self.gradient_accumulation_steps == 1:
            return False, ("gas=1: the micro step already covers the "
                           "window in one dispatch")
        if self.zero_cpu_offload:
            return False, "ZeRO-Offload runs the host Adam at the boundary"
        if self._onebit or self._onebit_dist:
            return False, "1-bit Adam phase switching is host-driven"
        if self._sparse_grad_paths:
            return False, ("sparse (CSR) grads surface a per-micro "
                           "overflow flag")
        return True, (f"scan over gas={self.gradient_accumulation_steps} "
                      "micro batches, one dispatch per train_batch")

    def _batch_path(self) -> bool:
        """Decide once (at first train_batch) which path compiles, with
        the one-line log the acceptance criteria require."""
        if self._use_fused_batch is None:
            fused, why = self._select_batch_path()
            self._use_fused_batch = fused
            log_dist("async_pipeline: train_batch path = "
                     + ("fused batch_step" if fused else "per-micro loop")
                     + f" ({why})", ranks=[0])
        return self._use_fused_batch

    def _get_compiled_batch_step(self):
        if self._compiled_batch_step is None:
            body = (self._batch_step_overlapped if self._overlap_path()
                    else self._batch_step)
            self._compiled_batch_step = self.observability.wrap_jit(
                jax.jit(body, donate_argnums=(0,)),
                "batch_step")
        return self._compiled_batch_step

    def _stacked_batch_sharding(self):
        """Sharding for the fused path's ``(gas, batch, ...)`` input:
        micro axis replicated, batch dim split over the data axes
        (cached — the mesh is fixed at construction)."""
        if self._stacked_shd is None:
            from deepspeed_tpu.parallel.mesh import data_axis_names
            axes = data_axis_names(self.mesh)
            if axes:
                entry = axes if len(axes) > 1 else axes[0]
                spec = PartitionSpec(None, entry)
            else:
                spec = PartitionSpec()
            self._stacked_shd = NamedSharding(self.mesh, spec)
        return self._stacked_shd

    def _micro_batch_sharding(self):
        """Cached per-micro batch sharding (leading dim over data)."""
        if self._micro_shd is None:
            from deepspeed_tpu.parallel.mesh import data_sharding
            self._micro_shd = data_sharding(self.mesh)
        return self._micro_shd

    def _next_stacked_batch(self, data_iter):
        """One ``(gas, ...)`` stacked device batch for the fused step:
        consumed directly from a stacking :class:`PrefetchLoader`, else
        ``gas`` micro batches are pulled and stacked host-side
        (device-array micros pay a D2H — feed host batches, or let the
        engine's own prefetcher assemble them off-thread)."""
        if getattr(data_iter, "stacks_micro_batches", False):
            return next(data_iter)
        micros = [next(data_iter)
                  for _ in range(self.gradient_accumulation_steps)]
        # device-resident micros (a user loader that already device_put
        # them) stack on-device — np.stack would pull every micro D2H
        # and re-upload, a per-step round-trip the per-micro loop never
        # paid
        on_device = all(isinstance(x, jax.Array)
                        for x in jax.tree_util.tree_leaves(micros[0]))
        stacked = (jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *micros)
                   if on_device else stack_micro_batches(micros))
        return self._put_stacked_batch(stacked)

    def _put_guarded(self, batch, shd, batch_dim):
        """Sharded put with a replication fallback: leaves whose batch
        dim (``batch_dim``) doesn't divide the dp degree — or that lack
        it entirely (scalars) — stay replicated. The per-micro loop fed
        such host batches to jit unsharded and GSPMD partitions the
        compute either way, so the prefetch/stacking puts can never
        crash a config that runs without them."""
        if shd.spec == PartitionSpec():
            return jax.device_put(batch, shd)
        repl = NamedSharding(self.mesh, PartitionSpec())
        dp = self.dp_world_size

        def put(x):
            ok = (hasattr(x, "ndim") and x.ndim > batch_dim
                  and x.shape[batch_dim] % dp == 0)
            return jax.device_put(x, shd if ok else repl)

        return jax.tree_util.tree_map(put, batch)

    def _put_stacked_batch(self, stacked):
        """Guarded put for a ``(gas, batch, ...)`` window (also the
        stacking prefetch worker's put)."""
        return self._put_guarded(stacked, self._stacked_batch_sharding(),
                                 batch_dim=1)

    def _put_micro_batch(self, batch):
        """Guarded put for one un-stacked micro batch (the non-fused
        prefetch path)."""
        return self._put_guarded(batch, self._micro_batch_sharding(),
                                 batch_dim=0)

    def _ensure_train_iter(self):
        """``train_batch(data_iter=None)`` plumbing, shared with the
        pipe engine: lazily wrap ``training_data``'s loader in a
        RepeatingLoader plus (base engine) the async prefetch stage."""
        assert self.training_dataloader is not None, \
            "train_batch() without data_iter requires training_data"
        if getattr(self, "_train_iter", None) is None:
            self._train_iter = iter(self._wrap_train_iter(
                RepeatingLoader(self.training_dataloader)))
        return self._train_iter

    def _wrap_train_iter(self, it):
        """Insert the background prefetch stage (``async_pipeline
        .prefetch_depth`` > 0): a worker thread assembles and
        device_puts batches — stacked to ``(gas, ...)`` on the fused
        path — so H2D for batch N+1 overlaps compute of batch N."""
        fused = self._batch_path()
        if isinstance(self.training_dataloader, DeepSpeedDataLoader) and \
                (fused or self._prefetch_depth > 0):
            # the stacking put (or the prefetch worker) owns the H2D; a
            # loader-side device_put would force a D2H round-trip at
            # the host stacking stage
            self.training_dataloader.device_put_enabled = False
        if self._prefetch_depth <= 0:
            return it
        stack = self.gradient_accumulation_steps if fused else 1
        put_fn = (self._put_stacked_batch if stack > 1
                  else self._put_micro_batch)
        self._prefetcher = PrefetchLoader(it, put_fn=put_fn,
                                          depth=self._prefetch_depth,
                                          stack_micros=stack)
        return self._prefetcher

    def close(self):
        """Release engine-owned background resources: drain any
        in-flight overlapped offload update AND any pending async
        checkpoint saves (the close barrier of the async-save contract —
        a stored writer exception is re-raised at the end, after every
        resource is released), stop the prefetch thread, flush deferred
        telemetry, uninstall the preemption guard, seal the
        observability log."""
        self._offload_drain()
        save_error = None
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()
            try:
                self._ckpt_writer.raise_pending_error()
            except Exception as e:   # surfaced below, not swallowed
                save_error = e
            self._ckpt_writer = None
        if self._elastic is not None:
            self._elastic.uninstall()
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        # drop the train iterator too: it wraps the closed prefetcher,
        # and a later train_batch() through it would silently restart a
        # worker thread the engine no longer tracks
        self._train_iter = None
        if self._monitor_ring:
            self._flush_monitor()
        import atexit
        try:
            atexit.unregister(self._atexit_flush_hook)
        except Exception:
            pass
        # health BEFORE observability: untapping the mirror restores
        # the Observer's own writer so its close-time identity check
        # (mirror is self._log) still clears it
        self.health.close()
        self.observability.close()
        if save_error is not None:
            raise save_error

    def _flush_monitor_atexit(self):
        """Interpreter-exit safety net for the deferred-telemetry ring
        (best-effort: the device may already be tearing down)."""
        try:
            if self._monitor_ring:
                self._flush_monitor()
        except Exception:
            pass

    # ------------------------------------------------------------------ #
    # reference-style facade: forward / backward / step
    # ------------------------------------------------------------------ #
    def forward(self, batch):
        """Compute loss for one micro batch (reference engine.py:729).

        NB: under XLA the backward pass is part of the same compiled graph,
        so ``forward`` runs value_and_grad and caches the grads;
        ``backward`` accumulates them; ``step`` applies at the boundary.
        Use ``train_batch`` for the single-dispatch fused path.
        """
        if self.wall_clock_breakdown_enabled:
            self.timers("forward").start()
        if self._compiled_grad is None:
            def fwd(state, batch):
                # same per-path dispatch as the micro/batch steps (incl.
                # the quantized exchange, which keeps the qwZ weight
                # quantization OUTSIDE autodiff — differentiating
                # through round() would zero the master gradients)
                rng, sub = jax.random.split(state.rng)
                loss, ovf, grads = self._grads_for_micro(state, batch, sub)
                if ovf is not None:
                    return loss, grads, rng, ovf
                return loss, grads, rng
            self._compiled_grad = self.observability.wrap_jit(
                jax.jit(fwd), "grad")
        with self.observability.span("forward"):
            out = self._compiled_grad(self.state, batch)
        if self._sparse_grad_paths and not self._onebit_dist:
            loss, grads, rng, self._csr_overflow = out
        else:
            loss, grads, rng = out
        self.state = self.state._replace(rng=rng)
        self._cached_grads = grads
        self._cached_loss = loss
        if self.wall_clock_breakdown_enabled:
            self.timers("forward").stop()
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients=True):
        """Accumulate the cached grads (reference engine.py:767). The DP
        allreduce happens implicitly: grads of replicated params over
        data-sharded batches are psum'd by GSPMD."""
        assert self._cached_grads is not None, \
            "backward() must follow forward() on the same micro batch"
        if self.wall_clock_breakdown_enabled:
            self.timers("backward").start()
        with self.observability.span("backward"):
            self._backward_inner()
        if self.wall_clock_breakdown_enabled:
            self.timers("backward").stop()
        return loss

    def _backward_inner(self):
        grads = self._cached_grads
        self._cached_grads = None
        if self.zero_cpu_offload and self.gradient_accumulation_steps == 1:
            # no device accumulator (micro-step parity): stash for the
            # boundary snapshot, cast to compute dtype like the fused
            # path so this API moves the same 16-bit D2H bytes
            self._offload_grads_device = _tree_cast(grads,
                                                    self.compute_dtype)
            self.state = self.state._replace(
                micro_step=self.state.micro_step + 1)
        elif self.gradient_accumulation_steps > 1 or self.zero_cpu_offload:
            accum = jax.tree_util.tree_map(jnp.add, self.state.accum_grads,
                                           grads)
            self.state = self.state._replace(
                accum_grads=accum, micro_step=self.state.micro_step + 1)
        else:
            self._pending_grads = grads
            self.state = self.state._replace(
                micro_step=self.state.micro_step + 1)

    # -- ZeRO-Offload boundary, split so the host Adam can overlap the
    # -- next window's device compute (reference overlaps D2H/H2D on side
    # -- streams, stage2.py:291-294 + async copy in csrc/adam/cpu_adam.cpp)
    def _host_grad_snapshot(self):
        """D2H of the summed, unscaled grads as host fp32. ga=1: the
        micro step emitted them as a compute-dtype output (no device
        accumulator to reset); ga>1: drain and zero the fp32
        accumulator so the next window can start immediately."""
        from deepspeed_tpu.runtime.checkpoint import _to_host_global
        scale = float(self.state.loss_scale.scale)
        inv = 1.0 / scale
        if self.gradient_accumulation_steps == 1:
            assert self._offload_grads_device is not None, \
                "offload boundary without a completed micro step"
            src, self._offload_grads_device = \
                self._offload_grads_device, None
            self.state = self.state._replace(
                micro_step=jnp.zeros((), jnp.int32))
            host = jax.tree_util.tree_map(_to_host_global, src)
            return jax.tree_util.tree_map(
                lambda g: np.asarray(g, np.float32) * inv, host)
        accum = jax.tree_util.tree_map(_to_host_global,
                                       self.state.accum_grads)
        grads = jax.tree_util.tree_map(
            lambda g: np.asarray(g, np.float32) * inv, accum)
        zero_accum = jax.tree_util.tree_map(
            lambda g: jnp.zeros(g.shape, g.dtype), self.state.accum_grads)
        self.state = self.state._replace(
            accum_grads=jax.device_put(
                zero_accum, self._state_shardings.accum_grads),
            micro_step=jnp.zeros((), jnp.int32))
        return grads

    def _host_optimize(self, grads, lr, mom=None):
        """Overflow check + clip + native C++ SIMD Adam on the host fp32
        master (reference stage2.py:1418-1431 DeepSpeedCPUAdam.step).
        Thread-safe w.r.t. device work: touches only host state."""
        overflow = any(not np.all(np.isfinite(g))
                       for g in jax.tree_util.tree_leaves(grads))
        if overflow:
            return None, True
        if self.gradient_clipping > 0:
            sq = sum(float(np.sum(g.astype(np.float64) ** 2))
                     for g in jax.tree_util.tree_leaves(grads))
            clip = min(1.0, self.gradient_clipping /
                       (np.sqrt(sq) + 1e-6))
            if clip < 1.0:
                grads = jax.tree_util.tree_map(
                    lambda g: g * np.float32(clip), grads)
        use_bf16 = self.compute_dtype == jnp.bfloat16
        new_params = self.optimizer.step(grads, lr=lr, bf16_out=use_bf16,
                                         beta1=mom)
        if not use_bf16:
            dtype = self.compute_dtype or jnp.float32
            new_params = jax.tree_util.tree_map(
                lambda p: p.astype(dtype), new_params)
        return new_params, False

    def _apply_host_result(self, new_params, overflow):
        """H2D of the updated compute-dtype params + counter/scale
        bookkeeping (reference's fp32->fp16 device copy)."""
        if overflow:
            device_params = self.state.params
        else:
            device_params = jax.device_put(new_params,
                                           self._param_shardings)
        new_scale = self.loss_scaler.update(
            self.state.loss_scale, jnp.asarray(overflow))
        inc = 0 if overflow else 1
        self.state = self.state._replace(
            params=device_params,
            loss_scale=new_scale,
            global_step=self.state.global_step + inc,
            skipped_steps=self.state.skipped_steps + (1 - inc),
        )

    def _host_apply_update(self):
        """Synchronous ZeRO-Offload boundary: snapshot -> Adam -> H2D."""
        grads = self._host_grad_snapshot()
        lr = float(self._lr_at(self.state.global_step))
        mom = self._mom_at(self.state.global_step)
        new_params, overflow = self._host_optimize(
            grads, lr, None if mom is None else float(mom))
        self._apply_host_result(new_params, overflow)

    def _host_apply_update_overlapped(self):
        """Overlapped boundary (zero_optimization.overlap_comm): apply the
        PREVIOUS window's pending update, snapshot this window's grads,
        and hand them to the worker thread — the host Adam then runs
        concurrently with the next window's device compute. Updates are
        one window delayed (window k+1 computes with params_{k-1}); call
        :meth:`synchronize` (or save/eval, which do) to drain."""
        self._offload_drain()
        grads = self._host_grad_snapshot()
        lr = float(self._lr_at(self.state.global_step))
        mom = self._mom_at(self.state.global_step)
        self._offload_pending = self._offload_pool.submit(
            self._host_optimize, grads, lr,
            None if mom is None else float(mom))

    def _offload_drain(self):
        if getattr(self, "_offload_pending", None) is not None:
            new_params, overflow = self._offload_pending.result()
            self._offload_pending = None
            self._apply_host_result(new_params, overflow)

    def synchronize(self):
        """Apply any in-flight overlapped offload update (no-op
        otherwise). Call before reading params outside the engine."""
        self._offload_drain()

    def _maybe_switch_onebit_phase(self):
        """Enter 1-bit compression once global_steps reaches freeze_step
        (reference onebit_adam.py:369-372). Recompiles the step functions —
        a one-time cost at the phase boundary."""
        if not self._onebit or self._onebit_compression:
            return  # phase is monotonic: once on, stay on (no per-step sync)
        # _host_global_step over-counts vs the device value by fp16
        # overflow skips (which DO happen in early fp16 training — the
        # initial dynamic scale of 2^32 typically overflows several steps).
        # The host mirror is only the cheap gate: at the boundary, confirm
        # with the authoritative device counter before flipping — the
        # one-time sync is amortized by the recompile that follows
        # (reference onebit_adam.py:369-372 gates on true optimizer steps).
        if self._host_global_step < self.optimizer.freeze_step:
            return
        phase = self.global_steps >= self.optimizer.freeze_step
        if phase != self._onebit_compression:
            self._onebit_compression = phase
            self._compiled_micro_step = None
            self._compiled_batch_step = None
            self._compiled_apply = None
            self._compiled_grad = None
            log_dist(f"OnebitAdam: compression phase = {phase} "
                     f"(step {self.global_steps})", ranks=[0])

    def step(self):
        """Apply the optimizer at the accumulation boundary
        (reference engine.py:903)."""
        self._maybe_switch_onebit_phase()
        if self.wall_clock_breakdown_enabled:
            self.timers("step").start()
        ga = self.gradient_accumulation_steps
        if self.zero_cpu_offload:
            if self.is_gradient_accumulation_boundary():
                if self._offload_overlap:
                    self._host_apply_update_overlapped()
                else:
                    self._host_apply_update()
                self._host_global_step += 1
                self._report_progress()
                self._write_monitor(self._cached_loss)
            self._host_micro_step += 1
            if self.wall_clock_breakdown_enabled:
                self.timers("step").stop()
            self._elastic_boundary()
            return
        if self._compiled_apply is None:
            if ga > 1:
                # grads live inside the (donated) state as accum_grads
                apply = jax.jit(
                    lambda s: self._apply_update(s, s.accum_grads),
                    donate_argnums=(0,))
            else:
                apply = jax.jit(self._apply_update, donate_argnums=(0,))
            self._compiled_apply = self.observability.wrap_jit(apply,
                                                               "apply")
        if ga > 1:
            if self.is_gradient_accumulation_boundary():
                with self.observability.span("step"):
                    self.state = self._compiled_apply(self.state)
                self._host_global_step += 1
                self._check_csr_overflow()
                self._report_progress()
                self._write_monitor(self._cached_loss)
        else:
            grads = getattr(self, "_pending_grads", None)
            assert grads is not None, "step() must follow backward()"
            self._pending_grads = None
            with self.observability.span("step"):
                self.state = self._compiled_apply(self.state, grads)
            self._host_global_step += 1
            self._check_csr_overflow()
            self._report_progress()
            self._write_monitor(self._cached_loss)
        self._host_micro_step += 1
        if self.wall_clock_breakdown_enabled:
            self.timers("step").stop()
            self.timers.log(["forward", "backward", "step"],
                            memory_breakdown=self._config.memory_breakdown)
        self._elastic_boundary()

    # ------------------------------------------------------------------ #
    # fused path
    # ------------------------------------------------------------------ #
    def train_batch(self, data_iter=None):
        """Process one *full* batch = grad_acc micro batches. On the
        scan-fused path (``async_pipeline.fused_accumulation``, the
        default for non-offload/1-bit/sparse configs) the whole window
        is ONE asynchronously-dispatched compiled program and the step
        returns without a device round-trip; otherwise the per-micro
        dispatch loop runs, one dispatch per micro batch. Mirrors
        PipelineEngine.train_batch (pipe/engine.py:229) semantics for
        the non-pipe engine.

        The returned loss is a device scalar (convert with ``float``,
        or read :meth:`last_loss` — both are explicit sync points)."""
        if data_iter is None:
            data_iter = self._ensure_train_iter()

        self._maybe_switch_onebit_phase()
        self._maybe_profile_step()
        # no-op unless a durability test armed it: deliver SIGTERM (or
        # the software preemption) here and the window below must still
        # run to completion before the boundary drain fires
        fault.fire("elastic.sigterm_mid_window", step=self._host_global_step)
        # health-plane liveness beat, then the armed-stall point: the
        # `stall` action wedges the loop HERE, past the beat, so the
        # watchdog observes a genuinely silent train_batch phase
        self.health.heartbeat("train_batch")
        fault.fire("health.stall", step=self._host_global_step)
        fused = self._batch_path()
        self.tput_timer.start()
        _t_step0 = time.perf_counter()
        if self._window_anchor is None:
            # telemetry window opens at the first dispatch after a
            # (re)anchor, so flush-time averages never include idle time
            self._window_anchor = _t_step0
        _t_dispatch = 0.0
        if fused:
            step_fn = self._get_compiled_batch_step()
            with self.observability.span("train_batch"):
                with self.observability.span("data"):
                    batch = self._next_stacked_batch(data_iter)
                _t0 = time.perf_counter()
                self.state, mean_loss = step_fn(self.state, batch)
                _t_dispatch = time.perf_counter() - _t0
        else:
            step_fn = self._get_compiled_micro_step()
            total = None
            offload_direct = (self.zero_cpu_offload and
                              self.gradient_accumulation_steps == 1)
            with self.observability.span("train_batch"):
                for _ in range(self.gradient_accumulation_steps):
                    with self.observability.span("data"):
                        batch = next(data_iter)
                    _t0 = time.perf_counter()
                    self.state, out = step_fn(self.state, batch)
                    _t_dispatch += time.perf_counter() - _t0
                    if offload_direct:
                        out, self._offload_grads_device = out
                    if self._sparse_grad_paths and not self._onebit_dist:
                        loss, self._csr_overflow = out
                    else:
                        loss = out
                    total = loss if total is None else total + loss
                if self.zero_cpu_offload:
                    if self._offload_overlap:
                        self._host_apply_update_overlapped()
                    else:
                        self._host_apply_update()
            mean_loss = total / self.gradient_accumulation_steps
        self.tput_timer.stop()
        self._last_step_time_ms = (time.perf_counter() - _t_step0) * 1e3
        # host time NOT spent inside a dispatch call: data wait + python
        # bookkeeping — the overhead the async pipeline exists to hide
        self._host_gap_ms = max(
            self._last_step_time_ms - _t_dispatch * 1e3, 0.0)
        self._host_micro_step += self.gradient_accumulation_steps
        self._host_global_step += 1
        # one-time FLOPs/MFU cost profile of the compiled step program —
        # OUTSIDE the timed window (it is an AOT re-compile); only the
        # last batch's shapes are read, never its (donated) buffers
        prog = "batch_step" if fused else "micro_step"
        if self.observability.wants_flops_profile(prog):
            self.observability.maybe_profile_flops(
                prog, step_fn, (self.state, batch),
                samples=self._host_global_step * self.train_batch_size())
        self._check_csr_overflow()
        self._report_progress()
        self._write_monitor(mean_loss)
        self._elastic_boundary()
        return mean_loss

    def last_loss(self):
        """Python float of the most recent ``train_batch`` mean loss —
        an explicit sync point that also flushes the deferred telemetry
        ring. ``None`` before the first step."""
        if self._last_loss_device is None:
            return None
        if self._monitor_ring:
            self._flush_monitor()
        else:
            self._host_sync_count += 1
        return float(self._last_loss_device)

    def eval_batch(self, batch):
        """Loss without grads/update. Accepts a single batch pytree OR
        an iterator of micro batches (the pipe engine's historical
        shape) — one eval API for both engines. An iterator is drained
        up to ``gradient_accumulation_steps`` micros (the engine's
        window, mirroring the pipe engine's ``micro_batches``) and the
        mean loss returned."""
        self._offload_drain()
        self._drain_saves()   # eval barrier: pending async saves land
        if self._monitor_ring:
            self._flush_monitor()   # eval is an explicit sync point
        it = normalize_eval_input(batch)
        micros = []
        for _ in range(self.gradient_accumulation_steps):
            try:
                micros.append(next(it))
            except StopIteration:
                break
        assert micros, "eval_batch: empty micro-batch iterator"
        if not hasattr(self, "_compiled_eval"):
            def ev(params, batch, rng):
                cp = self._cast_for_loss(params)
                out = (self._loss_fn(cp, batch, rng) if self._loss_takes_rng
                       else self._loss_fn(cp, batch))
                return out[0] if isinstance(out, tuple) else out
            self._compiled_eval = self.observability.wrap_jit(
                jax.jit(ev), "eval")
        total = None
        with self.observability.span("eval"):
            for m in micros:
                loss = self._compiled_eval(self.state.params, m,
                                           self.state.rng)
                total = loss if total is None else total + loss
        return total / len(micros)

    def _maybe_profile_step(self):
        """Start/stop a jax.profiler trace window around the configured
        steps. The captured trace (tensorboard-viewable) is the TPU
        analog of the reference's per-phase CUDA timers."""
        if not self._profiler_cfg["enabled"]:
            return
        step = self._host_global_step
        start = self._profiler_cfg["start_step"]
        stop = start + self._profiler_cfg["num_steps"]
        if not self._profiler_active and step == start:
            jax.profiler.start_trace(self._profiler_cfg["output_path"])
            self._profiler_active = True
            log_dist(f"profiler: trace started at step {step} -> "
                     f"{self._profiler_cfg['output_path']}", ranks=[0])
        elif self._profiler_active and step >= stop:
            jax.profiler.stop_trace()
            self._profiler_active = False
            log_dist(f"profiler: trace stopped at step {step}", ranks=[0])

    def _estimate_step_comm_bytes(self):
        """Host-side model of the per-rank DP gradient-exchange bytes per
        optimizer step (the wire SHAPE is pinned by the HLO audits in
        tests/unit/test_hlo_quantized_comm.py; this is the byte-level
        telemetry of the same model, written per step to the monitor).
        None at dp=1 (no exchange)."""
        from deepspeed_tpu.runtime.quantized_collectives import wire_bytes
        from deepspeed_tpu.utils.hlo_audit import dense_allreduce_ring_bytes
        W = self.dp_world_size
        if W <= 1:
            return None
        gas = self.gradient_accumulation_steps
        hier = None
        if self._dp_hierarchical:
            hier = (axis_size(self.mesh, "data_inter"),
                    axis_size(self.mesh, "data_intra"))
        total_q = total_d = 0
        for leaf in jax.tree_util.tree_leaves(self.state.params):
            if not (hasattr(leaf, "dtype")
                    and jnp.issubdtype(leaf.dtype, jnp.floating)):
                continue
            n = leaf.size
            dense = dense_allreduce_ring_bytes(n, W, dtype_bytes=4)  # fp32
            total_d += dense
            if self._quant_allreduce and n >= self._quant_block:
                qb, _ = wire_bytes(n, W, self._quant_block,
                                   algo=self._quant_algo,
                                   hierarchical=hier)
                total_q += qb
            else:
                total_q += dense
        active = total_q if self._quant_allreduce else total_d
        if self._quant_allreduce:
            mode = ("hierarchical-" + self._quant_algo if hier
                    else self._quant_algo)
        else:
            mode = "dense"
        return {"bytes_per_step": active * gas,
                "dense_bytes_per_step": total_d * gas,
                "compression_ratio": (total_d / active) if active else None,
                "mode": mode}

    # steady-state bound on the deferred-telemetry ring: past this many
    # unflushed steps the ring syncs regardless of steps_per_print (the
    # records are tiny, but unbounded deferral would hold a device
    # scalar per step for the run's lifetime)
    _MONITOR_RING_CAP = 512

    def _write_monitor(self, loss=None):
        """reference engine.py:780-790/:922-936 scalars, x-axis =
        cumulative samples — but sync-free in steady state: host-side
        scalars (step time, throughput, comm bytes, MFU, memory,
        dispatch counters) are written immediately, while device-valued
        ones (loss, lr, loss_scale) are queued in a small ring and
        materialized only at sync points — every ``steps_per_print``,
        on :meth:`last_loss`/:meth:`eval_batch`/:meth:`close`, or at
        the ring cap. ``async_pipeline.sync_loss_every_step=true``
        restores the old per-step ``float(loss)`` sync. Deferred lr
        records are computed from the host step mirror (identical to
        the device counter except under fp16 overflow skips within a
        flush window)."""
        if loss is not None:
            self._last_loss_device = loss
        if not (self.monitor.enabled or self.observability.enabled):
            return
        samples = self._host_global_step * self.train_batch_size()
        if self._comm_stats is not None:
            self.monitor.write_comm_metrics(
                bytes_per_step=self._comm_stats["bytes_per_step"],
                compression_ratio=self._comm_stats["compression_ratio"],
                samples=samples,
                mode=(self._comm_stats["mode"]
                      + ("+overlap" if self._use_overlap else "")))
        # dynamic fp16 scaling: snapshot the per-step scale (jnp.copy —
        # the state leaf itself is donated to the next dispatch) so the
        # flushed scale trajectory attributes backoffs to the right
        # step; static scalers are constant and read at flush time
        scale = (jnp.copy(self.state.loss_scale.scale)
                 if self._dynamic_scale_telemetry else None)
        self._monitor_ring.append(
            {"samples": samples, "host_step": self._host_global_step,
             "loss": loss, "scale": scale,
             "raw_step_ms": self._last_step_time_ms})
        if (self._sync_loss_every_step
                or self._host_global_step % self._config.steps_per_print
                == 0
                or len(self._monitor_ring) >= self._MONITOR_RING_CAP):
            self._flush_monitor(at_step_boundary=True)
        # recompile + dispatch counters / memory / trace refresh — all
        # host-side probes, no device round-trip (the sync counter
        # reflects any flush this step just performed). Step time, MFU
        # and throughput are emitted at flush barriers instead: once
        # the host runs ahead of an async device, per-dispatch wall
        # clock measures host time, not device time.
        self.observability.on_step(
            samples=samples, step_time_ms=None,
            host_gap_ms=self._host_gap_ms,
            host_syncs=self._host_sync_count)

    def _flush_monitor(self, at_step_boundary: bool = False):
        """Materialize the deferred loss/lr/scale records — the ONE
        periodic device round-trip of the async pipeline — and emit the
        window's honest step-time/throughput/MFU.

        The ``block_until_ready`` on the newest loss is the explicit
        periodic barrier: a flush at a step boundary reports
        barrier-to-barrier wall time divided by the window's step
        count, which IS the device step time regardless of how far the
        host's async dispatches ran ahead (per-dispatch wall clock
        would measure only host time). Out-of-band flushes (eval /
        save / last_loss — arbitrary idle time may have passed) write
        loss/lr/scale but NO step-time/throughput/MFU records: honest
        by omission beats an idle-inflated or host-only number."""
        ring, self._monitor_ring = self._monitor_ring, []
        if not ring:
            return
        self._host_sync_count += 1
        newest = next((r["loss"] for r in reversed(ring)
                       if r["loss"] is not None), None)
        if newest is not None:
            jax.block_until_ready(newest)
        avg_ms = None
        comp_by_step = {}
        if at_step_boundary:
            now = time.perf_counter()
            if self._window_anchor is not None:
                window_ms = (now - self._window_anchor) * 1e3
                # jit compiles block the dispatching step — attribute
                # their wall time to THAT step's record instead of
                # smearing it across the window (keeps compile spikes
                # in the p95 tail, as the per-step scheme did). Compile
                # events record the pre-increment host step, hence +1.
                tracker = self.observability.compile_tracker
                steps_in = {rec["host_step"] for rec in ring}
                if tracker is not None:
                    for ev in tracker.events:
                        # only the train-step programs compile inside
                        # the timed window; eval/grad/apply compiles
                        # happen between train dispatches and must not
                        # be deducted from it
                        if ev.fn_name not in ("batch_step",
                                              "micro_step"):
                            continue
                        s = ev.step + 1
                        if s in steps_in:
                            comp_by_step[s] = (comp_by_step.get(s, 0.0)
                                               + ev.wall_ms)
                elif 1 in steps_in and len(ring) > 1 and \
                        ring[0]["raw_step_ms"]:
                    # no tracker (observability off): at least keep the
                    # first compile pinned to step 1 via its raw time
                    comp_by_step[ring[0]["host_step"]] = \
                        ring[0]["raw_step_ms"]
                avg_ms = max(window_ms - sum(comp_by_step.values()),
                             0.0) / len(ring)
            self._window_anchor = now
        else:
            self._window_anchor = None   # re-anchor at the next step
        scale = self.loss_scale()
        # the host step mirror over-counts the device optimizer step by
        # the cumulative fp16 overflow skips; re-anchor on the (now
        # settled) device counter so logged lr indices drift at most
        # within one flush window, never for the rest of the run
        skip_offset = self._host_global_step - int(self.state.global_step)
        for rec in ring:
            lr_step = max(rec["host_step"] - skip_offset, 0)
            loss_val = (float(rec["loss"]) if rec["loss"] is not None
                        else None)
            # armed-fault poison (health.nan_loss): corrupt THIS record's
            # telemetry value to NaN — params and the returned device
            # loss are untouched; the detector below must catch it
            try:
                fault.fire("health.nan_loss", step=rec["host_step"])
            except fault.InjectedCrash:
                if loss_val is not None:
                    loss_val = float("nan")
            scale_val = (float(rec["scale"])
                         if rec.get("scale") is not None else scale)
            # numeric health detectors read the SAME host floats the
            # monitor writes — this flush barrier already materialized
            # them, so the feed adds no device sync
            self.health.observe_loss(loss_val, rec["host_step"])
            # a collapse needs a DYNAMIC scale: fp32 / static-scale
            # runs hold a constant (often 1.0) that must not alert
            if self.dynamic_loss_scale():
                self.health.observe_loss_scale(scale_val,
                                               rec["host_step"])
            self.monitor.write_train_metrics(
                loss=loss_val,
                lr=float(self._lr_at(lr_step)),
                loss_scale=scale_val,
                samples=rec["samples"], flush=False)
            # step time only from boundary flushes: an out-of-band
            # flush (eval/save/last_loss — arbitrary idle or mere host
            # time may have passed) writes no step time rather than a
            # misleading one
            if avg_ms is not None:
                step_ms = avg_ms + comp_by_step.get(rec["host_step"],
                                                    0.0)
                self.monitor.write_timer_values(
                    {"step_time_ms": step_ms}, rec["samples"])
                if step_ms > 0:
                    self.monitor.write_scalar(
                        "Train/Samples/samples_per_sec",
                        self.train_batch_size() / (step_ms / 1e3),
                        rec["samples"])
        tracker = self.observability.compile_tracker
        if tracker is not None:
            self.health.observe_recompiles(tracker.total_compiles,
                                           self._host_global_step)
        self.observability.write_mfu(
            avg_ms, ring[-1]["samples"],
            micro_steps_per_step=(1 if self._use_fused_batch
                                  else self.gradient_accumulation_steps),
            program=("batch_step" if self._use_fused_batch
                     else "micro_step"))
        self.monitor.flush()

    def _report_progress(self):
        # gate on the host mirror: no device sync unless actually printing
        step = self._host_global_step
        if step > 0 and step % self._config.steps_per_print == 0:
            log_dist(
                f"step={self.global_steps} lr={self.get_lr()[0]:.3e} "
                f"loss_scale={self.loss_scale():.0f} "
                f"skipped={self.skipped_steps}", ranks=[0])

    # ------------------------------------------------------------------ #
    # checkpointing (reference engine.py:1329/:1173)
    # ------------------------------------------------------------------ #
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        async_: Optional[bool] = None,
                        preempted: bool = False):
        """Atomic-commit save: shards land in ``<tag>.tmp/``, process 0
        seals a ``COMMITTED`` marker (process_count + per-file sizes and
        CRC32s) after a multihost barrier, renames the directory to its
        final tag, then repoints ``latest`` atomically. A crash at any
        point leaves either the previous checkpoint fully intact or the
        new one fully committed — never a half-save that resume trusts.

        ``async_`` (default: ``checkpoint.async_save``) turns the call
        into a snapshot-and-return: a donation-safe device->host copy of
        the train state is taken at this step boundary (O(local shard)),
        then the whole stage/commit protocol above runs on a single
        background writer thread while the step loop keeps dispatching —
        the loop stalls only for the snapshot. A save submitted while
        one is still writing JOINS it (same tag) or SUPERSEDES the
        still-waiting one (newer tag); two saves never interleave their
        staging I/O. ``close()``, ``eval_batch()`` and ``load_checkpoint``
        drain pending saves; a writer exception surfaces on the next
        ``save_checkpoint``/``close``. Multi-process runs fall back to
        blocking saves (the commit barriers must run on every process's
        main thread).

        ``preempted`` marks the checkpoint as committed by the graceful
        preemption drain (``meta.preempted``); such tags are reported
        distinctly by ``tools/verify_checkpoint.py`` and — when newer
        than ``latest`` — are never garbage-collected.
        """
        self._raise_async_save_error()
        self._offload_drain()
        if self._monitor_ring:
            self._flush_monitor()   # a save is a natural sync point
        # the retry policy is process-global; re-assert this engine's so
        # its own saves run under its own config even with several
        # engines alive in one process
        ckpt.set_retry_policy(self._ckpt_cfg["io_retries"],
                              self._ckpt_cfg["io_retry_backoff"])
        if async_ is None:
            async_ = bool(self._ckpt_cfg["async_save"])
        if async_ and jax.process_count() > 1:
            log_dist("async_save: multi-process run — the commit barriers "
                     "must run on every process's main thread; falling "
                     "back to a blocking save", ranks=[0])
            async_ = False
        t0 = time.time()
        snap_model, snap_optim, cpu_arrays, meta = \
            self._snapshot_train_state(client_state, preempted,
                                       copy=async_)
        if tag is None:
            tag = f"global_step{meta['global_step']}"
        snapshot_ms = (time.time() - t0) * 1000.0
        final_dir = os.path.join(save_dir, tag)
        samples = self._host_global_step * self.train_batch_size()
        self._last_ckpt_dir = save_dir
        job = partial(self._write_checkpoint_job, save_dir, tag,
                      snap_model, snap_optim, cpu_arrays, meta, samples)
        if async_:
            writer = self._ensure_ckpt_writer()
            verdict = writer.submit(tag, job)
            self.monitor.write_elastic_metrics(
                snapshot_ms=snapshot_ms,
                pending_saves=writer.pending_saves(), samples=samples)
            log_dist(f"async checkpoint {final_dir}: snapshot in "
                     f"{snapshot_ms:.0f}ms ({verdict}); commit continues "
                     "in background", ranks=[0])
            return final_dir
        # a blocking save must not run its commit inline while the async
        # writer is still staging an earlier one — same never-interleave
        # invariant the writer enforces for its own jobs
        self._drain_saves()
        self.monitor.write_elastic_metrics(
            snapshot_ms=snapshot_ms, pending_saves=0, samples=samples,
            flush=False)
        job()
        return final_dir

    def _snapshot_train_state(self, client_state=None, preempted=False,
                              copy=True):
        """The state a checkpoint carries, captured at the step boundary.

        ``copy=True`` (async saves): replica-0 shard copies of the
        model and optimizer state (donation-safe — the fused step
        donates these buffers on the very next dispatch) plus a COPY of
        the ZeRO-Offload host master state (the host optimizer mutates
        its buffers in place between snapshot and background write).
        Nothing the writer touches afterwards is ever written by the
        step loop again.

        ``copy=False`` (blocking saves): the live trees pass straight
        through — ``save_tree_sharded`` streams their shards
        tree-by-tree exactly as the pre-async protocol did, so a
        blocking save's peak host memory stays max(tree), not
        sum(trees). The ``ckpt.snapshot`` kill point fires identically
        on both paths."""
        if copy:
            snap_model = ckpt.snapshot_tree(self.state.params)
            snap_optim = ckpt.snapshot_tree(
                {"opt_state": self.state.opt_state,
                 "loss_scale": self.state.loss_scale})
        else:
            fault.fire("ckpt.snapshot")
            snap_model = self.state.params
            snap_optim = {"opt_state": self.state.opt_state,
                          "loss_scale": self.state.loss_scale}
        cpu_arrays = None
        if self.zero_cpu_offload and jax.process_index() == 0:
            # host-resident fp32 master + moments (reference saves the
            # fp32 partitions in zero_pp_rank files, engine.py:1409)
            sd = self.optimizer.state_dict()
            cp = (lambda a: np.array(a, copy=True)) if copy else \
                (lambda a: a)
            cpu_arrays = {"step": cp(sd["step"])}
            cpu_arrays.update({f"mp_{i}": cp(a)
                               for i, a in enumerate(sd["master_params"])})
            cpu_arrays.update({f"m_{i}": cp(a)
                               for i, a in enumerate(sd["exp_avg"])})
            cpu_arrays.update({f"v_{i}": cp(a)
                               for i, a in enumerate(sd["exp_avg_sq"])})
        meta = {
            "global_step": int(self.state.global_step),
            "micro_step": int(self.state.micro_step),
            "skipped_steps": int(self.state.skipped_steps),
            "rng": np.asarray(self.state.rng).tolist(),
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None and
                             hasattr(self.lr_scheduler, "state_dict")
                             else None),
            "dp_world_size": self.dp_world_size,
            "zero_stage": self.zero_stage,
            "client_state": client_state or {},
        }
        if preempted:
            meta["preempted"] = True
        return snap_model, snap_optim, cpu_arrays, meta

    def _write_checkpoint_job(self, save_dir, tag, snap_model, snap_optim,
                              cpu_arrays, meta, samples):
        """The stage/commit protocol, run off host snapshots — inline by
        a blocking save, on the writer thread by an async one. The fault
        points are identical on both paths, so the tier-1
        kill-at-every-stage contract covers async saves for free."""
        import shutil
        t0 = time.time()
        final_dir = os.path.join(save_dir, tag)
        tmp_dir = final_dir + ckpt.TMP_SUFFIX
        if jax.process_index() == 0:
            if os.path.isdir(tmp_dir):  # stale staging from a crashed save
                shutil.rmtree(tmp_dir)
            os.makedirs(tmp_dir, exist_ok=True)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_tmp_ready")
        # sharded format: every process writes only its local device shards
        # (reference per-dp-rank zero_pp_rank_* files, engine.py:1153-1164)
        # — no host-0 gather, flat host RAM regardless of model size
        ckpt.save_tree_sharded(tmp_dir, "model_states", snap_model)
        fault.fire("ckpt.after_shard", name="model_states", dir=tmp_dir)
        ckpt.save_tree_sharded(tmp_dir, "optim_states", snap_optim)
        fault.fire("ckpt.after_shard", name="optim_states", dir=tmp_dir)
        if jax.process_count() > 1:
            # every process's shard files must be durable before process 0
            # seals the marker — the marker asserts completeness
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_shards_written")
        if jax.process_index() == 0:
            if cpu_arrays is not None:
                ckpt._atomic_write_bytes(
                    os.path.join(tmp_dir, "cpu_optim_states.npz"),
                    ckpt._npz_bytes(cpu_arrays))
            self._save_checkpoint_extras(tmp_dir)
            ckpt.write_meta(tmp_dir, meta)
            fault.fire("ckpt.before_marker", dir=tmp_dir)
            ckpt.write_commit_marker(tmp_dir,
                                     process_count=jax.process_count())
            fault.fire("ckpt.before_rename", dir=tmp_dir)
            # re-saving an existing tag: rename the old committed copy
            # aside instead of deleting it — a crash between the two
            # renames leaves '<tag>.old', which list_tags still offers as
            # a fallback candidate, so no window ever has zero copies
            old_dir = final_dir + ckpt.OLD_SUFFIX
            if os.path.isdir(final_dir):
                if os.path.isdir(old_dir):
                    shutil.rmtree(old_dir)
                os.rename(final_dir, old_dir)
            os.replace(tmp_dir, final_dir)
            ckpt._fsync_dir(save_dir)
            if os.path.isdir(old_dir):
                shutil.rmtree(old_dir)
            ckpt.write_latest(save_dir, tag)
            keep_n = int(self._ckpt_cfg["keep_n"] or 0)
            if keep_n > 0:
                dropped = ckpt.gc_old_tags(save_dir, keep_n)
                if dropped:
                    log_dist(f"checkpoint retention (keep_n={keep_n}): "
                             f"removed {dropped}", ranks=[0])
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_committed")
        write_ms = (time.time() - t0) * 1000.0
        # liveness beat from the commit tail (thread-safe: the watchdog
        # timestamp is a plain assignment, and this runs on the async
        # writer thread for async saves) — a long blocking save must
        # not read as a stalled train loop
        self.health.heartbeat("checkpoint_commit")
        pending = (max(0, self._ckpt_writer.pending_saves() - 1)
                   if self._ckpt_writer is not None else 0)
        self.monitor.write_elastic_metrics(
            write_ms=write_ms, pending_saves=pending, samples=samples,
            flush=False)
        self.monitor.write_checkpoint_event(
            action="save", ok=True, duration_ms=write_ms, samples=samples)
        log_dist(f"saved checkpoint {final_dir} "
                 f"(committed in {write_ms:.0f}ms)", ranks=[0])
        return final_dir

    # ---------------------------------------------- async-save plumbing
    def _ensure_ckpt_writer(self):
        if self._ckpt_writer is None:
            self._ckpt_writer = ckpt.AsyncCheckpointWriter()
        return self._ckpt_writer

    def _drain_saves(self):
        """Barrier: block until every pending async save is durable
        (``close()`` / ``eval_batch`` / ``load_checkpoint`` call it).
        Writer errors are NOT raised here — they surface on the next
        ``save_checkpoint``/``close`` via _raise_async_save_error."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.drain()

    def _raise_async_save_error(self):
        if self._ckpt_writer is not None:
            self._ckpt_writer.raise_pending_error()

    def wait_pending_saves(self):
        """Public async-save barrier: block until every pending async
        checkpoint has committed, then surface any writer error. Call
        before handing a save_dir to another consumer (e.g.
        ``InferenceEngine.from_checkpoint``) mid-run; ``close()`` and
        ``eval_batch`` already drain implicitly."""
        self._drain_saves()
        self._raise_async_save_error()

    # ------------------------------------------------- preemption drain
    def _elastic_boundary(self):
        """Step-boundary preemption check — both engines call it at the
        end of ``train_batch`` (and the facade ``step()``), i.e. only
        once the in-flight accumulation window has fully dispatched, so
        'finish the window, then drain' holds by construction."""
        if self._elastic is None or not self._elastic.preempted:
            return
        if self.gradient_accumulation_steps > 1 and \
                self._host_micro_step % self.gradient_accumulation_steps:
            # facade forward/backward/step path, mid-window: accumulated
            # grads are not part of a checkpoint — wait for the boundary
            return
        self._handle_preemption()

    def _handle_preemption(self):
        """Graceful drain: pending async saves finish, a
        preemption-tagged checkpoint commits, a ``preemption`` event row
        lands, the engine closes, and :class:`elastic.Preempted`
        (``SystemExit`` with the resumable code) propagates so the
        supervisor relaunches us."""
        reason = self._elastic.reason or "signal"
        step = int(self.global_steps)   # boundary: device value is settled
        log_dist(f"preemption ({reason}): draining at step {step}",
                 ranks=[0])
        save_dir = self._ckpt_cfg["save_dir"] or self._last_ckpt_dir
        tag = None
        committed = False
        if save_dir:
            self._drain_saves()   # a new save never interleaves with one
            tag = f"preempt_step{step}"
            try:
                self.save_checkpoint(save_dir, tag=tag, async_=False,
                                     preempted=True)
                committed = True
            except fault.InjectedCrash:
                raise   # durability tests kill the drain's save too
            except Exception as e:
                logger.warning(
                    f"preemption drain: checkpoint failed ({e!r}); "
                    "exiting resumable anyway — resume falls back to the "
                    "newest committed tag")
        else:
            logger.warning(
                "preemption drain: no checkpoint.save_dir configured and "
                "no prior save/load dir — exiting without a preemption "
                "checkpoint")
        self.observability.event(
            "preemption", reason=reason, step=step, tag=tag,
            committed=committed, restarts=self._restart_count)
        # black-box dump before close tears the telemetry down: the
        # relaunched incarnation (or a human) reads flight.json to see
        # the final pre-drain ring
        self.health.dump("drain", reason=reason, step=step, tag=tag)
        try:
            self.close()
        except Exception as e:
            logger.warning(f"preemption drain: close() failed ({e!r})")
        raise elastic.Preempted(step=step, tag=tag, reason=reason)

    def _save_checkpoint_extras(self, ckpt_dir: str) -> None:
        """Subclass hook: extra files written here (process 0, staging
        dir) are sealed by the COMMITTED marker with the shards — they
        can never be missing from a visible checkpoint."""

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        verify_integrity: Optional[bool] = None):
        """Verified load with automatic fallback.

        With an explicit ``tag`` the checkpoint must verify (marker +
        sizes + CRC32 unless ``verify_integrity=False``) or this raises.
        With ``tag=None`` the directory is scanned newest-first and the
        newest *committed and verified* checkpoint is restored — a torn
        ``latest`` pointer or a corrupt newest tag costs at most one
        checkpoint of progress, never the run.
        """
        self._offload_drain()
        # loading while an async save of THIS dir is mid-commit would
        # race the newest-first scan; the drain also orders save->load
        self._drain_saves()
        ckpt.set_retry_policy(self._ckpt_cfg["io_retries"],
                              self._ckpt_cfg["io_retry_backoff"])
        self._last_ckpt_dir = load_dir
        t0 = time.time()
        if verify_integrity is None:
            verify_integrity = bool(self._ckpt_cfg["verify_checksums"])
        samples = self._host_global_step * self.train_batch_size()

        if tag is not None:
            ckpt_dir = os.path.join(load_dir, tag)
            ok, problems = ckpt.verify_checkpoint_dir(
                ckpt_dir, check_crc=verify_integrity)
            if not ok:
                raise RuntimeError(
                    f"checkpoint {ckpt_dir} failed integrity verification: "
                    f"{'; '.join(problems)}")
            result = self._load_checkpoint_dir(
                ckpt_dir, load_optimizer_states, load_lr_scheduler_states)
            self.monitor.write_checkpoint_event(
                action="load", ok=True,
                duration_ms=(time.time() - t0) * 1000.0, samples=samples)
            self._record_resume(ckpt_dir)
            return result

        latest = ckpt.read_latest(load_dir)
        candidates = ckpt.candidate_tags(load_dir)
        if not candidates:
            logger.warning(f"no loadable checkpoint tags in {load_dir}; "
                           "nothing loaded")
            return None, {}
        for cand in candidates:
            cand_dir = os.path.join(load_dir, cand)
            ok, problems = ckpt.verify_checkpoint_dir(
                cand_dir, check_crc=verify_integrity)
            if not ok:
                logger.warning(
                    f"skipping checkpoint {cand_dir}: "
                    f"{'; '.join(problems)} — falling back to an older tag")
                self.monitor.write_checkpoint_event(
                    action="fallback", ok=False, samples=samples)
                continue
            try:
                result = self._load_checkpoint_dir(
                    cand_dir, load_optimizer_states,
                    load_lr_scheduler_states)
            except fault.InjectedCrash:
                raise
            except Exception as e:
                logger.warning(
                    f"failed to load checkpoint {cand_dir} ({e!r}); "
                    "falling back to an older tag")
                self.monitor.write_checkpoint_event(
                    action="fallback", ok=False, samples=samples)
                continue
            if latest is not None and cand != latest:
                logger.warning(
                    f"'latest' pointer named {latest!r} but the newest "
                    f"committed+verified checkpoint is {cand!r}; resumed "
                    "from it (torn pointer or interrupted save)")
            self.monitor.write_checkpoint_event(
                action="load", ok=True,
                duration_ms=(time.time() - t0) * 1000.0, samples=samples)
            self._record_resume(cand_dir)
            return result
        logger.warning(f"no committed+verified checkpoint in {load_dir}; "
                       "nothing loaded")
        return None, {}

    def _record_resume(self, ckpt_dir: str) -> None:
        """One ``resume`` event row + the restart-count scalar after a
        successful restore — together with the save side's
        ``preemption`` row, obs_report can reconstruct the full
        preempt -> relaunch -> resume chain of a supervised run."""
        samples = self._host_global_step * self.train_batch_size()
        self.observability.event(
            "resume", step=self._host_global_step,
            tag=os.path.basename(ckpt_dir),
            restarts=self._restart_count,
            preempted=ckpt.is_preemption_tag(ckpt_dir))
        self.monitor.write_elastic_metrics(
            restarts=self._restart_count, samples=samples)

    def _load_checkpoint_dir(self, ckpt_dir: str,
                             load_optimizer_states: bool = True,
                             load_lr_scheduler_states: bool = True):
        """Restore engine state from one verified checkpoint directory."""
        # read + validate meta BEFORE any engine mutation: if it is
        # semantically incomplete, this raises while the engine is still
        # pristine and the fallback loop can cleanly try an older tag
        # (no half-loaded optimizer/lr state left behind)
        meta = ckpt.read_meta(ckpt_dir)
        missing = [k for k in ("global_step", "micro_step",
                               "skipped_steps", "rng") if k not in meta]
        if missing:
            raise KeyError(f"meta.json in {ckpt_dir} missing {missing}")
        meta_rng = np.asarray(meta["rng"], dtype=np.uint32)
        sharded = ckpt.sharded_exists(ckpt_dir, "model_states")
        if sharded:
            params = ckpt.load_tree_sharded(
                ckpt_dir, "model_states", self.state.params,
                shardings=self._state_shardings.params)
        else:  # legacy single-file format
            params = ckpt.load_tree(
                os.path.join(ckpt_dir, "model_states.npz"),
                self.state.params,
                shardings=self._state_shardings.params)
        new_state = self.state._replace(params=params)
        if load_optimizer_states:
            opt_tmpl = {"opt_state": self.state.opt_state,
                        "loss_scale": self.state.loss_scale}
            opt_shd = {"opt_state": self._state_shardings.opt_state,
                       "loss_scale": self._state_shardings.loss_scale}
            if sharded:
                opt = ckpt.load_tree_sharded(ckpt_dir, "optim_states",
                                             opt_tmpl, shardings=opt_shd)
            else:
                opt = ckpt.load_tree(
                    os.path.join(ckpt_dir, "optim_states.npz"),
                    opt_tmpl, shardings=opt_shd)
            new_state = new_state._replace(opt_state=opt["opt_state"],
                                           loss_scale=opt["loss_scale"])
            if self.zero_cpu_offload:
                cpu_path = os.path.join(ckpt_dir, "cpu_optim_states.npz")
                if not os.path.exists(cpu_path):
                    # without the host master state the first offload step
                    # would overwrite the loaded weights with init-time
                    # params — fail loudly instead
                    raise FileNotFoundError(
                        f"{cpu_path} missing: checkpoint was not saved by "
                        "a cpu_offload run. Re-save with offload enabled, "
                        "or pass load_optimizer_states=False and accept a "
                        "fresh optimizer (master params will be re-seeded "
                        "from the loaded model weights).")
                z = np.load(cpu_path)
                n = len(self.optimizer.master_params)
                self.optimizer.load_state_dict({
                    "step": int(z["step"]),
                    "master_params": [z[f"mp_{i}"] for i in range(n)],
                    "exp_avg": [z[f"m_{i}"] for i in range(n)],
                    "exp_avg_sq": [z[f"v_{i}"] for i in range(n)]})
        elif self.zero_cpu_offload:
            # fresh optimizer requested: re-seed the host master copy from
            # the loaded weights so the next step starts from them
            from deepspeed_tpu.runtime.checkpoint import _to_host_global
            for dst, src in zip(self.optimizer.master_params,
                                jax.tree_util.tree_leaves(params)):
                np.copyto(dst, np.asarray(_to_host_global(src),
                                          np.float32).ravel())
        # topology sanity (warn, don't crash: elastic resume across dp
        # worlds / ZeRO stages is the supported path — but the operator
        # should know it happened)
        saved_dp = meta.get("dp_world_size")
        if saved_dp is not None and saved_dp != self.dp_world_size:
            logger.warning(
                f"checkpoint {ckpt_dir} was saved at dp_world_size="
                f"{saved_dp}, resuming at {self.dp_world_size} "
                "(elastic repartition)")
        saved_stage = meta.get("zero_stage")
        if saved_stage is not None and saved_stage != self.zero_stage:
            logger.warning(
                f"checkpoint {ckpt_dir} was saved at zero_stage="
                f"{saved_stage}, resuming at {self.zero_stage}")
        repl = self._state_shardings.global_step
        new_state = new_state._replace(
            global_step=jax.device_put(
                jnp.asarray(meta["global_step"], jnp.int32), repl),
            micro_step=jax.device_put(
                jnp.asarray(meta["micro_step"], jnp.int32), repl),
            skipped_steps=jax.device_put(
                jnp.asarray(meta["skipped_steps"], jnp.int32), repl),
            rng=jax.device_put(
                jnp.asarray(meta_rng), repl),
        )
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                meta.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        self.state = new_state
        # host mirrors must track the restored device counters
        self._host_global_step = int(meta["global_step"])
        self._host_micro_step = (self._host_global_step *
                                 self.gradient_accumulation_steps +
                                 int(meta["micro_step"]))
        log_dist(f"loaded checkpoint {ckpt_dir} "
                 f"(step={int(meta['global_step'])} "
                 f"skipped_steps={int(meta['skipped_steps'])} "
                 f"loss_scale={self.loss_scale():.0f} "
                 f"saved at dp={meta.get('dp_world_size')}, now "
                 f"dp={self.dp_world_size})", ranks=[0])
        return ckpt_dir, meta.get("client_state", {})
