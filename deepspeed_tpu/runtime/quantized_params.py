"""int8-resident parameter storage — ZeRO++ qwZ blocks kept live.

``runtime/quantized_collectives.py`` established the wire format: int8
payload + per-block fp32 absmax scales (qwZ). Until PR 17 the serving
engine used it only as a *wire* format — ``qwz_distribute_params``
dequantized eagerly back to bf16 on the replica, so the resident HBM
footprint was the full bf16 tree and the only savings was replica
fan-out bytes. This module is the *resident* half: a registered pytree
leaf that keeps the int8 blocks + scales as the live param tree and
dequantizes per block at each matmul inside the compiled program
(EQuARX: quantize the bytes, not the math — the matmul itself runs in
the model dtype after an in-program dequant of the tile).

Layout: quantization is blockwise along the LAST axis, and ``q`` keeps
the ORIGINAL shape/rank of the weight (the last partial block is simply
narrower). Rank preservation is the point — the model families'
PartitionSpecs (``gpt2_param_specs`` / ``llama_param_specs``) apply to
``q`` unchanged, so int8-resident serving reuses the exact same
Megatron TP layout as bf16-resident serving. Scales have shape
``lead + (nb,)`` with ``nb = ceil(d / block)``.

HBM accounting: a (h, d) bf16 weight costs ``2*h*d`` bytes resident;
int8-resident costs ``h*d + 4*h*nb`` — ~0.51x at the default block of
256, i.e. the ~2x weight-HBM lever the bench row ``quant_serving_bytes``
pins.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedParam", "quantize_param", "dequantize_param",
           "quantize_param_tree", "dequantize_param_tree",
           "is_quantized_tree", "quantized_tree_bytes",
           "param_tree_bytes", "DEFAULT_WEIGHT_BLOCK"]

DEFAULT_WEIGHT_BLOCK = 256


@jax.tree_util.register_pytree_node_class
class QuantizedParam:
    """One int8-resident weight: ``q`` int8 (original shape), ``scale``
    fp32 ``lead + (nb,)``, plus the static original dtype it stands in
    for (what :func:`dequantize_param` casts back to when no dtype is
    given). Registered as a pytree node so quantized trees flow through
    ``jax.jit`` / ``device_put`` / ``tree_map`` unchanged — shardings
    trees mirror the same structure (a QuantizedParam whose children
    are NamedShardings)."""

    __slots__ = ("q", "scale", "orig_dtype", "block")

    def __init__(self, q, scale, orig_dtype, block: int):
        self.q = q
        self.scale = scale
        self.orig_dtype = jnp.dtype(orig_dtype)
        self.block = int(block)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        # the logical dtype callers see (what dequant produces); the
        # storage dtype is int8 + fp32 scales
        return self.orig_dtype

    @property
    def nbytes(self) -> int:
        return int(getattr(self.q, "nbytes", 0)) + \
            int(getattr(self.scale, "nbytes", 0))

    def tree_flatten(self):
        return (self.q, self.scale), (self.orig_dtype, self.block)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0], aux[1])

    def __repr__(self):
        return (f"QuantizedParam(shape={tuple(np.shape(self.q))}, "
                f"block={self.block}, orig_dtype={self.orig_dtype})")


def quantize_param(x, block: int = DEFAULT_WEIGHT_BLOCK) -> QuantizedParam:
    """Symmetric int8 absmax quantization per ``block`` values along the
    last axis. ``q`` keeps x's shape; ``scale`` is ``lead + (nb,)``."""
    x = jnp.asarray(x)
    d = x.shape[-1]
    nb = -(-d // block)
    pad = nb * block - d
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = xf.reshape(x.shape[:-1] + (nb, block))
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(blocks / scale[..., None]), -127, 127)
    q = q.reshape(x.shape[:-1] + (nb * block,))[..., :d].astype(jnp.int8)
    return QuantizedParam(q, scale, x.dtype, block)


def dequantize_param(p: QuantizedParam, dtype=None):
    """Per-block dequant back to ``dtype`` (default: the original dtype).
    Traceable — this is the in-program dequant the quantized matmul path
    calls right before each weight use."""
    d = p.q.shape[-1]
    block = p.block
    nb = p.scale.shape[-1]
    s = jnp.repeat(p.scale, block, axis=-1)
    if nb * block != d:
        s = s[..., :d]
    out = p.q.astype(jnp.float32) * s
    return out.astype(dtype if dtype is not None else p.orig_dtype)


def _is_qp(x) -> bool:
    return isinstance(x, QuantizedParam)


def quantize_param_tree(params, block: int = DEFAULT_WEIGHT_BLOCK):
    """Quantize every floating >=2-D leaf of ``params`` (matmul weights
    and embeddings); 1-D leaves (biases, layer norms) stay dense — their
    bytes are negligible and quantizing them buys nothing. Already-
    quantized leaves pass through unchanged, so re-quantizing a mixed or
    fully quantized tree is a no-op (the swap path relies on this)."""
    def one(x):
        if _is_qp(x):
            return x
        if getattr(x, "ndim", 0) >= 2 and \
                jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return quantize_param(x, block)
        return x
    return jax.tree_util.tree_map(one, params, is_leaf=_is_qp)


def dequantize_param_tree(params, dtype=None):
    """The fp oracle view of a (possibly) quantized tree."""
    return jax.tree_util.tree_map(
        lambda x: dequantize_param(x, dtype) if _is_qp(x) else x,
        params, is_leaf=_is_qp)


def is_quantized_tree(params) -> bool:
    return any(_is_qp(leaf) for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=_is_qp))


def _leaf_bytes(x) -> int:
    if _is_qp(x):
        return x.nbytes
    size = int(np.prod(np.shape(x))) if np.shape(x) else 1
    return size * jnp.dtype(getattr(x, "dtype", jnp.float32)).itemsize


def param_tree_bytes(params) -> int:
    """Resident HBM bytes of a param tree (quantized leaves count int8
    payload + fp32 scales). The bench cost model's weight-HBM lever."""
    return sum(_leaf_bytes(leaf) for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=_is_qp))


def quantized_tree_bytes(params) -> Tuple[int, int]:
    """(quantized_bytes, dense_bytes) of the SAME tree — dense counts
    every quantized leaf at its original dtype. The ratio is the
    ``quant_serving_bytes`` weight lever."""
    quant = param_tree_bytes(params)
    def dense_one(x):
        if _is_qp(x):
            size = int(np.prod(x.shape))
            return size * jnp.dtype(x.orig_dtype).itemsize
        return _leaf_bytes(x)
    dense = sum(dense_one(leaf) for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=_is_qp))
    return quant, dense


def map_quantized(params, fn, dense_fn=None):
    """tree_map with QuantizedParam as a leaf: ``fn`` on quantized
    leaves, ``dense_fn`` (default identity) elsewhere. The shardings
    builder uses this to mirror tree structure."""
    dense_fn = dense_fn or (lambda x: x)
    return jax.tree_util.tree_map(
        lambda x: fn(x) if _is_qp(x) else dense_fn(x),
        params, is_leaf=_is_qp)
