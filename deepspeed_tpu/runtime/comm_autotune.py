"""Topology-aware collective autotuner — algorithm selection from a
calibrated cost model.

PR 2 built four gradient/weight-exchange mechanisms (qgZ two-hop,
legacy allgather, hierarchical 2D, qwZ/hpZ) but selection was static
JSON config. This module picks the exchange ``(algo, block,
hierarchy split)`` per (mesh topology, message-size histogram) at
engine init, the EQuARX (arXiv:2506.17615) / "Big Send-off"
(arXiv:2504.18658) playbook: price every candidate with a per-hop
latency + bandwidth model over the existing ``wire_bytes`` /
``wire_hops`` byte accounting (runtime/quantized_collectives.py) and
take the argmin.

Time model, per tensor and per hop (``wire_hops`` gives the hop list)::

    t_hop = latency(axis) + send_bytes(hop) / bandwidth(axis)

with ``axis in {intra, inter}``: a flat collective on a topology whose
data axis spans a slow boundary (``topo_intra < world``) is priced at
the slow wire — its ring crosses the boundary and the slowest link
bottlenecks the whole hop — while the hierarchical 2D shape keeps its
bulk hops on the fast wire by construction. This reproduces the PR 2
pinned crossovers as *decisions*:

- dp=2: allgather and two-hop move the same bytes, two-hop pays one
  extra hop latency → **allgather** (its one-hop latency win).
- flat W>=4: allgather is O(W·n), two-hop O(n) → **twohop**.
- inter×intra topology: flat hops price at the slow wire, the 2D shape
  ships only the reduced 1/W_intra chunk across it → **hierarchical**.

Block size is tuned on the same model: padding (``pad_to_multiple(n,
W*block)``) dominates for small tensors (→ smaller block), fp32 scale
overhead (``4n/block``) for large ones (→ larger block).

Explicit ``quantized_comm`` keys act as overrides: a config that pins
``algo`` / ``block`` / ``hierarchical`` restricts the candidate set to
exactly that value (the pre-autotuner behavior, now opt-out).

``calibrate_wire_model`` closes the loop against measured programs: it
compiles the candidate exchange and compares the model's bytes with the
partitioned-HLO byte accounting (``utils/hlo_audit.send_bytes_of``) —
the tier-1 drift guard that keeps the autotuner's inputs honest, and an
opt-in init-time check (``comm_autotune.calibrate``) when a device (or
the virtual CPU mesh) is reachable.
"""

import json
import os
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

from deepspeed_tpu.runtime.quantized_collectives import (
    ALGO_ALLGATHER, ALGO_TWOHOP, DEFAULT_BLOCK, QUANTIZED_ALGOS, wire_hops)

__all__ = ["LinkModel", "CommPlan", "exchange_time_us", "plan_comm",
           "calibrate_wire_model", "candidate_label",
           "wire_calibration_path", "save_wire_calibration",
           "load_wire_calibration", "measure_link_constants"]

# nominal link defaults (per-direction): ICI-class fast wire vs
# DCN/inter-slice slow wire. Deliberately round numbers — the DECISIONS
# depend on byte/hop ratios, not absolute magnitudes; override via the
# comm_autotune config when the real fabric is known, or let a
# calibration artifact from a prior hardware run (see
# ``load_wire_calibration``) replace them wholesale.
DEFAULT_INTRA_GBPS = 75.0
DEFAULT_INTER_GBPS = 12.5
DEFAULT_INTRA_LATENCY_US = 1.0
DEFAULT_INTER_LATENCY_US = 10.0
DEFAULT_BLOCK_CANDIDATES = (64, 128, 256)

# measured-link-constants artifact (ROADMAP item 3 follow-on): a prior
# run that measured the fabric (``measure_link_constants`` /
# ``calibrate_wire_model`` on real hardware) persists its constants
# here; later runs pick them up as the LinkModel defaults. Precedence:
# explicit comm_autotune config keys > artifact > nominal constants.
WIRE_CALIBRATION_ENV = "DSTPU_WIRE_MODEL"
_LINK_KEYS = ("intra_gbps", "inter_gbps", "intra_latency_us",
              "inter_latency_us")


def wire_calibration_path(path: Optional[str] = None) -> str:
    """Resolve the artifact path: explicit arg > $DSTPU_WIRE_MODEL >
    the per-user cache default."""
    return path or os.environ.get(WIRE_CALIBRATION_ENV) or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu",
        "wire_model.json")


def save_wire_calibration(cal: Dict, path: Optional[str] = None) -> str:
    """Persist measured link constants (any subset of ``intra_gbps``,
    ``inter_gbps``, ``intra_latency_us``, ``inter_latency_us``, plus
    free-form provenance fields) for later runs to load as LinkModel
    defaults. Returns the path written."""
    p = wire_calibration_path(path)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cal, f, indent=1, sort_keys=True, default=str)
    os.replace(tmp, p)
    return p


def load_wire_calibration(path: Optional[str] = None) -> Optional[Dict]:
    """Load the measured-constants artifact; None when absent or
    malformed (a stale/corrupt artifact must never fail planning —
    the nominal constants are always a working fallback)."""
    p = wire_calibration_path(path)
    try:
        with open(p) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    out = {}
    for k in _LINK_KEYS:
        if k in raw:
            try:
                v = float(raw[k])
            except (TypeError, ValueError):
                continue
            if v > 0:
                out[k] = v
    return out or None


class LinkModel(NamedTuple):
    """Per-axis latency/bandwidth terms of the exchange time model."""
    intra_gbps: float = DEFAULT_INTRA_GBPS
    inter_gbps: float = DEFAULT_INTER_GBPS
    intra_latency_us: float = DEFAULT_INTRA_LATENCY_US
    inter_latency_us: float = DEFAULT_INTER_LATENCY_US

    def bytes_per_us(self, axis: str) -> float:
        gbps = self.intra_gbps if axis == "intra" else self.inter_gbps
        return gbps * 1e9 / 8 / 1e6       # GBit/s -> bytes/us

    def latency_us(self, axis: str) -> float:
        return (self.intra_latency_us if axis == "intra"
                else self.inter_latency_us)

    @classmethod
    def from_config(cls, ca: Dict,
                    calibration: Optional[Dict] = None) -> "LinkModel":
        """Per-key precedence: an EXPLICITLY configured value wins;
        otherwise a measured calibration artifact (``calibration``, or
        the on-disk one when None); otherwise the nominal defaults.
        ``ca["explicit"]`` (config layer) records which keys the user
        set; a hand-built dict without it treats key presence as
        explicit — the pre-artifact behavior."""
        explicit = ca.get("explicit")
        if explicit is None:
            explicit = {k: k in ca for k in _LINK_KEYS}
        if calibration is None:
            calibration = load_wire_calibration() or {}
        defaults = {"intra_gbps": DEFAULT_INTRA_GBPS,
                    "inter_gbps": DEFAULT_INTER_GBPS,
                    "intra_latency_us": DEFAULT_INTRA_LATENCY_US,
                    "inter_latency_us": DEFAULT_INTER_LATENCY_US}

        def pick(key):
            if explicit.get(key):
                return float(ca[key])
            if key in calibration:
                return float(calibration[key])
            return float(ca.get(key, defaults[key]))
        return cls(*(pick(k) for k in _LINK_KEYS))


class CommPlan(NamedTuple):
    """The autotuner's decision + its evidence (logged, written to the
    events log as a ``comm_plan`` row, and shown by obs_report)."""
    algo: str                 # 'twohop' | 'allgather'
    block: int
    hierarchical: int         # intra-slice size; 0 = flat exchange
    world: int                # data-parallel degree planned against
    topo_intra: int           # topology boundary used for pricing (0 = flat)
    reason: str               # one-line human 'why'
    modeled_us: Dict[str, float]   # candidate label -> per-step microseconds
    overridden: bool          # True when explicit config pinned the choice
    calibration: Optional[Dict] = None   # wire-model drift check result


def candidate_label(algo: str, block: int, hierarchical: int) -> str:
    hier = f"hier{hierarchical}-" if hierarchical else ""
    return f"{hier}{algo}/b{block}"


def _dense_ring_time_us(n: int, world: int, link: LinkModel,
                        axis: str, dtype_bytes: int = 4) -> float:
    """Sub-block tensors ship dense (pmean): reduce-scatter + all-gather
    legs on the pricing axis."""
    from deepspeed_tpu.utils.hlo_audit import dense_allreduce_ring_bytes
    b = dense_allreduce_ring_bytes(n, world, dtype_bytes)
    return 2 * link.latency_us(axis) + b / link.bytes_per_us(axis)


def exchange_time_us(sizes: Iterable[int], world: int, *,
                     algo: str = ALGO_TWOHOP, block: int = DEFAULT_BLOCK,
                     hierarchical: int = 0, topo_intra: int = 0,
                     link: Optional[LinkModel] = None) -> float:
    """Modeled per-step exchange time (microseconds) of one mean-
    allreduce over every tensor in ``sizes`` (element counts — the
    gradient leaf histogram; each leaf is its own collective, so each
    pays per-hop latency).

    ``topo_intra`` is the PHYSICAL fast-wire extent of the data axis
    (0 or >= world = uniform fabric). Flat algorithms on a split fabric
    are priced at the slow wire end-to-end; ``hierarchical=W_intra``
    prices intra hops on the fast wire and inter hops on the slow one
    (per ``wire_hops``' attribution).
    """
    link = link or LinkModel()
    split = bool(topo_intra) and topo_intra < world
    flat_axis = "inter" if split else "intra"
    hier = None
    if hierarchical:
        # hierarchical == world is the legal degenerate split (inter=1,
        # every collective intra) — split_data_axis and the exchange
        # both accept it, so the model must price it too
        if hierarchical > world or world % hierarchical:
            raise ValueError(
                f"hierarchical intra size {hierarchical} does not split "
                f"world {world}")
        hier = (world // hierarchical, hierarchical)
    total = 0.0
    for n in sizes:
        if world <= 1:
            continue
        if n < block:
            total += _dense_ring_time_us(n, world, link, flat_axis)
            continue
        hops = wire_hops(n, world, block, algo=algo, hierarchical=hier)
        for axis, b in hops:
            eff = axis if hier else flat_axis
            total += link.latency_us(eff) + b / link.bytes_per_us(eff)
    return total


def _hier_candidates(world: int, topo_intra: int) -> List[int]:
    """Hierarchy splits worth pricing: the physical boundary (and flat).
    Splits that don't divide the world — or degenerate ones — are not
    buildable meshes."""
    out = [0]
    if (topo_intra >= 2 and topo_intra < world
            and world % topo_intra == 0):
        out.append(topo_intra)
    return out


def plan_comm(sizes: Sequence[int], world: int, qc: Dict,
              ca: Dict, intra_hint: int = 0) -> CommPlan:
    """Pick the gradient-exchange configuration for this topology and
    message-size histogram.

    ``sizes``: float-leaf element counts of the gradient pytree.
    ``world``: planned data-parallel degree.
    ``qc``: the parsed ``quantized_comm`` config (its ``explicit`` map
    pins any key the user set — static config acts as an override).
    ``ca``: the parsed ``comm_autotune`` config (link model + topology
    hint). ``intra_hint``: physical fallback hint (devices per process)
    used when the config gives none.
    """
    cal = load_wire_calibration()
    link = LinkModel.from_config(ca, calibration=cal)
    # "measured" iff some artifact key actually WON in from_config —
    # mirror its explicitness rule (hand-built dicts without an
    # "explicit" map treat key presence as explicit)
    explicit_links = ca.get("explicit")
    if explicit_links is None:
        explicit_links = {k: k in ca for k in _LINK_KEYS}
    measured = bool(cal) and any(
        not explicit_links.get(k) for k in cal)
    topo_intra = int(ca.get("intra_size") or 0) or int(intra_hint or 0)
    explicit = qc.get("explicit", {})

    if explicit.get("hierarchical"):
        hier_opts = [int(qc["hierarchical"] or 0)]
        if hier_opts[0]:
            # a pinned split IS the topology statement
            topo_intra = topo_intra or hier_opts[0]
    else:
        hier_opts = _hier_candidates(world, topo_intra)
    algo_opts = ([qc["algo"]] if explicit.get("algo")
                 else list(QUANTIZED_ALGOS))
    block_opts = ([int(qc["block"])] if explicit.get("block")
                  else sorted({int(b) for b in ca.get(
                      "block_candidates", DEFAULT_BLOCK_CANDIDATES)}))

    sizes = [int(n) for n in sizes]
    table: Dict[str, float] = {}
    candidates: List[Tuple[float, int, int, int, str, int]] = []
    for hier in hier_opts:
        for algo in algo_opts:
            if hier and algo != ALGO_TWOHOP:
                continue          # the legacy exchange has no 2D form
            for blk in block_opts:
                t = exchange_time_us(sizes, world, algo=algo, block=blk,
                                     hierarchical=hier,
                                     topo_intra=topo_intra, link=link)
                table[candidate_label(algo, blk, hier)] = round(t, 3)
                # tie-breaks (stable, documented): faster first, then
                # flat before hierarchical (simpler program), larger
                # block (fewer scales), twohop before allgather
                candidates.append((round(t, 3), 0 if hier == 0 else 1,
                                   -blk, 0 if algo == ALGO_TWOHOP else 1,
                                   algo, hier, blk))
    if not candidates:
        # e.g. a pinned hierarchy with a pinned non-twohop algo; the
        # config layer owns the curated error message for these combos
        raise ValueError(
            "no exchange candidate survives the pinned quantized_comm "
            f"keys (algos {algo_opts}, hierarchy {hier_opts})")
    _t, _h, _b, _a, algo, hier, blk = min(candidates)[:7]

    overridden = bool(explicit.get("algo") or explicit.get("block")
                      or explicit.get("hierarchical"))
    label = candidate_label(algo, blk, hier)
    others = sorted((t, c) for c, t in table.items() if c != label)
    why = [f"dp={world}"]
    if topo_intra and topo_intra < world:
        why.append(f"topology {world // topo_intra}x{topo_intra} "
                   "(inter x intra)")
    else:
        why.append("uniform fabric")
    why.append(f"modeled {table[label]:.1f}us/step")
    if measured:
        why.append("measured link constants (wire_model artifact)")
    if others:
        why.append(f"next best {others[0][1]} {others[0][0]:.1f}us")
    if overridden:
        pins = [k for k in ("algo", "block", "hierarchical")
                if explicit.get(k)]
        why.append(f"pinned by quantized_comm.{{{','.join(pins)}}}")
    return CommPlan(algo=algo, block=blk, hierarchical=hier, world=world,
                    topo_intra=topo_intra, reason="; ".join(why),
                    modeled_us=table, overridden=overridden)


def calibrate_wire_model(world: int = 8, algo: str = ALGO_TWOHOP,
                         block: int = DEFAULT_BLOCK,
                         hierarchical: int = 0,
                         n: int = 1 << 16) -> Dict:
    """Compile the candidate exchange on the available devices and
    compare the host wire model against partitioned-HLO byte accounting
    (``send_bytes_of`` — per-rank send volume, the model's own
    convention). Returns ``{model_bytes, hlo_bytes, drift}`` with
    ``drift = hlo/model - 1``; raises when the device count cannot host
    a ``world``-wide mesh.

    Serves two callers: the tier-1 cost-model drift guard (every
    algo×topology config), and ``comm_autotune.calibrate`` at engine
    init (best-effort — a dead device must never fail training)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.quantized_collectives import (
        hierarchical_quantized_allreduce_mean, quantized_allreduce_mean,
        wire_bytes, wire_bytes_by_axis)
    from deepspeed_tpu.utils.hlo_audit import (collect_collectives_full,
                                               send_bytes_of)

    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(
            f"calibration needs {world} devices, have {len(devices)}")
    if hierarchical:
        inter = world // hierarchical
        mesh = Mesh(np.asarray(devices[:world]).reshape(inter,
                                                        hierarchical),
                    axis_names=("data_inter", "data_intra"))

        def inner(x):
            return hierarchical_quantized_allreduce_mean(
                x[0], "data_intra", "data_inter", hierarchical, inter,
                block)
        spec = P(("data_inter", "data_intra"))
        per_axis = wire_bytes_by_axis(n, inter, hierarchical, block)
        model = per_axis["intra"] + per_axis["inter"]
    else:
        mesh = build_mesh({"data": world}, devices=devices[:world])

        def inner(x):
            return quantized_allreduce_mean(x[0], "data", block,
                                            algo=algo, world_size=world)
        spec = P("data")
        model, _dense = wire_bytes(n, world, block, algo=algo)
    g = jax.ShapeDtypeStruct((world, n), jnp.float32)
    txt = jax.jit(jax.shard_map(inner, mesh=mesh, in_specs=(spec,),
                                out_specs=P(), check_vma=False)
                  ).lower(g).compile().as_text()
    hlo = send_bytes_of(collect_collectives_full(txt),
                        default_group=world)
    return {"model_bytes": int(model), "hlo_bytes": int(hlo),
            "drift": (hlo / model - 1.0) if model else 0.0,
            "world": world, "algo": algo, "block": block,
            "hierarchical": hierarchical, "elements": n}


def uniform_fabric(topo_intra: int, world: int) -> bool:
    """True only when the fabric is KNOWN to be uniform (every rank on
    the fast wire: ``topo_intra >= world``). Unknown topology
    (``topo_intra == 0``) is NOT uniform: a flat probe whose slowest
    hop might be the DCN must never persist as the intra constants."""
    return int(topo_intra or 0) >= int(world)


def measure_link_constants(world: int = 8, algo: str = ALGO_TWOHOP,
                           block: int = DEFAULT_BLOCK,
                           sizes: Tuple[int, int] = (1 << 16, 1 << 20),
                           iters: int = 5) -> Dict:
    """Measure effective link constants by TIMING the compiled flat
    exchange at two message sizes and solving the two-term model
    ``t = latency + bytes / bandwidth`` (two sizes, two unknowns).

    Returns ``{"intra_gbps", "intra_latency_us", ...provenance}`` —
    on a uniform fabric everything is the intra wire; callers on a
    split fabric run it per axis. Only meaningful on real hardware (a
    CPU "mesh" measures dispatch overhead, not a wire): callers gate
    persistence (``save_wire_calibration``) on the backend. Best-of-N
    timing so a stray scheduling hiccup can't poison the artifact.
    """
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.quantized_collectives import (
        quantized_allreduce_mean, wire_bytes)

    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(
            f"link measurement needs {world} devices, have {len(devices)}")
    mesh = build_mesh({"data": world}, devices=devices[:world])
    points = []
    for n in sizes:
        fn = jax.jit(jax.shard_map(
            lambda x: quantized_allreduce_mean(
                x[0], "data", block, algo=algo, world_size=world),
            mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_vma=False))
        x = jnp.ones((world, n), jnp.float32)
        jax.block_until_ready(fn(x))           # compile outside timing
        best = min(
            _timed(time, fn, x) for _ in range(max(1, iters)))
        b, _dense = wire_bytes(n, world, block, algo=algo)
        points.append((float(b), best * 1e6))  # (bytes, microseconds)
    (b1, t1), (b2, t2) = points
    if b2 == b1 or t2 <= t1:
        # degenerate measurement: report pure-bandwidth estimate
        bw_bytes_per_us = b2 / max(t2, 1e-9)
        lat = 0.0
    else:
        bw_bytes_per_us = (b2 - b1) / (t2 - t1)
        lat = max(0.0, t1 - b1 / bw_bytes_per_us)
    return {"intra_gbps": bw_bytes_per_us * 1e6 * 8 / 1e9,
            "intra_latency_us": lat, "world": world, "algo": algo,
            "block": block, "sizes": list(sizes),
            "backend": jax.default_backend()}


def _timed(time_mod, fn, x) -> float:
    t0 = time_mod.perf_counter()
    import jax
    jax.block_until_ready(fn(x))
    return time_mod.perf_counter() - t0
