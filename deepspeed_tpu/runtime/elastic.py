"""Preemption-survival plumbing: signal capture, graceful-drain contract,
and the resumable exit code the launcher supervisor keys on.

Production TPU pods get preempted and resized (ROADMAP item 4; the
reference's answer is ZeRO's elastic merge-then-repartition
checkpointing, stage2.py:1713-1779). The checkpoint layer already
reshards onto any mesh on load; this module supplies the missing
*runtime* half of the story:

- :class:`PreemptionGuard` installs SIGTERM/SIGINT handlers that only
  *flag* the preemption — the in-flight accumulation window always
  finishes. The engine checks the flag at each ``train_batch`` boundary
  (``_elastic_boundary``) and, when set, drains pending async saves,
  commits a preemption-tagged checkpoint, emits a ``preemption`` event
  row, and raises :class:`Preempted`.
- :class:`Preempted` subclasses ``SystemExit`` carrying
  :data:`RESUMABLE_EXIT_CODE`, so an unhandled drain exits the process
  with the distinguished code the launcher supervisor restarts on —
  while tests (and defensive user code) can still catch it.
- :func:`request_preemption` is the software trigger: it flags every
  installed guard without a real signal, which is what makes the drain
  path testable in-process and drivable from ``fault.py``'s env-armed
  injections across a real process boundary.

Deliberately stdlib-only (no jax import): ``launcher/runner.py`` reads
:data:`RESUMABLE_EXIT_CODE` for its supervisor loop and must stay
light, and the module must be importable inside a signal handler
context without triggering backend initialization.
"""

import os
import signal
import threading
from typing import Optional, Tuple

__all__ = [
    "RESUMABLE_EXIT_CODE", "RESTART_COUNT_ENV", "Preempted",
    "PreemptionGuard", "request_preemption", "restart_count",
]

# Distinguished "preempted after a clean drain — relaunch me" exit code.
# Anything else nonzero is a genuine failure the supervisor gives up on.
# 85 ('U') collides with no shell/POSIX convention (1/2 generic, 126/127
# exec errors, 128+N killed-by-signal) — an *uncaught* SIGTERM exits
# 143, so the supervisor can tell a drained preemption from a kill that
# outran the drain.
RESUMABLE_EXIT_CODE = 85

# The supervisor exports the attempt number to the relaunched process;
# the engine reads it for `Checkpoint/restarts` telemetry and the
# `resume` event row.
RESTART_COUNT_ENV = "DSTPU_RESTART_COUNT"

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class Preempted(SystemExit):
    """Raised at the step boundary after a graceful preemption drain.

    Subclasses ``SystemExit`` with :data:`RESUMABLE_EXIT_CODE` so the
    default outcome of a drain is a process exit the supervisor
    recognizes as resumable; ``step``/``tag``/``reason`` let a catching
    caller (or a test) see what was committed before the exit.
    """

    def __init__(self, step: Optional[int] = None,
                 tag: Optional[str] = None, reason: str = "signal"):
        super().__init__(RESUMABLE_EXIT_CODE)
        self.step = step
        self.tag = tag
        self.reason = reason

    def __str__(self):
        return (f"preempted ({self.reason}) at step {self.step}; "
                f"checkpoint tag={self.tag!r}; exit "
                f"{RESUMABLE_EXIT_CODE}")


# guards that should see a software-triggered preemption
# (request_preemption / fault.py's "preempt" env action)
_GUARDS_LOCK = threading.Lock()
_INSTALLED_GUARDS = []


class PreemptionGuard:
    """Latches a preemption request (signal or software) for the engine
    to act on at the next step boundary.

    The handler itself does nothing but set a flag: finishing the
    in-flight accumulation window, draining async saves, and committing
    the preemption checkpoint all happen in ordinary engine code where
    it is safe — never inside the handler. ``install()`` replaces the
    previous handlers and remembers them; ``uninstall()`` restores them
    (``engine.close()`` calls it), so a guard never outlives its engine.
    """

    def __init__(self, signals: Tuple = DEFAULT_SIGNALS):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._prev = {}
        self.installed = False

    # ------------------------------------------------------------ state
    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    def trigger(self, reason: str = "software") -> None:
        """Flag a preemption without a real signal (the testable path)."""
        if self._reason is None:
            self._reason = reason
        self._event.set()

    def clear(self) -> None:
        self._event.clear()
        self._reason = None

    # ---------------------------------------------------- signal wiring
    def _handler(self, signum, frame):
        del frame
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = f"signal {signum}"
        self.trigger(name)

    def install(self) -> bool:
        """Install the signal handlers; returns False (guard still
        usable via :meth:`trigger`) when not on the main thread — CPython
        only allows signal.signal there."""
        if self.installed:
            return True
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
        except ValueError:
            # not the main thread: signal capture unavailable, software
            # trigger still works; roll back any handlers already set
            for s, prev in self._prev.items():
                try:
                    signal.signal(s, prev)
                except ValueError:
                    pass
            self._prev.clear()
            with _GUARDS_LOCK:
                if self not in _INSTALLED_GUARDS:
                    _INSTALLED_GUARDS.append(self)
            return False
        self.installed = True
        with _GUARDS_LOCK:
            if self not in _INSTALLED_GUARDS:
                _INSTALLED_GUARDS.append(self)
        return True

    def uninstall(self) -> None:
        if self.installed:
            for s, prev in self._prev.items():
                try:
                    signal.signal(s, prev)
                except (ValueError, OSError):
                    pass
            self._prev.clear()
            self.installed = False
        with _GUARDS_LOCK:
            if self in _INSTALLED_GUARDS:
                _INSTALLED_GUARDS.remove(self)

    def __enter__(self):
        self.install()
        return self

    def __exit__(self, *exc):
        self.uninstall()
        return False


def request_preemption(reason: str = "software") -> int:
    """Software preemption trigger: flag every installed guard (no real
    signal involved). Returns how many guards were flagged. This is the
    hook ``fault.py``'s ``preempt`` env-armed action calls, so a
    *relaunched* subprocess can be preempted deterministically."""
    with _GUARDS_LOCK:
        guards = list(_INSTALLED_GUARDS)
    for g in guards:
        g.trigger(reason)
    return len(guards)


def restart_count(env=None) -> int:
    """The supervisor-exported restart attempt number (0 on a first
    launch or outside a supervisor)."""
    env = os.environ if env is None else env
    try:
        return max(0, int(env.get(RESTART_COUNT_ENV, "0")))
    except (TypeError, ValueError):
        return 0
