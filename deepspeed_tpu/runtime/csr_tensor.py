"""Compressed sparse row (CSR) tensor for bandwidth-saving embedding-grad
exchange (reference ``deepspeed/runtime/csr_tensor.py:11`` ``CSRTensor``;
allreduce path ``engine.py:1088-1139`` csr_allreduce_no_retain /
variable-length allgather with padding).

TPU re-design. The reference scans the dense grad for nonzero rows after
backward (eager torch, dynamic shapes). XLA needs static shapes, so the
in-jit path uses a **fixed row capacity**: an embedding grad produced by a
batch touches at most ``batch × seq`` rows, a static bound known at trace
time. The exchange is then

    all_gather(indices (cap,)) + all_gather(values (cap, dim))
    → densify via scatter-add (one XLA scatter, runs on device)

which ships ``world × cap × (dim + 1)`` elements instead of
``world × vocab × dim`` — the same bandwidth win as the reference's
variable-length gather, with XLA-friendly shapes. Padding slots carry
``index == rows`` (one past the end) and are dropped by the scatter.

The eager :class:`CSRTensor` keeps the reference's exact API
(``indices/values/to_dense/add/sparse_size``) for host-side use and tests.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["CSRTensor", "dense_to_csr", "csr_to_dense", "csr_allreduce"]


class CSRTensor:
    """Row-sparse tensor, eager mode (reference ``csr_tensor.py:11``).
    A row is kept iff its sum is nonzero (reference ``:16-18`` semantics)."""

    def __init__(self, dense_tensor=None):
        self.orig_dense_tensor = dense_tensor
        if dense_tensor is not None:
            row_sum = jnp.sum(dense_tensor, axis=1)
            self.indices = jnp.nonzero(row_sum)[0]
            self.values = dense_tensor[self.indices]
            self.dense_size = list(dense_tensor.shape)
        else:
            self.indices = None
            self.values = None
            self.dense_size = None

    @staticmethod
    def type():
        return "deepspeed.CSRTensor"

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self) -> Tuple[int, int]:
        index_size = int(self.indices.shape[0])
        value_size = int(np.prod(self.values.shape))
        dense_size = int(np.prod(self.dense_size))
        return index_size + value_size, dense_size

    def add(self, b: "CSRTensor"):
        """Concatenate entries (duplicates resolved by to_dense's
        scatter-add), reference ``:46-49``."""
        assert self.dense_size == b.dense_size
        self.indices = jnp.concatenate([self.indices, b.indices])
        self.values = jnp.concatenate([self.values, b.values])

    def __str__(self):
        sparse_size, dense_size = self.sparse_size()
        return (f"DeepSpeedTPU.CSRTensor(indices_size={self.indices.shape}, "
                f"values_size={self.values.shape}, "
                f"dense_size={self.dense_size}, "
                f"reduction_factor={dense_size / sparse_size:.1f})")

    __repr__ = __str__


# ---------------------------------------------------------------------------
# in-jit fixed-capacity path
# ---------------------------------------------------------------------------

def dense_to_csr(dense: jax.Array, capacity: int, with_overflow: bool = False):
    """Extract up to ``capacity`` nonzero rows, jit-friendly (static
    shapes). Returns ``(indices (capacity,), values (capacity, dim))``;
    unused slots have ``index == rows`` (dropped on densify).

    Capacity bound for an embedding grad: number of tokens in the batch.
    That bound holds for pure lookup (gather) embeddings; it does NOT hold
    for tied embeddings that also receive dense head gradients. With
    ``with_overflow=True`` a third return value flags ``nonzero rows >
    capacity`` — rows beyond capacity are silently dropped, so callers
    must surface this (the engine checks it at the boundary).
    """
    rows = dense.shape[0]
    nonzero = jnp.any(dense != 0, axis=1)
    # stable ordering of nonzero row ids, padded with `rows`
    order = jnp.argsort(~nonzero, stable=True)  # nonzero rows first
    idx = jnp.where(nonzero[order], order, rows)[:capacity]
    safe = jnp.minimum(idx, rows - 1)
    vals = jnp.where((idx < rows)[:, None], dense[safe], 0.0)
    if with_overflow:
        overflow = jnp.sum(nonzero) > capacity
        return idx.astype(jnp.int32), vals, overflow
    return idx.astype(jnp.int32), vals


def csr_to_dense(indices: jax.Array, values: jax.Array,
                 rows: int) -> jax.Array:
    """Scatter-add entries into a dense (rows, dim) tensor; ``index ==
    rows`` slots are dropped (XLA scatter drops out-of-bounds when we pad
    one extra row and trim)."""
    dim = values.shape[-1]
    out = jnp.zeros((rows + 1, dim), values.dtype)
    out = out.at[indices.reshape(-1)].add(values.reshape(-1, dim))
    return out[:rows]


def csr_allreduce(indices: jax.Array, values: jax.Array, rows: int,
                  axis_name: Optional[str] = None) -> jax.Array:
    """SUM-allreduce a row-sparse gradient across ``axis_name``
    (reference ``csr_allreduce_bucket engine.py:1095``: allgather indices +
    values, concatenate, densify). Inside ``shard_map``: two all_gathers of
    the compact representation; the densify scatter runs locally on every
    rank. Without an axis: just densify."""
    if axis_name is not None:
        indices = jax.lax.all_gather(indices, axis_name, tiled=True)
        values = jax.lax.all_gather(values, axis_name, tiled=True)
    return csr_to_dense(indices, values, rows)
