"""fp16 wrapper, unfused variant (reference
``deepspeed/runtime/fp16/unfused_optimizer.py:17`` ``FP16_UnfusedOptimizer``:
per-tensor fp32 masters instead of flat groups, ``step_fused_lamb:118``).

On TPU the fused/unfused distinction is moot — parameters are a pytree
either way and XLA fuses the update chain — so this subclass exists for API
parity and for LAMB-style wrapped optimizers (the reference routes LAMB
through the unfused path). Numerics are identical to ``FP16_Optimizer``.
"""

from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer

__all__ = ["FP16_UnfusedOptimizer"]


class FP16_UnfusedOptimizer(FP16_Optimizer):

    def step_fused_lamb(self, closure=None):
        """(reference ``step_fused_lamb:118``) — same pure update; the
        wrapped optimizer is expected to be Lamb."""
        return self.step(closure)
