"""1-bit Adam: communication-compressed Adam with error feedback.

Reference: ``deepspeed/runtime/fp16/onebit_adam.py:18`` (``OnebitAdam``):
- warmup phase (``step < freeze_step``): exact Adam, dense grad allreduce,
  variance ``exp_avg_sq`` still adapting (ref ``:319-324``);
- compression phase (``step >= freeze_step``): variance is FROZEN; the
  momentum ``exp_avg`` is updated with the *local* gradient and then
  exchanged via the error-compensated 1-bit compressed allreduce
  (ref ``:335-346``); the engine's normal dense grad allreduce is disabled
  (ref ``:369-372`` sets ``deepspeed.enable_backward_allreduce = False``,
  consumed at ``engine.py:828``).

TPU re-design: both phases are jit-traceable updates. The phase is a
*static* argument (``compression=bool``) selected by the caller per step —
mirroring the reference's Python-side ``adam_freeze_key`` flag — so XLA
compiles two clean programs instead of a ``cond`` over collectives. Error
feedback state (worker/server) lives in the optimizer state pytree and
shards over the data axis like the rest of ZeRO state.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.optimizers import Optimizer, _tree_zeros_like
from deepspeed_tpu.runtime.custom_collectives import (
    compressed_allreduce, padded_numel, server_chunk_size)

__all__ = ["OnebitAdam", "OnebitAdamState"]


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any   # per-leaf flat padded error feedback
    server_error: Any   # per-leaf flat chunk error feedback


class OnebitAdam(Optimizer):
    """1-bit Adam (ref ``onebit_adam.py:18``).

    ``axis_name``/``world_size``: the data-parallel mesh axis the compressed
    allreduce runs over when the update is traced inside ``shard_map``. With
    the default (no axis) the compression math (incl. error feedback) still
    runs — useful single-chip and in tests.
    """

    def __init__(self, lr: float = 1e-3, freeze_step: int = 100000,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 axis_name: Optional[str] = None, world_size: int = 1,
                 cuda_aware: bool = False):  # accepted for API parity
        self.lr = lr
        self.freeze_step = freeze_step
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.world_size = world_size

    def init(self, params):
        def werr(p):
            return jnp.zeros((padded_numel(int(np.prod(p.shape)),
                                           self.world_size),), jnp.float32)

        def serr(p):
            return jnp.zeros((server_chunk_size(int(np.prod(p.shape)),
                                                self.world_size),),
                             jnp.float32)

        return OnebitAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=_tree_zeros_like(params, jnp.float32),
            exp_avg_sq=_tree_zeros_like(params, jnp.float32),
            worker_error=jax.tree_util.tree_map(werr, params),
            server_error=jax.tree_util.tree_map(serr, params),
        )

    # NB: ``compression`` is static (two compiled programs), mirroring the
    # reference's python-side adam_freeze_key phase flag.
    def update(self, grads, state, params, lr=None, compression: bool = False):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        flat_we = treedef.flatten_up_to(state.worker_error)
        flat_se = treedef.flatten_up_to(state.server_error)

        out_p, out_m, out_v, out_we, out_se = [], [], [], [], []
        for p, g, m, v, we, se in zip(flat_p, flat_g, flat_m, flat_v,
                                      flat_we, flat_se):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not compression:
                # warmup: dense averaged grads (psum if axis bound), exact
                # Adam with adapting variance (ref :319-324)
                if self.axis_name is not None:
                    try:
                        g = jax.lax.pmean(g, self.axis_name)
                    except NameError:  # plain jit on global arrays
                        pass
                m = b1 * m + (1.0 - b1) * g
                v = b2 * v + (1.0 - b2) * (g * g)
            else:
                # compression: local momentum update, frozen variance,
                # compressed allreduce of the momentum (ref :335-346)
                m_local = b1 * m + (1.0 - b1) * g
                res = compressed_allreduce(
                    m_local, we, se, axis_name=self.axis_name,
                    world_size=self.world_size)
                m, we, se = res.tensor, res.worker_error, res.server_error
            update = m / (jnp.sqrt(v) + eps)  # no bias correction (ref :324)
            if wd > 0.0:
                update = update + wd * p32  # ref :352-353
            new_p = p32 - lr * update
            out_p.append(new_p.astype(p.dtype))
            out_m.append(m)
            out_v.append(v)
            out_we.append(we)
            out_se.append(se)

        return treedef.unflatten(out_p), OnebitAdamState(
            step=step,
            exp_avg=treedef.unflatten(out_m),
            exp_avg_sq=treedef.unflatten(out_v),
            worker_error=treedef.unflatten(out_we),
            server_error=treedef.unflatten(out_se))
