from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, LossScaleState, StaticLossScaler, has_overflow)
from deepspeed_tpu.runtime.fp16.fused_optimizer import (
    FP16_Optimizer, FP16OptimizerState)
from deepspeed_tpu.runtime.fp16.unfused_optimizer import FP16_UnfusedOptimizer
from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
