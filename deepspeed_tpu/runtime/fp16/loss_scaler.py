"""Loss scaling for fp16 training.

TPU-native analog of the reference's ``deepspeed/runtime/fp16/loss_scaler.py``
(LossScalerBase :34, static LossScaler :56, DynamicLossScaler :79 — init
2^32, x2 growth every ``scale_window`` good steps, /2 on overflow with
``delayed_shift`` hysteresis).

Difference from the reference: the scaler state is a jittable pytree and the
overflow-skip decision happens *inside* the compiled train step via
``jnp.where`` — there is no Python-side has_overflow round trip per step.
bf16 (TPU default) needs none of this; fp16 is kept for behavioral parity.
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # float32 scalar
    good_steps: jnp.ndarray     # int32: consecutive non-overflow steps
    hysteresis: jnp.ndarray     # int32: remaining tolerated overflows


class DynamicLossScaler:
    """Stateless transition rules over LossScaleState."""

    def __init__(self, init_scale: float = 2.0**32, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 delayed_shift: int = 1, consecutive_hysteresis: bool = False):
        self.init_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift
        self.consecutive_hysteresis = consecutive_hysteresis

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(self.init_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(self.delayed_shift, jnp.int32),
        )

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        """One-step transition (reference loss_scaler.py:151-166)."""
        overflow = jnp.asarray(overflow)
        # on overflow: consume hysteresis; halve scale once exhausted
        new_hyst = jnp.where(overflow,
                             jnp.maximum(state.hysteresis - 1, 0),
                             state.hysteresis)
        shrink = overflow & (state.hysteresis <= 1)
        shrunk_scale = jnp.maximum(state.scale / self.scale_factor,
                                   self.min_scale)
        # growth after scale_window consecutive good steps
        grown = (~overflow) & (state.good_steps + 1 >= self.scale_window)
        new_scale = jnp.where(shrink, shrunk_scale,
                              jnp.where(grown, state.scale * self.scale_factor,
                                        state.scale))
        new_good = jnp.where(overflow | grown, 0, state.good_steps + 1)
        if self.consecutive_hysteresis:
            # restock hysteresis on any good step
            new_hyst = jnp.where(~overflow,
                                 jnp.asarray(self.delayed_shift, jnp.int32),
                                 new_hyst)
        return LossScaleState(scale=new_scale,
                              good_steps=new_good.astype(jnp.int32),
                              hysteresis=new_hyst.astype(jnp.int32))


class StaticLossScaler(DynamicLossScaler):
    """Fixed scale (reference LossScaler :56)."""

    def __init__(self, scale: float = 1.0):
        super().__init__(init_scale=scale)

    def update(self, state: LossScaleState, overflow) -> LossScaleState:
        return state


def has_overflow(grads) -> jnp.ndarray:
    """True if any grad leaf contains inf/nan (reference
    CheckOverflow, runtime/utils.py:41). Computed on-device; under pjit the
    reduction spans all shards, so this is globally consistent."""
    import jax
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), bool)
    flags = [~jnp.isfinite(g.astype(jnp.float32)).all() for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out
