"""fp16 optimizer wrapper: mixed precision without ZeRO.

Reference: ``deepspeed/runtime/fp16/fused_optimizer.py:17`` ``FP16_Optimizer``
(flat fp32 master copy per group, dynamic loss scaling, overflow skip,
``step_fused_adam:133`` / ``step:191`` / ``backward:290`` /
``unscale_and_clip_grads:270`` / elastic ``state_dict:350``).

TPU re-design: the eager backward/step split collapses into one pure
``update`` — unscale → overflow check → ``lax.cond``-guarded inner step on
the fp32 master → fp16 copy-out → loss-scale bookkeeping — entirely
jit-traceable. The class keeps the reference's OO surface (``backward``,
``step``, ``state_dict``…) as a thin stateful facade over that pure
function, so user code written against the reference keeps working while
the engine (and tests) can call the pure path directly.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizers import Optimizer
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, LossScaleState, StaticLossScaler, has_overflow)
from deepspeed_tpu.utils.logging import logger

__all__ = ["FP16_Optimizer", "FP16OptimizerState"]


class FP16OptimizerState(NamedTuple):
    master_params: Any          # fp32 copy (reference fp32_groups_flat)
    inner_state: Any            # wrapped optimizer state
    loss_scale: LossScaleState
    overflow: jnp.ndarray       # bool: last step skipped?


class FP16_Optimizer:
    """Wraps a basic optimizer with fp16 master-copy semantics
    (reference ``fused_optimizer.py:17``)."""

    def __init__(self,
                 init_optimizer: Optimizer,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 initial_dynamic_scale: float = 2 ** 32,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False,
                 mpu=None,
                 clip_grad: float = 0.0,
                 fused_adam_legacy: bool = False):
        self.optimizer = init_optimizer
        self.clip_grad = clip_grad
        self.mpu = mpu
        self.verbose = verbose
        if dynamic_loss_scale:
            args = dict(dynamic_loss_args or {})
            self.loss_scaler = DynamicLossScaler(
                init_scale=args.get("init_scale", initial_dynamic_scale),
                scale_factor=args.get("scale_factor", 2.0),
                scale_window=args.get("scale_window", 1000),
                min_scale=args.get("min_scale", 1.0),
                delayed_shift=args.get("delayed_shift", 1))
        else:
            self.loss_scaler = StaticLossScaler(static_loss_scale)
        # stateful-facade slots
        self._state: Optional[FP16OptimizerState] = None
        self._params_fp16 = None
        self._pending_scaled_grads = None
        self._lr = getattr(init_optimizer, "lr", 1e-3)

    # ---------------- pure functional core ---------------------------- #
    def init(self, params_fp16) -> FP16OptimizerState:
        """Build state: fp32 master copy of the fp16 params (reference
        ctor ``:60-77`` flattening to fp32), inner optimizer state on the
        master."""
        master = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p, jnp.float32), params_fp16)
        return FP16OptimizerState(
            master_params=master,
            inner_state=self.optimizer.init(master),
            loss_scale=self.loss_scaler.init(),
            overflow=jnp.zeros((), bool))

    def update(self, scaled_grads_fp16, state: FP16OptimizerState,
               lr=None) -> Tuple[Any, FP16OptimizerState]:
        """One optimizer boundary, jit-traceable (reference ``step:191``).
        Takes grads of the *scaled* loss; returns (new fp16 params, state).
        """
        lr = self._lr if lr is None else lr
        inv = 1.0 / state.loss_scale.scale
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, scaled_grads_fp16)
        overflow = has_overflow(grads)

        if self.clip_grad > 0:
            sq = sum(jnp.sum(jnp.square(g))
                     for g in jax.tree_util.tree_leaves(grads))
            norm = jnp.sqrt(sq)
            clip = jnp.minimum(1.0, self.clip_grad / (norm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * clip, grads)

        def do(operand):
            master, inner, g = operand
            return self.optimizer.update(g, inner, master, lr=lr)

        def skip(operand):
            master, inner, _ = operand
            return master, inner

        master, inner = jax.lax.cond(
            overflow, skip, do,
            (state.master_params, state.inner_state, grads))
        new_scale = self.loss_scaler.update(state.loss_scale, overflow)
        new_state = FP16OptimizerState(
            master_params=master, inner_state=inner,
            loss_scale=new_scale, overflow=overflow)
        params_fp16 = jax.tree_util.tree_map(
            lambda m: m.astype(jnp.float16), master)
        return params_fp16, new_state

    # ---------------- reference-style stateful facade ------------------ #
    def bind(self, params_fp16):
        """Attach concrete fp16 params to the facade."""
        self._params_fp16 = params_fp16
        self._state = self.init(params_fp16)
        return self

    def backward(self, loss, loss_fn=None, *loss_args):
        """(reference ``backward:290``: scaled_loss.backward()). Functional
        JAX has no implicit autograd tape — pass ``loss_fn(params) -> loss``
        and the facade computes grads of ``loss_fn(p) * loss_scale``."""
        assert self._state is not None, "call bind(params) first"
        assert loss_fn is not None, \
            "FP16_Optimizer.backward needs loss_fn (no autograd tape in JAX)"
        scale = self._state.loss_scale.scale

        def scaled(p):
            return loss_fn(p, *loss_args) * scale

        self._pending_scaled_grads = jax.grad(scaled)(self._params_fp16)
        return loss

    def step(self, closure=None):
        """(reference ``step:191``) Returns True when the step was skipped
        on overflow, mirroring the reference's skip reporting."""
        assert self._pending_scaled_grads is not None, \
            "step() must follow backward()"
        self._params_fp16, self._state = self.update(
            self._pending_scaled_grads, self._state, lr=self._lr)
        self._pending_scaled_grads = None
        skipped = bool(self._state.overflow)
        if skipped and self.verbose:
            logger.info(
                f"[deepspeed_tpu] OVERFLOW! Skipping step, reducing loss "
                f"scale to {float(self._state.loss_scale.scale)}")
        return skipped

    def zero_grad(self, set_grads_to_None: bool = True):
        self._pending_scaled_grads = None

    @property
    def params(self):
        return self._params_fp16

    @property
    def cur_scale(self):
        assert self._state is not None
        return float(self._state.loss_scale.scale)

    # reference exposes loss_scale as a property (:338)
    @property
    def loss_scale(self):
        return self.cur_scale

    @property
    def overflow(self):
        assert self._state is not None
        return bool(self._state.overflow)

    def state_dict(self):
        """(reference ``state_dict:350``) Host-side snapshot."""
        assert self._state is not None
        dev = jax.device_get
        return {
            "loss_scaler": dev(self._state.loss_scale),
            "dynamic_loss_scale": isinstance(self.loss_scaler,
                                             DynamicLossScaler) and
            not isinstance(self.loss_scaler, StaticLossScaler),
            "overflow": bool(self._state.overflow),
            "fp32_groups_flat": dev(self._state.master_params),
            "optimizer_state_dict": dev(self._state.inner_state),
            "clip_grad": self.clip_grad,
        }

    def load_state_dict(self, sd, load_optimizer_states: bool = True):
        """(reference ``load_state_dict:379``)"""
        assert self._state is not None, "call bind(params) first"
        master = jax.tree_util.tree_map(jnp.asarray,
                                        sd["fp32_groups_flat"])
        inner = (jax.tree_util.tree_map(jnp.asarray,
                                        sd["optimizer_state_dict"])
                 if load_optimizer_states else self._state.inner_state)
        ls = sd["loss_scaler"]
        scale_state = LossScaleState(*[jnp.asarray(x) for x in ls])
        self._state = FP16OptimizerState(
            master_params=master, inner_state=inner,
            loss_scale=scale_state,
            overflow=jnp.asarray(bool(sd.get("overflow", False))))
        self._params_fp16 = jax.tree_util.tree_map(
            lambda m: m.astype(jnp.float16), master)
        self.clip_grad = sd.get("clip_grad", self.clip_grad)

    def refresh_fp32_params(self):
        """(reference ``refresh_fp32_params:375``) fp16 → fp32 master."""
        self._state = self._state._replace(
            master_params=jax.tree_util.tree_map(
                lambda p: jnp.asarray(p, jnp.float32), self._params_fp16))

    def __repr__(self):
        return (f"FP16_Optimizer(inner={type(self.optimizer).__name__}, "
                f"clip_grad={self.clip_grad})")
