"""LR schedules constructible from JSON config.

TPU-native analog of the reference's ``deepspeed/runtime/lr_schedules.py``
(LRRangeTest :298, OneCycle :398, WarmupLR :642). Each schedule is a pure
function of the global step implemented with jnp ops, so the engine can fold
the LR computation *inside* the compiled train step (no host round-trip per
step); the object wrapper keeps the torch-scheduler-style
step()/get_lr()/state_dict() facade for reference-API parity.
"""

from typing import Optional

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR]


def add_tuning_arguments(parser):
    """Convergence-tuning CLI argument group (reference
    lr_schedules.py:51-149 — same flags, names, and defaults)."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    # Learning rate range test
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001,
                       help="Starting lr value.")
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0,
                       help="scaling rate for LR range test.")
    group.add_argument("--lr_range_test_step_size", type=int, default=1000,
                       help="training steps per LR change.")
    group.add_argument("--lr_range_test_staircase", type=bool, default=False,
                       help="use staircase scaling for LR range test.")
    # OneCycle schedule
    group.add_argument("--cycle_first_step_size", type=int, default=1000,
                       help="size of first step of 1Cycle schedule "
                            "(training steps).")
    group.add_argument("--cycle_first_stair_count", type=int, default=-1,
                       help="first stair count for 1Cycle schedule.")
    group.add_argument("--cycle_second_step_size", type=int, default=-1,
                       help="size of second step of 1Cycle schedule "
                            "(default first_step_size).")
    group.add_argument("--cycle_second_stair_count", type=int, default=-1,
                       help="second stair count for 1Cycle schedule.")
    group.add_argument("--decay_step_size", type=int, default=1000,
                       help="size of intervals for applying post cycle "
                            "decay (training steps).")
    # 1Cycle LR
    group.add_argument("--cycle_min_lr", type=float, default=0.01,
                       help="1Cycle LR lower bound.")
    group.add_argument("--cycle_max_lr", type=float, default=0.1,
                       help="1Cycle LR upper bound.")
    group.add_argument("--decay_lr_rate", type=float, default=0.0,
                       help="post cycle LR decay rate.")
    # 1Cycle momentum
    group.add_argument("--cycle_momentum", default=False,
                       action="store_true",
                       help="Enable 1Cycle momentum schedule.")
    group.add_argument("--cycle_min_mom", type=float, default=0.8,
                       help="1Cycle momentum lower bound.")
    group.add_argument("--cycle_max_mom", type=float, default=0.9,
                       help="1Cycle momentum upper bound.")
    group.add_argument("--decay_mom_rate", type=float, default=0.0,
                       help="post cycle momentum decay rate.")
    # Warmup LR
    group.add_argument("--warmup_min_lr", type=float, default=0,
                       help="WarmupLR minimum/initial LR value")
    group.add_argument("--warmup_max_lr", type=float, default=0.001,
                       help="WarmupLR maximum LR value.")
    group.add_argument("--warmup_num_steps", type=int, default=1000,
                       help="WarmupLR step count for LR warmup.")
    return parser


def parse_arguments():
    import argparse
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    return parser.parse_known_args()


_OVERRIDE_KEYS = {
    LR_RANGE_TEST: ("lr_range_test_min_lr", "lr_range_test_step_rate",
                    "lr_range_test_step_size", "lr_range_test_staircase"),
    ONE_CYCLE: ("cycle_first_step_size", "cycle_first_stair_count",
                "cycle_second_step_size", "cycle_second_stair_count",
                "decay_step_size", "cycle_min_lr", "cycle_max_lr",
                "decay_lr_rate", "cycle_momentum", "cycle_min_mom",
                "cycle_max_mom", "decay_mom_rate"),
    WARMUP_LR: ("warmup_min_lr", "warmup_max_lr", "warmup_num_steps"),
}


def _override(args, params, schedule):
    for k in _OVERRIDE_KEYS[schedule]:
        v = getattr(args, k, None)
        if v is not None:
            params[k] = v
    return params


def override_lr_range_test_params(args, params):
    return _override(args, params, LR_RANGE_TEST)


def override_1cycle_params(args, params):
    return _override(args, params, ONE_CYCLE)


def override_warmupLR_params(args, params):
    return _override(args, params, WARMUP_LR)


def override_params(args, params):
    override_lr_range_test_params(args, params)
    override_1cycle_params(args, params)
    return override_warmupLR_params(args, params)


def get_config_from_args(args):
    """(config, error): scheduler config dict from tuning CLI args
    (reference lr_schedules.py:238)."""
    if not hasattr(args, LR_SCHEDULE) or args.lr_schedule is None:
        return None, f"--{LR_SCHEDULE} not specified on command line"
    if args.lr_schedule not in VALID_LR_SCHEDULES:
        return None, f"{args.lr_schedule} is not supported LR schedule"
    config = {"type": args.lr_schedule, "params": {}}
    _override(args, config["params"], args.lr_schedule)
    return config, None


def get_lr_from_config(config):
    """(lr, error): the schedule's nominal peak/start LR
    (reference lr_schedules.py:259)."""
    if "type" not in config:
        return None, "LR schedule type not defined in config"
    if "params" not in config:
        return None, "LR schedule params not defined in config"
    lr_schedule, lr_params = config["type"], config["params"]
    if lr_schedule not in VALID_LR_SCHEDULES:
        return None, f"{lr_schedule} is not a valid LR schedule"
    if lr_schedule == LR_RANGE_TEST:
        return lr_params["lr_range_test_min_lr"], ""
    if lr_schedule == ONE_CYCLE:
        return lr_params["cycle_max_lr"], ""
    return lr_params["warmup_max_lr"], ""


class _Schedule:
    """Host-facing facade; ``lr_at(step)`` is the jittable core."""

    def __init__(self):
        self.last_batch_iteration = -1
        self._last_lr = None

    def lr_at(self, step):
        raise NotImplementedError

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = float(self.lr_at(jnp.asarray(last_batch_iteration)))

    def get_lr(self):
        if self._last_lr is None:
            return [float(self.lr_at(jnp.asarray(0)))]
        return [self._last_lr]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_Schedule):
    """Linear (or log) warmup from warmup_min_lr to warmup_max_lr over
    warmup_num_steps, then constant (reference lr_schedules.py:642)."""

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", last_batch_iteration: int = -1):
        super().__init__()
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(1, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / jnp.log(self.warmup_num_steps) \
            if self.warmup_num_steps > 1 else 1.0
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        if self.warmup_type == "log":
            # reference lr_schedules.py:705: gamma = log(step + 1) / log(N)
            gamma = jnp.where(
                step + 1 >= self.warmup_num_steps, 1.0,
                self.inverse_log_warm_up * jnp.log(step + 1.0))
        else:
            gamma = jnp.minimum(step / self.warmup_num_steps, 1.0)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma


class LRRangeTest(_Schedule):
    """LR range test: ramp lr by lr_range_test_step_rate every
    lr_range_test_step_size steps, continuous or staircase
    (reference lr_schedules.py:298)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        super().__init__()
        self.min_lr = lr_range_test_min_lr
        self.step_size = max(1, lr_range_test_step_size)
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        if self.staircase:
            count = jnp.floor(step / self.step_size)
        else:
            count = step / self.step_size
        return self.min_lr * (1.0 + self.step_rate * count)


class OneCycle(_Schedule):
    """1-cycle policy: lr up then down, optional momentum counter-cycling
    and post-cycle decay (reference lr_schedules.py:398)."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 1e-4,
                 cycle_max_lr: float = 1e-3,
                 decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0,
                 cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.85,
                 cycle_max_mom: float = 0.99,
                 decay_mom_rate: float = 0.0,
                 last_batch_iteration: int = -1):
        super().__init__()
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = max(1, cycle_first_step_size)
        self.second_size = (cycle_second_step_size
                            if cycle_second_step_size is not None
                            else self.first_size)
        self.first_stair_count = cycle_first_stair_count
        self.second_stair_count = (cycle_second_stair_count
                                   if cycle_second_stair_count is not None
                                   else cycle_first_stair_count)
        self.decay_step_size = decay_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate
        self.total_size = self.first_size + self.second_size
        self.last_batch_iteration = last_batch_iteration

    @staticmethod
    def _stair(frac, stair_count):
        """Quantize a [0,1] phase fraction into stair_count flat steps
        (reference lr_schedules.py staircase interpolation)."""
        if stair_count and stair_count > 0:
            return jnp.floor(frac * stair_count) / stair_count
        return frac

    def lr_at(self, step):
        step = jnp.maximum(step, 0).astype(jnp.float32)
        in_cycle = step <= self.total_size
        # position within the (single) cycle
        up_frac = self._stair(jnp.clip(step / self.first_size, 0.0, 1.0),
                              self.first_stair_count)
        down_frac = self._stair(
            jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0),
            self.second_stair_count)
        cycle_lr = jnp.where(
            step < self.first_size,
            self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * up_frac,
            self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * down_frac)
        # post-cycle decay
        if self.decay_step_size > 0:
            decay_steps = jnp.maximum(step - self.total_size, 0.0) / self.decay_step_size
        else:
            decay_steps = jnp.maximum(step - self.total_size, 0.0)
        decay_lr = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_steps)
        return jnp.where(in_cycle, cycle_lr, decay_lr)

    def mom_at(self, step):
        """Momentum counter-cycles the LR (reference lr_schedules.py:518)."""
        step = jnp.maximum(step, 0).astype(jnp.float32)
        up_frac = jnp.clip(step / self.first_size, 0.0, 1.0)
        down_frac = jnp.clip((step - self.first_size) / self.second_size,
                             0.0, 1.0)
        in_cycle = step <= self.total_size
        cycle_mom = jnp.where(
            step < self.first_size,
            self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * up_frac,
            self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * down_frac)
        if self.decay_step_size > 0:
            decay_steps = jnp.maximum(step - self.total_size, 0.0) / self.decay_step_size
        else:
            decay_steps = jnp.maximum(step - self.total_size, 0.0)
        decay_mom = self.cycle_max_mom * (1.0 + self.decay_mom_rate * decay_steps)
        return jnp.where(in_cycle, cycle_mom, decay_mom)


def build_lr_schedule(name: Optional[str], params: Optional[dict]):
    """Construct from JSON config (reference engine.py:402-417)."""
    if name is None:
        return None
    params = dict(params or {})
    params.pop("warmup_proportion", None)  # client-side extension, ignored
    if name == WARMUP_LR:
        return WarmupLR(**params)
    if name == LR_RANGE_TEST:
        return LRRangeTest(**params)
    if name == ONE_CYCLE:
        return OneCycle(**params)
    raise ValueError(
        f"Unknown scheduler {name}; valid: {VALID_LR_SCHEDULES}")
