"""Checkpoint save/load for engine state.

TPU-native analog of the reference's checkpoint layer (engine.py:1329
save_checkpoint / :1173 load_checkpoint; ZeRO elastic merge-then-repartition
stage2.py:1713-1779). Layout under ``<save_dir>/<tag>/``:

- ``model_states.shard_<p>.npz`` + ``.json`` : this process's device shards
  of the master params, with a chunk manifest (global index per chunk) —
  reference mp_rank_XX_model_states.pt + zero_pp_rank_* partition files
- ``optim_states.shard_<p>.npz`` + ``.json`` : optimizer + loss-scale state
- ``meta.json``         : step counters, client state
- ``<save_dir>/latest`` : tag pointer (reference writes the same file)

No process ever materializes the global state: saving writes only local
replica-0 shards; loading reassembles through ``make_array_from_callback``
so each device reads only the manifest chunks overlapping its own shard of
the *new* sharding. Elastic resharding across dp/mesh changes (the
reference's merge-then-repartition, stage2.py:1713-1779) is therefore the
default load path, at O(local shard) host memory. ``save_tree``/
``load_tree`` remain for small replicated host state and legacy files.

Durability layer (fault model: preemption mid-save is *expected* on TPU
pods): every file is written via temp + ``os.replace`` + fsync and retried
through ``fault.retry_io``; a save is only visible once its directory
carries a ``COMMITTED`` marker recording process_count and per-file
sizes + CRC32 checksums, and the directory itself is renamed from
``<tag>.tmp`` to ``<tag>`` only after the marker is durable. Loading
verifies the marker (``verify_checkpoint_dir``) and the engine falls back
to the newest committed tag when ``latest`` is torn or a shard is corrupt.
Pre-durability checkpoints (no marker) remain loadable via a
best-effort legacy check.
"""

import io
import json
import os
import re
import shutil
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.runtime import fault

LATEST = "latest"
COMMIT_MARKER = "COMMITTED"
TMP_SUFFIX = ".tmp"
OLD_SUFFIX = ".old"
CHECKPOINT_FORMAT_VERSION = 1

# process-global retry policy for transient filesystem errors (GCS/NFS
# flakes); the engine overrides it from the `checkpoint` config section
_RETRY = {"retries": 3, "backoff": 0.05}


def set_retry_policy(retries: Optional[int] = None,
                     backoff: Optional[float] = None) -> None:
    if retries is not None:
        _RETRY["retries"] = int(retries)
    if backoff is not None:
        _RETRY["backoff"] = float(backoff)


def _retry(fn):
    return fault.retry_io(fn, retries=_RETRY["retries"],
                          backoff=_RETRY["backoff"])


def _fsync_dir(dirpath: str) -> None:
    """Flush directory metadata (the rename itself) to stable storage."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return  # some filesystems (or platforms) can't open dirs; best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-temp + fsync + ``os.replace``: readers never observe a torn
    file at ``path``. Retried on transient ``OSError``."""
    def _write():
        fault.fire("io_write", path=path)
        tmp = path + ".part"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path) or ".")
    _retry(_write)


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _flatten_named(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path)
        flat[key or "_root"] = leaf
    return flat


def _to_host_global(v):
    """Fetch a (possibly multi-host-sharded) array as a full host array."""
    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        # multi-host pod: shards live on other processes; gather first
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            v, tiled=True))
    return np.asarray(jax.device_get(v))


def save_tree(path: str, tree: Any) -> None:
    """Gather a (possibly sharded) pytree to host and save as npz."""
    named = _flatten_named(tree)
    arrays = {}
    for k, v in named.items():
        if hasattr(v, "shape"):
            arr = _to_host_global(v)
        else:
            arr = np.asarray(v)
        # npz cannot round-trip ml_dtypes (bfloat16/fp8 — void-kind dtypes
        # reload as raw |V bytes): store widened; load_tree's
        # astype(leaf.dtype) narrows back on restore
        if arr.dtype.kind == "V":
            arr = arr.astype(np.float32)
        arrays[k] = arr
    _atomic_write_bytes(path, _npz_bytes(arrays))


def load_tree(path: str, template: Any, shardings: Optional[Any] = None) -> Any:
    """Load arrays and restore into the template's structure, placing each
    leaf with the template's (or given) sharding — this is the elastic
    repartition step."""
    # dict() forces the reads eagerly so the retry covers the actual I/O,
    # not just the lazy zip-header open (legacy files are full arrays —
    # everything gets read anyway)
    data = _retry(lambda: dict(np.load(path)))
    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(leaves_paths))
    out = []
    for (path_elems, leaf), shd in zip(leaves_paths, shard_leaves):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path_elems) or "_root"
        if key not in data:
            raise KeyError(f"checkpoint missing leaf '{key}'")
        arr = data[key]
        if hasattr(leaf, "shape") and tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch for '{key}': ckpt {arr.shape} vs "
                f"model {leaf.shape}")
        if hasattr(leaf, "dtype"):
            arr = arr.astype(leaf.dtype)
        if shd is None and hasattr(leaf, "sharding"):
            shd = leaf.sharding
        out.append(jax.device_put(arr, shd) if shd is not None else arr)
    return treedef.unflatten(out)


# --------------------------------------------------------------------- #
# sharded (per-process) checkpoint format
#
# Reference DeepSpeed writes per-dp-rank ZeRO partition files
# (engine.py:1153-1164,1409-1413 zero_pp_rank_X_mp_rank_XX_optim_states.pt)
# precisely so no rank ever has to hold the full fp32 state. The TPU-native
# analog: every *process* writes only its addressable, replica-0 device
# shards to ``<name>.shard_<p>.npz`` plus a JSON chunk manifest
# ``<name>.shard_<p>.json`` recording each chunk's global index. Loading
# uses ``jax.make_array_from_callback`` so each device reads only the
# chunks overlapping its own shard of the *new* sharding — elastic
# resharding across dp/mesh changes (reference merge-then-repartition,
# stage2.py:1713-1779) without a host-0 gather on either side.
# --------------------------------------------------------------------- #

def _norm_bounds(index, shape):
    """Normalize a tuple of slices to (start, stop) int lists."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        b, e, step = sl.indices(dim)
        assert step == 1, "strided checkpoint shards unsupported"
        starts.append(int(b))
        stops.append(int(e))
    return starts, stops


def save_tree_sharded(ckpt_dir: str, name: str, tree: Any) -> None:
    """Write this process's shards of a (possibly sharded) pytree.

    Every process calls this; each writes exactly one ``.npz`` + one
    ``.json`` fragment containing only data it owns (replica 0 of each
    device shard), so no cross-process communication or full-array
    host materialization ever happens.
    """
    pidx = jax.process_index()
    named = _flatten_named(tree)
    arrays: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {}
    for key, v in named.items():
        if not hasattr(v, "addressable_shards"):
            # host scalar / numpy leaf: replicated; process 0 records it
            arr = np.asarray(v)
            entry = {"global_shape": list(arr.shape),
                     "dtype": str(arr.dtype), "chunks": []}
            if pidx == 0:
                ek = f"{key}::0"
                a = arr.astype(np.float32) if arr.dtype.kind == "V" else arr
                arrays[ek] = a
                entry["chunks"].append({
                    "entry": ek,
                    "start": [0] * arr.ndim,
                    "stop": list(arr.shape)})
            manifest[key] = entry
            continue
        entry = {"global_shape": list(v.shape), "dtype": str(v.dtype),
                 "chunks": []}
        n = 0
        for sh in v.addressable_shards:
            if sh.replica_id != 0:
                continue  # replicated copy: one writer is enough
            data = np.asarray(sh.data)
            if data.dtype.kind == "V":  # bf16/fp8: npz can't round-trip
                data = data.astype(np.float32)
            ek = f"{key}::{n}"
            n += 1
            arrays[ek] = data
            starts, stops = _norm_bounds(sh.index, v.shape)
            entry["chunks"].append({"entry": ek, "start": starts,
                                    "stop": stops})
        manifest[key] = entry
    _atomic_write_bytes(os.path.join(ckpt_dir, f"{name}.shard_{pidx}.npz"),
                        _npz_bytes(arrays))
    _atomic_write_bytes(os.path.join(ckpt_dir, f"{name}.shard_{pidx}.json"),
                        json.dumps(manifest).encode())


def sharded_exists(ckpt_dir: str, name: str) -> bool:
    """True when a complete sharded save of ``name`` is present.

    A COMMITTED marker is authoritative: the files it lists for ``name``
    must all exist. Pre-durability checkpoints (no marker) fall back to
    all-fragments-present — every ``shard_*.json`` manifest must have its
    paired ``.npz``, so a partial multi-process save no longer passes on
    the strength of ``shard_0.json`` alone.
    """
    marker = read_commit_marker(ckpt_dir)
    if marker is not None:
        listed = [f for f in marker["files"]
                  if f.startswith(f"{name}.shard_")]
        return bool(listed) and all(
            os.path.isfile(os.path.join(ckpt_dir, f)) for f in listed)
    import glob
    frags = glob.glob(os.path.join(ckpt_dir, f"{name}.shard_*.json"))
    if not frags:
        return False
    return all(os.path.isfile(f[:-len(".json")] + ".npz") for f in frags)


def _merged_manifest(ckpt_dir: str, name: str):
    """Merge all processes' manifest fragments into
    {leaf: (shape, dtype, [(file, entry, start, stop), ...])}."""
    import glob
    merged: Dict[str, Any] = {}
    frags = sorted(glob.glob(
        os.path.join(ckpt_dir, f"{name}.shard_*.json")))
    if not frags:
        raise FileNotFoundError(
            f"no {name}.shard_*.json manifests in {ckpt_dir}")
    for fpath in frags:
        npz = fpath[:-len(".json")] + ".npz"
        def _read(p=fpath):
            with open(p) as f:
                return json.load(f)
        frag = _retry(_read)
        for key, entry in frag.items():
            tgt = merged.setdefault(
                key, (tuple(entry["global_shape"]), entry["dtype"], []))
            for c in entry["chunks"]:
                tgt[2].append((npz, c["entry"],
                               tuple(c["start"]), tuple(c["stop"])))
    return merged


def load_tree_sharded(ckpt_dir: str, name: str, template: Any,
                      shardings: Optional[Any] = None) -> Any:
    """Reassemble a sharded checkpoint under *new* shardings.

    Each leaf is built with ``jax.make_array_from_callback``: the callback
    reads, per device shard, only the saved chunks overlapping that
    shard's index — the elastic repartition (reference
    stage2.py:1713-1779) without ever materializing the global array.
    """
    merged = _merged_manifest(ckpt_dir, name)
    npz_cache: Dict[str, Any] = {}

    def chunk(npz_path, entry):
        # lazy per-entry reads preserve O(local shard) host memory; the
        # retry must wrap the read itself, and a failed read drops the
        # cached NpzFile so the next attempt reopens a fresh handle
        def _read():
            if npz_path not in npz_cache:
                npz_cache[npz_path] = np.load(npz_path)
            try:
                return npz_cache[npz_path][entry]
            except OSError:
                npz_cache.pop(npz_path, None)
                raise
        return _retry(_read)

    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(leaves_paths))
    out = []
    for (path_elems, leaf), shd in zip(leaves_paths, shard_leaves):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx",
                                                     getattr(p, "name", p))))
                       for p in path_elems) or "_root"
        if key not in merged:
            raise KeyError(f"checkpoint missing leaf '{key}'")
        gshape, _dty, chunks = merged[key]
        if hasattr(leaf, "shape") and tuple(gshape) != tuple(leaf.shape):
            raise ValueError(f"shape mismatch for '{key}': ckpt {gshape} "
                             f"vs model {tuple(leaf.shape)}")
        dtype = leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype

        def read(index, _gshape=gshape, _chunks=chunks, _dtype=dtype,
                 _key=key):
            starts, stops = _norm_bounds(index, _gshape)
            shp = [e - b for b, e in zip(starts, stops)]
            buf = np.empty(shp, dtype=_dtype)
            filled = 0
            for npz_path, entry, cs, ce in _chunks:
                ob = [max(b, b2) for b, b2 in zip(starts, cs)]
                oe = [min(e, e2) for e, e2 in zip(stops, ce)]
                if any(b >= e for b, e in zip(ob, oe)):
                    continue
                data = chunk(npz_path, entry)
                src = tuple(slice(b - b2, e - b2)
                            for b, e, b2 in zip(ob, oe, cs))
                dst = tuple(slice(b - b2, e - b2)
                            for b, e, b2 in zip(ob, oe, starts))
                buf[dst] = data[src].astype(_dtype)
                filled += int(np.prod([e - b for b, e in zip(ob, oe)]))
            want = int(np.prod(shp)) if shp else 1
            if filled != want:
                raise ValueError(
                    f"incomplete checkpoint coverage for '{_key}': "
                    f"{filled}/{want} elements (missing shard files?)")
            return buf

        if shd is None and hasattr(leaf, "sharding"):
            shd = leaf.sharding
        if shd is not None and hasattr(leaf, "shape"):
            out.append(jax.make_array_from_callback(
                tuple(gshape), shd, lambda idx, _r=read: _r(idx)))
        else:
            full = read(tuple(slice(0, d) for d in gshape))
            out.append(full if gshape else full[()])
    return treedef.unflatten(out)


def load_params_only(ckpt_dir: str, template: Any,
                     shardings: Optional[Any] = None) -> Any:
    """Params-only load mode: restore exactly the ``model_states`` group
    of a committed checkpoint — never optimizer moments, loss scale, or
    host offload state. The checkpoint -> serving bridge
    (``InferenceEngine.from_checkpoint``): a serving replica needs the
    weights (1x model size), not the 3-4x training state the full
    ``load_checkpoint`` path reassembles. Works against both the sharded
    per-process format (elastic resharding onto any serving mesh via
    ``shardings``) and the legacy single-file ``model_states.npz``."""
    if sharded_exists(ckpt_dir, "model_states"):
        return load_tree_sharded(ckpt_dir, "model_states", template,
                                 shardings)
    single = os.path.join(ckpt_dir, "model_states.npz")
    if os.path.isfile(single):
        return load_tree(single, template, shardings)
    raise FileNotFoundError(
        f"no model_states (sharded or single-file) in {ckpt_dir}")


# --------------------------------------------------------------------- #
# device -> host snapshots: the async-save boundary copy
#
# save_tree_sharded reads `.addressable_shards` off live jax arrays; an
# async save cannot — the step loop keeps dispatching and the compiled
# step DONATES the state buffers, so by the time a background writer
# touches them they are freed (or worse, reused). snapshot_tree takes
# an explicit host copy of exactly the replica-0 shards at the step
# boundary (O(local shard) host memory — the same bytes a blocking
# save_tree_sharded would have materialized anyway) into leaves that
# duck-type the jax.Array surface save_tree_sharded consumes, so the
# stage/commit protocol runs UNCHANGED off the snapshot.
# --------------------------------------------------------------------- #

class _SnapshotShard:
    """One replica-0 device shard, copied to host."""
    __slots__ = ("replica_id", "data", "index")

    def __init__(self, data: np.ndarray, index):
        self.replica_id = 0
        self.data = data
        self.index = index


class _SnapshotLeaf:
    """Host copy of one (possibly sharded) array; duck-types the subset
    of ``jax.Array`` that ``save_tree_sharded`` reads."""
    __slots__ = ("shape", "dtype", "addressable_shards")

    def __init__(self, shape, dtype, shards):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.addressable_shards = shards


def snapshot_tree(tree: Any) -> Any:
    """Donation-safe device->host snapshot of a (possibly sharded)
    pytree: same treedef, every array leaf replaced by a
    :class:`_SnapshotLeaf` holding explicit ``np.array(..., copy=True)``
    copies of its replica-0 shards (``np.asarray`` of a CPU-backend jax
    array can alias the device buffer — a later donation would free the
    memory out from under the writer). Host scalars/numpy leaves are
    copied too (a ZeRO-Offload host optimizer mutates its buffers in
    place between the snapshot and the background write).
    """
    fault.fire("ckpt.snapshot")

    def snap(v):
        if hasattr(v, "addressable_shards"):
            shards = [_SnapshotShard(np.array(sh.data, copy=True), sh.index)
                      for sh in v.addressable_shards if sh.replica_id == 0]
            return _SnapshotLeaf(v.shape, v.dtype, shards)
        if hasattr(v, "shape") or isinstance(v, (int, float, complex)):
            return np.array(v, copy=True)
        return v

    return jax.tree_util.tree_map(snap, tree)


class AsyncCheckpointWriter:
    """Single background writer thread running staged commit jobs.

    The collision guard the async save contract needs: at most one job
    *runs* and at most one *waits*; submitting while one waits REPLACES
    the waiting job's payload with the newest snapshot — reported as
    ``"superseded"`` for a different key, ``"joined"`` for the same key
    (same tag, fresher snapshot; writing an already-superseded older
    snapshot would be wasted I/O and could commit out of order). Two
    jobs can therefore never interleave their staging I/O. A job exception (including an armed ``ckpt.writer_crash``
    InjectedCrash) is stored, not swallowed: ``raise_pending_error`` —
    called by the engine on the next ``save_checkpoint``/``close`` —
    re-raises it.
    """

    def __init__(self, name: str = "dstpu-ckpt-writer"):
        self._name = name
        self._cv = threading.Condition()
        self._pending: Optional[Tuple[str, Callable[[], None]]] = None
        self._running_key: Optional[str] = None
        self._error: Optional[Tuple[str, BaseException]] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.completed = 0
        self.superseded = 0

    # ------------------------------------------------------------ submit
    def submit(self, key: str, job: Callable[[], None]) -> str:
        """Queue ``job``; returns ``"queued"``, ``"joined"`` (same key
        already waiting) or ``"superseded"`` (replaced a waiting job)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            if self._pending is not None:
                verdict = ("joined" if self._pending[0] == key
                           else "superseded")
                if verdict == "superseded":
                    self.superseded += 1
                # either way the NEWEST snapshot wins the waiting slot —
                # a join that kept the older queued job would silently
                # commit stale state under the caller's tag
                self._pending = (key, job)
                self._cv.notify_all()
                return verdict
            self._pending = (key, job)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread.start()
            self._cv.notify_all()
            return "queued"

    def _run(self):
        while True:
            with self._cv:
                while self._pending is None and not self._closed:
                    self._cv.wait()
                if self._pending is None:
                    return
                key, job = self._pending
                self._pending = None
                self._running_key = key
            try:
                fault.fire("ckpt.writer_crash", key=key)
                job()
            except BaseException as e:  # noqa: BLE001 — stored, surfaced
                with self._cv:
                    self._error = (key, e)
            finally:
                with self._cv:
                    self._running_key = None
                    self.completed += 1
                    self._cv.notify_all()

    # ------------------------------------------------------------- state
    def pending_saves(self) -> int:
        """Jobs not yet durable (waiting + running)."""
        with self._cv:
            return ((1 if self._pending is not None else 0)
                    + (1 if self._running_key is not None else 0))

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no job is waiting or running (the ``close()`` /
        eval-barrier semantics). Returns False on timeout."""
        with self._cv:
            return self._cv.wait_for(
                lambda: self._pending is None and self._running_key is None,
                timeout=timeout)

    def raise_pending_error(self) -> None:
        """Re-raise (once) the last job exception, chained so the
        traceback names the failed tag."""
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            key, exc = err
            raise RuntimeError(
                f"async checkpoint write of {key!r} failed") from exc

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain, stop the thread. Does NOT raise the stored error —
        callers decide (the engine raises it after releasing resources)."""
        self.drain(timeout=timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None


# state groups a tag directory may carry, in report order; "extras" are
# engine-subclass files sealed via _save_checkpoint_extras (pipe layout)
_STATE_GROUP_NAMES = ("model_states", "optim_states")


def state_groups(ckpt_dir: str) -> Dict[str, Any]:
    """Which state groups a checkpoint directory contains.

    Returns ``{group: "sharded" | "single-file" | None}`` for the
    array groups, plus ``cpu_optim_states``/``meta`` booleans and the
    list of extra sealed files. Consumed by ``tools/verify_checkpoint.py``
    (report) and the serving bridge (a params-only consumer can tell up
    front whether a tag even carries weights)."""
    groups: Dict[str, Any] = {}
    for name in _STATE_GROUP_NAMES:
        if sharded_exists(ckpt_dir, name):
            groups[name] = "sharded"
        elif os.path.isfile(os.path.join(ckpt_dir, f"{name}.npz")):
            groups[name] = "single-file"
        else:
            groups[name] = None
    groups["cpu_optim_states"] = os.path.isfile(
        os.path.join(ckpt_dir, "cpu_optim_states.npz"))
    groups["meta"] = os.path.isfile(os.path.join(ckpt_dir, "meta.json"))
    known_prefixes = tuple(f"{n}.shard_" for n in _STATE_GROUP_NAMES)
    known = {COMMIT_MARKER, "meta.json", "cpu_optim_states.npz",
             "model_states.npz", "optim_states.npz"}
    extras = []
    if os.path.isdir(ckpt_dir):
        for fn in sorted(os.listdir(ckpt_dir)):
            if fn in known or fn.startswith(known_prefixes) or \
                    fn.endswith(".part"):
                continue
            if os.path.isfile(os.path.join(ckpt_dir, fn)):
                extras.append(fn)
    groups["extras"] = extras
    return groups


def write_meta(ckpt_dir: str, meta: Dict) -> None:
    _atomic_write_bytes(
        os.path.join(ckpt_dir, "meta.json"),
        json.dumps(meta, indent=2, default=str).encode())


def read_meta(ckpt_dir: str) -> Dict:
    def _read():
        with open(os.path.join(ckpt_dir, "meta.json")) as f:
            return json.load(f)
    return _retry(_read)


def write_latest(save_dir: str, tag: str) -> None:
    """Atomically repoint ``latest``: write-temp + fsync + ``os.replace``
    so a crash mid-update can never leave a torn pointer."""
    path = os.path.join(save_dir, LATEST)

    def _write():
        fault.fire("io_write", path=path)
        tmp = path + TMP_SUFFIX
        with open(tmp, "w") as f:
            f.write(tag)
            f.flush()
            os.fsync(f.fileno())
        fault.fire("ckpt.latest_tmp_written", path=path, tag=tag)
        os.replace(tmp, path)
        _fsync_dir(save_dir)
    _retry(_write)


def read_latest(save_dir: str) -> Optional[str]:
    p = os.path.join(save_dir, LATEST)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        tag = f.read().strip()
    # an empty/whitespace pointer (torn write from a pre-durability run)
    # must not join into a nonsense path
    return tag or None


# --------------------------------------------------------------------- #
# commit protocol: COMMITTED marker, verification, tag scan, retention
# --------------------------------------------------------------------- #

def write_commit_marker(ckpt_dir: str, process_count: int = 1) -> Dict:
    """Seal a checkpoint directory: record process_count and every file's
    size + CRC32 in the ``COMMITTED`` marker (written atomically, last).

    Reading each file back for its checksum doubles as write-read
    verification before the checkpoint becomes visible.
    """
    files: Dict[str, Dict[str, int]] = {}
    for fn in sorted(os.listdir(ckpt_dir)):
        p = os.path.join(ckpt_dir, fn)
        if fn == COMMIT_MARKER or fn.endswith(".part") or not os.path.isfile(p):
            continue
        files[fn] = {"size": os.path.getsize(p),
                     "crc32": _retry(lambda p=p: fault.crc32_file(p))}
    marker = {"format_version": CHECKPOINT_FORMAT_VERSION,
              "process_count": int(process_count), "files": files}
    _atomic_write_bytes(os.path.join(ckpt_dir, COMMIT_MARKER),
                        json.dumps(marker, indent=2).encode())
    return marker


def read_commit_marker(ckpt_dir: str) -> Optional[Dict]:
    p = os.path.join(ckpt_dir, COMMIT_MARKER)
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            marker = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None  # unreadable marker == uncommitted
    if not isinstance(marker.get("files"), dict):
        return None
    return marker


def is_committed(ckpt_dir: str) -> bool:
    return read_commit_marker(ckpt_dir) is not None


def verify_checkpoint_dir(ckpt_dir: str,
                          check_crc: bool = True) -> Tuple[bool, List[str]]:
    """Integrity-check one checkpoint directory.

    Committed dirs: every file the marker lists must exist with the
    recorded size (and CRC32 unless ``check_crc=False``). Legacy dirs
    (no marker): best-effort — ``meta.json`` plus either a single-file
    ``model_states.npz`` or a complete set of paired shard fragments.
    Returns ``(ok, problems)``.
    """
    problems: List[str] = []
    if not os.path.isdir(ckpt_dir):
        return False, [f"{ckpt_dir}: not a directory"]
    marker = read_commit_marker(ckpt_dir)
    if marker is None:
        if not os.path.isfile(os.path.join(ckpt_dir, "meta.json")):
            problems.append("no COMMITTED marker and no meta.json "
                            "(incomplete or torn save)")
        if not (os.path.isfile(os.path.join(ckpt_dir, "model_states.npz"))
                or sharded_exists(ckpt_dir, "model_states")):
            problems.append("no complete model_states (single-file or "
                            "all shard fragments)")
        return not problems, problems
    for fn, info in marker["files"].items():
        p = os.path.join(ckpt_dir, fn)
        if not os.path.isfile(p):
            problems.append(f"{fn}: listed in COMMITTED but missing")
            continue
        size = os.path.getsize(p)
        if size != info.get("size"):
            problems.append(f"{fn}: size {size} != recorded {info.get('size')}")
            continue
        if check_crc and fault.crc32_file(p) != info.get("crc32"):
            problems.append(f"{fn}: CRC32 mismatch (corrupt bytes)")
    return not problems, problems


_STEP_RE = re.compile(r"(\d+)$")


def _tag_rank(fn: str) -> Tuple[int, int]:
    """(step, freshness) sort key: a ``<tag>.old`` rename-aside leftover
    ranks by its base tag's step but *below* the live copy of that tag."""
    base = fn[:-len(OLD_SUFFIX)] if fn.endswith(OLD_SUFFIX) else fn
    m = _STEP_RE.search(base)
    step = int(m.group(1)) if m else -1
    return step, (0 if fn.endswith(OLD_SUFFIX) else 1)


def tag_step(fn: str) -> int:
    return _tag_rank(fn)[0]


def list_tags(save_dir: str) -> List[str]:
    """Checkpoint tags newest-first (step number when the tag ends in
    digits — ``.old`` leftovers count as their base step — else mtime).
    ``.tmp`` staging dirs are never tags."""
    if not os.path.isdir(save_dir):
        return []
    ranked = []
    for fn in os.listdir(save_dir):
        p = os.path.join(save_dir, fn)
        if not os.path.isdir(p) or fn.endswith(TMP_SUFFIX):
            continue
        if not (os.path.isfile(os.path.join(p, COMMIT_MARKER))
                or os.path.isfile(os.path.join(p, "meta.json"))):
            continue
        step, fresh = _tag_rank(fn)
        ranked.append((step, fresh, os.path.getmtime(p), fn))
    ranked.sort(reverse=True)
    return [fn for _, _, _, fn in ranked]


def candidate_tags(save_dir: str) -> List[str]:
    """Resume candidates, best-first.

    A healthy ``latest`` pointer leads — it is the last *completed* save
    and may deliberately name a non-step tag (``best``). The one case
    where it is demoted: both ``latest`` and some other tag parse as step
    numbers and the other tag is numerically newer — that only happens
    when a save committed but crashed before the pointer update, so the
    newest committed step should win (the save "finished").
    """
    tags = list_tags(save_dir)
    latest = read_latest(save_dir)
    if not latest:
        return tags
    if latest not in tags:
        if os.path.isdir(os.path.join(save_dir, latest)):
            return [latest] + tags
        return tags
    lstep = tag_step(latest)
    if lstep >= 0 and any(tag_step(t) > lstep for t in tags):
        return tags  # stale pointer: newest-first scan
    return [latest] + [t for t in tags if t != latest]


def is_preemption_tag(ckpt_dir: str) -> bool:
    """True when the tag was committed by the graceful preemption drain
    (``meta.json`` carries ``preempted: true``). Detection is by meta,
    not tag name, so operator-renamed tags keep their protection."""
    try:
        return bool(read_meta(ckpt_dir).get("preempted"))
    except (OSError, json.JSONDecodeError, ValueError):
        return False


def newest_committed_step(save_dir: str) -> int:
    """Step number of the newest committed step-suffixed tag, -1 when
    none exist. The supervisor's resume sanity check
    (``tools/verify_checkpoint.py --expect-step``) keys on this."""
    steps = [tag_step(t) for t in list_tags(save_dir)
             if tag_step(t) >= 0 and is_committed(os.path.join(save_dir, t))]
    return max(steps) if steps else -1


def gc_old_tags(save_dir: str, keep_n: int) -> List[str]:
    """Retention: delete committed *step-suffixed* tags beyond the newest
    ``keep_n``.

    Only automatic ``...<step>`` tags (and their ``.old`` leftovers) are
    managed; custom-named tags (``best``) are user-owned and never GC'd.
    Two tags are protected REGARDLESS of ``keep_n`` (the fallback-load
    safety net — deleting either races a loader that is mid-fallback to
    it):

    - whatever tag ``latest`` currently points to (the last completed
      save as far as any resumer knows), and
    - any committed *preemption* tag newer than ``latest`` — the drain
      commits it and may die before repointing the pointer, and it is
      precisely the newest state a relaunched run must resume.

    Uncommitted or legacy dirs are never touched (they may be someone's
    in-flight save or the only pre-durability copy); ``keep_n <= 0``
    keeps everything.
    """
    if keep_n <= 0:
        return []
    latest = read_latest(save_dir)
    lstep = tag_step(latest) if latest else -1
    managed = [t for t in list_tags(save_dir)
               if tag_step(t) >= 0
               and is_committed(os.path.join(save_dir, t))]
    doomed = []
    for t in managed[keep_n:]:
        if t == latest:
            continue
        if tag_step(t) > lstep and \
                is_preemption_tag(os.path.join(save_dir, t)):
            continue
        doomed.append(t)
    for t in doomed:
        shutil.rmtree(os.path.join(save_dir, t), ignore_errors=True)
    return doomed
