"""Checkpoint save/load for engine state.

TPU-native analog of the reference's checkpoint layer (engine.py:1329
save_checkpoint / :1173 load_checkpoint; ZeRO elastic merge-then-repartition
stage2.py:1713-1779). Layout under ``<save_dir>/<tag>/``:

- ``model_states.npz``  : master params (+ counters, lr-sched, client state
                          in ``meta.json``) — reference mp_rank_XX_model_states.pt
- ``optim_states.npz``  : optimizer + loss-scale state — reference
                          zero_pp_rank_*_optim_states.pt
- ``meta.json``         : step counters, client state, leaf manifest
- ``<save_dir>/latest`` : tag pointer (reference writes the same file)

Elastic resharding is free by construction: arrays are saved as *global*
(unsharded) host arrays and re-``device_put`` with whatever sharding the new
mesh/world prescribes on load — the reference's merge-then-repartition dance
collapses into sharding assignment.
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

LATEST = "latest"


def _flatten_named(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path)
        flat[key or "_root"] = leaf
    return flat


def _to_host_global(v):
    """Fetch a (possibly multi-host-sharded) array as a full host array."""
    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        # multi-host pod: shards live on other processes; gather first
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            v, tiled=True))
    return np.asarray(jax.device_get(v))


def save_tree(path: str, tree: Any) -> None:
    """Gather a (possibly sharded) pytree to host and save as npz."""
    named = _flatten_named(tree)
    arrays = {}
    for k, v in named.items():
        if hasattr(v, "shape"):
            arr = _to_host_global(v)
        else:
            arr = np.asarray(v)
        # npz cannot round-trip ml_dtypes (bfloat16/fp8 — void-kind dtypes
        # reload as raw |V bytes): store widened; load_tree's
        # astype(leaf.dtype) narrows back on restore
        if arr.dtype.kind == "V":
            arr = arr.astype(np.float32)
        arrays[k] = arr
    np.savez(path, **arrays)


def load_tree(path: str, template: Any, shardings: Optional[Any] = None) -> Any:
    """Load arrays and restore into the template's structure, placing each
    leaf with the template's (or given) sharding — this is the elastic
    repartition step."""
    data = np.load(path)
    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(leaves_paths))
    out = []
    for (path_elems, leaf), shd in zip(leaves_paths, shard_leaves):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path_elems) or "_root"
        if key not in data:
            raise KeyError(f"checkpoint missing leaf '{key}'")
        arr = data[key]
        if hasattr(leaf, "shape") and tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch for '{key}': ckpt {arr.shape} vs "
                f"model {leaf.shape}")
        if hasattr(leaf, "dtype"):
            arr = arr.astype(leaf.dtype)
        if shd is None and hasattr(leaf, "sharding"):
            shd = leaf.sharding
        out.append(jax.device_put(arr, shd) if shd is not None else arr)
    return treedef.unflatten(out)


def write_meta(ckpt_dir: str, meta: Dict) -> None:
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)


def read_meta(ckpt_dir: str) -> Dict:
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        return json.load(f)


def write_latest(save_dir: str, tag: str) -> None:
    with open(os.path.join(save_dir, LATEST), "w") as f:
        f.write(tag)


def read_latest(save_dir: str) -> Optional[str]:
    p = os.path.join(save_dir, LATEST)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        return f.read().strip()
