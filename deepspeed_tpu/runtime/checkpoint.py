"""Checkpoint save/load for engine state.

TPU-native analog of the reference's checkpoint layer (engine.py:1329
save_checkpoint / :1173 load_checkpoint; ZeRO elastic merge-then-repartition
stage2.py:1713-1779). Layout under ``<save_dir>/<tag>/``:

- ``model_states.shard_<p>.npz`` + ``.json`` : this process's device shards
  of the master params, with a chunk manifest (global index per chunk) —
  reference mp_rank_XX_model_states.pt + zero_pp_rank_* partition files
- ``optim_states.shard_<p>.npz`` + ``.json`` : optimizer + loss-scale state
- ``meta.json``         : step counters, client state
- ``<save_dir>/latest`` : tag pointer (reference writes the same file)

No process ever materializes the global state: saving writes only local
replica-0 shards; loading reassembles through ``make_array_from_callback``
so each device reads only the manifest chunks overlapping its own shard of
the *new* sharding. Elastic resharding across dp/mesh changes (the
reference's merge-then-repartition, stage2.py:1713-1779) is therefore the
default load path, at O(local shard) host memory. ``save_tree``/
``load_tree`` remain for small replicated host state and legacy files.
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

LATEST = "latest"


def _flatten_named(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path)
        flat[key or "_root"] = leaf
    return flat


def _to_host_global(v):
    """Fetch a (possibly multi-host-sharded) array as a full host array."""
    if hasattr(v, "is_fully_addressable") and not v.is_fully_addressable:
        # multi-host pod: shards live on other processes; gather first
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            v, tiled=True))
    return np.asarray(jax.device_get(v))


def save_tree(path: str, tree: Any) -> None:
    """Gather a (possibly sharded) pytree to host and save as npz."""
    named = _flatten_named(tree)
    arrays = {}
    for k, v in named.items():
        if hasattr(v, "shape"):
            arr = _to_host_global(v)
        else:
            arr = np.asarray(v)
        # npz cannot round-trip ml_dtypes (bfloat16/fp8 — void-kind dtypes
        # reload as raw |V bytes): store widened; load_tree's
        # astype(leaf.dtype) narrows back on restore
        if arr.dtype.kind == "V":
            arr = arr.astype(np.float32)
        arrays[k] = arr
    np.savez(path, **arrays)


def load_tree(path: str, template: Any, shardings: Optional[Any] = None) -> Any:
    """Load arrays and restore into the template's structure, placing each
    leaf with the template's (or given) sharding — this is the elastic
    repartition step."""
    data = np.load(path)
    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(leaves_paths))
    out = []
    for (path_elems, leaf), shd in zip(leaves_paths, shard_leaves):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                       for p in path_elems) or "_root"
        if key not in data:
            raise KeyError(f"checkpoint missing leaf '{key}'")
        arr = data[key]
        if hasattr(leaf, "shape") and tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"shape mismatch for '{key}': ckpt {arr.shape} vs "
                f"model {leaf.shape}")
        if hasattr(leaf, "dtype"):
            arr = arr.astype(leaf.dtype)
        if shd is None and hasattr(leaf, "sharding"):
            shd = leaf.sharding
        out.append(jax.device_put(arr, shd) if shd is not None else arr)
    return treedef.unflatten(out)


# --------------------------------------------------------------------- #
# sharded (per-process) checkpoint format
#
# Reference DeepSpeed writes per-dp-rank ZeRO partition files
# (engine.py:1153-1164,1409-1413 zero_pp_rank_X_mp_rank_XX_optim_states.pt)
# precisely so no rank ever has to hold the full fp32 state. The TPU-native
# analog: every *process* writes only its addressable, replica-0 device
# shards to ``<name>.shard_<p>.npz`` plus a JSON chunk manifest
# ``<name>.shard_<p>.json`` recording each chunk's global index. Loading
# uses ``jax.make_array_from_callback`` so each device reads only the
# chunks overlapping its own shard of the *new* sharding — elastic
# resharding across dp/mesh changes (reference merge-then-repartition,
# stage2.py:1713-1779) without a host-0 gather on either side.
# --------------------------------------------------------------------- #

def _norm_bounds(index, shape):
    """Normalize a tuple of slices to (start, stop) int lists."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        b, e, step = sl.indices(dim)
        assert step == 1, "strided checkpoint shards unsupported"
        starts.append(int(b))
        stops.append(int(e))
    return starts, stops


def save_tree_sharded(ckpt_dir: str, name: str, tree: Any) -> None:
    """Write this process's shards of a (possibly sharded) pytree.

    Every process calls this; each writes exactly one ``.npz`` + one
    ``.json`` fragment containing only data it owns (replica 0 of each
    device shard), so no cross-process communication or full-array
    host materialization ever happens.
    """
    pidx = jax.process_index()
    named = _flatten_named(tree)
    arrays: Dict[str, np.ndarray] = {}
    manifest: Dict[str, Any] = {}
    for key, v in named.items():
        if not hasattr(v, "addressable_shards"):
            # host scalar / numpy leaf: replicated; process 0 records it
            arr = np.asarray(v)
            entry = {"global_shape": list(arr.shape),
                     "dtype": str(arr.dtype), "chunks": []}
            if pidx == 0:
                ek = f"{key}::0"
                a = arr.astype(np.float32) if arr.dtype.kind == "V" else arr
                arrays[ek] = a
                entry["chunks"].append({
                    "entry": ek,
                    "start": [0] * arr.ndim,
                    "stop": list(arr.shape)})
            manifest[key] = entry
            continue
        entry = {"global_shape": list(v.shape), "dtype": str(v.dtype),
                 "chunks": []}
        n = 0
        for sh in v.addressable_shards:
            if sh.replica_id != 0:
                continue  # replicated copy: one writer is enough
            data = np.asarray(sh.data)
            if data.dtype.kind == "V":  # bf16/fp8: npz can't round-trip
                data = data.astype(np.float32)
            ek = f"{key}::{n}"
            n += 1
            arrays[ek] = data
            starts, stops = _norm_bounds(sh.index, v.shape)
            entry["chunks"].append({"entry": ek, "start": starts,
                                    "stop": stops})
        manifest[key] = entry
    np.savez(os.path.join(ckpt_dir, f"{name}.shard_{pidx}.npz"), **arrays)
    with open(os.path.join(ckpt_dir, f"{name}.shard_{pidx}.json"),
              "w") as f:
        json.dump(manifest, f)


def sharded_exists(ckpt_dir: str, name: str) -> bool:
    return os.path.isfile(os.path.join(ckpt_dir, f"{name}.shard_0.json"))


def _merged_manifest(ckpt_dir: str, name: str):
    """Merge all processes' manifest fragments into
    {leaf: (shape, dtype, [(file, entry, start, stop), ...])}."""
    import glob
    merged: Dict[str, Any] = {}
    frags = sorted(glob.glob(
        os.path.join(ckpt_dir, f"{name}.shard_*.json")))
    if not frags:
        raise FileNotFoundError(
            f"no {name}.shard_*.json manifests in {ckpt_dir}")
    for fpath in frags:
        npz = fpath[:-len(".json")] + ".npz"
        with open(fpath) as f:
            frag = json.load(f)
        for key, entry in frag.items():
            tgt = merged.setdefault(
                key, (tuple(entry["global_shape"]), entry["dtype"], []))
            for c in entry["chunks"]:
                tgt[2].append((npz, c["entry"],
                               tuple(c["start"]), tuple(c["stop"])))
    return merged


def load_tree_sharded(ckpt_dir: str, name: str, template: Any,
                      shardings: Optional[Any] = None) -> Any:
    """Reassemble a sharded checkpoint under *new* shardings.

    Each leaf is built with ``jax.make_array_from_callback``: the callback
    reads, per device shard, only the saved chunks overlapping that
    shard's index — the elastic repartition (reference
    stage2.py:1713-1779) without ever materializing the global array.
    """
    merged = _merged_manifest(ckpt_dir, name)
    npz_cache: Dict[str, Any] = {}

    def chunk(npz_path, entry):
        if npz_path not in npz_cache:
            npz_cache[npz_path] = np.load(npz_path)
        return npz_cache[npz_path][entry]

    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    shard_leaves = (jax.tree_util.tree_leaves(shardings)
                    if shardings is not None else [None] * len(leaves_paths))
    out = []
    for (path_elems, leaf), shd in zip(leaves_paths, shard_leaves):
        key = "/".join(str(getattr(p, "key", getattr(p, "idx",
                                                     getattr(p, "name", p))))
                       for p in path_elems) or "_root"
        if key not in merged:
            raise KeyError(f"checkpoint missing leaf '{key}'")
        gshape, _dty, chunks = merged[key]
        if hasattr(leaf, "shape") and tuple(gshape) != tuple(leaf.shape):
            raise ValueError(f"shape mismatch for '{key}': ckpt {gshape} "
                             f"vs model {tuple(leaf.shape)}")
        dtype = leaf.dtype if hasattr(leaf, "dtype") else np.asarray(leaf).dtype

        def read(index, _gshape=gshape, _chunks=chunks, _dtype=dtype,
                 _key=key):
            starts, stops = _norm_bounds(index, _gshape)
            shp = [e - b for b, e in zip(starts, stops)]
            buf = np.empty(shp, dtype=_dtype)
            filled = 0
            for npz_path, entry, cs, ce in _chunks:
                ob = [max(b, b2) for b, b2 in zip(starts, cs)]
                oe = [min(e, e2) for e, e2 in zip(stops, ce)]
                if any(b >= e for b, e in zip(ob, oe)):
                    continue
                data = chunk(npz_path, entry)
                src = tuple(slice(b - b2, e - b2)
                            for b, e, b2 in zip(ob, oe, cs))
                dst = tuple(slice(b - b2, e - b2)
                            for b, e, b2 in zip(ob, oe, starts))
                buf[dst] = data[src].astype(_dtype)
                filled += int(np.prod([e - b for b, e in zip(ob, oe)]))
            want = int(np.prod(shp)) if shp else 1
            if filled != want:
                raise ValueError(
                    f"incomplete checkpoint coverage for '{_key}': "
                    f"{filled}/{want} elements (missing shard files?)")
            return buf

        if shd is None and hasattr(leaf, "sharding"):
            shd = leaf.sharding
        if shd is not None and hasattr(leaf, "shape"):
            out.append(jax.make_array_from_callback(
                tuple(gshape), shd, lambda idx, _r=read: _r(idx)))
        else:
            full = read(tuple(slice(0, d) for d in gshape))
            out.append(full if gshape else full[()])
    return treedef.unflatten(out)


def write_meta(ckpt_dir: str, meta: Dict) -> None:
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2, default=str)


def read_meta(ckpt_dir: str) -> Dict:
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        return json.load(f)


def write_latest(save_dir: str, tag: str) -> None:
    with open(os.path.join(save_dir, LATEST), "w") as f:
        f.write(tag)


def read_latest(save_dir: str) -> Optional[str]:
    p = os.path.join(save_dir, LATEST)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        return f.read().strip()
