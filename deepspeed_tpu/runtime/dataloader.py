"""Data loading: repeating + distributed-sharded + prefetching loaders.

TPU-native analog of the reference's ``deepspeed/runtime/dataloader.py``
(RepeatingLoader :10, DeepSpeedDataLoader :33 which auto-installed a
DistributedSampler per dp rank). Under single-controller SPMD we instead
device_put each host batch with a NamedSharding over the ``data`` axis — the
global batch is laid out across chips in one call; no sampler zoo.

:class:`PrefetchLoader` is the async-pipeline input stage
(docs/performance.md "Async step pipeline"): a background thread pulls
host batches, optionally stacks ``stack_micros`` of them to the
``(gas, ...)`` layout the scan-fused batch step consumes, and issues the
sharded ``device_put`` — so H2D transfer for batch N+1 overlaps device
compute of batch N instead of serializing in front of the dispatch.
"""

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    dataloader.py:10)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def stack_micro_batches(micros):
    """Stack a list of micro-batch pytrees on a new leading axis (the
    ``(gas, ...)`` layout the scan-fused batch step scans over). Leaves
    are pulled to host (``np.asarray``) — callers feeding device arrays
    pay a D2H; the prefetch/train paths stack host batches."""
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *micros)


def normalize_eval_input(batch_or_iter, micro_batches: int = 1):
    """One eval API shape for both engines (the base engine historically
    took a batch pytree, the pipe engine an iterator): accept either and
    return an iterator of micro batches.

    A ``list`` whose elements are all containers (dict/tuple/list) is
    read as a SEQUENCE of micro batches — the pipe engine previously
    raised TypeError on lists, and stacking one as a single batch would
    be silently wrong. A list of array leaves (e.g. ``[inputs,
    targets]``) stays a single batch pytree, as the base engine always
    accepted. A single batch pytree is repeated to fill a multi-micro
    window — the mean loss over identical micros equals that batch's
    loss."""
    if hasattr(batch_or_iter, "__next__"):
        return batch_or_iter
    if hasattr(batch_or_iter, "__iter__") and \
            not isinstance(batch_or_iter, (dict, tuple, list)) and \
            not hasattr(batch_or_iter, "shape"):
        # a loader-like iterable (has __iter__, is no container/array
        # pytree): iterate it — replicating the object itself would
        # reach jax as an opaque non-array leaf and crash far away
        return iter(batch_or_iter)
    if isinstance(batch_or_iter, list) and batch_or_iter and \
            all(isinstance(m, (dict, tuple, list))
                for m in batch_or_iter):
        global _WARNED_LIST_EVAL
        if not _WARNED_LIST_EVAL:
            _WARNED_LIST_EVAL = True
            from deepspeed_tpu.utils.logging import logger
            logger.info(
                "eval_batch: a list of containers is interpreted as a "
                "sequence of micro batches; pass a tuple/dict pytree "
                "for a single list-structured batch")
        return iter(batch_or_iter)
    return iter([batch_or_iter] * max(int(micro_batches), 1))


_WARNED_LIST_EVAL = False


class DeepSpeedDataLoader:
    """Yields device-sharded global batches.

    ``dataset`` is any indexable of pytrees (dict/tuple of numpy arrays) or
    an iterable of already-batched pytrees. When ``mesh`` is given, each
    batch's leading dim is sharded over ``batch_axis``.
    """

    def __init__(self, dataset, batch_size: int, mesh=None,
                 batch_axis: str = "data", shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh = mesh
        self.batch_axis = batch_axis
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.data_sampler = data_sampler
        self._epoch = 0
        # the mesh is fixed at construction, so the NamedSharding is too —
        # cache it instead of rebuilding per batch
        self._cached_sharding = self._build_sharding()
        # the engine's prefetch stage flips this off and owns the H2D
        # itself (its worker thread device_puts with the same sharding)
        self.device_put_enabled = True
        try:
            n = len(dataset)
            self.len = (n // batch_size if drop_last
                        else -(-n // batch_size))
        except TypeError:
            self.len = None

    def __len__(self):
        if self.len is None:
            raise TypeError("underlying dataset has no length")
        return self.len

    def _build_sharding(self):
        if self.mesh is None:
            return None
        if self.batch_axis not in self.mesh.axis_names:
            if self.batch_axis == "data":
                # hierarchical data mesh: the batch splits over BOTH
                # data sub-axes (parallel.mesh.data_sharding)
                from deepspeed_tpu.parallel.mesh import (data_axis_names,
                                                         data_sharding)
                if data_axis_names(self.mesh):
                    return data_sharding(self.mesh)
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(self.batch_axis))

    def _sharding(self):
        return self._cached_sharding

    def _put(self, batch):
        sharding = self._cached_sharding
        if sharding is None or not self.device_put_enabled:
            return batch
        return jax.tree_util.tree_map(
            lambda x: _put_leaf(x, sharding), batch)

    def __iter__(self) -> Iterator[Any]:
        if hasattr(self.dataset, "__getitem__") and self.len is not None:
            n_total = len(self.dataset)
            n = (self.len * self.batch_size if self.drop_last else n_total)
            order = np.arange(n_total)
            if self.shuffle:
                rng = np.random.RandomState(self.seed + self._epoch)
                rng.shuffle(order)
            self._epoch += 1
            for i in range(0, n, self.batch_size):
                idx = order[i:i + self.batch_size]
                items = [self.dataset[int(j)] for j in idx]
                if self.collate_fn is not None:
                    batch = self.collate_fn(items)
                else:
                    batch = jax.tree_util.tree_map(
                        lambda *xs: np.stack(xs), *items)
                yield self._put(batch)
        else:
            for batch in self.dataset:
                yield self._put(batch)


def _put_leaf(x, sharding):
    """Sharded device_put that skips leaves already resident in the
    target layout (a re-put of a committed same-sharding jax.Array is
    pure overhead — a copy at best)."""
    if isinstance(x, jax.Array):
        try:
            if x.sharding == sharding:
                return x
        except Exception:
            pass
        return jax.device_put(x, sharding)
    return jax.device_put(np.asarray(x), sharding)


class PrefetchLoader:
    """Background-prefetching, device-putting wrapper around any batch
    iterable.

    A worker thread pulls host batches from ``loader``, stacks groups of
    ``stack_micros`` micro-batches to a ``(stack_micros, ...)`` leading
    layout (``stack_micros=1`` passes batches through unstacked), and —
    when ``sharding`` is given — issues the sharded ``device_put``. The
    consumer therefore always finds the next batch already on device:
    H2D for batch N+1 overlaps compute of batch N. ``depth`` bounds the
    number of prepared batches in flight (double buffering by default).

    ``put_fn`` (a callable ``batch -> device batch``) overrides the
    plain sharded put — the engines pass their guarded put so undersized
    or scalar leaves degrade to replication exactly as they do on the
    non-prefetched path, instead of crashing the worker thread.
    ``stack_always=True`` stacks even a group of one (the pipe engine's
    ``(M=1, batch, ...)`` window layout).

    Lifecycle: the thread starts lazily on first ``__next__``, dies on
    iterator exhaustion (a partial trailing group of fewer than
    ``stack_micros`` micros is dropped, drop_last-style), and is joined
    by :meth:`close` / ``__del__`` — no thread leak. Exceptions raised
    in the worker propagate to the consumer's ``next()`` call.
    Re-iterating after exhaustion restarts from ``iter(loader)``.
    """

    def __init__(self, loader: Iterable, sharding=None, depth: int = 2,
                 stack_micros: int = 1, put_fn: Optional[Callable] = None,
                 stack_always: bool = False):
        self.loader = loader
        self.sharding = sharding
        self.put_fn = put_fn
        self.depth = max(int(depth), 1)
        self.stack_micros = max(int(stack_micros), 1)
        self.stack_always = bool(stack_always)
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._failed: Optional[BaseException] = None

    @property
    def stacks_micro_batches(self) -> bool:
        """True when this loader yields pre-stacked ``(gas, ...)``
        batches (the engines' fused/pipe paths consume them directly)."""
        return self.stack_micros > 1 or self.stack_always

    # ------------------------------------------------------------ worker
    def _enqueue(self, item) -> bool:
        """Blocking put that stays responsive to close(); False when the
        loader is shutting down (drop the item, exit the worker)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, it):
        try:
            while not self._stop.is_set():
                micros = []
                for _ in range(self.stack_micros):
                    try:
                        micros.append(next(it))
                    except StopIteration:
                        break
                if len(micros) < self.stack_micros:
                    self._enqueue(("end", None))
                    return
                batch = (stack_micro_batches(micros)
                         if self.stacks_micro_batches else micros[0])
                if self.put_fn is not None:
                    batch = self.put_fn(batch)
                elif self.sharding is not None:
                    batch = jax.tree_util.tree_map(
                        lambda x: _put_leaf(x, self.sharding), batch)
                if not self._enqueue(("item", batch)):
                    return
            # stop requested: fall through without an "end" marker —
            # close() owns the shutdown
        except BaseException as e:  # propagate to the consumer
            self._enqueue(("error", e))

    def _start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._q = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(
            target=self._worker, args=(iter(self.loader),),
            name="ds-prefetch", daemon=True)
        self._thread.start()

    # ---------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._failed is not None:
            # a worker error is sticky: restarting from iter(loader)
            # would silently re-serve (and re-train on) early batches;
            # an explicit close() resets the loader
            raise self._failed
        if self._thread is None or (not self._thread.is_alive()
                                    and (self._q is None
                                         or self._q.empty())):
            self._start()
        kind, val = self._q.get()
        if kind == "item":
            return val
        if kind == "end":
            self._join()
            raise StopIteration
        self._join()
        self._failed = val
        raise val

    def _join(self):
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def close(self):
        """Stop the worker and reclaim the thread (idempotent). Batches
        already prefetched are discarded; a sticky worker error is
        cleared (close is the explicit reset)."""
        self._failed = None
        self._stop.set()
        q = self._q
        if q is not None:
            try:  # unblock a worker waiting on a full queue
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        self._join()
        self._q = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
