"""Data loading: repeating + distributed-sharded loaders.

TPU-native analog of the reference's ``deepspeed/runtime/dataloader.py``
(RepeatingLoader :10, DeepSpeedDataLoader :33 which auto-installed a
DistributedSampler per dp rank). Under single-controller SPMD we instead
device_put each host batch with a NamedSharding over the ``data`` axis — the
global batch is laid out across chips in one call; no sampler zoo.
"""

from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    dataloader.py:10)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


class DeepSpeedDataLoader:
    """Yields device-sharded global batches.

    ``dataset`` is any indexable of pytrees (dict/tuple of numpy arrays) or
    an iterable of already-batched pytrees. When ``mesh`` is given, each
    batch's leading dim is sharded over ``batch_axis``.
    """

    def __init__(self, dataset, batch_size: int, mesh=None,
                 batch_axis: str = "data", shuffle: bool = True,
                 seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh = mesh
        self.batch_axis = batch_axis
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.data_sampler = data_sampler
        self._epoch = 0
        try:
            n = len(dataset)
            self.len = (n // batch_size if drop_last
                        else -(-n // batch_size))
        except TypeError:
            self.len = None

    def __len__(self):
        if self.len is None:
            raise TypeError("underlying dataset has no length")
        return self.len

    def _sharding(self):
        if self.mesh is None:
            return None
        if self.batch_axis not in self.mesh.axis_names:
            if self.batch_axis == "data":
                # hierarchical data mesh: the batch splits over BOTH
                # data sub-axes (parallel.mesh.data_sharding)
                from deepspeed_tpu.parallel.mesh import (data_axis_names,
                                                         data_sharding)
                if data_axis_names(self.mesh):
                    return data_sharding(self.mesh)
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(self.batch_axis))

    def _put(self, batch):
        sharding = self._sharding()
        if sharding is None:
            return batch
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x), sharding), batch)

    def __iter__(self) -> Iterator[Any]:
        if hasattr(self.dataset, "__getitem__") and self.len is not None:
            n_total = len(self.dataset)
            n = (self.len * self.batch_size if self.drop_last else n_total)
            order = np.arange(n_total)
            if self.shuffle:
                rng = np.random.RandomState(self.seed + self._epoch)
                rng.shuffle(order)
            self._epoch += 1
            for i in range(0, n, self.batch_size):
                idx = order[i:i + self.batch_size]
                items = [self.dataset[int(j)] for j in idx]
                if self.collate_fn is not None:
                    batch = self.collate_fn(items)
                else:
                    batch = jax.tree_util.tree_map(
                        lambda *xs: np.stack(xs), *items)
                yield self._put(batch)
        else:
            for batch in self.dataset:
                yield self._put(batch)
