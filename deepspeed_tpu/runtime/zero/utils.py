"""ZeRO utilities (reference deepspeed/runtime/zero/utils.py).

The reference gates ZeRO on a torch-optimizer allowlist and builds
parameter-parallel NCCL groups; here the allowlist maps to our
optimizer classes and "parameter parallelism" IS the mesh's 'data'
axis sharding (runtime/zero/sharding.py) — there are no groups to
build, so the group helper returns the axis name it would shard over.
"""

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.optimizers import Adam, Adam8bit, FusedAdam, Lamb, SGD
from deepspeed_tpu.utils.logging import logger

ZERO_SUPPORTED_OPTIMIZERS = [Adam, Adam8bit, FusedAdam, Lamb, SGD,
                             DeepSpeedCPUAdam]


def is_zero_supported_optimizer(optimizer) -> bool:
    """(reference zero/utils.py is_zero_supported_optimizer)"""
    logger.info(
        f"Checking ZeRO support for optimizer="
        f"{optimizer.__class__.__name__} type={type(optimizer)}")
    return type(optimizer) in ZERO_SUPPORTED_OPTIMIZERS


def _initialize_parameter_parallel_groups(parameter_parallel_size=None):
    """Reference analog (zero/utils.py:8): with GSPMD there is no group
    object to construct — optimizer state shards over the 'data' mesh
    axis. Kept for API compatibility; returns the axis name."""
    del parameter_parallel_size
    return "data"
