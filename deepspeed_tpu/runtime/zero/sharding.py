"""ZeRO = sharding rules over the ``data`` mesh axis.

The reference implements ZeRO with ~2.8 kLoC of hook-and-bucket machinery
(``runtime/zero/stage1.py``, ``stage2.py``): flatten params, partition,
register per-param backward hooks, bucket reductions onto a side stream,
reduce-to-owner, step on the local partition, allgather updated params.
All of that exists because PyTorch is eager.

Under XLA/GSPMD the whole dance is a *sharding assignment*: give the fp32
master params + optimizer state (and, for stage 2, the gradient accumulator)
a NamedSharding over the ``data`` axis, and the compiler emits exactly the
ZeRO communication pattern inside the one compiled train step —
reduce-scatter of grads to the owning shard, sharded optimizer math, and an
all-gather of updated params where the next forward needs them — scheduled
with overlap by XLA's latency-hiding scheduler (the reference's
``overlap_comm`` stream juggling, stage2.py:291-294, for free).

Stage map (reference zero/constants.py:28-40 caps at 2; stage 3 is a
TPU-native extension here):
- stage 0: everything replicated (plain DP)
- stage 1: optimizer state + fp32 master sharded (stage1.py sub-partitions)
- stage 2: + gradient accumulator sharded (stage2.py grad partitioning)
- stage 3: same persistent shardings as stage 2, but the engine skips the
  up-front compute-dtype cast (engine._cast_for_loss), so no replicated
  full-parameter transient is ever materialized: weights are gathered +
  cast at their use sites, per layer, and rematerialized blocks re-gather
  in backward — the param-sharded-forward lifecycle as a GSPMD schedule.
"""

from typing import Any, Optional, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import axis_size

# a ZeRO shard axis is either one mesh axis name or — on a hierarchical
# data mesh — the ('data_inter', 'data_intra') tuple, which PartitionSpec
# accepts as a single composite dim entry
AxisName = Union[str, Tuple[str, ...]]


def _axes_size(mesh: Mesh, axis_name: AxisName) -> int:
    if isinstance(axis_name, str):
        return axis_size(mesh, axis_name)
    n = 1
    for a in axis_name:
        n *= axis_size(mesh, a)
    return n


def leaf_partition_spec(shape, axis_name: AxisName, axis_n: int,
                        model_spec: Optional[PartitionSpec] = None
                        ) -> PartitionSpec:
    """Choose a PartitionSpec that shards one array over ``axis_name``
    (one mesh axis, or a tuple of axes sharding a single dim over their
    product — the hierarchical data mesh).

    Picks the first dimension divisible by the axis size that is not already
    taken by ``model_spec`` (tensor-parallel sharding); falls back to
    replication for small/indivisible leaves (cheap: they are tiny).
    """
    base = list(model_spec) if model_spec is not None else []
    base += [None] * (len(shape) - len(base))
    for i, d in enumerate(shape):
        if base[i] is None and d % axis_n == 0 and d >= axis_n:
            base[i] = axis_name
            return PartitionSpec(*base)
    return PartitionSpec(*base) if model_spec is not None else PartitionSpec()


def zero_shardings(tree: Any, mesh: Mesh, stage: int,
                   axis_name: AxisName = "data",
                   model_specs: Optional[Any] = None) -> Any:
    """NamedSharding pytree for optimizer state / master params.

    ``model_specs`` optionally carries per-leaf tensor-parallel
    PartitionSpecs to compose with (ZeRO over 'data' × TP over 'model').
    """
    n = _axes_size(mesh, axis_name)

    def one(leaf, mspec=None):
        if not hasattr(leaf, "shape") or leaf.ndim == 0 or stage < 1 or n == 1:
            return NamedSharding(mesh, mspec if mspec is not None
                                 else PartitionSpec())
        return NamedSharding(
            mesh, leaf_partition_spec(leaf.shape, axis_name, n, mspec))

    if model_specs is None:
        return jax.tree_util.tree_map(one, tree)
    return jax.tree_util.tree_map(one, tree, model_specs)


def replicated_shardings(tree: Any, mesh: Mesh,
                         model_specs: Optional[Any] = None) -> Any:
    def one(leaf, mspec=None):
        return NamedSharding(mesh, mspec if mspec is not None
                             else PartitionSpec())
    if model_specs is None:
        return jax.tree_util.tree_map(one, tree)
    return jax.tree_util.tree_map(one, tree, model_specs)
