"""Shared runtime utilities (reference ``deepspeed/runtime/utils.py``).

TPU-native re-design notes:
- ``PartitionedTensor`` (ref ``:379``) — the pipe×MP activation-dedup
  mechanism — becomes a thin wrapper over in-jit ``lax.all_gather`` when used
  under ``shard_map`` (axis names replace process groups), and a pure
  host-side scatter/gather when used eagerly. The CSR-rowptr meta encoding
  (``to_meta``/``from_meta``, ref ``:458``) is kept verbatim so pipeline
  stages can hand partitioned activations across the wire.
- ``CheckOverflow`` (ref ``:41``) — inf/nan detection is a reduction over
  the grad pytree; the MP-group MAX-allreduce (ref ``:92-99``) becomes a
  ``lax.pmax`` over the named axis when called inside ``shard_map``; on
  global (addressable) arrays the values are already global so no collective
  is needed.
- ``get_grad_norm``/``get_weight_norm`` (ref ``:154,212``) — pytree norms;
  under GSPMD a global array's norm is already the model-parallel-correct
  value, so the reference's "avoid double counting replicated params" rank-0
  filter (ref ``:171-177``) is unnecessary by construction.
- ``memory_status``/``see_memory_usage`` (ref ``:489,531``) — read TPU HBM
  stats from ``device.memory_stats()`` and host RSS from ``resource``.
"""

import os
import random
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.partition import (  # re-export (ref :282-378)
    partition_balanced, partition_uniform, prefix_sum_inc)

__all__ = [
    "ensure_directory_exists", "set_random_seed", "CheckOverflow",
    "get_grad_norm", "get_weight_norm", "global_norm",
    "partition_uniform", "partition_balanced", "prefix_sum_inc",
    "PartitionedTensor", "memory_status", "see_memory_usage", "call_to_str",
]


def ensure_directory_exists(filename: str):
    """mkdir -p the parent of ``filename`` (ref ``:23``)."""
    dirname = os.path.dirname(filename)
    if dirname:
        os.makedirs(dirname, exist_ok=True)


def set_random_seed(seed: int):
    """Seed python/numpy RNGs and return a JAX PRNG key (ref ``:33`` seeds
    torch; JAX RNG is functional so the key is returned, not installed)."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def _leaves(tree) -> List[jax.Array]:
    return [x for x in jax.tree_util.tree_leaves(tree)
            if hasattr(x, "dtype") and jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.inexact)]


def _axis_reduce_max(flag: jax.Array, axis_names: Sequence[str]):
    """MAX-reduce a boolean over named mesh axes when they are bound (i.e.
    inside shard_map). Under plain jit on global arrays the axes are unbound
    — the value is already global, so the reduction is skipped."""
    for ax in axis_names:
        if not isinstance(flag, jax.core.Tracer):
            break  # concrete: nothing to reduce over
        try:
            flag = jax.lax.pmax(flag.astype(jnp.int32), ax) > 0
        except NameError:  # unbound axis: plain jit over global arrays
            break
    return flag


class CheckOverflow:
    """Inf/nan detection across the grad pytree (ref ``:41``).

    ``axis_names``: mesh axes to MAX-reduce the flag over when invoked
    inside ``shard_map`` (the analogue of the reference's model-parallel /
    world allreduce). On global arrays no reduction is needed.
    """

    def __init__(self, param_groups=None, mpu=None,
                 zero_reduce_scatter: bool = False,
                 axis_names: Sequence[str] = ()):
        self.mpu = mpu
        self.params = param_groups
        self.zero_reduce_scatter = zero_reduce_scatter
        self.axis_names = tuple(axis_names)

    @staticmethod
    def _has_inf_or_nan(x) -> jax.Array:
        x = jnp.asarray(x)
        return ~jnp.all(jnp.isfinite(x.astype(jnp.float32)))

    def has_overflow(self, grads) -> jax.Array:
        """Boolean (traced or concrete): any non-finite value in ``grads``,
        reduced over ``axis_names`` when traced inside shard_map. The leaf
        scan delegates to the single shared implementation in
        fp16/loss_scaler.py (what the engine uses)."""
        from deepspeed_tpu.runtime.fp16.loss_scaler import has_overflow
        flag = has_overflow(
            [jnp.asarray(x) for x in _leaves(grads)])
        return _axis_reduce_max(flag, self.axis_names)

    def check(self, param_groups=None):
        groups = param_groups if param_groups is not None else self.params
        assert groups is not None, \
            "self.params and param_groups both cannot be none"
        return self.has_overflow(groups)

    def check_using_norm(self, norm_group, reduce_overflow: bool = True):
        """-1 in a norm group signals overflow (ref ``:53``)."""
        norms = jnp.stack([jnp.asarray(n, jnp.float32)
                           for n in jax.tree_util.tree_leaves(norm_group)])
        flag = jnp.any(norms == -1.0)
        return _axis_reduce_max(flag, self.axis_names)


def global_norm(tree, norm_type: float = 2.0) -> jax.Array:
    """Norm over every inexact leaf of a pytree."""
    leaves = _leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    if norm_type == float("inf"):
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(x.astype(jnp.float32))) for x in leaves]))
    norm_type = float(norm_type)
    total = sum(jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type)
                for x in leaves)
    return total ** (1.0 / norm_type)


def _guard_norm(norm: jax.Array) -> jax.Array:
    """Reference returns -1 for inf/nan norms (ref ``:205-208``)."""
    bad = ~jnp.isfinite(norm)
    return jnp.where(bad, -1.0, norm)


def get_grad_norm(gradients, norm_type: float = 2.0,
                  mpu=None) -> jax.Array:
    """Grad norm with the reference's -1-on-overflow convention
    (ref ``:154``). ``mpu`` accepted for API parity; under GSPMD the norm of
    a global array is already aggregated across model-parallel shards."""
    return _guard_norm(global_norm(gradients, norm_type))


def get_weight_norm(parameters, norm_type: float = 2.0,
                    mpu=None) -> jax.Array:
    """Weight norm (ref ``:212``), same conventions as get_grad_norm."""
    return _guard_norm(global_norm(parameters, norm_type))


class PartitionedTensor:
    """A tensor scattered 1/N over a group (ref ``:379``).

    Two modes:
    - **eager** (``axis_name=None``): operates on concrete arrays; ``full()``
      reconstructs from the locally stored part plus ``parts`` handed in by
      peers (single-controller: all parts are addressable).
    - **in-jit** (``axis_name='model'`` inside ``shard_map``): the local part
      is this shard's slice; ``full()`` is a ``lax.all_gather`` over the
      named axis — the XLA-native form of the reference's
      ``dist.all_gather`` (ref ``:449``).

    Meta encoding kept from the reference (ref ``to_meta:458``):
    ``[ndims, *shape, num_parts, rank, 0, part_1, ..., part_num_parts]``.
    """

    def __init__(self, tensor=None, num_parts: int = 1, rank: int = 0,
                 axis_name: Optional[str] = None):
        self.axis_name = axis_name
        self.num_parts = num_parts
        self.rank = rank
        if tensor is not None:
            self.orig_size = list(tensor.shape)
            self.local_data, self.partition = self._partition_tensor(tensor)
        else:
            self.orig_size = []
            self.local_data = None
            self.partition = []

    # -- construction ---------------------------------------------------- #
    def _partition_tensor(self, tensor):
        flat = jnp.ravel(tensor)
        if self.axis_name is not None:
            # in-jit: uniform padded slices so shapes are static
            numel = flat.shape[0]
            chunk = -(-numel // self.num_parts)
            padded = jnp.pad(flat, (0, chunk * self.num_parts - numel))
            idx = jax.lax.axis_index(self.axis_name)
            local = jax.lax.dynamic_slice_in_dim(padded, idx * chunk, chunk)
            partition = [min(i * chunk, numel)
                         for i in range(self.num_parts + 1)]
            return local, partition
        partition = partition_uniform(flat.shape[0], self.num_parts)
        start = partition[self.rank]
        local = flat[start:partition[self.rank + 1]]
        return local, partition

    @classmethod
    def from_meta(cls, meta, local_part, num_parts: Optional[int] = None,
                  axis_name: Optional[str] = None):
        """Rebuild from a meta vector + this rank's part (ref ``:392``)."""
        meta = [int(v) for v in np.asarray(meta).tolist()]
        ndims = meta[0]
        obj = cls(tensor=None, axis_name=axis_name)
        obj.orig_size = meta[1:1 + ndims]
        rest = meta[1 + ndims:]
        obj.num_parts = rest[0]
        obj.rank = rest[1]
        obj.partition = rest[2:]
        obj.local_data = local_part
        if num_parts is not None:
            assert obj.num_parts == num_parts
        return obj

    # -- API -------------------------------------------------------------- #
    def to_meta(self) -> np.ndarray:
        meta = [len(self.orig_size)] + list(self.orig_size)
        meta += [self.num_parts, self.rank]
        meta += list(self.partition)
        return np.asarray(meta, dtype=np.int64)

    def full_size(self):
        return tuple(self.orig_size)

    def data(self):
        return self.local_data

    def full(self, parts: Optional[List[Any]] = None):
        """Reconstruct the full tensor.

        In-jit: all_gather over ``axis_name``. Eager: concatenate ``parts``
        (or treat local_data as the whole thing when num_parts == 1).
        """
        numel = int(np.prod(self.orig_size)) if self.orig_size else 0
        if self.axis_name is not None:
            gathered = jax.lax.all_gather(self.local_data, self.axis_name,
                                          tiled=True)
            return gathered[:numel].reshape(self.full_size())
        if parts is None:
            assert self.num_parts == 1, \
                "eager full() with num_parts>1 needs all peer parts"
            parts = [self.local_data]
        assert len(parts) == self.num_parts
        flat = jnp.concatenate([jnp.ravel(p) for p in parts])
        return flat[:numel].reshape(self.full_size())


def memory_status(msg: str = "", print_rank: int = -1,
                  reset_max: bool = False):
    """Log accelerator memory stats (ref ``:489``). Returns the stats dict
    of device 0 (bytes) or None when the backend exposes none."""
    try:
        dev = jax.local_devices()[0]
        stats = dev.memory_stats() or {}
    except Exception:
        stats = {}
    if stats:
        in_use = stats.get("bytes_in_use", 0)
        peak = stats.get("peak_bytes_in_use", 0)
        limit = stats.get("bytes_limit", 0)
        logger.info(
            f"MEMSTATS {msg} device={dev.platform} "
            f"current={in_use / 2**30:.3f}GB peak={peak / 2**30:.3f}GB "
            f"limit={limit / 2**30:.3f}GB")
    else:
        logger.info(f"MEMSTATS {msg} (no device memory stats available)")
    return stats or None


def see_memory_usage(message: str = "", force: bool = True):
    """Log device + host memory usage (ref ``:531``)."""
    if not force:
        return
    memory_status(message)
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        logger.info(f"MEMSTATS {message} host max_rss={rss_kb / 2**20:.3f}GB")
    except Exception:
        pass


def call_to_str(base: str, *args, **kwargs) -> str:
    """Printable function-call string (ref ``:556``)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"
