"""PipelineEngine — the training engine for pipeline-parallel models.

TPU-native analog of the reference's ``deepspeed/runtime/pipe/engine.py``
(PipelineEngine :45, train_batch :229, eval_batch :306). The reference
subclasses DeepSpeedEngine and *interprets* a PipeSchedule instruction
stream per rank with blocking p2p; here the subclass swaps the engine's
compiled micro-step for a compiled **pipelined batch step**
(runtime/pipe/spmd.py): one dispatch covers all micro-batches, every stage,
forward + backward + optimizer — the reference's
``_exec_schedule``/``_exec_*`` handlers (:1132-1145, :480-941) collapse
into the scan the compiler schedules.

What is inherited unchanged from DeepSpeedEngine: optimizer construction,
ZeRO shardings (over 'data', composing with the 'pipe'-stacked stage
params), fp16/bf16 policy + loss scaling, LR schedules, checkpointing,
timers/throughput. Reference parity notes:

- micro_batches per train_batch = gradient_accumulation_steps (the batch
  triangle, config.py:557 — same here);
- ``_aggregate_total_loss`` (ref :374) = the psum/pmean inside the compiled
  loss;
- tied-weight grad reduction (ref :203) is the automatic psum transpose of
  replicated tied params;
- PP×ZeRO-2 composes here (grad accumulation happens inside one compiled
  step, so the reference's conflict — engine.py:751-754 — does not exist).
"""

import os
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.parallel.mesh import axis_size
from deepspeed_tpu.runtime import fault
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              PrefetchLoader,
                                              normalize_eval_input,
                                              stack_micro_batches)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine, TrainState
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.spmd import (
    PipelineSpec, build_pipeline_grad_fn, build_pipeline_loss_fn,
    microbatch_sharding, module_pipeline_spec, pipeline_param_specs)
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    """Engine over a PipelineSpec (or homogeneous PipelineModule).

    ``train_batch(data_iter)`` consumes ``micro_batches`` micro-batches,
    stacks them on a leading axis, and runs ONE compiled pipelined step.
    """

    def __init__(self, model=None, config=None, config_params=None,
                 seed: int = 0, **kwargs):
        raw = config if config is not None else config_params
        if isinstance(raw, str):
            import json as _json
            with open(raw) as f:
                raw = _json.load(f)
        assert isinstance(raw, dict), "PipelineEngine needs a config dict/path"

        # resolve the batch triangle against the data-parallel world size
        # BEFORE super().__init__: micro_batches = grad-accum steps
        # (reference pipe/engine.py:79: micro_batches = gas)
        from deepspeed_tpu.parallel.mesh import build_mesh
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        mesh_axes = raw.get("mesh", {}).get("axes")
        probe_mesh = build_mesh(mesh_axes)
        if "pipe" not in probe_mesh.axis_names or \
                axis_size(probe_mesh, "pipe") < 1:
            raise ValueError("PipelineEngine requires a 'pipe' mesh axis in "
                             "config['mesh']['axes']")
        dp = axis_size(probe_mesh, "data")
        resolved = DeepSpeedConfig(raw, world_size=dp)
        if resolved.zero_optimization_stage >= 3:
            raise ValueError(
                "ZeRO stage 3 does not compose with pipeline parallelism "
                "(the pipeline executor owns its param lifecycle; stage "
                "<= 2 shards optimizer/gradient state over 'data')")
        self.micro_batches = resolved.gradient_accumulation_steps
        self._true_train_batch_size = resolved.train_batch_size

        # the pipelined step consumes the whole accumulation window in one
        # dispatch, so the base engine runs with gas=1 (no accum buffer)
        inner = dict(raw)
        inner["gradient_accumulation_steps"] = 1
        inner["train_batch_size"] = \
            resolved.train_micro_batch_size_per_gpu * dp
        inner["train_micro_batch_size_per_gpu"] = \
            resolved.train_micro_batch_size_per_gpu

        # interleaved virtual stages: each device hosts V chunks of 1/V
        # the layers, cutting the normalized fill/drain bubble from
        # 2(S-1) to ((V-1)S + 2(S-1))/V ticks (spmd.py module docstring)
        self.num_virtual = int(raw.get("pipeline", {})
                               .get("virtual_stages", 1))
        if self.num_virtual < 1:
            raise ValueError("pipeline.virtual_stages must be >= 1")
        num_stages = axis_size(probe_mesh, "pipe") * self.num_virtual
        if isinstance(model, PipelineModule):
            self.pipeline_spec = module_pipeline_spec(model, num_stages)
            self.module = model
        elif isinstance(model, PipelineSpec):
            self.pipeline_spec = model
            self.module = None
        else:
            raise TypeError(
                "PipelineEngine model must be a PipelineModule or "
                f"PipelineSpec, got {type(model)}")
        if self.pipeline_spec.num_stages != num_stages:
            raise ValueError(
                f"spec has {self.pipeline_spec.num_stages} stages; mesh "
                f"pipe axis x virtual_stages = {num_stages}")

        params = kwargs.pop("model_parameters", None)
        if params is None:
            params = self.pipeline_spec.init(jax.random.PRNGKey(seed))
        elif self.module is not None and not (
                isinstance(params, dict) and "stages" in params):
            # flat per-layer PipelineModule params -> stacked pipeline form
            params = {"pre": {}, "stages": self.module.stack_stage_params(
                params), "post": {}}
        if self.num_virtual > 1:
            # caller-facing layout is global-stage order; the executors
            # (and checkpoints) use the interleaved at-rest layout so the
            # contiguous 'pipe' sharding lands each device's cyclic chunks
            from deepspeed_tpu.runtime.pipe.spmd import interleave_stages
            params = dict(params)
            params["stages"] = interleave_stages(
                params["stages"], axis_size(probe_mesh, "pipe"),
                self.num_virtual)
        specs = pipeline_param_specs(self.pipeline_spec, params)

        if resolved.fp16_enabled:
            compute_dtype = jnp.float16
        elif resolved.bf16_enabled:
            compute_dtype = jnp.bfloat16
        else:
            compute_dtype = None
        loss_fn = build_pipeline_loss_fn(
            self.pipeline_spec, probe_mesh, num_micro=self.micro_batches,
            remat=raw.get("pipeline", {}).get("activation_checkpoint", True),
            compute_dtype=compute_dtype, num_virtual=self.num_virtual)
        # training runs the explicit 1F1B executor (O(S) activation memory,
        # grads computed in-schedule); the forward-only wavefront above
        # remains for eval_batch
        loss_fn.grad_fn = build_pipeline_grad_fn(
            self.pipeline_spec, probe_mesh, num_micro=self.micro_batches,
            compute_dtype=compute_dtype, num_virtual=self.num_virtual)

        super().__init__(model=loss_fn, model_parameters=params,
                         param_specs=specs, config=inner, seed=seed,
                         **kwargs)
        self.num_stages = num_stages
        # the inner config runs at gas=1, but each train_batch() consumes
        # the full accumulation window — retune the throughput timer so
        # samples/sec reflects micro_batches per tick
        self.tput_timer.batch_size = (
            self._true_train_batch_size // max(self.dp_world_size, 1))
        self._batch_sharding = microbatch_sharding(self.mesh)
        log_dist(
            f"PipelineEngine: stages={num_stages} "
            f"micro_batches={self.micro_batches} "
            f"global_batch={self._true_train_batch_size}", ranks=[0])

    # the externally visible batch size is the full accumulation window
    def train_batch_size(self):
        return self._true_train_batch_size

    def _wrap_train_iter(self, it):
        """The pipelined step stacks its own micro window; the async
        prefetch stage (when configured) assembles + device_puts the
        stacked (M, ...) batch off-thread with the pipe sharding."""
        if self._prefetch_depth <= 0:
            return it
        if isinstance(self.training_dataloader, DeepSpeedDataLoader):
            self.training_dataloader.device_put_enabled = False
        # stack_always: even an M=1 window needs the leading micro axis
        # the pipelined program (and self._batch_sharding) expect
        self._prefetcher = PrefetchLoader(
            it, put_fn=self._put_stacked_batch,
            depth=self._prefetch_depth, stack_micros=self.micro_batches,
            stack_always=True)
        return self._prefetcher

    def _stack_micro_batches(self, data_iter):
        """Pull micro_batches items and stack on a new leading axis (a
        stacking PrefetchLoader already yields the (M, ...) batch)."""
        if getattr(data_iter, "stacks_micro_batches", False):
            return next(data_iter)
        micros = [next(data_iter) for _ in range(self.micro_batches)]
        return jax.device_put(stack_micro_batches(micros),
                              self._batch_sharding)

    def train_batch(self, data_iter=None) -> jnp.ndarray:
        """One full pipelined optimizer step (reference pipe/engine.py:229).

        Accepts an iterator of micro-batches (engine-style) or of
        pre-stacked (M, ...) batches is NOT supported — always micro.
        """
        if data_iter is None:
            data_iter = self._ensure_train_iter()

        self._maybe_profile_step()
        # elastic passthrough: same window-then-drain contract as the
        # base engine (runtime/elastic.py; no-op unless armed)
        fault.fire("elastic.sigterm_mid_window",
                   step=self._host_global_step)
        # health passthrough: same beat-then-armed-stall order as the
        # base engine's train_batch
        self.health.heartbeat("train_batch")
        fault.fire("health.stall", step=self._host_global_step)
        with self.observability.span("pipe/stack_batch"):
            batch = self._stack_micro_batches(data_iter)
        step_fn = self._get_compiled_micro_step()
        self.tput_timer.start()
        import time as _time
        _t0 = _time.perf_counter()
        if self._window_anchor is None:
            self._window_anchor = _t0   # see base train_batch
        with self.observability.span("pipe/train_batch"):
            self.state, loss = step_fn(self.state, batch)
        self.tput_timer.stop()
        self._last_step_time_ms = (_time.perf_counter() - _t0) * 1e3
        self._host_micro_step += self.micro_batches
        self._host_global_step += 1
        # the pipelined program consumes the WHOLE accumulation window in
        # one dispatch, so its cost profile is already per optimizer step
        if self.observability.wants_flops_profile("micro_step"):
            self.observability.maybe_profile_flops(
                "micro_step", step_fn, (self.state, batch),
                samples=self._host_global_step * self.train_batch_size())
        self._report_progress()
        self._write_monitor(loss)  # tensorboard (reference pipe :283-292)
        self._elastic_boundary()
        return loss

    def eval_batch(self, data_iter) -> jnp.ndarray:
        """Pipelined forward-only loss (reference pipe/engine.py:306) —
        realizes InferenceSchedule's wavefront (the same scan, no grad).
        Accepts an iterator of micro batches or — like the base engine —
        a single batch pytree (repeated across the micro window; the
        mean loss over identical micros equals that batch's loss)."""
        self._drain_saves()   # eval barrier: pending async saves land
        if self._monitor_ring:
            self._flush_monitor()   # eval is an explicit sync point
        if not hasattr(self, "_compiled_pipe_eval"):
            def ev(params, batch, rng):
                return self._loss_fn(self._cast_for_loss(params), batch, rng)
            self._compiled_pipe_eval = self.observability.wrap_jit(
                jax.jit(ev), "pipe_eval")
        data_iter = normalize_eval_input(data_iter, self.micro_batches)
        batch = self._stack_micro_batches(data_iter)
        with self.observability.span("pipe/eval_batch"):
            return self._compiled_pipe_eval(self.state.params, batch,
                                            self.state.rng)

    # ---------------- checkpoint layout portability ----------------- #
    # stage weights are stored in the V-dependent interleaved layout
    # (spmd.py module docstring); a resume at a different pipe width or
    # virtual_stages must re-permute or every device silently runs the
    # wrong layers' weights. save records the layout; load converts.

    def _stage_order(self):
        from deepspeed_tpu.runtime.pipe.spmd import interleave_stage_order
        S = axis_size(self.mesh, "pipe")
        return interleave_stage_order(S, self.num_virtual)

    def _save_checkpoint_extras(self, ckpt_dir: str) -> None:
        # written into the staging dir and sealed by the COMMITTED marker
        # alongside the shards: a V>1 checkpoint can never become visible
        # without its layout file and be misread as V=1 (mis-permuted);
        # atomic+fsync'd like every other committed file so the marker's
        # recorded size/CRC can't outlive the bytes on power loss
        import json as _json
        from deepspeed_tpu.runtime import checkpoint as _ckpt
        _ckpt._atomic_write_bytes(
            os.path.join(ckpt_dir, "pipe_layout.json"),
            _json.dumps({"pipe_axis": axis_size(self.mesh, "pipe"),
                         "virtual_stages": self.num_virtual}).encode())

    def load_checkpoint(self, load_dir: str, tag=None, **kw):
        ret = super().load_checkpoint(load_dir, tag, **kw)
        if not ret or ret[0] is None:
            return ret
        ckpt_dir = ret[0]
        import json as _json
        from deepspeed_tpu.runtime.pipe.spmd import interleave_stage_order
        layout_path = os.path.join(ckpt_dir, "pipe_layout.json")
        if os.path.exists(layout_path):
            with open(layout_path) as f:
                saved = _json.load(f)
        else:
            # pre-layout checkpoints were only ever written at V=1
            # (identity order)
            saved = {"pipe_axis": self.pipeline_spec.num_stages,
                     "virtual_stages": 1}
        saved_order = interleave_stage_order(saved["pipe_axis"],
                                             saved["virtual_stages"])
        cur_order = self._stage_order()
        if saved_order != cur_order:
            from deepspeed_tpu.ops.optimizers import Adam8bitState
            if isinstance(self.state.opt_state, Adam8bitState):
                # the quantized moments are flattened (nblocks, block)
                # arrays — axis 0 is quantization blocks, NOT the stage
                # axis, so they cannot be re-permuted across layouts
                raise ValueError(
                    "pipeline layout changed (saved "
                    f"{saved['pipe_axis']}x{saved['virtual_stages']} vs "
                    f"current {self.pipeline_spec.num_stages}x"
                    f"{getattr(self, 'virtual_stages', 1)}) but Adam8bit "
                    "stores stage-stacked moments as flattened "
                    "quantization blocks and cannot re-permute them; "
                    "resume with the same layout, or use Adam for "
                    "layout-change resumes")
            # slot j currently holds global stage saved_order[j]; we need
            # it to hold cur_order[j]
            pos = {g: j for j, g in enumerate(saved_order)}
            perm = jnp.asarray([pos[g] for g in cur_order])

            def permute(tree, shd):
                if isinstance(tree, dict):
                    if "stages" in tree:
                        out = dict(tree)
                        out["stages"] = jax.tree_util.tree_map(
                            lambda x, s: jax.device_put(
                                jnp.take(x, perm, axis=0), s),
                            tree["stages"], shd["stages"])
                        return out
                    return {k: permute(v, shd[k]) for k, v in tree.items()}
                if hasattr(tree, "_fields"):
                    return type(tree)(*(
                        permute(getattr(tree, f), getattr(shd, f))
                        for f in tree._fields))
                if isinstance(tree, (list, tuple)):
                    return type(tree)(
                        permute(t, s) for t, s in zip(tree, shd))
                return tree

            shardings = self._state_shardings
            self.state = self.state._replace(
                params=permute(self.state.params, shardings.params),
                opt_state=permute(self.state.opt_state,
                                  shardings.opt_state))
            if getattr(self, "zero_cpu_offload", False):
                # the host-resident fp32 master + moments (ZeRO-Offload)
                # were restored in the saved layout too; left unpermuted,
                # the first host Adam step would push the wrong layers'
                # weights back to every device
                perm_np = np.asarray([pos[g] for g in cur_order])
                leaves = jax.tree_util.tree_flatten_with_path(
                    self.state.params)[0]
                for i, (path, leaf) in enumerate(leaves):
                    if not any(getattr(p, "key", None) == "stages"
                               for p in path):
                        continue
                    for arrs in (self.optimizer.master_params,
                                 self.optimizer.exp_avg,
                                 self.optimizer.exp_avg_sq):
                        a = arrs[i].reshape(leaf.shape)
                        arrs[i] = np.ascontiguousarray(
                            a[perm_np]).ravel()
            log_dist(
                f"pipe checkpoint re-permuted: saved layout "
                f"{saved['pipe_axis']}x{saved['virtual_stages']} -> "
                f"{axis_size(self.mesh, 'pipe')}x{self.num_virtual}",
                ranks=[0])
        return ret

    # forward/backward/step facade does not decompose for a pipelined
    # batch — the reference documents the same restriction
    # (pipe/engine.py:1078-1094 train_batch is the API)
    def forward(self, *a, **k):
        raise RuntimeError("PipelineEngine: use train_batch()/eval_batch() "
                           "(reference pipe/engine.py also forbids "
                           "forward()/backward() on pipelined models)")

    backward = forward
    step = forward
