"""Pipeline schedules — instruction streams for pipelined training.

TPU-native analog of the reference's ``deepspeed/runtime/pipe/schedule.py``
(PipeSchedule :6, InferenceSchedule :129, TrainSchedule :182,
DataParallelSchedule :292, instruction classes :336-478).

Role in this framework: on GPU the engine *interprets* these instructions
rank-by-rank with blocking NCCL p2p. On TPU the hot path is a single
compiled SPMD program (runtime/pipe/spmd.py) whose dataflow — ppermute
rotations inside a ``lax.scan`` — realizes exactly the dependency structure
these schedules describe. The instruction stream remains first-class
because (a) it is the specification the compiled executor is tested
against, (b) host-side orchestration (multi-controller deployments,
logging, debugging) still walks it, and (c) it is the reference's best
abstraction and part of the public API surface.

Tick math (derived, not copied): with M micro-batches and S stages,
stage ``s`` runs ForwardPass of micro-batch ``m`` at tick ``2m + s`` and
BackwardPass of ``m`` at tick ``2m + 2S - 1 - s``; total ticks
``2(M + S - 1)`` (matches the reference's step count, schedule.py:192).
Forward slots have tick parity ``s % 2``, backward slots the opposite, so
the two waves interleave 1F1B-style without collisions.
"""

from typing import Iterable, List


class PipeInstruction:
    """Base class; instructions carry kwargs (micro_batch_id, buffer_id)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if not self.kwargs:
            return self.name
        args = ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items()))
        return f"{self.name}({args})"

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Apply the optimizer at the batch boundary (reference schedule.py:336)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction (reference schedule.py:346)."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce of tied-weight grads across owning stages (ref :352)."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on a pipeline buffer slot (ref :358)."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """First/last stage pulls a micro-batch from the loader (ref :375)."""


class ForwardPass(BufferOpInstruction):
    """Run the stage's layers forward on a buffer (ref :388)."""


class BackwardPass(BufferOpInstruction):
    """Backprop through the stage's layers for a buffer (ref :400)."""


class SendActivation(BufferOpInstruction):
    """Send a buffer's activations to the next stage (ref :416)."""


class RecvActivation(BufferOpInstruction):
    """Receive activations from the previous stage (ref :432)."""


class SendGrad(BufferOpInstruction):
    """Send input-activation grads to the previous stage (ref :448)."""


class RecvGrad(BufferOpInstruction):
    """Receive output grads from the next stage (ref :463)."""


class PipeSchedule:
    """Iterable of per-tick instruction lists for one (stage, micro_batches)
    pair (reference schedule.py:6).

    Subclasses implement ``steps()`` yielding ``List[PipeInstruction]``.
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterable[List[PipeInstruction]]:
        raise NotImplementedError

    def num_pipe_buffers(self) -> int:
        """Number of in-flight activation buffers this stage needs."""
        raise NotImplementedError

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only wavefront (reference schedule.py:129): stage ``s``
    forwards micro-batch ``m`` at tick ``m + s``; double-buffered."""

    def num_pipe_buffers(self) -> int:
        return 2  # reference schedule.py:173

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            m = tick - self.stage_id
            if 0 <= m < self.micro_batches:
                buf = self._buffer_idx(m)
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf, micro_batch_id=m))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf, micro_batch_id=m))
                cmds.append(ForwardPass(buf, micro_batch_id=m))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf, micro_batch_id=m))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B interleave (reference schedule.py:182): forward of ``m`` at tick
    ``2m + s``, backward at ``2m + 2S - 1 - s``; 2(M+S-1) total ticks."""

    def _fwd_micro_batch(self, tick: int):
        m, r = divmod(tick - self.stage_id, 2)
        if r == 0 and 0 <= m < self.micro_batches:
            return m
        return None

    def _bwd_micro_batch(self, tick: int):
        m, r = divmod(tick - (2 * self.stages - 1 - self.stage_id), 2)
        if r == 0 and 0 <= m < self.micro_batches:
            return m
        return None

    def num_pipe_buffers(self) -> int:
        """Max forwarded-but-not-backwarded micro-batches = pipeline depth
        remaining below this stage (reference schedule.py:243 keeps
        min(S - s, M) buffers; derivation: fwd(m) at 2m+s, bwd(m) at
        2m+2S-1-s → (S - s) in flight in steady state)."""
        return max(1, min(self.stages - self.stage_id, self.micro_batches))

    def steps(self):
        total = 2 * (self.micro_batches + self.stages - 1)
        for tick in range(total):
            cmds: List[PipeInstruction] = []
            fwd = self._fwd_micro_batch(tick)
            bwd = self._bwd_micro_batch(tick)

            if bwd is not None:
                buf = self._buffer_idx(bwd)
                if not self.is_last_stage:
                    cmds.append(RecvGrad(buf, micro_batch_id=bwd))
                cmds.append(BackwardPass(buf, micro_batch_id=bwd))
                if not self.is_first_stage:
                    cmds.append(SendGrad(buf, micro_batch_id=bwd))

            if fwd is not None:
                buf = self._buffer_idx(fwd)
                if self.is_first_stage or self.is_last_stage:
                    cmds.append(LoadMicroBatch(buf, micro_batch_id=fwd))
                if not self.is_first_stage:
                    cmds.append(RecvActivation(buf, micro_batch_id=fwd))
                cmds.append(ForwardPass(buf, micro_batch_id=fwd))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf, micro_batch_id=fwd))

            if tick == total - 1:
                # batch boundary (reference schedule.py:230-236)
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: plain gradient accumulation
    (reference schedule.py:292)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for m in range(self.micro_batches):
            cmds: List[PipeInstruction] = [
                LoadMicroBatch(0, micro_batch_id=m),
                ForwardPass(0, micro_batch_id=m),
                BackwardPass(0, micro_batch_id=m),
            ]
            if m == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds
