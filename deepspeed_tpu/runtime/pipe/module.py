"""PipelineModule — express a model as a list of layers, partition it into
pipeline stages.

TPU-native analog of the reference's ``deepspeed/runtime/pipe/module.py``
(LayerSpec :23 with lazy build :63, TiedLayerSpec :71, PipelineModule :85,
``_partition_layers`` :348 with uniform/parameters/type:regex methods,
sequential ``forward`` :292 with activation-checkpoint intervals :323-345,
per-layer checkpoint files :526-546).

Functional layer contract (this framework's analog of nn.Module):

- a **layer object** exposes ``init(key) -> params`` and is callable as
  ``layer(params, x, *, rng=None) -> y``;
- a **plain callable** ``f(x) -> y`` is a param-less layer (like the
  reference's lambda layers, module.py:259-263).

``LayerSpec`` defers construction (the reference builds layers lazily so a
trillion-param model never materializes on one host, module.py:63 — here it
additionally keeps `init` pure so params can be created directly into
sharded device buffers).

Stage grouping for the compiled SPMD executor (runtime/pipe/spmd.py)
requires the per-stage param pytrees to be *homogeneous* (same treedef and
leaf shapes) so they can be stacked over the ``pipe`` mesh axis; the
partitioner checks and reports this. Heterogeneous first/last layers
(embedding, loss head) should go through ``PipelineSpec``'s pre/post slots
instead — see spmd.py.
"""

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.partition import partition_balanced, partition_uniform


class LayerSpec:
    """Deferred layer constructor (reference module.py:23)."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not callable(typename):
            raise RuntimeError("LayerSpec requires a callable type")

    @property
    def name(self) -> str:
        return getattr(self.typename, "__name__", str(self.typename))

    def build(self, log: bool = False):
        """(reference module.py:63)"""
        if log:
            logger.info(f"building {self}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared with every other tied layer of the same
    ``key`` (reference module.py:71 — e.g. embedding reused as the LM head).

    In the functional regime tying is *structural*: all tied instances read
    the same entry of the params pytree, so their gradient contributions sum
    automatically in the backward pass — the reference needed explicit
    all-reduce groups for this (module.py:405-474); compiled SPMD gets it
    from the psum transpose.
    """

    def __init__(self, key: str, typename: Callable, *module_args,
                 forward_fn: Optional[Callable] = None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn

    def __repr__(self):
        return f"TiedLayerSpec({self.name}, key={self.key!r})"


def _as_spec(obj) -> LayerSpec:
    if isinstance(obj, LayerSpec):
        return obj
    if callable(obj):
        # an already-built layer object or plain function
        return LayerSpec(lambda o=obj: o)
    raise TypeError(f"layer must be a LayerSpec or callable, got {type(obj)}")


def _layer_init(layer, key):
    if hasattr(layer, "init"):
        return layer.init(key)
    return None  # param-less


def _layer_apply(layer, params, x, rng=None):
    if params is None:
        return layer(x)
    try:
        return layer(params, x, rng=rng)
    except TypeError:
        return layer(params, x)


def _num_params(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "shape"))


class PipelineModule:
    """A model as a layer list + a stage partitioning (reference
    module.py:85).

    Parameters
    ----------
    layers: sequence of LayerSpec / layer objects / callables.
    num_stages: pipeline depth (defaults to the topology's 'pipe' dim, 1 if
        absent).
    topology: optional ProcessTopology carrying the 'pipe' axis.
    loss_fn: ``loss_fn(outputs, batch) -> scalar`` applied after the last
        layer (reference passed ``loss_fn`` to PipelineModule too).
    partition_method: 'parameters' (balance param counts — reference
        default), 'uniform' (balance layer counts), or 'type:regex'
        (balance layers whose class name matches; reference module.py:352).
    activation_checkpoint_interval: remat every N layers in ``forward``
        (reference module.py:323-345; 0 disables).
    """

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 seed: int = 1234):
        self.specs: List[LayerSpec] = [_as_spec(l) for l in layers]
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed = seed

        if num_stages is None:
            num_stages = topology.get_dim("pipe") if topology is not None else 1
            num_stages = max(1, num_stages)
        self.num_stages = num_stages
        self.topology = topology

        # build all layers (host-side objects are light; params are built
        # separately/purely in init_params)
        self.layers = [spec.build() for spec in self.specs]
        self.tied_keys = sorted({s.key for s in self.specs
                                 if isinstance(s, TiedLayerSpec)})

        self.parts = self._partition_layers()

    # ------------------------------------------------------------------ #
    # partitioning (reference module.py:348-404)
    # ------------------------------------------------------------------ #
    def _layer_weights(self) -> List[float]:
        method = self.partition_method.lower()
        if method == "uniform":
            return [1.0] * len(self.specs)
        if method == "parameters":
            weights = []
            key = jax.random.PRNGKey(self.seed)
            for layer in self.layers:
                # count params abstractly — eval_shape never materializes
                # the weights, so a huge model costs nothing to weigh
                if hasattr(layer, "init"):
                    params = jax.eval_shape(layer.init, key)
                else:
                    params = None
                weights.append(float(_num_params(params)) if params is not None
                               else 0.0)
            # all-zero (param-less model) degrades to uniform
            return weights if any(weights) else [1.0] * len(self.specs)
        if method.startswith("type:"):
            pattern = self.partition_method[len("type:"):]
            return [1.0 if re.search(pattern, spec.name, re.IGNORECASE)
                    else 0.0 for spec in self.specs]
        raise NotImplementedError(
            f"partition_method {self.partition_method!r} not supported")

    def _partition_layers(self) -> List[int]:
        parts = partition_balanced(self._layer_weights(), self.num_stages)
        if any(parts[i] == parts[i + 1] for i in range(self.num_stages)) \
                and len(self.specs) >= self.num_stages:
            logger.warning(
                f"partition {parts} leaves an empty stage; "
                f"falling back to uniform")
            parts = partition_uniform(len(self.specs), self.num_stages)
        return parts

    def stage_layers(self, stage_id: int) -> List[int]:
        """Layer indices owned by a stage."""
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    def stage_of_layer(self, layer_idx: int) -> int:
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    # ------------------------------------------------------------------ #
    # params
    # ------------------------------------------------------------------ #
    def init_params(self, key=None) -> Dict[str, Any]:
        """Build the full params pytree:
        ``{"layer_00": ..., "tied": {key: ...}}``.

        Tied specs' params live once under ``tied/<key>``; their per-layer
        slot is the string reference (resolved in apply)."""
        if key is None:
            key = jax.random.PRNGKey(self.seed)
        params: Dict[str, Any] = {}
        tied: Dict[str, Any] = {}
        keys = jax.random.split(key, len(self.layers))
        for i, (spec, layer) in enumerate(zip(self.specs, self.layers)):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = _layer_init(layer, keys[i])
                continue
            p = _layer_init(layer, keys[i])
            if p is not None:
                params[f"layer_{i:02d}"] = p
        if tied:
            params["tied"] = tied
        return params

    def _params_for(self, params: Dict[str, Any], i: int):
        spec = self.specs[i]
        if isinstance(spec, TiedLayerSpec):
            return params["tied"][spec.key]
        return params.get(f"layer_{i:02d}")

    # ------------------------------------------------------------------ #
    # sequential forward (correctness path / single stage;
    # reference module.py:292)
    # ------------------------------------------------------------------ #
    def forward(self, params: Dict[str, Any], x, rng=None,
                start: int = 0, stop: Optional[int] = None):
        stop = len(self.layers) if stop is None else stop
        interval = self.activation_checkpoint_interval

        def run_span(x, lo, hi, rng):
            for i in range(lo, hi):
                spec, layer = self.specs[i], self.layers[i]
                p = self._params_for(params, i)
                r = None
                if rng is not None:
                    r = jax.random.fold_in(rng, i)
                if isinstance(spec, TiedLayerSpec) and spec.forward_fn:
                    x = spec.forward_fn(p, x)
                else:
                    x = _layer_apply(layer, p, x, rng=r)
            return x

        if interval and interval > 0:
            # route through the checkpointing subsystem so configure()'s
            # partition/offload knobs apply (reference module.py:323-345
            # calls deepspeed.checkpointing.checkpoint here)
            from deepspeed_tpu.runtime.activation_checkpointing import (
                checkpointing as ds_ckpt)
            lo = start
            while lo < stop:
                hi = min(lo + interval, stop)
                x = ds_ckpt.checkpoint(
                    lambda x, rng, lo=lo, hi=hi: run_span(x, lo, hi, rng),
                    x, rng)
                lo = hi
            return x
        return run_span(x, start, stop, rng)

    __call__ = forward

    # ------------------------------------------------------------------ #
    # stage stacking for the compiled SPMD pipeline (spmd.py)
    # ------------------------------------------------------------------ #
    def stackable(self, params: Dict[str, Any]) -> bool:
        """True if every stage's param sub-tree has identical structure."""
        try:
            self.stack_stage_params(params)
            return True
        except ValueError:
            return False

    def stage_params(self, params: Dict[str, Any], stage_id: int) -> List:
        return [self._params_for(params, i)
                for i in self.stage_layers(stage_id)]

    def stage_layer_counts(self) -> List[int]:
        return [self.parts[s + 1] - self.parts[s]
                for s in range(self.num_stages)]

    def stack_stage_params(self, params: Dict[str, Any]):
        """Stack per-stage param lists into leaves with a leading ``pipe``
        dim: returns a pytree whose leaves have shape (num_stages, ...).

        Uneven partitions (``parameters``-balanced or L %% S != 0 —
        reference module.py:348) are supported by padding shorter stages
        with zero no-op layers up to the max stage depth; the padded slots
        are skipped (data-masked, never branched) by ``stage_apply_fn``
        using the static per-stage layer-count table.
        """
        per_stage = [self.stage_params(params, s)
                     for s in range(self.num_stages)]
        counts = [len(sp) for sp in per_stage]
        max_n = max(counts)
        if min(counts) != max_n:
            tmpl = per_stage[counts.index(max_n)]
            for sp in per_stage:
                while len(sp) < max_n:
                    sp.append(jax.tree_util.tree_map(
                        jnp.zeros_like, tmpl[len(sp)]))
        ref = jax.tree_util.tree_structure(per_stage[0])
        shapes0 = [l.shape for l in jax.tree_util.tree_leaves(per_stage[0])]
        for s, sp in enumerate(per_stage[1:], start=1):
            if jax.tree_util.tree_structure(sp) != ref:
                raise ValueError(
                    f"stage {s} params structure differs from stage 0 — "
                    f"stages must be homogeneous (same layer type) to "
                    f"stack over the pipe axis; move odd layers into "
                    f"PipelineSpec pre/post")
            shapes = [l.shape for l in jax.tree_util.tree_leaves(sp)]
            if shapes != shapes0:
                raise ValueError(
                    f"stage {s} param shapes {shapes} != stage 0 {shapes0}")
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *per_stage)

    def stage_apply_fn(self) -> Callable:
        """Returns ``f(stage_param_list, x, rng)`` applying one stage's
        layers; identical code for every stage (required by SPMD).

        With an uneven partition each stage runs ``max(counts)`` layer
        slots and masks padded slots by ``where`` on the stage's layer
        count (looked up via ``lax.axis_index('pipe')`` — so the uneven
        path only executes inside the pipeline shard_map). The uniformity
        invariant (spmd.py) holds: every device executes every slot.
        """
        counts = self.stage_layer_counts()
        max_n = max(counts)
        even = min(counts) == max_n
        # representative layer objects per slot, taken from a deepest
        # stage (stages are homogeneous in layer type — checked at stack)
        lo = self.parts[counts.index(max_n)]
        layers = self.layers[lo:lo + max_n]
        counts_arr = jnp.asarray(counts, jnp.int32)

        def apply(stage_params: List, x, rng=None):
            cnt = None
            if not even:
                cnt = counts_arr[jax.lax.axis_index("pipe")]
            for j, layer in enumerate(layers):
                r = jax.random.fold_in(rng, j) if rng is not None else None
                y = _layer_apply(layer, stage_params[j], x, rng=r)
                if not even and j >= min(counts):
                    y = jnp.where(j < cnt, y, x)
                x = y
            return x
        return apply

    # ------------------------------------------------------------------ #
    # per-layer checkpoints (reference module.py:526-546)
    # ------------------------------------------------------------------ #
    def ckpt_layer_path(self, ckpt_dir: str, layer_idx: int) -> str:
        import os
        return os.path.join(ckpt_dir, f"layer_{layer_idx:02d}-model_states.npz")

    def save_state_dict(self, params: Dict[str, Any], ckpt_dir: str):
        import os
        from deepspeed_tpu.runtime import checkpoint as ckpt
        os.makedirs(ckpt_dir, exist_ok=True)
        for i in range(len(self.layers)):
            p = self._params_for(params, i)
            if p is None:
                continue
            if isinstance(self.specs[i], TiedLayerSpec) and \
                    self.stage_of_layer(i) != 0 and \
                    any(isinstance(s, TiedLayerSpec) and s.key ==
                        self.specs[i].key for s in self.specs[:i]):
                continue  # tied copy already saved by its first occurrence
            ckpt.save_tree(self.ckpt_layer_path(ckpt_dir, i), p)

    def load_state_dir(self, params: Dict[str, Any], ckpt_dir: str):
        """Load per-layer files into a params pytree (repartitioning across
        stage counts is free: files are per *layer*, reference
        module.py:548)."""
        from deepspeed_tpu.runtime import checkpoint as ckpt
        import os
        new_params = dict(params)
        tied = dict(params.get("tied", {}))
        seen_tied = set()
        for i in range(len(self.layers)):
            path = self.ckpt_layer_path(ckpt_dir, i)
            spec = self.specs[i]
            if isinstance(spec, TiedLayerSpec):
                if spec.key in seen_tied or not os.path.exists(path):
                    continue
                tied[spec.key] = ckpt.load_tree(path, tied[spec.key])
                seen_tied.add(spec.key)
            elif f"layer_{i:02d}" in new_params and os.path.exists(path):
                new_params[f"layer_{i:02d}"] = ckpt.load_tree(
                    path, new_params[f"layer_{i:02d}"])
        if tied:
            new_params["tied"] = tied
        return new_params
