"""Pipeline parallelism (reference ``deepspeed/runtime/pipe/``)."""

from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec, PipelineModule, TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass, DataParallelSchedule, ForwardPass, InferenceSchedule,
    LoadMicroBatch, OptimizerStep, PipeInstruction, PipeSchedule,
    RecvActivation, RecvGrad, ReduceGrads, ReduceTiedGrads, SendActivation,
    SendGrad, TrainSchedule)
from deepspeed_tpu.runtime.pipe.spmd import (
    PipelineSpec, build_pipeline_loss_fn, module_pipeline_spec,
    pipeline_param_specs)
