"""Compiled SPMD pipeline execution — the TPU-native pipeline engine core.

The reference executes pipeline schedules as a per-rank Python interpreter
over blocking NCCL p2p ops (``runtime/pipe/engine.py:1145`` _exec_schedule,
``p2p.py:31,44`` send/recv as 2-rank broadcasts). On TPU that design wastes
the compiler: instead, the *entire* pipelined batch — all micro-batches,
all stages, forward and backward — is ONE jitted program over a mesh with a
``pipe`` axis:

- stage weights are stacked on a leading ``pipe``-sharded dimension, so
  "stage s holds layers [s]" is a *sharding*, not a process assignment;
- each scan tick, every stage applies its layers to its current activation
  and the activations rotate one stage forward via ``lax.ppermute`` (the
  ICI-neighbor collective — the analog of p2p.send/recv);
- micro-batch injection at stage 0 and loss extraction at stage S-1 are
  ``where``-masks on ``lax.axis_index('pipe')``;
- the backward schedule is not hand-written at all: it is the transpose of
  the forward scan (ppermute transposes to the reverse rotation), which
  yields the inverted-wavefront grad flow the reference implements manually
  (_exec_backward_pass / SendGrad / RecvGrad).

Schedule realized: GPipe-style fill-drain with ``M + S - 1`` forward ticks
followed by the transposed backward sweep; remat (``jax.checkpoint``) on
the stage body keeps the activation footprint at one carry per tick, the
same asymptotics as the reference's 1F1B + activation checkpointing. The
instruction-stream view of this dataflow lives in runtime/pipe/schedule.py
and is what the tests check the executor against.

Cost note (inherent to single-program SPMD): the pre/post functions
(embedding, loss head) run redundantly on every pipe row with their
results masked off except at the owning row. This buys compiler-scheduled
overlap and zero host involvement; pre/post are small relative to S stage
bodies for the deep models pipelining targets.
"""

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import axis_size


class PipelineSpec(NamedTuple):
    """A pipelined model in functional form.

    - ``init(key) -> {"pre": ..., "stages": ..., "post": ...}`` where the
      ``stages`` leaves carry a leading ``num_stages`` dim (stacked).
    - ``pre_apply(pre_params, micro_batch, rng) -> act``: input layers
      (embedding); runs at stage 0's slot.
    - ``stage_apply(stage_params, act, rng) -> act``: one stage's layers;
      ``stage_params`` is the leading-dim slice for this stage.
    - ``post_apply(post_params, pre_params, act, micro_batch) -> scalar``:
      output layers + loss; receives ``pre_params`` so heads can tie to
      embedding weights (reference TiedLayerSpec, module.py:71).
    - ``*_specs``: optional PartitionSpec pytrees for tensor-parallel
      sharding of each group; stage specs are per-stacked-leaf *without*
      the leading pipe dim (it is prepended here).
    """
    init: Callable
    pre_apply: Callable
    stage_apply: Callable
    post_apply: Callable
    num_stages: int
    pre_specs: Optional[Any] = None
    stage_specs: Optional[Any] = None
    post_specs: Optional[Any] = None


def _prepend_pipe(spec: Optional[P]) -> P:
    if spec is None:
        return P("pipe")
    return P("pipe", *tuple(spec))


def pipeline_param_specs(spec: PipelineSpec, params: Any) -> Any:
    """PartitionSpec pytree for the full pipeline params: stacked stage
    leaves get 'pipe' on dim 0 (+ any TP spec shifted right); pre/post get
    their TP specs or replication."""
    def expand(group, tp_specs, stacked: bool):
        if tp_specs is None:
            return jax.tree_util.tree_map(
                lambda _: _prepend_pipe(None) if stacked else P(), group)
        return jax.tree_util.tree_map(
            lambda _, s: _prepend_pipe(s) if stacked else (s or P()),
            group, tp_specs)
    return {
        "pre": expand(params["pre"], spec.pre_specs, stacked=False),
        "stages": expand(params["stages"], spec.stage_specs, stacked=True),
        "post": expand(params["post"], spec.post_specs, stacked=False),
    }


def build_pipeline_loss_fn(spec: PipelineSpec, mesh: Mesh, num_micro: int,
                           remat: bool = True,
                           compute_dtype=None) -> Callable:
    """Return ``loss_fn(params, batch, rng) -> scalar`` running the full
    pipelined forward; engine-contract compatible (runtime/engine.py).

    ``batch`` leaves must have leading dim ``num_micro`` then the global
    micro-batch dim (sharded over 'data').

    ``compute_dtype``: when set, fp32 params are cast INSIDE the mapped
    program (the returned fn carries ``owns_cast=True`` so the engine skips
    its own cast). This keeps every cross-stage gradient psum in fp32 —
    the master-grad precision ZeRO expects — with only the bf16 compute
    copies crossing into the stage bodies.
    """
    if "pipe" not in mesh.axis_names:
        raise ValueError("pipeline execution requires a 'pipe' mesh axis")
    S = spec.num_stages
    M = num_micro
    if axis_size(mesh, "pipe") != S:
        raise ValueError(
            f"mesh pipe axis {axis_size(mesh, 'pipe')} != num_stages {S}")

    stage_apply = spec.stage_apply
    if remat:
        stage_apply = jax.checkpoint(spec.stage_apply)

    # pipeline + data flow are hand-scheduled (manual axes); tensor/sequence
    # parallel axes stay in "auto" mode so GSPMD keeps doing TP inside each
    # stage body (specs naming auto axes must be filtered from in_specs)
    manual_axes = frozenset(a for a in ("pipe", "data")
                            if a in mesh.axis_names)

    def manual_only(p: P) -> P:
        def keep(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in manual_axes)
                return kept if kept else None
            return entry if entry in manual_axes else None
        return P(*(keep(e) for e in tuple(p)))

    def per_device(params, batch, rng):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        s_idx = jax.lax.axis_index("pipe")
        pre_p, post_p = params["pre"], params["post"]
        # local slice of the stacked stage weights: (1, ...) -> (...)
        st_p = jax.tree_util.tree_map(lambda x: x[0], params["stages"])

        def tick(carry, t):
            act, outbuf = carry
            in_idx = jnp.clip(t, 0, M - 1)
            micro = jax.tree_util.tree_map(lambda x: x[in_idx], batch)
            # LoadMicroBatch + first-stage layers (masked to stage 0)
            # disjoint fold-in domains mod (S+1): pre uses residue 0, stages
            # use residues 1..S — no dropout-mask key ever collides
            fresh = spec.pre_apply(pre_p, micro,
                                   jax.random.fold_in(rng, t * (S + 1)))
            act_in = jnp.where(s_idx == 0, fresh.astype(act.dtype), act)
            # ForwardPass for every stage's current micro-batch
            r = jax.random.fold_in(rng, t * (S + 1) + s_idx + 1)
            out = stage_apply(st_p, act_in, r)
            # collect the wave exiting the last stage (micro-batch t-(S-1))
            out_t = t - (S - 1)
            o_idx = jnp.clip(out_t, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outbuf, o_idx, keepdims=True)
            valid = jnp.logical_and(out_t >= 0, out_t < M)
            outbuf = jax.lax.dynamic_update_slice_in_dim(
                outbuf, jnp.where(valid, out[None], cur), o_idx, axis=0)
            # SendActivation/RecvActivation: rotate stage s -> s+1
            act = jax.lax.ppermute(
                out, "pipe", [(i, (i + 1) % S) for i in range(S)])
            return (act, outbuf), None

        # probe activation shape/dtype via the first micro-batch
        micro0 = jax.tree_util.tree_map(lambda x: x[0], batch)
        probe = jax.eval_shape(spec.pre_apply, pre_p, micro0, rng)
        act0 = jnp.zeros(probe.shape, probe.dtype)
        outbuf0 = jnp.zeros((M,) + probe.shape, probe.dtype)

        (_, outbuf), _ = jax.lax.scan(
            tick, (act0, outbuf0), jnp.arange(M + S - 1))

        # output layers + loss over all M collected micro-batches at once
        # (batched: better MXU shapes than per-tick heads)
        losses = jax.vmap(
            lambda a, mb: spec.post_apply(post_p, pre_p, a, mb),
            in_axes=(0, 0))(outbuf, batch)
        # _aggregate_total_loss (reference pipe/engine.py:374): select the
        # last stage's mean, share it with every stage/DP rank
        local = jnp.where(s_idx == S - 1, jnp.mean(losses), 0.0)
        total = jax.lax.psum(local, "pipe")
        if "data" in manual_axes:
            total = jax.lax.pmean(total, "data")
        return total

    def loss_fn(params, batch, rng):
        # spec trees built against the actual pytree (PipelineSpec TP specs
        # may be None => replicated/pipe-stacked defaults), then filtered to
        # the manual axes — TP ('model'/'seq') sharding is carried by the
        # arguments themselves in auto mode
        full_specs = jax.tree_util.tree_map(
            manual_only, pipeline_param_specs(spec, params),
            is_leaf=lambda x: isinstance(x, P))
        batch_specs = jax.tree_util.tree_map(
            lambda _: P(None, "data") if "data" in mesh.axis_names else P(),
            batch)
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(full_specs, batch_specs, P()),
            out_specs=P(),
            axis_names=manual_axes,
            check_vma=False)
        return mapped(params, batch, rng)

    loss_fn.owns_cast = compute_dtype is not None
    return loss_fn


def microbatch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a stacked (M, global_mb, ...) pipeline batch."""
    if "data" in mesh.axis_names:
        return NamedSharding(mesh, P(None, "data"))
    return NamedSharding(mesh, P())


def module_pipeline_spec(module, mesh_or_stages, input_key: str = "x",
                         loss_fn: Optional[Callable] = None) -> PipelineSpec:
    """Adapt a PipelineModule with homogeneous stages to a PipelineSpec.

    - pre: identity on ``micro_batch[input_key]`` (first stage "loads" the
      micro-batch, reference pipe/engine.py:613);
    - stage: the module's per-stage layer chain;
    - post: ``loss_fn(act, micro_batch)`` (module.loss_fn by default).
    """
    num_stages = (mesh_or_stages if isinstance(mesh_or_stages, int)
                  else axis_size(mesh_or_stages, "pipe"))
    if module.num_stages != num_stages:
        raise ValueError(f"module has {module.num_stages} stages, "
                         f"mesh/pipe axis has {num_stages}")
    final_loss = loss_fn or module.loss_fn
    if final_loss is None:
        raise ValueError("a loss_fn is required (module.loss_fn or arg)")

    stage_fn = module.stage_apply_fn()

    def init(key):
        flat = module.init_params(key)
        return {"pre": {}, "stages": module.stack_stage_params(flat),
                "post": {}}

    def pre_apply(pre_p, micro, rng):
        x = micro[input_key] if isinstance(micro, dict) else micro
        return x

    def stage_apply(st_p, act, rng):
        return stage_fn(st_p, act, rng=rng)

    def post_apply(post_p, pre_p, act, micro):
        return final_loss(act, micro)

    return PipelineSpec(init=init, pre_apply=pre_apply,
                        stage_apply=stage_apply, post_apply=post_apply,
                        num_stages=num_stages)
