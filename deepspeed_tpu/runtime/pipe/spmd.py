"""Compiled SPMD pipeline execution — the TPU-native pipeline engine core.

The reference executes pipeline schedules as a per-rank Python interpreter
over blocking NCCL p2p ops (``runtime/pipe/engine.py:1145`` _exec_schedule,
``p2p.py:31,44`` send/recv as 2-rank broadcasts). On TPU that design wastes
the compiler: instead, the *entire* pipelined batch — all micro-batches,
all stages, forward and backward — is ONE jitted program over a mesh with a
``pipe`` axis:

- stage weights are stacked on a leading ``pipe``-sharded dimension, so
  "stage s holds layers [s]" is a *sharding*, not a process assignment;
- each scan tick, every stage applies its layers to its current activation
  and the activations rotate one stage forward via ``lax.ppermute`` (the
  ICI-neighbor collective — the analog of p2p.send/recv);
- micro-batch injection at stage 0 and loss extraction at stage S-1 are
  ``where``-masks on ``lax.axis_index('pipe')``;
- the backward schedule is not hand-written at all: it is the transpose of
  the forward scan (ppermute transposes to the reverse rotation), which
  yields the inverted-wavefront grad flow the reference implements manually
  (_exec_backward_pass / SendGrad / RecvGrad).

Two executors share this dataflow:

- ``build_pipeline_loss_fn``: forward-only wavefront (M + S - 1 ticks) with
  the loss head applied per tick to the wave exiting the last stage —
  realizes InferenceSchedule; differentiable (autodiff transposes the
  ppermute rotation into the reverse grad flow) for callers that want it.
- ``build_pipeline_grad_fn``: the training path — an explicit 1F1B-style
  schedule (reference TrainSchedule, runtime/pipe/schedule.py:182) as one
  scan of M + 2S - 2 macro-ticks, each an unconditional forward sub-step
  (stage s forwards micro u - s) plus backward sub-step (stage s backwards
  micro u - (2S-2-s), recomputing its stage body under ``jax.vjp`` —
  activation checkpointing, inherent). Each stage keeps a depth-(2S-1)
  circular buffer of stage inputs, so peak activation memory is O(S),
  independent of the accumulation depth M — the reference's 1F1B in-flight
  bound (schedule.py:243 num_pipe_buffers). Gradients accumulate
  explicitly in fp32 and are returned directly; the engine skips autodiff
  for pipelined models.

**Uniformity invariant (why there is no lax.cond here):** every collective
— the two ppermute rotations, the head broadcast, and any GSPMD-inserted
TP collective inside stage/pre/post bodies — must execute on every device
on every tick. A branch whose predicate varies along 'pipe' (e.g. "am I
the last stage") would send device cohorts into different collectives and
deadlock (observed as a rendezvous hang on the CPU mesh; a real-TPU hang
in the field). So validity is handled by ``where``-masks on data, never by
skipping code. The cost is honest: fill/drain bubble is 2(S-1) ticks
instead of the reference 1F1B's S-1 — the price of single-program SPMD —
while utilization M/(M+2S-2) approaches 1 at pipelining's target depths.

Head placement: the loss head would naively run (masked) on every pipe row
— S redundant vocab-GEMMs per micro. When the spec provides
``post_shard_apply`` (and seq %% S == 0), the last row's exiting
activation is instead pipe-broadcast and each row computes a 1/S sequence
chunk of the head (forward and backward), psum-reassembled: total head
work is 1x per micro-batch, spread across the pipe as a
sequence-parallel head.
"""

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import axis_size


class PipelineSpec(NamedTuple):
    """A pipelined model in functional form.

    - ``init(key) -> {"pre": ..., "stages": ..., "post": ...}`` where the
      ``stages`` leaves carry a leading ``num_stages`` dim (stacked).
    - ``pre_apply(pre_params, micro_batch, rng) -> act``: input layers
      (embedding); runs at stage 0's slot.
    - ``stage_apply(stage_params, act, rng) -> act``: one stage's layers;
      ``stage_params`` is the leading-dim slice for this stage.
    - ``post_apply(post_params, pre_params, act, micro_batch) -> scalar``:
      output layers + loss; receives ``pre_params`` so heads can tie to
      embedding weights (reference TiedLayerSpec, module.py:71).
    - ``post_shard_apply(post_params, pre_params, act_slice, micro_batch,
      start) -> loss_sum`` (optional): the same head on a contiguous
      sequence slice ``act[:, start:start+chunk]``, returning the SUM of
      per-token losses. When provided (and seq divides the stage count)
      the executors compute the head cooperatively across pipe rows —
      each row takes one sequence chunk — instead of redundantly on every
      row. Only valid for losses that decompose per token given the micro
      batch (next-token LM xent does).
    - ``*_specs``: optional PartitionSpec pytrees for tensor-parallel
      sharding of each group; stage specs are per-stacked-leaf *without*
      the leading pipe dim (it is prepended here).
    """
    init: Callable
    pre_apply: Callable
    stage_apply: Callable
    post_apply: Callable
    num_stages: int
    pre_specs: Optional[Any] = None
    stage_specs: Optional[Any] = None
    post_specs: Optional[Any] = None
    post_shard_apply: Optional[Callable] = None


def _prepend_pipe(spec: Optional[P]) -> P:
    if spec is None:
        return P("pipe")
    return P("pipe", *tuple(spec))


def _pipe_manual_axes(mesh: Mesh) -> frozenset:
    return frozenset(a for a in ("pipe", "data") if a in mesh.axis_names)


def _manual_only(p: P, manual_axes) -> P:
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in manual_axes)
            return kept if kept else None
        return entry if entry in manual_axes else None
    return P(*(keep(e) for e in tuple(p)))


def _psum_act(x, axis_name: str):
    """psum of an activation-sized tensor inside the pipeline scan.

    XLA@jax-0.9.0 bug workaround: a *bfloat16* psum over a manual shard_map
    axis inside lax.scan, with an auto (GSPMD) axis present in the mesh,
    aborts the SPMD partitioner with ``Invalid binary instruction opcode
    copy`` (hlo_instruction.cc:1585). Summing in fp32 and casting back
    partitions cleanly — and is numerically at least as good (the psum
    accumulates in fp32).
    """
    if x.dtype == jnp.float32:
        return jax.lax.psum(x, axis_name)
    return jax.lax.psum(x.astype(jnp.float32), axis_name).astype(x.dtype)


def seq_chunk_select(x, s_idx, S: int, axis: int = 1):
    """Select sequence block ``s_idx`` of ``S`` equal chunks along ``axis``
    WITHOUT a traced-start dynamic_slice: reshape (.., S*chunk, ..) ->
    (.., S, chunk, ..) and contract with a one-hot of ``s_idx``.

    Rationale: under shard_map with auto (GSPMD) axes present in the mesh,
    traced-start dynamic-slice/update-slice on these activations trips an
    XLA partitioner CHECK ("Invalid binary instruction opcode copy",
    hlo_instruction.cc:1585, XLA@jax 0.9.0) while compiling the pipelined
    step. The reshape + one-hot masked-sum form partitions cleanly and
    costs one extra elementwise pass over the block — noise next to the
    head GEMM it feeds.
    """
    shape = x.shape
    chunk = shape[axis] // S
    resh = x.reshape(shape[:axis] + (S, chunk) + shape[axis + 1:])
    bshape = (1,) * axis + (S,) + (1,) * (resh.ndim - axis - 1)
    onehot = (jax.lax.iota(jnp.int32, S) == s_idx).reshape(bshape)
    return jnp.sum(jnp.where(onehot, resh, jnp.zeros((), resh.dtype)),
                   axis=axis)


def seq_chunk_scatter(chunk_val, s_idx, S: int, axis: int = 1):
    """Inverse of :func:`seq_chunk_select`: embed a (.., chunk, ..) block
    at position ``s_idx`` of ``S`` along ``axis``, zeros elsewhere —
    again avoiding traced-index dynamic_update_slice (see select)."""
    shape = chunk_val.shape
    expanded = jnp.expand_dims(chunk_val, axis)
    bshape = (1,) * axis + (S,) + (1,) * (expanded.ndim - axis - 1)
    onehot = (jax.lax.iota(jnp.int32, S) == s_idx).reshape(bshape)
    full = jnp.where(onehot, expanded, jnp.zeros((), chunk_val.dtype))
    return full.reshape(shape[:axis] + (S * shape[axis],) + shape[axis + 1:])


def _head_mode(spec: "PipelineSpec", S: int, act_shape):
    """(coop, chunk, ntok): cooperative sequence-sharded head is usable
    when the spec provides post_shard_apply, the activation is (mb, seq,
    ...) and seq divides into S chunks."""
    if (spec.post_shard_apply is not None and len(act_shape) >= 2
            and act_shape[1] % S == 0):
        return True, act_shape[1] // S, act_shape[0] * act_shape[1]
    return False, 0, 0


def pipeline_param_specs(spec: PipelineSpec, params: Any) -> Any:
    """PartitionSpec pytree for the full pipeline params: stacked stage
    leaves get 'pipe' on dim 0 (+ any TP spec shifted right); pre/post get
    their TP specs or replication."""
    def expand(group, tp_specs, stacked: bool):
        if tp_specs is None:
            return jax.tree_util.tree_map(
                lambda _: _prepend_pipe(None) if stacked else P(), group)
        return jax.tree_util.tree_map(
            lambda _, s: _prepend_pipe(s) if stacked else (s or P()),
            group, tp_specs)
    return {
        "pre": expand(params["pre"], spec.pre_specs, stacked=False),
        "stages": expand(params["stages"], spec.stage_specs, stacked=True),
        "post": expand(params["post"], spec.post_specs, stacked=False),
    }


def build_pipeline_loss_fn(spec: PipelineSpec, mesh: Mesh, num_micro: int,
                           remat: bool = True,
                           compute_dtype=None) -> Callable:
    """Return ``loss_fn(params, batch, rng) -> scalar`` running the full
    pipelined forward; engine-contract compatible (runtime/engine.py).

    ``batch`` leaves must have leading dim ``num_micro`` then the global
    micro-batch dim (sharded over 'data').

    ``compute_dtype``: when set, fp32 params are cast INSIDE the mapped
    program (the returned fn carries ``owns_cast=True`` so the engine skips
    its own cast). This keeps every cross-stage gradient psum in fp32 —
    the master-grad precision ZeRO expects — with only the bf16 compute
    copies crossing into the stage bodies.
    """
    if "pipe" not in mesh.axis_names:
        raise ValueError("pipeline execution requires a 'pipe' mesh axis")
    S = spec.num_stages
    M = num_micro
    if axis_size(mesh, "pipe") != S:
        raise ValueError(
            f"mesh pipe axis {axis_size(mesh, 'pipe')} != num_stages {S}")

    stage_apply = spec.stage_apply
    if remat:
        stage_apply = jax.checkpoint(spec.stage_apply)

    # pipeline + data flow are hand-scheduled (manual axes); tensor/sequence
    # parallel axes stay in "auto" mode so GSPMD keeps doing TP inside each
    # stage body (specs naming auto axes must be filtered from in_specs)
    manual_axes = _pipe_manual_axes(mesh)
    manual_only = partial(_manual_only, manual_axes=manual_axes)

    def per_device(params, batch, rng):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        s_idx = jax.lax.axis_index("pipe")
        pre_p, post_p = params["pre"], params["post"]
        # local slice of the stacked stage weights: (1, ...) -> (...)
        st_p = jax.tree_util.tree_map(lambda x: x[0], params["stages"])

        # probe activation shape/dtype via the first micro-batch
        micro0 = jax.tree_util.tree_map(lambda x: x[0], batch)
        probe = jax.eval_shape(spec.pre_apply, pre_p, micro0, rng)
        act_shape, act_dtype = probe.shape, probe.dtype
        coop, chunk, ntok = _head_mode(spec, S, act_shape)

        def tick(carry, t):
            act, loss_acc = carry
            in_idx = jnp.clip(t, 0, M - 1)
            micro = jax.tree_util.tree_map(lambda x: x[in_idx], batch)
            # LoadMicroBatch + first-stage layers (computed uniformly on
            # every row — NO branch: pre may contain TP collectives —
            # selected by where to stage 0).
            # disjoint fold-in domains mod (S+1): pre uses residue 0, stages
            # use residues 1..S — no dropout-mask key ever collides
            fresh = spec.pre_apply(pre_p, micro,
                                   jax.random.fold_in(rng, t * (S + 1)))
            act_in = jnp.where(s_idx == 0, fresh.astype(act.dtype), act)
            # ForwardPass for every stage's current micro-batch
            r = jax.random.fold_in(rng, t * (S + 1) + s_idx + 1)
            out = stage_apply(st_p, act_in, r)
            # loss head on the wave exiting the last stage (micro t-(S-1)):
            # cooperative sequence-sharded head when available, else the
            # masked redundant head — always executed uniformly
            out_t = t - (S - 1)
            o_idx = jnp.clip(out_t, 0, M - 1)
            micro_out = jax.tree_util.tree_map(lambda x: x[o_idx], batch)
            valid = jnp.logical_and(out_t >= 0, out_t < M)
            if coop:
                out_last = _psum_act(
                    jnp.where(s_idx == S - 1, out,
                              jnp.zeros(act_shape, act_dtype)), "pipe")
                start = s_idx * chunk
                sl = seq_chunk_select(out_last, s_idx, S, axis=1)
                lsum = spec.post_shard_apply(post_p, pre_p, sl, micro_out,
                                             start)
                loss_m = jnp.where(valid, lsum.astype(jnp.float32), 0.0)
            else:
                lm = spec.post_apply(post_p, pre_p, out, micro_out)
                loss_m = jnp.where(
                    jnp.logical_and(valid, s_idx == S - 1),
                    lm.astype(jnp.float32), 0.0)
            # SendActivation/RecvActivation: rotate stage s -> s+1
            act = jax.lax.ppermute(
                out, "pipe", [(i, (i + 1) % S) for i in range(S)])
            return (act, loss_acc + loss_m), None

        act0 = jnp.zeros(act_shape, act_dtype)
        (_, loss_sum), _ = jax.lax.scan(
            tick, (act0, jnp.zeros((), jnp.float32)), jnp.arange(M + S - 1))

        # _aggregate_total_loss (reference pipe/engine.py:374): psum shares
        # the per-row partial losses with every stage, pmean averages DP
        denom = M * ntok if coop else M
        total = jax.lax.psum(loss_sum, "pipe") / denom
        if "data" in manual_axes:
            total = jax.lax.pmean(total, "data")
        return total

    def loss_fn(params, batch, rng):
        # spec trees built against the actual pytree (PipelineSpec TP specs
        # may be None => replicated/pipe-stacked defaults), then filtered to
        # the manual axes — TP ('model'/'seq') sharding is carried by the
        # arguments themselves in auto mode
        full_specs = jax.tree_util.tree_map(
            manual_only, pipeline_param_specs(spec, params),
            is_leaf=lambda x: isinstance(x, P))
        batch_specs = jax.tree_util.tree_map(
            lambda _: P(None, "data") if "data" in mesh.axis_names else P(),
            batch)
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(full_specs, batch_specs, P()),
            out_specs=P(),
            axis_names=manual_axes,
            check_vma=False)
        return mapped(params, batch, rng)

    loss_fn.owns_cast = compute_dtype is not None
    return loss_fn


def build_pipeline_grad_fn(spec: PipelineSpec, mesh: Mesh, num_micro: int,
                           compute_dtype=None) -> Callable:
    """Return ``grad_fn(params, batch, rng, scale) -> (loss, grads)``
    executing a 1F1B-style pipeline schedule (reference TrainSchedule,
    runtime/pipe/schedule.py:182) as one compiled scan.

    Timing (0-indexed stage s of S, micro m of M): macro-tick u of
    M + 2S - 2 runs, on EVERY row, one forward sub-step (stage s forwards
    micro u - s) and one backward sub-step (stage s backwards micro
    u - (2S-2-s), recomputing its stage body under ``jax.vjp``). Out-of-
    range micros execute on garbage data and are ``where``-masked out —
    never skipped, preserving the uniformity invariant (module docstring):
    all collectives run on every device every tick. The last stage's
    forward and backward of a micro coincide (in-flight depth 0), stage 0
    holds the deepest window (2S-2); the circular stage-input buffer has
    depth 2S-1, so peak activation memory is O(S), flat in M — the
    reference's 1F1B in-flight bound (schedule.py:243 num_pipe_buffers).

    Gradient semantics: returns ``d(mean_micro_loss * scale)/d(params)`` in
    fp32 (accumulated across ticks in fp32; cross-stage grad messages
    travel in the compute dtype like the reference's fp16 p2p grads).
    Tied-weight grads (post head reading pre_p, reference TiedLayerSpec /
    ReduceTiedGrads, pipe/engine.py:203) emerge from the head vjp plus
    stage 0's embedding vjp, combined by a pipe-psum at the end. The loss
    is the unscaled mean micro loss, pmean'd over data.
    """
    if "pipe" not in mesh.axis_names:
        raise ValueError("pipeline execution requires a 'pipe' mesh axis")
    S = spec.num_stages
    M = num_micro
    if axis_size(mesh, "pipe") != S:
        raise ValueError(
            f"mesh pipe axis {axis_size(mesh, 'pipe')} != num_stages {S}")

    manual_axes = _pipe_manual_axes(mesh)
    manual_only = partial(_manual_only, manual_axes=manual_axes)
    B = 2 * S - 1   # circular buffer depth >= deepest in-flight window + 1

    def per_device(params, batch, rng, scale):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        s_idx = jax.lax.axis_index("pipe")
        pre_p, post_p = params["pre"], params["post"]
        st_p = jax.tree_util.tree_map(lambda x: x[0], params["stages"])

        micro0 = jax.tree_util.tree_map(lambda x: x[0], batch)
        probe = jax.eval_shape(spec.pre_apply, pre_p, micro0, rng)
        act_shape, act_dtype = probe.shape, probe.dtype
        coop, chunk, ntok = _head_mode(spec, S, act_shape)
        zeros_act = jnp.zeros(act_shape, act_dtype)

        def key_pre(m):
            return jax.random.fold_in(rng, m * (S + 1))

        def key_stage(m):
            return jax.random.fold_in(rng, m * (S + 1) + s_idx + 1)

        f32_zeros = lambda tree: jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), tree)
        acc_masked = lambda acc, g, valid: jax.tree_util.tree_map(
            lambda a, x: a + jnp.where(valid, x.astype(jnp.float32), 0.0),
            acc, g)

        # loss cotangents: d(mean_over_micros * scale)
        ct_sum = scale / (M * max(ntok, 1))    # per-token-sum head (coop)
        ct_mean = scale / M                    # per-micro-mean head

        def micro_at(m):
            return jax.tree_util.tree_map(lambda x: x[m], batch)

        def tick(carry, u):
            fwd_msg, bwd_msg, buf, loss_acc, g_pre, g_st, g_post = carry

            # ---------------- forward sub-step: micro u - s -------------
            mf_raw = u - s_idx
            mf = jnp.clip(mf_raw, 0, M - 1)
            valid_f = jnp.logical_and(mf_raw >= 0, mf_raw < M)
            micro_f = micro_at(mf)
            fresh = spec.pre_apply(pre_p, micro_f, key_pre(mf))
            act_in = jnp.where(s_idx == 0, fresh.astype(act_dtype), fwd_msg)
            out = spec.stage_apply(st_p, act_in, key_stage(mf))
            slot = mf % B
            old = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(valid_f, act_in, old), slot, 0)

            # ------------- head: micro u - (S-1), all rows --------------
            # (the last stage's forward and backward of a micro coincide,
            # so its head input is this tick's fresh `out`)
            mh_raw = u - (S - 1)
            mh = jnp.clip(mh_raw, 0, M - 1)
            valid_h = jnp.logical_and(mh_raw >= 0, mh_raw < M)
            micro_h = micro_at(mh)
            if coop:
                # sequence-sharded cooperative head: broadcast the exiting
                # activation, each row computes (and differentiates) its
                # 1/S sequence chunk — total head work 1x per micro
                out_last = _psum_act(
                    jnp.where(s_idx == S - 1, out, zeros_act), "pipe")
                start = s_idx * chunk
                sl = seq_chunk_select(out_last, s_idx, S, axis=1)
                lsum, vjp_head = jax.vjp(
                    lambda pp, prp, a: spec.post_shard_apply(
                        pp, prp, a, micro_h, start), post_p, pre_p, sl)
                gpo, gpr, d_sl = vjp_head(ct_sum.astype(lsum.dtype))
                d_sl = jnp.where(valid_h, d_sl, 0.0).astype(act_dtype)
                d_out_head = _psum_act(
                    seq_chunk_scatter(d_sl, s_idx, S, axis=1), "pipe")
                loss_add = jnp.where(valid_h, lsum.astype(jnp.float32), 0.0)
                head_valid = valid_h
            else:
                # masked redundant head: every row computes post_apply on
                # its own `out`; only the last row's input is meaningful
                lmean, vjp_head = jax.vjp(
                    lambda pp, prp, a: spec.post_apply(
                        pp, prp, a, micro_h), post_p, pre_p, out)
                gpo, gpr, d_out_head = vjp_head(ct_mean.astype(lmean.dtype))
                sel = jnp.logical_and(valid_h, s_idx == S - 1)
                loss_add = jnp.where(sel, lmean.astype(jnp.float32), 0.0)
                head_valid = sel
            g_post = acc_masked(g_post, gpo, head_valid)
            g_pre = acc_masked(g_pre, gpr, head_valid)

            # ------------- backward sub-step: micro u - (2S-2-s) --------
            mb_raw = u - (2 * S - 2 - s_idx)
            mb = jnp.clip(mb_raw, 0, M - 1)
            valid_b = jnp.logical_and(mb_raw >= 0, mb_raw < M)
            micro_b = micro_at(mb)
            a_stored = jax.lax.dynamic_index_in_dim(
                buf, mb % B, 0, keepdims=False)
            kb = key_stage(mb)
            _, vjp_stage = jax.vjp(
                lambda sp, a: spec.stage_apply(sp, a, kb), st_p, a_stored)
            g_out = jnp.where(s_idx == S - 1,
                              d_out_head.astype(act_dtype), bwd_msg)
            g_st_m, d_act = vjp_stage(g_out)
            g_st = acc_masked(g_st, g_st_m, valid_b)

            # embedding backward (BackwardPass reaching LoadMicroBatch's
            # producer): executed by every row, input masked to stage 0
            d_for_pre = jnp.where(
                jnp.logical_and(s_idx == 0, valid_b), d_act, 0.0
            ).astype(act_dtype)
            _, vjp_pre = jax.vjp(
                lambda pp: spec.pre_apply(pp, micro_b, key_pre(mb)
                                          ).astype(act_dtype), pre_p)
            g_pre = acc_masked(g_pre, vjp_pre(d_for_pre)[0], True)

            # SendActivation (s -> s+1) and SendGrad (s -> s-1)
            new_fwd = jax.lax.ppermute(
                out, "pipe", [(i, (i + 1) % S) for i in range(S)])
            new_bwd = jax.lax.ppermute(
                jnp.where(valid_b, d_act, 0.0).astype(act_dtype),
                "pipe", [(i, (i - 1) % S) for i in range(S)])
            return (new_fwd, new_bwd, buf, loss_acc + loss_add,
                    g_pre, g_st, g_post), None

        buf0 = jnp.zeros((B,) + act_shape, act_dtype)
        carry0 = (zeros_act, zeros_act, buf0, jnp.zeros((), jnp.float32),
                  f32_zeros(pre_p), f32_zeros(st_p), f32_zeros(post_p))
        (_, _, _, loss_sum, g_pre, g_st, g_post), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + 2 * S - 2))

        # ReduceTiedGrads + loss aggregation: pipe-psum combines the head
        # chunks / embedding / tied contributions and replicates them
        denom = M * ntok if coop else M
        loss = jax.lax.psum(loss_sum, "pipe") / denom
        g_pre = jax.lax.psum(g_pre, "pipe")
        g_post = jax.lax.psum(g_post, "pipe")
        if "data" in manual_axes:
            loss = jax.lax.pmean(loss, "data")
            g_pre = jax.lax.pmean(g_pre, "data")
            g_post = jax.lax.pmean(g_post, "data")
            g_st = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "data"), g_st)
        g_stages = jax.tree_util.tree_map(lambda x: x[None], g_st)
        return loss, {"pre": g_pre, "stages": g_stages, "post": g_post}

    def grad_fn(params, batch, rng, scale):
        full_specs = jax.tree_util.tree_map(
            manual_only, pipeline_param_specs(spec, params),
            is_leaf=lambda x: isinstance(x, P))
        batch_specs = jax.tree_util.tree_map(
            lambda _: P(None, "data") if "data" in mesh.axis_names else P(),
            batch)
        grad_specs = {
            "pre": jax.tree_util.tree_map(lambda _: P(), params["pre"]),
            "stages": full_specs["stages"],
            "post": jax.tree_util.tree_map(lambda _: P(), params["post"]),
        }
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(full_specs, batch_specs, P(), P()),
            out_specs=(P(), grad_specs),
            axis_names=manual_axes,
            check_vma=False)
        return mapped(params, batch, rng,
                      jnp.asarray(scale, jnp.float32))

    return grad_fn


def microbatch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a stacked (M, global_mb, ...) pipeline batch."""
    if "data" in mesh.axis_names:
        return NamedSharding(mesh, P(None, "data"))
    return NamedSharding(mesh, P())


def module_pipeline_spec(module, mesh_or_stages, input_key: str = "x",
                         loss_fn: Optional[Callable] = None) -> PipelineSpec:
    """Adapt a PipelineModule with homogeneous stages to a PipelineSpec.

    - pre: identity on ``micro_batch[input_key]`` (first stage "loads" the
      micro-batch, reference pipe/engine.py:613);
    - stage: the module's per-stage layer chain;
    - post: ``loss_fn(act, micro_batch)`` (module.loss_fn by default).
    """
    num_stages = (mesh_or_stages if isinstance(mesh_or_stages, int)
                  else axis_size(mesh_or_stages, "pipe"))
    if module.num_stages != num_stages:
        raise ValueError(f"module has {module.num_stages} stages, "
                         f"mesh/pipe axis has {num_stages}")
    final_loss = loss_fn or module.loss_fn
    if final_loss is None:
        raise ValueError("a loss_fn is required (module.loss_fn or arg)")

    stage_fn = module.stage_apply_fn()

    def init(key):
        flat = module.init_params(key)
        return {"pre": {}, "stages": module.stack_stage_params(flat),
                "post": {}}

    def pre_apply(pre_p, micro, rng):
        x = micro[input_key] if isinstance(micro, dict) else micro
        return x

    def stage_apply(st_p, act, rng):
        return stage_fn(st_p, act, rng=rng)

    def post_apply(post_p, pre_p, act, micro):
        return final_loss(act, micro)

    return PipelineSpec(init=init, pre_apply=pre_apply,
                        stage_apply=stage_apply, post_apply=post_apply,
                        num_stages=num_stages)
