"""Compiled SPMD pipeline execution — the TPU-native pipeline engine core.

The reference executes pipeline schedules as a per-rank Python interpreter
over blocking NCCL p2p ops (``runtime/pipe/engine.py:1145`` _exec_schedule,
``p2p.py:31,44`` send/recv as 2-rank broadcasts). On TPU that design wastes
the compiler: instead, the *entire* pipelined batch — all micro-batches,
all stages, forward and backward — is ONE jitted program over a mesh with a
``pipe`` axis:

- stage weights are stacked on a leading ``pipe``-sharded dimension, so
  "stage s holds layers [s]" is a *sharding*, not a process assignment;
- each scan tick, every stage applies its layers to its current activation
  and the activations rotate one stage forward via ``lax.ppermute`` (the
  ICI-neighbor collective — the analog of p2p.send/recv);
- micro-batch injection at stage 0 and loss extraction at stage S-1 are
  ``where``-masks on ``lax.axis_index('pipe')``;
- the backward schedule is not hand-written at all: it is the transpose of
  the forward scan (ppermute transposes to the reverse rotation), which
  yields the inverted-wavefront grad flow the reference implements manually
  (_exec_backward_pass / SendGrad / RecvGrad).

Two executors share this dataflow:

- ``build_pipeline_loss_fn``: forward-only wavefront (M + S - 1 ticks) with
  the loss head applied per tick to the wave exiting the last stage —
  realizes InferenceSchedule; differentiable (autodiff transposes the
  ppermute rotation into the reverse grad flow) for callers that want it.
- ``build_pipeline_grad_fn``: the training path — an explicit 1F1B-style
  schedule (reference TrainSchedule, runtime/pipe/schedule.py:182) as one
  scan of M + 2S - 2 macro-ticks, each an unconditional forward sub-step
  (stage s forwards micro u - s) plus backward sub-step (stage s backwards
  micro u - (2S-2-s), recomputing its stage body under ``jax.vjp`` —
  activation checkpointing, inherent). Each stage keeps a depth-(2S-1)
  circular buffer of stage inputs, so peak activation memory is O(S),
  independent of the accumulation depth M — the reference's 1F1B in-flight
  bound (schedule.py:243 num_pipe_buffers). Gradients accumulate
  explicitly in fp32 and are returned directly; the engine skips autodiff
  for pipelined models.

**Uniformity invariant (why there is no lax.cond here):** every collective
— the two ppermute rotations, the head broadcast, and any GSPMD-inserted
TP collective inside stage/pre/post bodies — must execute on every device
on every tick. A branch whose predicate varies along 'pipe' (e.g. "am I
the last stage") would send device cohorts into different collectives and
deadlock (observed as a rendezvous hang on the CPU mesh; a real-TPU hang
in the field). So validity is handled by ``where``-masks on data, never by
skipping code. The cost is honest: at ``num_virtual=1`` the fill/drain
bubble is 2(S-1) ticks instead of the reference 1F1B's S-1 — the price of
single-program SPMD.

**Interleaved virtual stages (``num_virtual=V``)** recover most of that
bubble, the Megatron-LM interleaved-1F1B idea re-derived for the SPMD
scan: each device hosts V *chunks* of 1/V the layers — device s owns
global stages {c*S + s : c < V} (cyclic assignment). The SAME single
ppermute rotation carries the interleaved flow: a forward item index
j = tick - s decodes to chunk c = (j // S) %% V *independently of s*, so
the neighbor rotation always delivers the activation the receiver needs
next tick, and the S-1 -> 0 wraparound carries chunk c's exit back as
chunk c+1's entry. Each macro-tick still runs exactly one forward and one
backward sub-step per device, but a sub-step is now 1/V the work, so in
units of a full (fwd+bwd) stage pass the bubble shrinks from 2(S-1) to
((V-1)S + 2(S-1))/V — ~1.5(S-1) at V=2, approaching S at large V. The
price is the interleaved in-flight window: the stage-input buffer deepens
from 2S-1 to 2VS-1 (1/V-sized) entries, i.e. ~2x activation memory at
V=2 — the same trade Megatron's interleaved schedule makes.

Stage weights for V>1 are stored **interleaved**: stacked index
j = s*V + c holds global stage c*S + s, so the plain contiguous
P('pipe') sharding gives device s exactly its V chunks (local leading
dim V, local index = chunk). Use :func:`interleave_stages` /
:func:`deinterleave_stages` to convert; PipelineEngine does this once at
init and checkpoints store the interleaved layout.

Head placement: the loss head would naively run (masked) on every pipe row
— S redundant vocab-GEMMs per micro. When the spec provides
``post_shard_apply`` (and seq %% S == 0), the last row's exiting
activation is instead pipe-broadcast and each row computes a 1/S sequence
chunk of the head (forward and backward), psum-reassembled: total head
work is 1x per micro-batch, spread across the pipe as a
sequence-parallel head.
"""

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import axis_size


class PipelineSpec(NamedTuple):
    """A pipelined model in functional form.

    - ``init(key) -> {"pre": ..., "stages": ..., "post": ...}`` where the
      ``stages`` leaves carry a leading ``num_stages`` dim (stacked).
    - ``pre_apply(pre_params, micro_batch, rng) -> act``: input layers
      (embedding); runs at stage 0's slot.
    - ``stage_apply(stage_params, act, rng) -> act``: one stage's layers;
      ``stage_params`` is the leading-dim slice for this stage.
    - ``post_apply(post_params, pre_params, act, micro_batch) -> scalar``:
      output layers + loss; receives ``pre_params`` so heads can tie to
      embedding weights (reference TiedLayerSpec, module.py:71).
    - ``post_shard_apply(post_params, pre_params, act_slice, micro_batch,
      start) -> loss_sum`` (optional): the same head on a contiguous
      sequence slice ``act[:, start:start+chunk]``, returning the SUM of
      per-token losses. When provided (and seq divides the stage count)
      the executors compute the head cooperatively across pipe rows —
      each row takes one sequence chunk — instead of redundantly on every
      row. Only valid for losses that decompose per token given the micro
      batch (next-token LM xent does).
    - ``*_specs``: optional PartitionSpec pytrees for tensor-parallel
      sharding of each group; stage specs are per-stacked-leaf *without*
      the leading pipe dim (it is prepended here).
    """
    init: Callable
    pre_apply: Callable
    stage_apply: Callable
    post_apply: Callable
    num_stages: int
    pre_specs: Optional[Any] = None
    stage_specs: Optional[Any] = None
    post_specs: Optional[Any] = None
    post_shard_apply: Optional[Callable] = None


def _prepend_pipe(spec: Optional[P]) -> P:
    if spec is None:
        return P("pipe")
    return P("pipe", *tuple(spec))


def _pipe_manual_axes(mesh: Mesh) -> frozenset:
    return frozenset(a for a in ("pipe", "data") if a in mesh.axis_names)


def _manual_only(p: P, manual_axes) -> P:
    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in manual_axes)
            return kept if kept else None
        return entry if entry in manual_axes else None
    return P(*(keep(e) for e in tuple(p)))


def _psum_act(x, axis_name: str):
    """psum of an activation-sized tensor inside the pipeline scan.

    XLA@jax-0.9.0 bug workaround: a *bfloat16* psum over a manual shard_map
    axis inside lax.scan, with an auto (GSPMD) axis present in the mesh,
    aborts the SPMD partitioner with ``Invalid binary instruction opcode
    copy`` (hlo_instruction.cc:1585). Summing in fp32 and casting back
    partitions cleanly — and is numerically at least as good (the psum
    accumulates in fp32).
    """
    if x.dtype == jnp.float32:
        return jax.lax.psum(x, axis_name)
    return jax.lax.psum(x.astype(jnp.float32), axis_name).astype(x.dtype)


def seq_chunk_select(x, s_idx, S: int, axis: int = 1):
    """Select sequence block ``s_idx`` of ``S`` equal chunks along ``axis``
    WITHOUT a traced-start dynamic_slice: reshape (.., S*chunk, ..) ->
    (.., S, chunk, ..) and contract with a one-hot of ``s_idx``.

    Rationale: under shard_map with auto (GSPMD) axes present in the mesh,
    traced-start dynamic-slice/update-slice on these activations trips an
    XLA partitioner CHECK ("Invalid binary instruction opcode copy",
    hlo_instruction.cc:1585, XLA@jax 0.9.0) while compiling the pipelined
    step. The reshape + one-hot masked-sum form partitions cleanly and
    costs one extra elementwise pass over the block — noise next to the
    head GEMM it feeds.
    """
    shape = x.shape
    chunk = shape[axis] // S
    resh = x.reshape(shape[:axis] + (S, chunk) + shape[axis + 1:])
    bshape = (1,) * axis + (S,) + (1,) * (resh.ndim - axis - 1)
    onehot = (jax.lax.iota(jnp.int32, S) == s_idx).reshape(bshape)
    return jnp.sum(jnp.where(onehot, resh, jnp.zeros((), resh.dtype)),
                   axis=axis)


def seq_chunk_scatter(chunk_val, s_idx, S: int, axis: int = 1):
    """Inverse of :func:`seq_chunk_select`: embed a (.., chunk, ..) block
    at position ``s_idx`` of ``S`` along ``axis``, zeros elsewhere —
    again avoiding traced-index dynamic_update_slice (see select)."""
    shape = chunk_val.shape
    expanded = jnp.expand_dims(chunk_val, axis)
    bshape = (1,) * axis + (S,) + (1,) * (expanded.ndim - axis - 1)
    onehot = (jax.lax.iota(jnp.int32, S) == s_idx).reshape(bshape)
    full = jnp.where(onehot, expanded, jnp.zeros((), chunk_val.dtype))
    return full.reshape(shape[:axis] + (S * shape[axis],) + shape[axis + 1:])


def _head_mode(spec: "PipelineSpec", S: int, act_shape):
    """(coop, chunk, ntok): cooperative sequence-sharded head is usable
    whenever the spec provides post_shard_apply and the activation is
    (mb, seq, ...). Ragged sequences (seq %% S != 0) are zero-padded to
    S*chunk at the head boundary (chunk = ceil(seq/S)); the spec's
    post_shard_apply weight-masks the pad positions (models/gpt2.py).
    ``ntok`` counts only REAL tokens."""
    if spec.post_shard_apply is not None and len(act_shape) >= 2:
        return True, -(-act_shape[1] // S), act_shape[0] * act_shape[1]
    return False, 0, 0


def _pad_head_seq(x, S: int, chunk: int):
    """Zero-pad the (mb, seq, ...) head input to seq = S*chunk."""
    pad = S * chunk - x.shape[1]
    if pad == 0:
        return x
    cfg = ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2)
    return jnp.pad(x, cfg)


def _run_phased(tick, carry, S: int, V: int, Mp: int, drain: bool):
    """Drive a tick body over the phased head schedule — the ONE place
    encoding the head-active-tick invariant for both executors.

    ``tick(carry, u, with_head) -> carry`` with STATIC ``with_head``.
    Head-active ticks are u with (u-(S-1))//S %% V == V-1: runs of S
    ticks starting at u = (q+1)VS - 1 per micro group q < Mp/S. Phases:
    fill (VS-1 headless), Mp/S superblocks (S head + (V-1)S headless),
    then for the grad executor (``drain=True``) S-1 headless drain ticks
    — total Mp·V + VS + S - 2 = pipeline_tick_counts; the forward-only
    wavefront (``drain=False``) instead ends ON the final head run —
    total Mp·V + S - 1. Requires Mp %% S == 0 (callers fall back to a
    uniform head-on-every-tick scan otherwise).
    """
    assert Mp % S == 0, (Mp, S)
    G = V * S

    def scan_range(carry, start, length, with_head):
        if length <= 0:
            return carry
        carry, _ = jax.lax.scan(
            lambda c, u: (tick(c, u, with_head), None),
            carry, start + jnp.arange(length))
        return carry

    def qblock(c, q0):
        c = scan_range(c, q0, S, True)
        c = scan_range(c, q0 + S, (V - 1) * S, False)
        return c, None

    carry = scan_range(carry, jnp.int32(0), G - 1, False)
    if drain:
        starts = (G - 1) + G * jnp.arange(Mp // S)
        carry, _ = jax.lax.scan(qblock, carry, starts)
        return scan_range(carry, jnp.int32(Mp * V + G - 1), S - 1, False)
    if Mp // S > 1:
        starts = (G - 1) + G * jnp.arange(Mp // S - 1)
        carry, _ = jax.lax.scan(qblock, carry, starts)
    return scan_range(carry, jnp.int32(Mp * V - 1), S, True)


def _run_uniform(tick, carry, num_ticks: int):
    """Fallback: every tick carries the (masked) head."""
    carry, _ = jax.lax.scan(
        lambda c, u: (tick(c, u, True), None),
        carry, jnp.arange(num_ticks))
    return carry


def interleave_stage_order(S: int, V: int):
    """Permutation: interleaved slot ``j = s*V + c`` holds global stage
    ``c*S + s`` (device s's contiguous block = its V cyclic chunks)."""
    return [(j % V) * S + j // V for j in range(S * V)]


def interleave_stages(stages: Any, S: int, V: int) -> Any:
    """Reorder a (G, ...)-stacked stage pytree from global-stage order to
    the interleaved at-rest layout the V>1 executors expect."""
    if V == 1:
        return stages
    order = jnp.asarray(interleave_stage_order(S, V))
    return jax.tree_util.tree_map(lambda x: jnp.take(x, order, axis=0),
                                  stages)


def deinterleave_stages(stages: Any, S: int, V: int) -> Any:
    """Inverse of :func:`interleave_stages` (global stage g sits at
    interleaved slot (g %% S)*V + g//S)."""
    if V == 1:
        return stages
    inv = jnp.asarray([(g % S) * V + g // S for g in range(S * V)])
    return jax.tree_util.tree_map(lambda x: jnp.take(x, inv, axis=0),
                                  stages)


def _padded_micro_count(S: int, M: int, V: int) -> int:
    """Interleaving schedules micros in groups of S (the cyclic rotation
    only lines up for full groups — a partial group's chunk handoff would
    arrive a tick early). For V>1 the item space is padded to whole
    groups; padded micros decode as invalid and are masked, costing
    (Mp-M)V bubble ticks. V=1 needs no grouping (decode is exact)."""
    if V == 1:
        return M
    return -(-M // S) * S


def pipeline_tick_counts(S: int, M: int, V: int = 1):
    """(scan_ticks, normalized_ticks) for the 1F1B grad executor.

    ``normalized`` is in units of one full (fwd+bwd) pass over a device's
    whole layer share — the V=1 macro-tick — so the ideal is M and the
    bubble is ``normalized - M`` = ((V-1)S + 2(S-1))/V when S divides M
    (plus the group-padding ticks otherwise).
    """
    Mp = _padded_micro_count(S, M, V)
    total = Mp * V + (V - 1) * S + 2 * (S - 1)
    return total, total / V


def _decode_fwd(j, S: int, V: int, M: int, Mp: int):
    """Forward work-item index -> (micro, chunk, clipped_item, valid).

    Device s's ordered forward list: for group q, for chunk c, for i < S:
    item q*V*S + c*S + i = micro q*S + i, chunk c — over the PADDED micro
    space [0, Mp); items whose micro lands in the pad tail [M, Mp) are
    invalid (masked)."""
    in_items = jnp.logical_and(j >= 0, j < Mp * V)
    jc = jnp.clip(j, 0, Mp * V - 1)
    c = (jc // S) % V
    m = (jc // (S * V)) * S + jc % S
    valid = jnp.logical_and(in_items, m < M)
    return jnp.clip(m, 0, M - 1), c, jc, valid


def _decode_bwd(k, S: int, V: int, M: int, Mp: int):
    """Backward work-item index -> (micro, chunk, fwd_item, valid);
    chunks drain in reverse (c = V-1 first), mirroring the forward list."""
    in_items = jnp.logical_and(k >= 0, k < Mp * V)
    kc = jnp.clip(k, 0, Mp * V - 1)
    c = V - 1 - (kc // S) % V
    m = (kc // (S * V)) * S + kc % S
    jf = (kc // (S * V)) * (S * V) + c * S + kc % S
    valid = jnp.logical_and(in_items, m < M)
    return jnp.clip(m, 0, M - 1), c, jf, valid


def _select_chunk(tree: Any, c, V: int) -> Any:
    """Slice chunk ``c`` from local (V, ...)-leading stage leaves via a
    one-hot contraction (traced-index dynamic_slice on shard_map operands
    trips the XLA partitioner — see seq_chunk_select). Reads all V chunks,
    but the V chunks together are one stage's weights: total read
    bandwidth matches V=1."""
    if V == 1:
        return jax.tree_util.tree_map(lambda x: x[0], tree)
    oh = jax.lax.iota(jnp.int32, V) == c

    def sel(x):
        m = oh.reshape((V,) + (1,) * (x.ndim - 1))
        return jnp.sum(jnp.where(m, x, jnp.zeros((), x.dtype)), axis=0)
    return jax.tree_util.tree_map(sel, tree)


def _acc_chunk(acc: Any, grads: Any, c, valid, V: int) -> Any:
    """Accumulate chunk-shaped fp32 grads into the (V, ...)-leading
    accumulator at row ``c`` (transpose of :func:`_select_chunk`)."""
    if V == 1:
        return jax.tree_util.tree_map(
            lambda a, x: a + jnp.where(valid, x.astype(jnp.float32), 0.0),
            acc, grads)
    oh = jax.lax.iota(jnp.int32, V) == c

    def add(a, x):
        m = jnp.logical_and(oh, valid).reshape((V,) + (1,) * x.ndim)
        return a + jnp.where(m, x.astype(jnp.float32)[None], 0.0)
    return jax.tree_util.tree_map(add, acc, grads)


def pipeline_param_specs(spec: PipelineSpec, params: Any) -> Any:
    """PartitionSpec pytree for the full pipeline params: stacked stage
    leaves get 'pipe' on dim 0 (+ any TP spec shifted right); pre/post get
    their TP specs or replication."""
    def expand(group, tp_specs, stacked: bool):
        if tp_specs is None:
            return jax.tree_util.tree_map(
                lambda _: _prepend_pipe(None) if stacked else P(), group)
        return jax.tree_util.tree_map(
            lambda _, s: _prepend_pipe(s) if stacked else (s or P()),
            group, tp_specs)
    return {
        "pre": expand(params["pre"], spec.pre_specs, stacked=False),
        "stages": expand(params["stages"], spec.stage_specs, stacked=True),
        "post": expand(params["post"], spec.post_specs, stacked=False),
    }


def build_pipeline_loss_fn(spec: PipelineSpec, mesh: Mesh, num_micro: int,
                           remat: bool = True,
                           compute_dtype=None,
                           num_virtual: int = 1) -> Callable:
    """Return ``loss_fn(params, batch, rng) -> scalar`` running the full
    pipelined forward; engine-contract compatible (runtime/engine.py).

    ``batch`` leaves must have leading dim ``num_micro`` then the global
    micro-batch dim (sharded over 'data').

    ``compute_dtype``: when set, fp32 params are cast INSIDE the mapped
    program (the returned fn carries ``owns_cast=True`` so the engine skips
    its own cast). This keeps every cross-stage gradient psum in fp32 —
    the master-grad precision ZeRO expects — with only the bf16 compute
    copies crossing into the stage bodies.

    ``num_virtual``: interleaved virtual stages per device (module
    docstring); ``spec.num_stages`` must equal ``num_virtual * pipe-axis``
    and the stacked stage params must be in the interleaved layout
    (:func:`interleave_stages`).
    """
    if "pipe" not in mesh.axis_names:
        raise ValueError("pipeline execution requires a 'pipe' mesh axis")
    V = num_virtual
    S = axis_size(mesh, "pipe")
    M = num_micro
    if spec.num_stages != V * S:
        raise ValueError(
            f"num_stages {spec.num_stages} != num_virtual {V} * pipe axis "
            f"{S}")

    stage_apply = spec.stage_apply
    if remat:
        stage_apply = jax.checkpoint(spec.stage_apply)

    # pipeline + data flow are hand-scheduled (manual axes); tensor/sequence
    # parallel axes stay in "auto" mode so GSPMD keeps doing TP inside each
    # stage body (specs naming auto axes must be filtered from in_specs)
    manual_axes = _pipe_manual_axes(mesh)
    manual_only = partial(_manual_only, manual_axes=manual_axes)

    def per_device(params, batch, rng):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        s_idx = jax.lax.axis_index("pipe")
        pre_p, post_p = params["pre"], params["post"]
        # local slice of the stacked stage weights: (V, ...) chunks
        st_p = params["stages"]

        # probe activation shape/dtype via the first micro-batch
        micro0 = jax.tree_util.tree_map(lambda x: x[0], batch)
        probe = jax.eval_shape(spec.pre_apply, pre_p, micro0, rng)
        act_shape, act_dtype = probe.shape, probe.dtype
        coop, chunk, ntok = _head_mode(spec, S, act_shape)
        G = V * S  # global stage count; fold-in domain stride is G+1
        Mp = _padded_micro_count(S, M, V)

        def tick(carry, t, with_head):
            act, loss_acc = carry
            # forward work item t - s: micro m_f, chunk c_f
            m_f, c_f, _, _ = _decode_fwd(t - s_idx, S, V, M, Mp)
            micro = jax.tree_util.tree_map(lambda x: x[m_f], batch)
            # LoadMicroBatch + first-stage layers (computed uniformly on
            # every row — NO branch: pre may contain TP collectives —
            # selected by where to global stage 0 = (row 0, chunk 0)).
            # disjoint fold-in domains mod (G+1): pre uses residue 0,
            # stages use residues 1..G — no dropout-mask key ever collides
            fresh = spec.pre_apply(pre_p, micro,
                                   jax.random.fold_in(rng, m_f * (G + 1)))
            act_in = jnp.where(
                jnp.logical_and(s_idx == 0, c_f == 0),
                fresh.astype(act.dtype), act)
            # ForwardPass for every row's current (micro, chunk) item
            g_idx = c_f * S + s_idx  # global stage
            r = jax.random.fold_in(rng, m_f * (G + 1) + g_idx + 1)
            out = stage_apply(_select_chunk(st_p, c_f, V), act_in, r)
            # loss head on the wave exiting the LAST GLOBAL stage — the
            # tick where row S-1 forwards a chunk V-1 item: cooperative
            # sequence-sharded head when available, else the masked
            # redundant head. ``with_head`` is STATIC (grad-fn tick
            # docstring): headless ticks skip the head entirely.
            if with_head:
                m_h, c_h, _, in_range = _decode_fwd(t - (S - 1), S, V, M, Mp)
                micro_out = jax.tree_util.tree_map(lambda x: x[m_h], batch)
                valid = jnp.logical_and(in_range, c_h == V - 1)
            if with_head and coop:
                out_last = _psum_act(
                    jnp.where(s_idx == S - 1, out,
                              jnp.zeros(act_shape, act_dtype)), "pipe")
                out_last = _pad_head_seq(out_last, S, chunk)
                start = s_idx * chunk
                sl = seq_chunk_select(out_last, s_idx, S, axis=1)
                lsum = spec.post_shard_apply(post_p, pre_p, sl, micro_out,
                                             start)
                loss_m = jnp.where(valid, lsum.astype(jnp.float32), 0.0)
            elif with_head:
                lm = spec.post_apply(post_p, pre_p, out, micro_out)
                loss_m = jnp.where(
                    jnp.logical_and(valid, s_idx == S - 1),
                    lm.astype(jnp.float32), 0.0)
            else:
                loss_m = jnp.zeros((), jnp.float32)
            # SendActivation/RecvActivation: rotate stage s -> s+1 (the
            # S-1 -> 0 wraparound carries chunk c's exit to chunk c+1)
            act = jax.lax.ppermute(
                out, "pipe", [(i, (i + 1) % S) for i in range(S)])
            return (act, loss_acc + loss_m)

        carry = (jnp.zeros(act_shape, act_dtype),
                 jnp.zeros((), jnp.float32))
        if Mp % S == 0:
            carry = _run_phased(tick, carry, S, V, Mp, drain=False)
        else:
            carry = _run_uniform(tick, carry, Mp * V + S - 1)
        (_, loss_sum) = carry

        # _aggregate_total_loss (reference pipe/engine.py:374): psum shares
        # the per-row partial losses with every stage, pmean averages DP
        denom = M * ntok if coop else M
        total = jax.lax.psum(loss_sum, "pipe") / denom
        if "data" in manual_axes:
            total = jax.lax.pmean(total, "data")
        return total

    def loss_fn(params, batch, rng):
        # spec trees built against the actual pytree (PipelineSpec TP specs
        # may be None => replicated/pipe-stacked defaults), then filtered to
        # the manual axes — TP ('model'/'seq') sharding is carried by the
        # arguments themselves in auto mode
        full_specs = jax.tree_util.tree_map(
            manual_only, pipeline_param_specs(spec, params),
            is_leaf=lambda x: isinstance(x, P))
        batch_specs = jax.tree_util.tree_map(
            lambda _: P(None, "data") if "data" in mesh.axis_names else P(),
            batch)
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(full_specs, batch_specs, P()),
            out_specs=P(),
            axis_names=manual_axes,
            check_vma=False)
        return mapped(params, batch, rng)

    loss_fn.owns_cast = compute_dtype is not None
    loss_fn.num_virtual = V
    return loss_fn


def build_pipeline_grad_fn(spec: PipelineSpec, mesh: Mesh, num_micro: int,
                           compute_dtype=None,
                           num_virtual: int = 1) -> Callable:
    """Return ``grad_fn(params, batch, rng, scale) -> (loss, grads)``
    executing a 1F1B-style pipeline schedule (reference TrainSchedule,
    runtime/pipe/schedule.py:182) as one compiled scan.

    Timing (0-indexed device s of S, V chunks per device, micro m of M):
    macro-tick u of MV + (V-1)S + 2(S-1) runs, on EVERY row, one forward
    sub-step (device s forwards its work item u - s: micro/chunk decoded
    by :func:`_decode_fwd`) and one backward sub-step (work item
    u - (VS + S - 2 - s), chunks draining in reverse, recomputing the
    chunk body under ``jax.vjp``). Out-of-range items execute on garbage
    data and are ``where``-masked out — never skipped, preserving the
    uniformity invariant (module docstring): all collectives run on every
    device every tick. The last global stage's forward and backward of a
    micro coincide (in-flight depth 0); the circular stage-input buffer
    has depth 2VS-1, so peak activation memory is O(VS) 1/V-sized
    entries, flat in M — the reference's 1F1B in-flight bound
    (schedule.py:243 num_pipe_buffers) times the interleaving window.
    At V=1 this is exactly the classic schedule: forward micro u - s,
    backward micro u - (2S-2-s), M + 2S - 2 ticks.

    Gradient semantics: returns ``d(mean_micro_loss * scale)/d(params)`` in
    fp32 (accumulated across ticks in fp32; cross-stage grad messages
    travel in the compute dtype like the reference's fp16 p2p grads).
    Tied-weight grads (post head reading pre_p, reference TiedLayerSpec /
    ReduceTiedGrads, pipe/engine.py:203) emerge from the head vjp plus
    stage 0's embedding vjp, combined by a pipe-psum at the end. The loss
    is the unscaled mean micro loss, pmean'd over data.
    """
    if "pipe" not in mesh.axis_names:
        raise ValueError("pipeline execution requires a 'pipe' mesh axis")
    V = num_virtual
    S = axis_size(mesh, "pipe")
    M = num_micro
    if spec.num_stages != V * S:
        raise ValueError(
            f"num_stages {spec.num_stages} != num_virtual {V} * pipe axis "
            f"{S}")

    manual_axes = _pipe_manual_axes(mesh)
    manual_only = partial(_manual_only, manual_axes=manual_axes)
    G = V * S
    Mp = _padded_micro_count(S, M, V)
    B = 2 * G - 1   # circular buffer depth >= deepest in-flight window + 1
    num_ticks, normalized_ticks = pipeline_tick_counts(S, M, V)

    def per_device(params, batch, rng, scale):
        if compute_dtype is not None:
            params = jax.tree_util.tree_map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        s_idx = jax.lax.axis_index("pipe")
        pre_p, post_p = params["pre"], params["post"]
        st_p = params["stages"]  # local (V, ...) chunks

        micro0 = jax.tree_util.tree_map(lambda x: x[0], batch)
        probe = jax.eval_shape(spec.pre_apply, pre_p, micro0, rng)
        act_shape, act_dtype = probe.shape, probe.dtype
        coop, chunk, ntok = _head_mode(spec, S, act_shape)
        zeros_act = jnp.zeros(act_shape, act_dtype)

        def key_pre(m):
            return jax.random.fold_in(rng, m * (G + 1))

        def key_stage(m, c):
            return jax.random.fold_in(rng, m * (G + 1) + c * S + s_idx + 1)

        f32_zeros = lambda tree: jax.tree_util.tree_map(
            lambda x: jnp.zeros(x.shape, jnp.float32), tree)
        acc_masked = lambda acc, g, valid: jax.tree_util.tree_map(
            lambda a, x: a + jnp.where(valid, x.astype(jnp.float32), 0.0),
            acc, g)

        # loss cotangents: d(mean_over_micros * scale)
        ct_sum = scale / (M * max(ntok, 1))    # per-token-sum head (coop)
        ct_mean = scale / M                    # per-micro-mean head

        def micro_at(m):
            return jax.tree_util.tree_map(lambda x: x[m], batch)

        def tick(carry, u, with_head):
            """One macro-tick. ``with_head`` is STATIC: ticks where no
            micro can exit the last global stage skip the head entirely.
            The head-active ticks form a static pattern (runs of S every
            VS ticks), so the caller phases the scan instead of paying a
            masked full head (+ its vjp) on every tick — without this,
            interleaving (V>1) would multiply total head work by ~V and
            eat its own bubble gain."""
            fwd_msg, bwd_msg, buf, loss_acc, g_pre, g_st, g_post = carry

            # ------------- forward sub-step: work item u - s ------------
            mf, cf, jf, valid_f = _decode_fwd(u - s_idx, S, V, M, Mp)
            micro_f = micro_at(mf)
            fresh = spec.pre_apply(pre_p, micro_f, key_pre(mf))
            act_in = jnp.where(
                jnp.logical_and(s_idx == 0, cf == 0),
                fresh.astype(act_dtype), fwd_msg)
            out = spec.stage_apply(_select_chunk(st_p, cf, V), act_in,
                                   key_stage(mf, cf))
            slot = jf % B
            old = jax.lax.dynamic_index_in_dim(buf, slot, 0, keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(valid_f, act_in, old), slot, 0)

            # --- head: item u - (S-1) when it exits chunk V-1, all rows -
            # (the last global stage's forward and backward of a micro
            # coincide, so its head input is this tick's fresh `out`)
            if with_head:
                mh, ch, _, h_range = _decode_fwd(u - (S - 1), S, V, M, Mp)
                valid_h = jnp.logical_and(h_range, ch == V - 1)
                micro_h = micro_at(mh)
            if with_head and coop:
                # sequence-sharded cooperative head: broadcast the exiting
                # activation, each row computes (and differentiates) its
                # 1/S sequence chunk — total head work 1x per micro
                out_last = _psum_act(
                    jnp.where(s_idx == S - 1, out, zeros_act), "pipe")
                out_last = _pad_head_seq(out_last, S, chunk)
                start = s_idx * chunk
                sl = seq_chunk_select(out_last, s_idx, S, axis=1)
                lsum, vjp_head = jax.vjp(
                    lambda pp, prp, a: spec.post_shard_apply(
                        pp, prp, a, micro_h, start), post_p, pre_p, sl)
                gpo, gpr, d_sl = vjp_head(ct_sum.astype(lsum.dtype))
                d_sl = jnp.where(valid_h, d_sl, 0.0).astype(act_dtype)
                d_full = seq_chunk_scatter(d_sl, s_idx, S, axis=1)
                if d_full.shape[1] != act_shape[1]:   # drop ragged pad
                    d_full = jax.lax.slice_in_dim(
                        d_full, 0, act_shape[1], axis=1)
                d_out_head = _psum_act(d_full, "pipe")
                loss_add = jnp.where(valid_h, lsum.astype(jnp.float32), 0.0)
                head_valid = valid_h
            elif with_head:
                # masked redundant head: every row computes post_apply on
                # its own `out`; only the last row's input is meaningful
                lmean, vjp_head = jax.vjp(
                    lambda pp, prp, a: spec.post_apply(
                        pp, prp, a, micro_h), post_p, pre_p, out)
                gpo, gpr, d_out_head = vjp_head(ct_mean.astype(lmean.dtype))
                sel = jnp.logical_and(valid_h, s_idx == S - 1)
                loss_add = jnp.where(sel, lmean.astype(jnp.float32), 0.0)
                head_valid = sel
            else:
                # no micro exits the last global stage on this tick: the
                # backward's cb==V-1 selector can only fire on garbage
                # (valid_b False), so a zero stand-in is sound
                d_out_head = zeros_act
                loss_add = jnp.zeros((), jnp.float32)
            if with_head:
                g_post = acc_masked(g_post, gpo, head_valid)
                g_pre = acc_masked(g_pre, gpr, head_valid)

            # ------ backward sub-step: work item u - (VS + S - 2 - s) ---
            mb, cb, jfb, valid_b = _decode_bwd(
                u - (G + S - 2 - s_idx), S, V, M, Mp)
            micro_b = micro_at(mb)
            a_stored = jax.lax.dynamic_index_in_dim(
                buf, jfb % B, 0, keepdims=False)
            kb = key_stage(mb, cb)
            st_c = _select_chunk(st_p, cb, V)
            _, vjp_stage = jax.vjp(
                lambda sp, a: spec.stage_apply(sp, a, kb), st_c, a_stored)
            g_out = jnp.where(
                jnp.logical_and(s_idx == S - 1, cb == V - 1),
                d_out_head.astype(act_dtype), bwd_msg)
            g_st_m, d_act = vjp_stage(g_out)
            g_st = _acc_chunk(g_st, g_st_m, cb, valid_b, V)

            # embedding backward (BackwardPass reaching LoadMicroBatch's
            # producer): executed by every row, input masked to global
            # stage 0 = (row 0, chunk 0)
            d_for_pre = jnp.where(
                jnp.logical_and(jnp.logical_and(s_idx == 0, cb == 0),
                                valid_b), d_act, 0.0
            ).astype(act_dtype)
            _, vjp_pre = jax.vjp(
                lambda pp: spec.pre_apply(pp, micro_b, key_pre(mb)
                                          ).astype(act_dtype), pre_p)
            g_pre = acc_masked(g_pre, vjp_pre(d_for_pre)[0], True)

            # SendActivation (s -> s+1) and SendGrad (s -> s-1)
            new_fwd = jax.lax.ppermute(
                out, "pipe", [(i, (i + 1) % S) for i in range(S)])
            new_bwd = jax.lax.ppermute(
                jnp.where(valid_b, d_act, 0.0).astype(act_dtype),
                "pipe", [(i, (i - 1) % S) for i in range(S)])
            return (new_fwd, new_bwd, buf, loss_acc + loss_add,
                    g_pre, g_st, g_post)

        buf0 = jnp.zeros((B,) + act_shape, act_dtype)
        g_st0 = f32_zeros(_select_chunk(st_p, 0, V) if V == 1 else st_p)
        carry0 = (zeros_act, zeros_act, buf0, jnp.zeros((), jnp.float32),
                  f32_zeros(pre_p), g_st0, f32_zeros(post_p))
        if Mp % S == 0:
            carry = _run_phased(tick, carry0, S, V, Mp, drain=True)
        else:
            # uneven micro count (only reachable at V=1 where Mp == M):
            # fall back to head-on-every-tick
            carry = _run_uniform(tick, carry0, num_ticks)
        (_, _, _, loss_sum, g_pre, g_st, g_post) = carry

        # ReduceTiedGrads + loss aggregation: pipe-psum combines the head
        # chunks / embedding / tied contributions and replicates them
        denom = M * ntok if coop else M
        loss = jax.lax.psum(loss_sum, "pipe") / denom
        g_pre = jax.lax.psum(g_pre, "pipe")
        g_post = jax.lax.psum(g_post, "pipe")
        if "data" in manual_axes:
            loss = jax.lax.pmean(loss, "data")
            g_pre = jax.lax.pmean(g_pre, "data")
            g_post = jax.lax.pmean(g_post, "data")
            g_st = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "data"), g_st)
        g_stages = (jax.tree_util.tree_map(lambda x: x[None], g_st)
                    if V == 1 else g_st)
        return loss, {"pre": g_pre, "stages": g_stages, "post": g_post}

    def grad_fn(params, batch, rng, scale):
        full_specs = jax.tree_util.tree_map(
            manual_only, pipeline_param_specs(spec, params),
            is_leaf=lambda x: isinstance(x, P))
        batch_specs = jax.tree_util.tree_map(
            lambda _: P(None, "data") if "data" in mesh.axis_names else P(),
            batch)
        grad_specs = {
            "pre": jax.tree_util.tree_map(lambda _: P(), params["pre"]),
            "stages": full_specs["stages"],
            "post": jax.tree_util.tree_map(lambda _: P(), params["post"]),
        }
        mapped = jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(full_specs, batch_specs, P(), P()),
            out_specs=(P(), grad_specs),
            axis_names=manual_axes,
            check_vma=False)
        return mapped(params, batch, rng,
                      jnp.asarray(scale, jnp.float32))

    grad_fn.num_ticks = num_ticks
    grad_fn.normalized_ticks = normalized_ticks
    grad_fn.num_virtual = V
    return grad_fn


def microbatch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a stacked (M, global_mb, ...) pipeline batch."""
    if "data" in mesh.axis_names:
        return NamedSharding(mesh, P(None, "data"))
    return NamedSharding(mesh, P())


def module_pipeline_spec(module, mesh_or_stages, input_key: str = "x",
                         loss_fn: Optional[Callable] = None) -> PipelineSpec:
    """Adapt a PipelineModule with homogeneous stages to a PipelineSpec.

    - pre: identity on ``micro_batch[input_key]`` (first stage "loads" the
      micro-batch, reference pipe/engine.py:613);
    - stage: the module's per-stage layer chain;
    - post: ``loss_fn(act, micro_batch)`` (module.loss_fn by default).
    """
    num_stages = (mesh_or_stages if isinstance(mesh_or_stages, int)
                  else axis_size(mesh_or_stages, "pipe"))
    if module.num_stages != num_stages:
        raise ValueError(f"module has {module.num_stages} stages, "
                         f"mesh/pipe axis has {num_stages}")
    final_loss = loss_fn or module.loss_fn
    if final_loss is None:
        raise ValueError("a loss_fn is required (module.loss_fn or arg)")

    stage_fn = module.stage_apply_fn()

    def init(key):
        flat = module.init_params(key)
        return {"pre": {}, "stages": module.stack_stage_params(flat),
                "post": {}}

    def pre_apply(pre_p, micro, rng):
        x = micro[input_key] if isinstance(micro, dict) else micro
        return x

    def stage_apply(st_p, act, rng):
        return stage_fn(st_p, act, rng=rng)

    def post_apply(post_p, pre_p, act, micro):
        return final_loss(act, micro)

    return PipelineSpec(init=init, pre_apply=pre_apply,
                        stage_apply=stage_apply, post_apply=post_apply,
                        num_stages=num_stages)
