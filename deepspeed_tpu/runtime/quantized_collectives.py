"""Hierarchical block-quantized collectives — ZeRO++-style comm compression.

TPU-native extension past the reference snapshot (whose only compressed
collective is 1-bit Adam's sign exchange): data-parallel gradients and
(opt-in) ZeRO weight gathers cross the wire as int8 with per-block fp32
scales, following ZeRO++'s qgZ/qwZ/hpZ (arXiv:2306.10209) and EQuARX
(arXiv:2506.17615), re-expressed as in-jit XLA collectives so the whole
exchange is auditable in partitioned HLO.

Three gradient-exchange algorithms, all shard_map-composable:

``allgather`` (legacy; only sane at dp=2)::

    quantize -> all_gather(int8 + scales) over 'data' -> dequant + mean

  Per-rank wire is O(W*n): every rank receives every other rank's FULL
  quantized gradient. At W >= 4 this moves MORE bytes than a plain bf16
  ring allreduce (2n * 2B) — compression defeated by the exchange shape.

``twohop`` (qgZ; the default)::

    quantize -> all_to_all chunk j -> rank j        (~n int8 out/in)
    -> fp32 partial-sum of the owned 1/W chunk
    -> requantize -> all_gather(reduced chunk)      (~n int8 in)

  Per-rank wire is ~2n int8 bytes + scales, INDEPENDENT of W — always
  below the 4n-byte dense bf16 ring.

``twohop`` + hierarchical (qgZ over a 2D data axis)::

    intra hop : quantize -> all_to_all over 'data_intra' -> partial sum
    inter hop : two-hop allreduce of the owned 1/Wi chunk over
                'data_inter' (~2n/Wi int8 on the slow axis)
    gather    : requantize -> all_gather over 'data_intra'

  The bandwidth-heavy hops (~2n int8) stay on the fast intra-slice ICI;
  only the reduced 1/Wi chunk ever crosses the slow inter axis.

Summation always happens in fp32 AFTER dequantization (int8 sums would
overflow) — EQuARX's "quantize the wire, not the math". Quantization is
symmetric per block of 256 values (absmax scaling, round-to-nearest):
unbiased up to rounding, error bounded by absmax/127 per element; the
two-hop paths requantize the reduced chunk, compounding one extra
rounding (the ZeRO++ trade).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.custom_collectives import (pad_flat_to_multiple,
                                                      pad_to_multiple)

__all__ = ["quantize_blockwise", "dequantize_blockwise",
           "quantized_allreduce_mean", "hierarchical_quantized_allreduce_mean",
           "wire_bytes", "wire_bytes_by_axis", "wire_hops",
           "ALGO_ALLGATHER", "ALGO_TWOHOP", "QUANTIZED_ALGOS"]

DEFAULT_BLOCK = 256
ALGO_ALLGATHER = "allgather"
ALGO_TWOHOP = "twohop"
QUANTIZED_ALGOS = (ALGO_TWOHOP, ALGO_ALLGATHER)


def quantize_blockwise(x: jax.Array, block: int = DEFAULT_BLOCK
                       ) -> Tuple[jax.Array, jax.Array, int]:
    """Flatten + symmetric int8 quantization per block of ``block``
    values. Returns (q (nb, block) int8, scales (nb,) fp32, orig_size)."""
    n = x.size
    flat, _ = pad_flat_to_multiple(x.reshape(-1).astype(jnp.float32), block)
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -127, 127
                 ).astype(jnp.int8)
    return q, scale.astype(jnp.float32), n


def dequantize_blockwise(q: jax.Array, scale: jax.Array, n: int,
                         shape=None) -> jax.Array:
    out = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)[:n]
    return out.reshape(shape) if shape is not None else out


def _quantize_chunked(flat: jax.Array, world: int, block: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Quantize a (pre-padded, multiple of world*block) flat fp32 array
    into per-rank chunks: q (world, cb, block) int8, s (world, cb) f32."""
    cb = flat.shape[0] // (world * block)
    q, s, _ = quantize_blockwise(flat, block)
    return q.reshape(world, cb, block), s.reshape(world, cb)


def _dequant_mean(q: jax.Array, s: jax.Array, world: int) -> jax.Array:
    """fp32 mean over the leading (source-rank) axis of quantized rows."""
    return jnp.sum(q.astype(jnp.float32) * s[..., None], axis=0) / world


def _allgather_dequant(part: jax.Array, axis_name: str, block: int
                       ) -> jax.Array:
    """Requantize a locally-owned reduced chunk and all_gather it: the
    second hop of qgZ. Returns the full flat fp32 tensor (padded)."""
    q, s, _ = quantize_blockwise(part, block)
    q_all = jax.lax.all_gather(q, axis_name)      # (W, cb, block) int8
    s_all = jax.lax.all_gather(s, axis_name)      # (W, cb) f32
    return (q_all.astype(jnp.float32) * s_all[..., None]).reshape(-1)


def _twohop_mean_flat(flat: jax.Array, axis_name: str, world: int,
                      block: int) -> jax.Array:
    """qgZ two-hop mean of a flat fp32 array over one mesh axis.
    Returns the (padded) flat fp32 mean, identical on every rank."""
    padded, _ = pad_flat_to_multiple(flat, world * block)
    q, s = _quantize_chunked(padded, world, block)
    # hop 1: rank i ships its quantized chunk j to rank j (row j of the
    # result came from rank j) — ~n int8 per rank on the wire
    q_x = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    s_x = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    # fp32 partial-sum of the owned 1/W chunk (never sum in int8)
    part = _dequant_mean(q_x, s_x, world)          # (cb, block) f32
    # hop 2: requantize + all_gather the reduced chunk — ~n int8 per rank
    return _allgather_dequant(part, axis_name, block)


def quantized_allreduce_mean(grad: jax.Array, axis_name: str,
                             block: int = DEFAULT_BLOCK,
                             algo: str = ALGO_TWOHOP,
                             world_size: Optional[int] = None) -> jax.Array:
    """Mean-allreduce ``grad`` across ``axis_name`` shipping int8 + block
    scales on the wire. Call inside shard_map; every rank returns the
    identical fp32 mean (cast back to ``grad.dtype``).

    ``algo='twohop'`` (default) is the qgZ shape — per-rank wire ~2n int8
    bytes independent of the axis size (requires ``world_size``, the
    static mesh-axis extent). ``algo='allgather'`` is the legacy O(W*n)
    exchange, kept for dp=2 where its single hop wins on latency.
    """
    if algo == ALGO_ALLGATHER:
        q, scale, n = quantize_blockwise(grad, block)
        q_all = jax.lax.all_gather(q, axis_name)        # (W, nb, block)
        s_all = jax.lax.all_gather(scale, axis_name)    # (W, nb)
        W = q_all.shape[0]
        mean = _dequant_mean(q_all, s_all, W)
        return mean.reshape(-1)[:n].reshape(grad.shape).astype(grad.dtype)
    if algo != ALGO_TWOHOP:
        raise ValueError(f"unknown quantized allreduce algo {algo!r}; "
                         f"expected one of {QUANTIZED_ALGOS}")
    assert world_size is not None and world_size >= 1, \
        "algo='twohop' needs the static world_size of the mesh axis"
    n = grad.size
    full = _twohop_mean_flat(grad.reshape(-1).astype(jnp.float32),
                             axis_name, world_size, block)
    return full[:n].reshape(grad.shape).astype(grad.dtype)


def hierarchical_quantized_allreduce_mean(
        grad: jax.Array, intra_axis: str, inter_axis: str,
        intra_size: int, inter_size: int,
        block: int = DEFAULT_BLOCK) -> jax.Array:
    """2D qgZ: two-hop quantized mean over ``intra_axis`` x ``inter_axis``
    keeping the bandwidth-heavy hops on the (fast) intra axis.

    Shape: quantize -> all_to_all over intra (~n int8, fast wire) ->
    fp32 partial-sum of the owned 1/Wi chunk -> full two-hop mean of
    that chunk over inter (~2n/Wi int8, slow wire) -> requantize ->
    all_gather over intra (~n int8, fast wire). The slow axis only ever
    carries the reduced chunk.
    """
    n = grad.size
    flat = grad.reshape(-1).astype(jnp.float32)
    padded, _ = pad_flat_to_multiple(flat, intra_size * block)
    q, s = _quantize_chunked(padded, intra_size, block)
    # intra hop (fast axis): chunk j -> intra-rank j, fp32 partial sum
    q_x = jax.lax.all_to_all(q, intra_axis, split_axis=0, concat_axis=0,
                             tiled=False)
    s_x = jax.lax.all_to_all(s, intra_axis, split_axis=0, concat_axis=0,
                             tiled=False)
    part = _dequant_mean(q_x, s_x, intra_size)       # (cb, block) f32
    # inter hop (slow axis): only the reduced 1/Wi chunk crosses it.
    # Skipped entirely when the inter axis is degenerate (hierarchical
    # == full dp width): every collective would be a no-op but the
    # quantize/requantize round-trip would still cost compute + error.
    if inter_size > 1:
        cb = part.shape[0]
        part = _twohop_mean_flat(part.reshape(-1), inter_axis, inter_size,
                                 block)[:cb * block].reshape(cb, block)
    # gather (fast axis): requantized reduced chunk back to every rank
    full = _allgather_dequant(part, intra_axis, block)
    return full[:n].reshape(grad.shape).astype(grad.dtype)


# --------------------------------------------------------------- wire model


def _scaled_payload(elems: int, block: int) -> int:
    """int8 payload + fp32 block scales, in bytes, for ``elems`` values."""
    return elems + 4 * (elems // block)


def wire_bytes(n: int, world_size: int, block: int = DEFAULT_BLOCK,
               algo: str = ALGO_TWOHOP,
               hierarchical: Optional[Tuple[int, int]] = None,
               dense_dtype_bytes: int = 2) -> Tuple[int, int]:
    """(quantized, dense) TOTAL per-rank wire bytes for one mean-allreduce
    of ``n`` elements across ``world_size`` ranks.

    Models the full algorithm, not a single leg: bytes a rank RECEIVES
    across every hop (send volume is symmetric). ``dense`` is the ring
    bf16 allreduce baseline, ``2*(W-1)/W * n * dense_dtype_bytes``
    (reduce-scatter + all-gather legs).

    - ``allgather`` (legacy): ``(W-1) * (n + scales)`` — O(W*n); exceeds
      the dense bf16 ring whenever W >= 4 (at default block).
    - ``twohop`` (qgZ): ``2*(W-1)/W * (n + scales)`` — O(n), independent
      of W.
    - ``hierarchical=(inter, intra)``: sum of the intra hops on n and
      the inter hops on the n/intra chunk (see
      :func:`wire_bytes_by_axis` for the per-axis split).
    """
    from deepspeed_tpu.utils.hlo_audit import dense_allreduce_ring_bytes
    W = max(world_size, 1)
    dense = dense_allreduce_ring_bytes(n, W, dense_dtype_bytes)
    if W == 1:
        return 0, 0
    if hierarchical is not None:
        per_axis = wire_bytes_by_axis(n, hierarchical[0], hierarchical[1],
                                      block)
        return per_axis["intra"] + per_axis["inter"], dense
    return sum(b for _, b in wire_hops(n, W, block, algo=algo)), dense


def wire_bytes_by_axis(n: int, inter_size: int, intra_size: int,
                       block: int = DEFAULT_BLOCK) -> dict:
    """Per-axis per-rank wire bytes of the hierarchical two-hop mean:
    ``{'intra': fast-axis bytes (~2n), 'inter': slow-axis bytes
    (~2n/intra)}``."""
    Wo, Wi = max(inter_size, 1), max(intra_size, 1)
    hops = wire_hops(n, Wo * Wi, block, hierarchical=(Wo, Wi))
    return {"intra": sum(b for a, b in hops if a == "intra"),
            "inter": sum(b for a, b in hops if a == "inter")}


def wire_hops(n: int, world_size: int, block: int = DEFAULT_BLOCK,
              algo: str = ALGO_TWOHOP,
              hierarchical: Optional[Tuple[int, int]] = None) -> list:
    """Per-hop breakdown of one quantized mean-allreduce: a list of
    ``(axis, bytes)`` tuples, one per dependent collective hop, where
    ``axis`` is ``'intra'`` (fast wire) or ``'inter'`` (slow wire) and
    ``bytes`` is the per-rank send volume of that hop.

    This is the hop-level view the topology-aware autotuner's time
    model consumes (``runtime/comm_autotune.py``): each hop pays one
    link latency plus ``bytes / bandwidth(axis)``, so latency-bound
    small messages and bandwidth-bound large ones price differently —
    the EQuARX-style crossover structure. Flat algorithms report every
    hop as ``'intra'``. This is the SINGLE copy of the payload/padding
    math: :func:`wire_bytes` and :func:`wire_bytes_by_axis` are sums
    over this hop list, so the autotuner's time model and the byte
    model cannot desynchronize.
    """
    W = max(world_size, 1)
    if hierarchical is not None:
        Wo, Wi = max(hierarchical[0], 1), max(hierarchical[1], 1)
        padded = pad_to_multiple(n, Wi * block)
        payload = _scaled_payload(padded, block)
        hops = []
        if Wi > 1:           # intra all_to_all of the full payload
            hops.append(("intra", (Wi - 1) * payload // Wi))
        if Wo > 1:           # inter two-hop on the reduced 1/Wi chunk
            chunk = pad_to_multiple(padded // Wi, Wo * block)
            cpay = _scaled_payload(chunk, block)
            hops.append(("inter", (Wo - 1) * cpay // Wo))
            hops.append(("inter", (Wo - 1) * cpay // Wo))
        if Wi > 1:           # intra all_gather of the reduced chunk
            hops.append(("intra", (Wi - 1) * payload // Wi))
        return hops
    if W == 1:
        return []
    padded = pad_to_multiple(n, W * block)
    payload = _scaled_payload(padded, block)
    if algo == ALGO_ALLGATHER:
        return [("intra", (W - 1) * payload)]
    if algo != ALGO_TWOHOP:
        raise ValueError(f"unknown quantized allreduce algo {algo!r}")
    leg = (W - 1) * payload // W
    return [("intra", leg), ("intra", leg)]
