"""Block-quantized gradient allreduce — ZeRO++-style comm compression.

TPU-native extension past the reference snapshot (whose only compressed
collective is 1-bit Adam's sign exchange): data-parallel gradients are
exchanged as int8 with per-block fp32 scales (~3.7x less ICI/DCN traffic
than fp32, ~1.9x vs bf16), the pattern of ZeRO++'s quantized gradient
collectives (arXiv:2306.10209) and EQuARX (arXiv:2506.17615) re-expressed
as in-jit XLA collectives:

    quantize(local grad) -> all_gather(int8 + scales) over 'data'
    -> dequantize + mean locally on every rank

Summation happens in fp32 AFTER dequantization (int8 sums would
overflow), which is exactly EQuARX's "quantize the wire, not the math".
Quantization is symmetric per block of 256 values (absmax scaling,
round-to-nearest): unbiased up to rounding, error bounded by
absmax/127 per element.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["quantize_blockwise", "dequantize_blockwise",
           "quantized_allreduce_mean", "wire_bytes"]

DEFAULT_BLOCK = 256


def _pad_to(x, m):
    pad = (-x.shape[0]) % m
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x, pad


def quantize_blockwise(x: jax.Array, block: int = DEFAULT_BLOCK
                       ) -> Tuple[jax.Array, jax.Array, int]:
    """Flatten + symmetric int8 quantization per block of ``block``
    values. Returns (q (nb, block) int8, scales (nb,) fp32, orig_size)."""
    n = x.size
    flat, _ = _pad_to(x.reshape(-1).astype(jnp.float32), block)
    blocks = flat.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(blocks / scale[:, None]), -127, 127
                 ).astype(jnp.int8)
    return q, scale.astype(jnp.float32), n


def dequantize_blockwise(q: jax.Array, scale: jax.Array, n: int,
                         shape=None) -> jax.Array:
    out = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)[:n]
    return out.reshape(shape) if shape is not None else out


def quantized_allreduce_mean(grad: jax.Array, axis_name: str,
                             block: int = DEFAULT_BLOCK) -> jax.Array:
    """Mean-allreduce ``grad`` across ``axis_name`` shipping int8 + block
    scales on the wire. Call inside shard_map; every rank returns the
    identical fp32 mean."""
    q, scale, n = quantize_blockwise(grad, block)
    q_all = jax.lax.all_gather(q, axis_name)            # (W, nb, block)
    s_all = jax.lax.all_gather(scale, axis_name)        # (W, nb)
    W = q_all.shape[0]
    deq = q_all.astype(jnp.float32) * s_all[:, :, None]
    mean = jnp.sum(deq, axis=0) / W
    return mean.reshape(-1)[:n].reshape(grad.shape).astype(grad.dtype)


def wire_bytes(n: int, block: int = DEFAULT_BLOCK,
               dense_dtype_bytes: int = 4) -> Tuple[int, int]:
    """(quantized, dense) per-leg payload bytes for n elements."""
    nb = -(-n // block)
    return nb * block * 1 + nb * 4, n * dense_dtype_bytes
