"""Fault-injection harness for checkpoint durability testing.

On preemptible TPU pods a crash mid-save is the *expected* failure mode
(ISSUE: the reference treats checkpoints as the recovery backbone,
engine.py:1329/:1173). This module provides the monkeypatch-free shim the
checkpoint layer is instrumented with: production code calls
``fire("<point>")`` at named fault points (a no-op unless a test armed
that point), tests arm points to simulate torn writes, crash-after-shard,
transient ``OSError`` flakes, and bit-flips, then prove resume survives.

Fault points instrumented in the save path (see ``runtime/checkpoint.py``
and ``engine.save_checkpoint``):

- ``io_write``                 : inside every atomic file write, before any
                                 bytes hit disk (arm with ``OSError`` to
                                 simulate GCS/NFS flakes; retried)
- ``ckpt.after_shard``         : after one pytree's shard files are written
                                 (ctx: ``name``) — crash-after-shard-0
- ``ckpt.before_marker``       : all shards + meta written, COMMITTED not
- ``ckpt.before_rename``       : COMMITTED written, tmp dir not yet renamed
- ``ckpt.latest_tmp_written``  : ``latest.tmp`` durable, ``os.replace``
                                 not yet executed — torn-latest window

``retry_io`` is the exponential-backoff wrapper used around all checkpoint
I/O; it retries ``OSError`` (transient filesystem flakes) but never
``InjectedCrash`` (a simulated process death must kill the save).
"""

import os
import time
import zlib
from typing import Any, Callable, Dict, Optional

__all__ = [
    "InjectedCrash", "FaultInjector", "get_injector", "fire", "arm",
    "reset", "retry_io", "flip_byte", "truncate_file", "crc32_file",
]


class InjectedCrash(Exception):
    """Simulated process death at a named fault point.

    Deliberately NOT an ``OSError``: the retry wrapper must never swallow
    it — a preemption does not come back for attempt two.
    """


class FaultInjector:
    """Registry of armed fault points.

    ``arm(point, ...)`` installs an action; instrumented code calls
    ``fire(point, **ctx)`` which is a no-op unless that point is armed.
    An armed point fires at most ``times`` times (None = unlimited) and
    only when ``filter(**ctx)`` (if given) returns truthy.
    """

    def __init__(self):
        self._arms: Dict[str, Dict[str, Any]] = {}

    def arm(self, point: str, *, exc: Optional[BaseException] = None,
            times: Optional[int] = 1,
            callback: Optional[Callable[..., None]] = None,
            filter: Optional[Callable[..., bool]] = None) -> None:
        """Arm ``point`` to raise ``exc`` (class or instance) and/or run
        ``callback(**ctx)`` the next ``times`` matching fires."""
        if exc is None and callback is None:
            raise ValueError("arm() needs exc and/or callback")
        self._arms[point] = {"exc": exc, "times": times, "fired": 0,
                             "callback": callback, "filter": filter}

    def fire(self, point: str, **ctx) -> None:
        spec = self._arms.get(point)
        if spec is None:
            return
        if spec["times"] is not None and spec["fired"] >= spec["times"]:
            return
        if spec["filter"] is not None and not spec["filter"](**ctx):
            return
        spec["fired"] += 1
        if spec["callback"] is not None:
            spec["callback"](**ctx)
        exc = spec["exc"]
        if exc is not None:
            raise exc if isinstance(exc, BaseException) else exc()

    def fired(self, point: str) -> int:
        """How many times an armed point has actually fired."""
        spec = self._arms.get(point)
        return 0 if spec is None else spec["fired"]

    def reset(self) -> None:
        self._arms.clear()


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


def fire(point: str, **ctx) -> None:
    """Production-side hook: no-op unless a test armed ``point``."""
    _INJECTOR.fire(point, **ctx)


def arm(point: str, **kw) -> None:
    _INJECTOR.arm(point, **kw)


def reset() -> None:
    _INJECTOR.reset()


def retry_io(fn: Callable[[], Any], *, retries: int = 3,
             backoff: float = 0.05,
             sleep: Callable[[float], None] = time.sleep) -> Any:
    """Run ``fn`` retrying transient ``OSError`` with exponential backoff.

    ``retries`` is the number of *re*-attempts after the first failure.
    ``InjectedCrash`` (and any non-OSError) propagates immediately — a
    simulated preemption is not a flake.
    """
    attempt = 0
    while True:
        try:
            return fn()
        except InjectedCrash:
            raise
        except OSError:
            if attempt >= retries:
                raise
            sleep(backoff * (2 ** attempt))
            attempt += 1


# --------------------------------------------------------------------- #
# corruption helpers for tests and the offline verifier
# --------------------------------------------------------------------- #

def crc32_file(path: str, chunk_bytes: int = 1 << 20) -> int:
    """Streaming CRC32 of a file's content (matches the COMMITTED
    marker's per-file checksum)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def flip_byte(path: str, offset: Optional[int] = None) -> int:
    """XOR one byte in-place (default: middle of the file) — simulates
    silent media corruption. Returns the offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a byte of empty file {path}")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return offset


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Cut a file short (default: half) — simulates a torn write."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = size // 2
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)
