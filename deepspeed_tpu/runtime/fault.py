"""Fault-injection harness for checkpoint durability testing.

On preemptible TPU pods a crash mid-save is the *expected* failure mode
(ISSUE: the reference treats checkpoints as the recovery backbone,
engine.py:1329/:1173). This module provides the monkeypatch-free shim the
checkpoint layer is instrumented with: production code calls
``fire("<point>")`` at named fault points (a no-op unless a test armed
that point), tests arm points to simulate torn writes, crash-after-shard,
transient ``OSError`` flakes, and bit-flips, then prove resume survives.

Fault points instrumented in the save path (see ``runtime/checkpoint.py``
and ``engine.save_checkpoint``):

- ``io_write``                 : inside every atomic file write, before any
                                 bytes hit disk (arm with ``OSError`` to
                                 simulate GCS/NFS flakes; retried)
- ``ckpt.snapshot``            : at the device->host snapshot that opens
                                 every save — kill here and NOTHING of the
                                 save exists on disk
- ``ckpt.after_shard``         : after one pytree's shard files are written
                                 (ctx: ``name``) — crash-after-shard-0
- ``ckpt.before_marker``       : all shards + meta written, COMMITTED not
- ``ckpt.before_rename``       : COMMITTED written, tmp dir not yet renamed
- ``ckpt.latest_tmp_written``  : ``latest.tmp`` durable, ``os.replace``
                                 not yet executed — torn-latest window
- ``ckpt.writer_crash``        : in the async checkpoint writer thread, at
                                 job start — a stored writer exception must
                                 surface on the next save/close, never die
                                 silently
- ``elastic.sigterm_mid_window``: at the top of every ``train_batch``
                                 window — arm a callback that delivers
                                 SIGTERM (or triggers the software
                                 preemption) to prove the in-flight window
                                 still finishes before the drain

Serve-plane points (ISSUE 14 — ``inference/engine.py`` and
``inference/fleet.py``; the fleet tests arm them through the same env
grammar):

- ``serve.swap_load``          : in ``engine.swap_params``, after the tag
                                 pre-flight and BEFORE the params load —
                                 arm ``oserror``/``crash`` to prove a
                                 failed mid-swap load leaves the replica
                                 serving the OLD weights (swap is
                                 atomic-or-rollback, never half-loaded)
- ``serve.replica_preempt``    : once per live replica per router step
                                 (ctx: ``replica``) — a raised injection
                                 preempts THAT replica (drain +
                                 redistribute); the ``preempt`` action
                                 instead flags every installed
                                 PreemptionGuard, same as a real SIGTERM
- ``serve.dispatch``           : in the router's dispatch of one request
                                 to its chosen replica (ctx: ``replica``,
                                 ``uid``) — a transient failure here must
                                 reroute the request to the next-best
                                 replica, never drop it

RPC-plane points (ISSUE 16 — ``inference/rpc.py`` client and the
``replica_worker`` child; one point per pinned error-taxonomy kind so a
test targets exactly one failure mode):

- ``rpc.transport``            : at the top of every RPC call attempt
                                 (ctx: ``method``, ``name``) — raises
                                 surface as ``RpcTransportError``, the
                                 TRANSIENT kind the client retries with
                                 bounded exponential backoff
- ``rpc.timeout``              : same site — raises surface as
                                 ``RpcTimeoutError`` (per-call deadline
                                 exceeded; never retried, the call may
                                 have been applied)
- ``rpc.replica_dead``         : same site — raises surface as
                                 ``ReplicaDeadError`` (peer gone;
                                 terminal for the connection — the
                                 router salvages/migrates/relaunches)
- ``serve.replica_kill``       : in the replica worker's step handler,
                                 fired ONLY while a request is
                                 mid-decode (ctx: ``pid``) — the
                                 env-armed kill test's hook: ``crash``
                                 triggers the deathbed protocol (export
                                 live pages, dump flight.json, exit 85)
                                 at the worst possible moment

Health-plane points (ISSUE 15 — ``utils/health.py`` watchdog and
detectors; process-boundary-testable like the supervisor tests):

- ``health.stall``             : at the top of every ``train_batch``
                                 window, right after the heartbeat — arm
                                 the ``stall`` env action (or a sleeping
                                 callback) to wedge the step loop past
                                 ``stall_timeout_s`` and prove the
                                 watchdog dumps flight.json + stacks and
                                 emits ``stall_detected``
- ``health.nan_loss``          : at the monitor-flush barrier where each
                                 deferred loss is materialized host-side
                                 (ctx: ``step``) — arm ``crash`` and the
                                 engine poisons THAT loss value to NaN
                                 (telemetry only, params untouched) to
                                 prove the nonfinite-streak detector
                                 emits its pinned ``health`` row

``retry_io`` is the exponential-backoff wrapper used around all checkpoint
I/O; it retries ``OSError`` (transient filesystem flakes) but never
``InjectedCrash`` (a simulated process death must kill the save).

Env-armed injections (``DSTPU_FAULT_ARM``): a *relaunched* process — the
launcher supervisor's child, which no in-process test can reach — arms
itself at engine init from the environment. Grammar (comma-separated)::

    point:action[:times][@once_file]

with actions ``crash`` (raise InjectedCrash), ``oserror`` (raise OSError),
``sigterm`` (deliver a real SIGTERM to this process), ``preempt`` (flag
the installed PreemptionGuards via ``elastic.request_preemption``), and
``stall`` (sleep ``DSTPU_FAULT_STALL_S`` seconds — default 30 — inside
the fault point, wedging the caller past the health watchdog's timeout).
``@once_file`` makes the arm cross-process-one-shot: the spec only arms
while the file exists and the first fire deletes it, so a supervisor
relaunch with the *same* environment is not re-faulted forever.
"""

import os
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "InjectedCrash", "FaultInjector", "get_injector", "fire", "arm",
    "reset", "retry_io", "flip_byte", "truncate_file", "crc32_file",
    "arm_from_env", "ENV_ARM",
]

ENV_ARM = "DSTPU_FAULT_ARM"


class InjectedCrash(Exception):
    """Simulated process death at a named fault point.

    Deliberately NOT an ``OSError``: the retry wrapper must never swallow
    it — a preemption does not come back for attempt two.
    """


class FaultInjector:
    """Registry of armed fault points.

    ``arm(point, ...)`` installs an action; instrumented code calls
    ``fire(point, **ctx)`` which is a no-op unless that point is armed.
    An armed point fires at most ``times`` times (None = unlimited) and
    only when ``filter(**ctx)`` (if given) returns truthy.
    """

    def __init__(self):
        self._arms: Dict[str, Dict[str, Any]] = {}

    def arm(self, point: str, *, exc: Optional[BaseException] = None,
            times: Optional[int] = 1,
            callback: Optional[Callable[..., None]] = None,
            filter: Optional[Callable[..., bool]] = None) -> None:
        """Arm ``point`` to raise ``exc`` (class or instance) and/or run
        ``callback(**ctx)`` the next ``times`` matching fires."""
        if exc is None and callback is None:
            raise ValueError("arm() needs exc and/or callback")
        self._arms[point] = {"exc": exc, "times": times, "fired": 0,
                             "callback": callback, "filter": filter}

    def fire(self, point: str, **ctx) -> None:
        spec = self._arms.get(point)
        if spec is None:
            return
        if spec["times"] is not None and spec["fired"] >= spec["times"]:
            return
        if spec["filter"] is not None and not spec["filter"](**ctx):
            return
        spec["fired"] += 1
        if spec["callback"] is not None:
            spec["callback"](**ctx)
        exc = spec["exc"]
        if exc is not None:
            raise exc if isinstance(exc, BaseException) else exc()

    def fired(self, point: str) -> int:
        """How many times an armed point has actually fired."""
        spec = self._arms.get(point)
        return 0 if spec is None else spec["fired"]

    def reset(self) -> None:
        self._arms.clear()


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    return _INJECTOR


def fire(point: str, **ctx) -> None:
    """Production-side hook: no-op unless a test armed ``point``."""
    _INJECTOR.fire(point, **ctx)


def arm(point: str, **kw) -> None:
    _INJECTOR.arm(point, **kw)


def reset() -> None:
    _INJECTOR.reset()


def retry_io(fn: Callable[[], Any], *, retries: int = 3,
             backoff: float = 0.05,
             sleep: Callable[[float], None] = time.sleep) -> Any:
    """Run ``fn`` retrying transient ``OSError`` with exponential backoff.

    ``retries`` is the number of *re*-attempts after the first failure.
    ``InjectedCrash`` (and any non-OSError) propagates immediately — a
    simulated preemption is not a flake.
    """
    attempt = 0
    while True:
        try:
            return fn()
        except InjectedCrash:
            raise
        except OSError:
            if attempt >= retries:
                raise
            sleep(backoff * (2 ** attempt))
            attempt += 1


# --------------------------------------------------------------------- #
# env-armed injections: fault a process you can only reach by env
# --------------------------------------------------------------------- #

def _env_action(name: str, point: str) -> Callable[..., None]:
    if name == "crash":
        def act(**ctx):
            raise InjectedCrash(point)
    elif name == "oserror":
        def act(**ctx):
            raise OSError(f"injected transient failure at {point}")
    elif name == "sigterm":
        def act(**ctx):
            import signal
            os.kill(os.getpid(), signal.SIGTERM)
    elif name == "preempt":
        def act(**ctx):
            from deepspeed_tpu.runtime import elastic
            elastic.request_preemption(f"env-armed fault at {point}")
    elif name == "stall":
        def act(**ctx):
            # wedge the CALLER (not a side thread): the health
            # watchdog must observe a genuinely silent step loop
            time.sleep(float(os.environ.get("DSTPU_FAULT_STALL_S",
                                            "30")))
    else:
        raise ValueError(
            f"{ENV_ARM}: unknown action {name!r} (want crash | oserror "
            f"| sigterm | preempt | stall)")
    return act


# process-global one-shot latch for the engine-init call: arming is
# per-PROCESS, not per-engine — re-arming on a second engine's init
# would reset the fired counter and turn a `times:1` spec into
# once-per-engine. Deliberately NOT cleared by reset().
_ENV_ARMED = False


def arm_from_env(env=None) -> List[str]:
    """Arm fault points from ``DSTPU_FAULT_ARM`` (see module docstring).

    Called at engine init so a supervisor-relaunched subprocess can be
    faulted without any in-process handle on it; with ``env=None`` (the
    engine path) it arms at most once per process. Returns the points
    armed (empty when the variable is unset or already armed). A
    malformed spec raises ``ValueError`` — a silently ignored fault arm
    would make a durability test pass vacuously.
    """
    global _ENV_ARMED
    if env is None:
        if _ENV_ARMED:
            return []
        _ENV_ARMED = True
    env = os.environ if env is None else env
    raw = env.get(ENV_ARM, "").strip()
    if not raw:
        return []
    armed: List[str] = []
    for spec in raw.split(","):
        spec = spec.strip()
        if not spec:
            continue
        once_file = None
        if "@" in spec:
            spec, once_file = spec.split("@", 1)
        parts = spec.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"{ENV_ARM}: bad spec {spec!r} (want "
                "point:action[:times][@once_file])")
        point, action = parts[0], parts[1]
        times = int(parts[2]) if len(parts) > 2 else 1
        if once_file is not None and not os.path.exists(once_file):
            continue  # one-shot already consumed by a prior incarnation
        act = _env_action(action, point)

        def callback(_act=act, _once=once_file, **ctx):
            if _once is not None:
                try:
                    os.remove(_once)
                except OSError:
                    pass
            _act(**ctx)

        _INJECTOR.arm(point, callback=callback,
                      times=None if times <= 0 else times)
        armed.append(point)
    return armed


# --------------------------------------------------------------------- #
# corruption helpers for tests and the offline verifier
# --------------------------------------------------------------------- #

def crc32_file(path: str, chunk_bytes: int = 1 << 20) -> int:
    """Streaming CRC32 of a file's content (matches the COMMITTED
    marker's per-file checksum)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def flip_byte(path: str, offset: Optional[int] = None) -> int:
    """XOR one byte in-place (default: middle of the file) — simulates
    silent media corruption. Returns the offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot flip a byte of empty file {path}")
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return offset


def truncate_file(path: str, keep_bytes: Optional[int] = None) -> None:
    """Cut a file short (default: half) — simulates a torn write."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = size // 2
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)
