"""Compressed collectives for 1-bit Adam, TPU-native.

Reference: ``deepspeed/runtime/custom_collectives.py`` (MPI gather/allgather,
``gather_cuda:23`` / ``allgather_cuda:113``) + the compression math in
``deepspeed/runtime/fp16/onebit_adam.py`` (``Compressed_Allreduce:104``:
sign+scale with error feedback, cupy ``packbits``, 2-phase gather+allgather).

TPU re-design: the whole compressed allreduce is ONE jit-traceable function
running inside ``shard_map`` over a named mesh axis. The MPI side-channel
disappears:

- phase 1 "gather to chunk owners"  → ``lax.all_to_all``  (each rank ships
  its packed sign chunk j to rank j) + ``lax.all_gather`` of the fp32 scales
- phase 2 "allgather server chunks" → ``lax.all_gather`` of the re-packed
  server chunk + server scales

Payload on the wire is uint8-packed sign bits (32× smaller than fp32) plus
one fp32 scale per chunk — the same ≤5× e2e communication-volume reduction
the reference claims (BASELINE.md: 1-bit Adam row). Packing/unpacking is a
reshape+dot that XLA vectorizes on the VPU; no Pallas needed.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["pack_signs", "unpack_signs", "compressed_allreduce",
           "CompressedAllreduceResult", "padded_numel", "server_chunk_size",
           "pad_to_multiple", "pad_flat_to_multiple"]

_BITS = 8
_POWERS = 2 ** np.arange(_BITS - 1, -1, -1, dtype=np.uint8)  # MSB-first


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` that is >= ``n`` (host-side size math,
    shared by the 1-bit chunking and the int8 block collectives)."""
    return n + (-n) % m


def pad_flat_to_multiple(x: jax.Array, m: int) -> Tuple[jax.Array, int]:
    """Zero-pad a flat array so its length is a multiple of ``m``.
    Returns ``(padded, pad_count)``; shared by every compressed
    collective that chunks a flattened tensor."""
    pad = (-x.shape[0]) % m
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    return x, pad


def pack_signs(x: jax.Array) -> jax.Array:
    """Pack sign bits of ``x`` (flat, numel % 8 == 0) into uint8, MSB-first
    (cupy.packbits convention, ref onebit_adam.py:97-100). bit=1 ⇔ x >= 0."""
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, _BITS)
    return (bits * _POWERS).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed: jax.Array, dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`pack_signs`: uint8 → ±1 values (ref ``:167-173``
    unpackbits then ``.add_(-0.5).mul_(2.0)``)."""
    bits = (packed[:, None] & _POWERS) > 0
    return jnp.where(bits, 1.0, -1.0).astype(dtype).reshape(-1)


def padded_numel(numel: int, world_size: int, divider: int = _BITS) -> int:
    """Corrected tensor size: numel rounded up so each of the world_size
    server chunks is a multiple of ``divider`` bits
    (ref onebit_adam.py:294-300 ``corrected_tensor_size``)."""
    quantum = world_size * divider
    return numel + (-numel) % quantum


def server_chunk_size(numel: int, world_size: int) -> int:
    return padded_numel(numel, world_size) // world_size


class CompressedAllreduceResult(NamedTuple):
    tensor: jax.Array        # averaged, decompressed (original shape)
    worker_error: jax.Array  # updated worker error feedback (padded flat)
    server_error: jax.Array  # updated server error feedback (chunk flat)


def _sign_compress(x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """sign+scale compression: returns (scale, signs ±1, new_error).
    scale = ||x|| / sqrt(numel) (ref ``:123``); error = x - scale*sign."""
    scale = jnp.linalg.norm(x) / np.sqrt(x.size)
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    return scale, signs, x - scale * signs


def compressed_allreduce(
        buffer_m: jax.Array,
        worker_error: jax.Array,
        server_error: jax.Array,
        axis_name: Optional[str] = None,
        world_size: int = 1) -> CompressedAllreduceResult:
    """Error-compensated 1-bit averaging allreduce
    (ref ``Compressed_Allreduce:104``).

    Call inside ``shard_map`` with ``axis_name`` bound (each rank passes its
    own local ``buffer_m``); with ``world_size == 1`` / no axis it degrades
    to local sign+scale compression with error feedback (useful for tests
    and single-chip parity).

    ``worker_error`` must have ``padded_numel(buffer_m.size, world_size)``
    elements; ``server_error`` one server chunk.
    """
    orig_shape = buffer_m.shape
    orig_size = int(np.prod(orig_shape))
    flat = buffer_m.reshape(-1).astype(jnp.float32)
    padded = worker_error.shape[0]
    chunk = padded // world_size
    assert padded == padded_numel(orig_size, world_size), \
        f"worker_error size {padded} != padded_numel({orig_size}, {world_size})"
    assert server_error.shape[0] == chunk

    if padded != orig_size:
        flat, _ = pad_flat_to_multiple(flat, padded)

    # ---- worker-side compression with error feedback (ref :122-128) ----
    compensated = flat + worker_error
    worker_scale, signs, new_worker_error = _sign_compress(compensated)
    packed = pack_signs(signs).reshape(world_size, chunk // _BITS)

    if axis_name is None or world_size == 1:
        assert world_size == 1, "axis_name is required when world_size > 1"
        # degenerate single-rank path: the server sees exactly this worker
        comp_server = signs * worker_scale + server_error
        server_scale, s_signs, new_server_error = _sign_compress(comp_server)
        out = (s_signs * server_scale)[:orig_size]
        return CompressedAllreduceResult(
            tensor=out.reshape(orig_shape),
            worker_error=new_worker_error,
            server_error=new_server_error)

    # ---- phase 1: ship chunk j to rank j (ref gather_cuda:23) ----------
    # all_to_all over leading axis: row j of the result came from rank j
    recv_sign = jax.lax.all_to_all(packed, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
    scales = jax.lax.all_gather(worker_scale, axis_name)  # (world,)

    # ---- server-side: average contributions, recompress (ref :167-186) -
    # recv_sign: (world, chunk/8) — contribution of every worker to MY chunk
    unpacked = jax.vmap(lambda r: unpack_signs(r))(recv_sign)  # (world, chunk)
    server_m = (unpacked * scales[:, None]).mean(axis=0)
    comp_server = server_m + server_error
    server_scale, s_signs, new_server_error = _sign_compress(comp_server)
    server_packed = pack_signs(s_signs)

    # ---- phase 2: allgather server chunks (ref allgather_cuda:113) -----
    all_server_sign = jax.lax.all_gather(server_packed, axis_name)
    all_server_scale = jax.lax.all_gather(server_scale, axis_name)
    full = jax.vmap(lambda r, s: unpack_signs(r) * s)(
        all_server_sign, all_server_scale).reshape(-1)

    return CompressedAllreduceResult(
        tensor=full[:orig_size].reshape(orig_shape),
        worker_error=new_worker_error,
        server_error=new_server_error)
