"""Activation-checkpointing sub-config
(reference ``deepspeed/runtime/activation_checkpointing/config.py:59``).

On TPU the knobs map onto ``jax.checkpoint`` policies:
- partition_activations → save sharded residuals over the model axis
- cpu_checkpointing     → ``jax.checkpoint`` with host offload policy
- contiguous_memory_optimization / synchronize_checkpoint_boundary are no-ops
  under XLA (the compiler owns buffers and streams) but are accepted.
"""

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import get_scalar_param


class DeepSpeedActivationCheckpointingConfig:

    def __init__(self, param_dict):
        self.partition_activations = None
        self.contiguous_memory_optimization = None
        self.cpu_checkpointing = None
        self.number_checkpoints = None
        self.synchronize_checkpoint_boundary = None
        self.profile = None

        act_dict = param_dict.get(C.ACTIVATION_CHECKPOINTING, {})
        self._initialize(act_dict)

    def _initialize(self, act_dict):
        self.partition_activations = get_scalar_param(
            act_dict, C.ACT_CKPT_PARTITION_ACTIVATIONS,
            C.ACT_CKPT_PARTITION_ACTIVATIONS_DEFAULT)
        self.contiguous_memory_optimization = get_scalar_param(
            act_dict, C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION,
            C.ACT_CKPT_CONTIGUOUS_MEMORY_OPTIMIZATION_DEFAULT)
        self.cpu_checkpointing = get_scalar_param(
            act_dict, C.ACT_CKPT_CPU_CHECKPOINTING,
            C.ACT_CKPT_CPU_CHECKPOINTING_DEFAULT)
        self.number_checkpoints = get_scalar_param(
            act_dict, C.ACT_CKPT_NUMBER_CHECKPOINTS,
            C.ACT_CKPT_NUMBER_CHECKPOINTS_DEFAULT)
        self.profile = get_scalar_param(
            act_dict, C.ACT_CKPT_PROFILE, C.ACT_CKPT_PROFILE_DEFAULT)
        self.synchronize_checkpoint_boundary = get_scalar_param(
            act_dict, C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY,
            C.ACT_CKPT_SYNCHRONIZE_CHECKPOINT_BOUNDARY_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        import json
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
