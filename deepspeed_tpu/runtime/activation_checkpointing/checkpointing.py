"""Activation checkpointing, TPU-native.

Re-implements the reference subsystem
(``deepspeed/runtime/activation_checkpointing/checkpointing.py``:
``CheckpointFunction:314``, ``configure():653``, RNG tracker
``CudaRNGStatesTracker:147``, ``model_parallel_cuda_manual_seed:223``) on
JAX. The eager-autograd machinery — stashing inputs, restoring RNG states,
re-running forward inside backward — collapses onto ``jax.checkpoint``
(rematerialization): under remat XLA recomputes the wrapped function during
the backward pass and RNG is functional (keys are part of the program), so no
state save/restore is needed.

Knob mapping (reference config flags → TPU semantics):

- ``partition_activations`` (ref ``checkpointing.py:370-413``): the stashed
  activation inputs are sharded across the ``model`` mesh axis instead of
  replicated. Here: a ``with_sharding_constraint`` over the model axis is
  applied to the saved inputs, so under GSPMD each model-parallel shard holds
  1/mp_size of the checkpoint. The backward-pass allgather that the reference
  does by hand (``get_full_inputs:281``) is inserted by XLA when the
  recomputation needs the full value.
- ``cpu_checkpointing`` / ``checkpoint_in_cpu`` (ref ``PA_TO_CPU:410``): the
  saved inputs are placed in ``pinned_host`` memory via in-jit
  ``jax.device_put``; XLA schedules the D2H/H2D transfers around the
  recompute.
- ``contiguous_memory_optimization`` / ``synchronize_checkpoint_boundary``:
  accepted no-ops — XLA owns buffer layout and stream ordering.
- ``profile``: wraps each checkpointed call in a ``jax.named_scope`` so the
  cost shows up under a stable name in ``jax.profiler`` traces (the
  reference logs wall-clock per call, ``checkpointing.py:331-335``).
"""

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name  # re-exported for users

from deepspeed_tpu.utils.logging import logger

__all__ = [
    "configure", "is_configured", "reset", "checkpoint", "checkpoint_name",
    "non_reentrant_checkpoint", "RNGStatesTracker", "get_rng_tracker",
    "get_cuda_rng_tracker", "model_parallel_seed",
    "model_parallel_cuda_manual_seed", "CheckpointFunction",
]

# module-level flags (reference checkpointing.py:50-54)
_CONFIGURED = False
PARTITION_ACTIVATIONS = False
PA_TO_CPU = False
CONTIGUOUS_CHECKPOINTING = False
SYNCHRONIZE = False
PROFILE_TIME = False

num_layers: Optional[int] = None
mpu = None
_MODEL_AXIS = "model"
_MESH: Optional[jax.sharding.Mesh] = None
_WARNED_NO_MESH = False
_WARNED_NO_HOST = False


def set_mesh(mesh: Optional[jax.sharding.Mesh]):
    """Record the device mesh partition_activations shards over. Called by
    the engine at init (the TPU analogue of the reference passing ``mpu``);
    user code may also call it directly."""
    global _MESH
    _MESH = mesh


def _detect_model_axis():
    """Mesh axis the activation checkpoints are partitioned over."""
    if mpu is not None and hasattr(mpu, "model_axis_name"):
        return mpu.model_axis_name
    return _MODEL_AXIS


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              num_checkpoints=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None):
    """Configure activation checkpointing (reference ``configure():653``).

    ``deepspeed_config`` may be a path/dict consumed by ``DeepSpeedConfig``
    or an already-built config object with an
    ``activation_checkpointing_config`` attribute. Explicit kwargs override
    the config file, as in the reference.
    """
    global _CONFIGURED, PARTITION_ACTIVATIONS, PA_TO_CPU
    global CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME
    global num_layers, mpu

    mpu = mpu_

    cfg = None
    if deepspeed_config is not None:
        if hasattr(deepspeed_config, "activation_checkpointing_config"):
            cfg = deepspeed_config.activation_checkpointing_config
        else:
            from deepspeed_tpu.runtime.config import DeepSpeedConfig
            cfg = DeepSpeedConfig(deepspeed_config) \
                .activation_checkpointing_config

    def pick(explicit, from_cfg, default):
        if explicit is not None:
            return explicit
        if from_cfg is not None:
            return from_cfg
        return default

    PARTITION_ACTIVATIONS = pick(
        partition_activations,
        getattr(cfg, "partition_activations", None), False)
    CONTIGUOUS_CHECKPOINTING = pick(
        contiguous_checkpointing,
        getattr(cfg, "contiguous_memory_optimization", None), False)
    num_layers = pick(
        num_checkpoints, getattr(cfg, "number_checkpoints", None), None)
    PA_TO_CPU = pick(
        checkpoint_in_cpu, getattr(cfg, "cpu_checkpointing", None), False)
    SYNCHRONIZE = pick(
        synchronize,
        getattr(cfg, "synchronize_checkpoint_boundary", None), False)
    PROFILE_TIME = pick(profile, getattr(cfg, "profile", None), False)

    if CONTIGUOUS_CHECKPOINTING:
        assert PARTITION_ACTIVATIONS, \
            "contiguous_checkpointing requires partition_activations " \
            "(reference checkpointing.py asserts the same)"
        logger.info("contiguous_memory_optimization accepted; XLA owns "
                    "buffer allocation so this is a no-op on TPU")
    _CONFIGURED = True


def is_configured() -> bool:
    return _CONFIGURED


def reset():
    """Reset flags to defaults (reference ``reset():630``). The recorded
    mesh is environmental and survives reset."""
    global _CONFIGURED, PARTITION_ACTIVATIONS, PA_TO_CPU
    global CONTIGUOUS_CHECKPOINTING, SYNCHRONIZE, PROFILE_TIME, num_layers
    global _WARNED_NO_MESH, _WARNED_NO_HOST
    _WARNED_NO_MESH = False
    _WARNED_NO_HOST = False
    _CONFIGURED = False
    PARTITION_ACTIVATIONS = False
    PA_TO_CPU = False
    CONTIGUOUS_CHECKPOINTING = False
    SYNCHRONIZE = False
    PROFILE_TIME = False
    num_layers = None


def _is_floating(x) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) and \
        jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)


def _current_mesh() -> Optional[jax.sharding.Mesh]:
    """The mesh partition_activations shards over. Precedence: explicit
    set_mesh() (what the engine wires) > ambient jax.sharding.set_mesh
    context > legacy `with mesh:` context (deprecated thread_resources —
    guarded so its eventual removal degrades to the set_mesh path)."""
    if _MESH is not None and not _MESH.empty:
        return _MESH
    try:
        gm = jax.sharding.get_mesh()
        if isinstance(gm, jax.sharding.Mesh) and not gm.empty:
            return gm
    except Exception:
        pass
    try:
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            env_mesh = \
                jax.interpreters.pxla.thread_resources.env.physical_mesh
        if not env_mesh.empty:
            return env_mesh
    except Exception:
        pass
    return None


def _constrain_saved(args):
    """Apply the partition/offload placement to the values jax.checkpoint
    will stash (its primal inputs)."""
    def place(x):
        global _WARNED_NO_MESH, _WARNED_NO_HOST
        if not _is_floating(x):
            return x
        if PARTITION_ACTIVATIONS:
            axis = _detect_model_axis()
            mesh = _current_mesh()
            if mesh is None or axis not in mesh.axis_names:
                if not _WARNED_NO_MESH:
                    _WARNED_NO_MESH = True
                    logger.warning(
                        "partition_activations=True but no mesh with a "
                        f"'{axis}' axis is known — call checkpointing."
                        "set_mesh(mesh) (the engine does this automatically)"
                        "; activations stay replicated")
            else:
                x = jnp.asarray(x)
                # shard the stashed copy along its last partitionable dim;
                # explicit NamedSharding works inside jit w/o a mesh context
                spec = [None] * x.ndim
                sz = mesh.shape[axis]
                for d in range(x.ndim - 1, -1, -1):
                    if x.shape[d] % sz == 0 and x.shape[d] >= sz:
                        spec[d] = axis
                        break
                x = jax.lax.with_sharding_constraint(
                    x, jax.sharding.NamedSharding(
                        mesh, jax.sharding.PartitionSpec(*spec)))
        if PA_TO_CPU:
            try:
                x = jax.device_put(x, jax.memory.Space.Host)
            except Exception as e:  # backend without host memory space
                if not _WARNED_NO_HOST:
                    _WARNED_NO_HOST = True
                    logger.warning(
                        "cpu_checkpointing requested but host memory space "
                        f"unavailable on this backend ({e}); checkpoints "
                        "stay in device memory")
        return x
    return jax.tree_util.tree_map(place, args)


def checkpoint(function, *args, **kwargs):
    """Checkpoint a forward segment (reference ``CheckpointFunction:314`` /
    module-level ``checkpoint():578``).

    The segment's outputs are returned; during the backward pass the segment
    is recomputed instead of its intermediates being saved. Differentiable
    and jit-compatible: call inside a jitted/`grad`ed function.

    With ``cpu_checkpointing`` the primal inputs (what ``jax.checkpoint``
    stashes) are placed in host memory before the remat boundary and fetched
    back to device inside it, so the live fwd→bwd value is the host copy and
    the backward recompute pays one H2D transfer (reference ``PA_TO_CPU``
    semantics, ``get_full_inputs:281``).
    """
    inner = function
    if PA_TO_CPU:
        def inner(*a, _fn=function):
            def to_dev(x):
                if _is_floating(x):
                    try:
                        return jax.device_put(x, jax.memory.Space.Device)
                    except Exception:
                        return x
                return x
            return _fn(*jax.tree_util.tree_map(to_dev, a))
    rematted = jax.checkpoint(inner, **kwargs)
    args = _constrain_saved(args)
    if PROFILE_TIME:
        with jax.named_scope("ds_act_checkpoint"):
            return rematted(*args)
    return rematted(*args)


def non_reentrant_checkpoint(function, *args):
    """Alias — JAX remat has no reentrancy distinction."""
    return checkpoint(function, *args)


class CheckpointFunction:
    """API-parity shim for code written against the reference's
    ``torch.autograd.Function`` class (``checkpointing.py:314``)."""

    @staticmethod
    def apply(run_function, *args):
        return checkpoint(run_function, *args)


# ---------------------------------------------------------------------------
# RNG tracker (reference CudaRNGStatesTracker:147 / Megatron mpu/random.py).
# JAX RNG is functional, so "states" are just named base keys; fork() hands
# out a fresh fold_in'd subkey each call, which is the functional analogue of
# advancing a stateful generator.
# ---------------------------------------------------------------------------

_MODEL_PARALLEL_RNG = "model-parallel-rng"
_DATA_PARALLEL_RNG = "data-parallel-rng"


class RNGStatesTracker:

    def __init__(self):
        self._keys = {}
        self._counts = {}

    def reset(self):
        self._keys.clear()
        self._counts.clear()

    def get_states(self):
        return dict(self._keys), dict(self._counts)

    def set_states(self, states):
        keys, counts = states
        self._keys = dict(keys)
        self._counts = dict(counts)

    def add(self, name: str, seed: int):
        if name in self._keys:
            raise Exception(f"rng state {name} already exists")
        self._keys[name] = jax.random.PRNGKey(seed)
        self._counts[name] = 0

    def key(self, name: str = _MODEL_PARALLEL_RNG, step=None) -> jax.Array:
        """A fresh subkey from the named stream (advances the stream).

        WARNING (jit semantics): the Python-side counter advances at *trace*
        time. Calling ``key()`` with no ``step`` inside a jitted train step
        bakes one constant key into the compiled program — every execution
        would reuse the same dropout mask. Inside jit, pass the traced step
        counter: ``tracker.key(step=state.global_step)``; the key is then
        ``fold_in(base, count, step)`` and varies per executed step. (The
        framework's own engines thread rng through TrainState instead.)
        """
        if name not in self._keys:
            raise Exception(f"rng state {name} is not added")
        k = jax.random.fold_in(self._keys[name], self._counts[name])
        self._counts[name] += 1
        if step is not None:
            k = jax.random.fold_in(k, step)
        return k

    class _Fork:
        def __init__(self, key):
            self.key = key

        def __enter__(self):
            return self.key

        def __exit__(self, *exc):
            return False

    def fork(self, name: str = _MODEL_PARALLEL_RNG, step=None):
        """Context manager yielding a fresh subkey (reference ``fork:186``).
        See :meth:`key` for the jit caveat — pass ``step`` inside jit."""
        return self._Fork(self.key(name, step=step))


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _RNG_TRACKER


# reference-name alias (``get_cuda_rng_tracker:215``)
get_cuda_rng_tracker = get_rng_tracker


def model_parallel_seed(seed: int, model_parallel_rank: Optional[int] = None):
    """Seed the named RNG streams (reference
    ``model_parallel_cuda_manual_seed:223``): the data-parallel stream is the
    raw seed (same across MP ranks), the model-parallel stream is offset per
    MP rank so dropout differs across tensor shards."""
    if model_parallel_rank is None:
        if mpu is not None and hasattr(mpu, "get_model_parallel_rank"):
            model_parallel_rank = mpu.get_model_parallel_rank()
        else:
            model_parallel_rank = 0
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add(_DATA_PARALLEL_RNG, seed)
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718 + model_parallel_rank)


# reference-name alias
model_parallel_cuda_manual_seed = model_parallel_seed


# --------------------------------------------------------------------- #
# reference-name aliases (Megatron-style integrations call these names;
# reference checkpointing.py:57,218,223,584,592)
# --------------------------------------------------------------------- #
from deepspeed_tpu.runtime.utils import see_memory_usage  # noqa: E402,F401


def get_cuda_rng_tracker():
    """Alias of :func:`get_rng_tracker` (no CUDA here; the tracker keys
    jax PRNG streams)."""
    return get_rng_tracker()


def model_parallel_cuda_manual_seed(seed: int):
    """Alias of :func:`model_parallel_seed`."""
    return model_parallel_seed(seed)


def partition_activations_in_checkpoint(partition_activation):
    """(reference checkpointing.py:584) Toggle activation partitioning
    outside configure()."""
    global PARTITION_ACTIVATIONS
    PARTITION_ACTIVATIONS = bool(partition_activation)


def set_num_layers(nlayers):
    """(reference checkpointing.py:592)"""
    global num_layers
    num_layers = nlayers


def detach_variable(inputs, device=None):
    """(reference checkpointing.py:89) — functional analog:
    lax.stop_gradient over the pytree."""
    del device
    return jax.tree_util.tree_map(
        lambda x: jax.lax.stop_gradient(x) if _is_floating(x) else x,
        inputs)
