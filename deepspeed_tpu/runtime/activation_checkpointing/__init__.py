from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig)
