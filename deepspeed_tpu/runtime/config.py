"""The single-JSON config system.

TPU-native analog of the reference's ``deepspeed/runtime/config.py``
(DeepSpeedConfig at config.py:464). One JSON file (or dict) drives the whole
framework. The batch-size triangle invariant is preserved
(reference config.py:557)::

    train_batch_size == train_micro_batch_size_per_gpu
                        * gradient_accumulation_steps
                        * data-parallel world size
"""

import json
import os
from typing import Optional

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (
    get_scalar_param,
    dict_raise_error_on_duplicate_keys,
)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.constants import MAX_STAGE_ZERO_OPTIMIZATION
from deepspeed_tpu.runtime.activation_checkpointing.config import (
    DeepSpeedActivationCheckpointingConfig,
)
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


def get_fp16_enabled(param_dict):
    if C.FP16 in param_dict:
        return get_scalar_param(param_dict[C.FP16], C.FP16_ENABLED,
                                C.FP16_ENABLED_DEFAULT)
    return False


def get_bf16_enabled(param_dict):
    if C.BF16 in param_dict:
        return get_scalar_param(param_dict[C.BF16], C.BF16_ENABLED,
                                C.BF16_ENABLED_DEFAULT)
    return False


def get_bf16_master_weights(param_dict):
    if C.BF16 in param_dict:
        return get_scalar_param(param_dict[C.BF16], C.BF16_MASTER_WEIGHTS,
                                C.BF16_MASTER_WEIGHTS_DEFAULT)
    return C.BF16_MASTER_WEIGHTS_DEFAULT


def get_bf16_stochastic_rounding(param_dict):
    if C.BF16 in param_dict:
        return get_scalar_param(param_dict[C.BF16],
                                C.BF16_STOCHASTIC_ROUNDING,
                                C.BF16_STOCHASTIC_ROUNDING_DEFAULT)
    return C.BF16_STOCHASTIC_ROUNDING_DEFAULT


def get_bf16_sr_seed(param_dict):
    if C.BF16 in param_dict:
        return get_scalar_param(param_dict[C.BF16], C.BF16_SR_SEED,
                                C.BF16_SR_SEED_DEFAULT)
    return C.BF16_SR_SEED_DEFAULT


def get_loss_scale(param_dict):
    if get_fp16_enabled(param_dict):
        return get_scalar_param(param_dict[C.FP16], C.FP16_LOSS_SCALE,
                                C.FP16_LOSS_SCALE_DEFAULT)
    return C.FP16_LOSS_SCALE_DEFAULT


def get_initial_dynamic_scale(param_dict):
    if get_fp16_enabled(param_dict):
        initial_scale_power = get_scalar_param(param_dict[C.FP16],
                                               C.FP16_INITIAL_SCALE_POWER,
                                               C.FP16_INITIAL_SCALE_POWER_DEFAULT)
    else:
        initial_scale_power = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    return 2**initial_scale_power


def get_dynamic_loss_scale_args(param_dict):
    loss_scale_args = None
    if get_fp16_enabled(param_dict):
        fp16_dict = param_dict[C.FP16]
        dynamic_props = [
            C.FP16_INITIAL_SCALE_POWER, C.FP16_LOSS_SCALE_WINDOW,
            C.FP16_MIN_LOSS_SCALE, C.FP16_HYSTERESIS
        ]
        if any(d in fp16_dict for d in dynamic_props):
            init_scale = get_scalar_param(fp16_dict, C.FP16_INITIAL_SCALE_POWER,
                                          C.FP16_INITIAL_SCALE_POWER_DEFAULT)
            scale_window = get_scalar_param(fp16_dict, C.FP16_LOSS_SCALE_WINDOW,
                                            C.FP16_LOSS_SCALE_WINDOW_DEFAULT)
            delayed_shift = get_scalar_param(fp16_dict, C.FP16_HYSTERESIS,
                                             C.FP16_HYSTERESIS_DEFAULT)
            min_loss_scale = get_scalar_param(fp16_dict, C.FP16_MIN_LOSS_SCALE,
                                              C.FP16_MIN_LOSS_SCALE_DEFAULT)
            loss_scale_args = {
                "init_scale": 2**init_scale,
                "scale_window": scale_window,
                "delayed_shift": delayed_shift,
                "min_scale": min_loss_scale,
            }
    return loss_scale_args


def get_gradient_accumulation_steps(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_ACCUMULATION_STEPS,
                            C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)


def get_sparse_gradients_enabled(param_dict):
    return get_scalar_param(param_dict, C.SPARSE_GRADIENTS,
                            C.SPARSE_GRADIENTS_DEFAULT)


def get_sparse_gradients_params(param_dict):
    return get_scalar_param(param_dict, C.SPARSE_GRADIENTS_PARAMS,
                            C.SPARSE_GRADIENTS_PARAMS_DEFAULT)


def get_steps_per_print(param_dict):
    return get_scalar_param(param_dict, C.STEPS_PER_PRINT,
                            C.STEPS_PER_PRINT_DEFAULT)


def get_disable_allgather(param_dict):
    return get_scalar_param(param_dict, C.DISABLE_ALLGATHER,
                            C.DISABLE_ALLGATHER_DEFAULT)


def get_dump_state(param_dict):
    return get_scalar_param(param_dict, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)


def get_gradient_clipping(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_CLIPPING,
                            C.GRADIENT_CLIPPING_DEFAULT)


def get_prescale_gradients(param_dict):
    return get_scalar_param(param_dict, C.PRESCALE_GRADIENTS,
                            C.PRESCALE_GRADIENTS_DEFAULT)


def get_gradient_predivide_factor(param_dict):
    return get_scalar_param(param_dict, C.GRADIENT_PREDIVIDE_FACTOR,
                            C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)


def get_sparse_attention(param_dict):
    """Parse the sparse-attention sub-config (reference config.py:156-317)."""
    if C.SPARSE_ATTENTION not in param_dict:
        return None
    sparsity = param_dict[C.SPARSE_ATTENTION]
    mode = get_scalar_param(sparsity, C.SPARSE_MODE, C.SPARSE_MODE_DEFAULT)

    common = {
        C.SPARSE_MODE: mode,
        C.SPARSE_BLOCK: get_scalar_param(sparsity, C.SPARSE_BLOCK, C.SPARSE_BLOCK_DEFAULT),
        C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD: get_scalar_param(
            sparsity, C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD,
            C.SPARSE_DIFFERENT_LAYOUT_PER_HEAD_DEFAULT),
    }
    if mode == C.SPARSE_DENSE_MODE:
        return common
    if mode == C.SPARSE_FIXED_MODE:
        common.update({
            C.SPARSE_NUM_LOCAL_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_LOCAL_BLOCKS, C.SPARSE_NUM_LOCAL_BLOCKS_DEFAULT),
            C.SPARSE_NUM_GLOBAL_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_GLOBAL_BLOCKS, C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
            C.SPARSE_ATTENTION_TYPE: get_scalar_param(
                sparsity, C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT),
            C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION: get_scalar_param(
                sparsity, C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
            C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS: get_scalar_param(
                sparsity, C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS,
                C.SPARSE_NUM_DIFFERENT_GLOBAL_PATTERNS_DEFAULT),
        })
        return common
    if mode == C.SPARSE_VARIABLE_MODE:
        common.update({
            C.SPARSE_NUM_RANDOM_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_RANDOM_BLOCKS, C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            C.SPARSE_LOCAL_WINDOW_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_LOCAL_WINDOW_BLOCKS, C.SPARSE_LOCAL_WINDOW_BLOCKS_DEFAULT),
            C.SPARSE_GLOBAL_BLOCK_INDICES: get_scalar_param(
                sparsity, C.SPARSE_GLOBAL_BLOCK_INDICES, C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            C.SPARSE_GLOBAL_BLOCK_END_INDICES: get_scalar_param(
                sparsity, C.SPARSE_GLOBAL_BLOCK_END_INDICES,
                C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
            C.SPARSE_ATTENTION_TYPE: get_scalar_param(
                sparsity, C.SPARSE_ATTENTION_TYPE, C.SPARSE_ATTENTION_TYPE_DEFAULT),
            C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION: get_scalar_param(
                sparsity, C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION,
                C.SPARSE_HORIZONTAL_GLOBAL_ATTENTION_DEFAULT),
        })
        return common
    if mode == C.SPARSE_BIGBIRD_MODE:
        common.update({
            C.SPARSE_NUM_RANDOM_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_RANDOM_BLOCKS, C.SPARSE_NUM_RANDOM_BLOCKS_DEFAULT),
            C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            C.SPARSE_NUM_GLOBAL_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_GLOBAL_BLOCKS, C.SPARSE_NUM_GLOBAL_BLOCKS_DEFAULT),
        })
        return common
    if mode == C.SPARSE_BSLONGFORMER_MODE:
        common.update({
            C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS: get_scalar_param(
                sparsity, C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS,
                C.SPARSE_NUM_SLIDING_WINDOW_BLOCKS_DEFAULT),
            C.SPARSE_GLOBAL_BLOCK_INDICES: get_scalar_param(
                sparsity, C.SPARSE_GLOBAL_BLOCK_INDICES, C.SPARSE_GLOBAL_BLOCK_INDICES_DEFAULT),
            C.SPARSE_GLOBAL_BLOCK_END_INDICES: get_scalar_param(
                sparsity, C.SPARSE_GLOBAL_BLOCK_END_INDICES,
                C.SPARSE_GLOBAL_BLOCK_END_INDICES_DEFAULT),
        })
        return common
    raise NotImplementedError(
        f"Given sparsity mode, {mode}, has not been implemented yet!")


def get_pipeline_config(param_dict):
    """Parse the pipeline sub-config (reference config.py:327)."""
    default_pipeline = {
        C.PIPELINE_STAGES: C.PIPELINE_STAGES_DEFAULT,
        C.PIPELINE_PARTITION: C.PIPELINE_PARTITION_DEFAULT,
        C.PIPELINE_SEED_LAYERS: C.PIPELINE_SEED_LAYERS_DEFAULT,
        C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL:
            C.PIPELINE_ACTIVATION_CHECKPOINT_INTERVAL_DEFAULT,
    }
    config = default_pipeline.copy()
    for key, val in param_dict.get(C.PIPELINE, {}).items():
        config[key] = val
    return config


def get_optimizer_name(param_dict):
    if C.OPTIMIZER in param_dict and C.TYPE in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.TYPE]
    return C.OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict):
    if get_optimizer_name(param_dict) is not None and \
            C.OPTIMIZER_PARAMS in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.OPTIMIZER_PARAMS]
    return None


def get_optimizer_gradient_clipping(param_dict):
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and C.MAX_GRAD_NORM in optimizer_params:
        return optimizer_params[C.MAX_GRAD_NORM]
    return None


def get_optimizer_legacy_fusion(param_dict):
    if C.OPTIMIZER in param_dict and C.LEGACY_FUSION in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.LEGACY_FUSION]
    return C.LEGACY_FUSION_DEFAULT


def get_scheduler_name(param_dict):
    if C.SCHEDULER in param_dict and C.TYPE in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.TYPE]
    return C.SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict):
    if get_scheduler_name(param_dict) is not None and \
            C.SCHEDULER_PARAMS in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.SCHEDULER_PARAMS]
    return None


def get_train_batch_size(param_dict):
    return get_scalar_param(param_dict, C.TRAIN_BATCH_SIZE,
                            C.TRAIN_BATCH_SIZE_DEFAULT)


def get_train_micro_batch_size_per_gpu(param_dict):
    v = get_scalar_param(param_dict, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, None)
    if v is None:
        v = get_scalar_param(param_dict, C.TRAIN_MICRO_BATCH_SIZE_PER_CHIP,
                             C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
    return v


def get_wall_clock_breakdown(param_dict):
    return get_scalar_param(param_dict, C.WALL_CLOCK_BREAKDOWN,
                            C.WALL_CLOCK_BREAKDOWN_DEFAULT)


def get_memory_breakdown(param_dict):
    return get_scalar_param(param_dict, C.MEMORY_BREAKDOWN,
                            C.MEMORY_BREAKDOWN_DEFAULT)


def get_quantized_comm_config(param_dict):
    """Hierarchical quantized collectives (TPU-native extension; ZeRO++
    qgZ/qwZ/hpZ shapes — runtime/quantized_collectives.py).

    The older ``compressed_allreduce: {enabled, block}`` block is still
    accepted as a legacy alias: its keys seed the defaults, and any
    explicit ``quantized_comm`` key wins.
    """
    legacy = param_dict.get(C.COMPRESSED_ALLREDUCE, {})
    sub = param_dict.get(C.QUANTIZED_COMM, {})
    hierarchical = sub.get(C.QUANTIZED_COMM_HIERARCHICAL,
                           C.QUANTIZED_COMM_HIERARCHICAL_DEFAULT)
    # bools are accepted for ergonomics; True means "let the engine pick"
    # which it cannot (the intra size is a topology fact) — refuse early
    if hierarchical is True:
        raise DeepSpeedConfigError(
            "quantized_comm.hierarchical must be the intra-slice size "
            "(an int >= 2), not true — the split is a topology fact the "
            "engine cannot guess")
    return {
        "enabled": sub.get(
            C.QUANTIZED_COMM_ENABLED,
            legacy.get(C.COMPRESSED_ALLREDUCE_ENABLED,
                       C.QUANTIZED_COMM_ENABLED_DEFAULT)),
        "algo": sub.get(C.QUANTIZED_COMM_ALGO,
                        C.QUANTIZED_COMM_ALGO_DEFAULT),
        "block": sub.get(
            C.QUANTIZED_COMM_BLOCK,
            legacy.get(C.COMPRESSED_ALLREDUCE_BLOCK,
                       C.QUANTIZED_COMM_BLOCK_DEFAULT)),
        "hierarchical": int(hierarchical or 0),
        "quantize_weights": sub.get(
            C.QUANTIZED_COMM_QUANTIZE_WEIGHTS,
            C.QUANTIZED_COMM_QUANTIZE_WEIGHTS_DEFAULT),
        "secondary_partition": sub.get(
            C.QUANTIZED_COMM_SECONDARY_PARTITION,
            C.QUANTIZED_COMM_SECONDARY_PARTITION_DEFAULT),
        # which knobs the user set EXPLICITLY: with comm_autotune
        # enabled these act as overrides that pin the candidate set
        # (runtime/comm_autotune.plan_comm); without it they are simply
        # the values in effect
        "explicit": {
            "algo": C.QUANTIZED_COMM_ALGO in sub,
            "block": (C.QUANTIZED_COMM_BLOCK in sub
                      or C.COMPRESSED_ALLREDUCE_BLOCK in legacy),
            "hierarchical": C.QUANTIZED_COMM_HIERARCHICAL in sub,
        },
    }


def get_comm_autotune_config(param_dict):
    """Topology-aware collective autotuner + compute/comm overlap
    (runtime/comm_autotune.py; docs/performance.md). Off by default;
    when enabled it selects the quantized_comm exchange per topology
    and overlaps the gradient exchange with the next micro-step's
    compute inside the fused scan."""
    from deepspeed_tpu.runtime.comm_autotune import (
        DEFAULT_BLOCK_CANDIDATES, DEFAULT_INTER_GBPS,
        DEFAULT_INTER_LATENCY_US, DEFAULT_INTRA_GBPS,
        DEFAULT_INTRA_LATENCY_US)
    sub = param_dict.get(C.COMM_AUTOTUNE, {})
    overlap = sub.get(C.COMM_AUTOTUNE_OVERLAP,
                      C.COMM_AUTOTUNE_OVERLAP_DEFAULT)
    if isinstance(overlap, int) and not isinstance(overlap, bool):
        # JSON 0/1 must mean false/true downstream, where the overlap
        # decision tests `is False` — identity, not truthiness
        overlap = bool(overlap)
    try:
        return {
            "enabled": sub.get(C.COMM_AUTOTUNE_ENABLED,
                               C.COMM_AUTOTUNE_ENABLED_DEFAULT),
            "overlap": overlap,
            "calibrate": sub.get(C.COMM_AUTOTUNE_CALIBRATE,
                                 C.COMM_AUTOTUNE_CALIBRATE_DEFAULT),
            "intra_size": int(sub.get(C.COMM_AUTOTUNE_INTRA_SIZE,
                                      C.COMM_AUTOTUNE_INTRA_SIZE_DEFAULT)
                              or 0),
            "intra_gbps": float(sub.get(C.COMM_AUTOTUNE_INTRA_GBPS,
                                        DEFAULT_INTRA_GBPS)),
            "inter_gbps": float(sub.get(C.COMM_AUTOTUNE_INTER_GBPS,
                                        DEFAULT_INTER_GBPS)),
            "intra_latency_us": float(sub.get(
                C.COMM_AUTOTUNE_INTRA_LATENCY_US,
                DEFAULT_INTRA_LATENCY_US)),
            "inter_latency_us": float(sub.get(
                C.COMM_AUTOTUNE_INTER_LATENCY_US,
                DEFAULT_INTER_LATENCY_US)),
            "block_candidates": list(sub.get(
                C.COMM_AUTOTUNE_BLOCK_CANDIDATES,
                DEFAULT_BLOCK_CANDIDATES)),
            # which link-model knobs the user set EXPLICITLY: explicit
            # values always win; otherwise a calibrate_wire_model()
            # artifact from a prior run (comm_autotune.load_wire_
            # calibration) overrides the hardcoded nominal constants
            "explicit": {
                k: k in sub
                for k in (C.COMM_AUTOTUNE_INTRA_GBPS,
                          C.COMM_AUTOTUNE_INTER_GBPS,
                          C.COMM_AUTOTUNE_INTRA_LATENCY_US,
                          C.COMM_AUTOTUNE_INTER_LATENCY_US)
            },
        }
    except (TypeError, ValueError) as e:
        # the coercions run at parse time (before _do_sanity_check),
        # so malformed values get the section's curated error here
        raise DeepSpeedConfigError(
            f"comm_autotune: malformed value ({e}); intra_size and "
            "latencies/bandwidths must be numbers, block_candidates a "
            "list of ints")


def get_async_pipeline_config(param_dict):
    """Async step pipeline (scan-fused accumulation + prefetching
    dataloader + deferred loss telemetry; docs/performance.md "Async
    step pipeline"). All knobs have safe defaults — the section is
    purely an override surface."""
    sub = param_dict.get(C.ASYNC_PIPELINE, {})
    return {
        "fused_accumulation": sub.get(C.ASYNC_FUSED_ACCUMULATION,
                                      C.ASYNC_FUSED_ACCUMULATION_DEFAULT),
        "prefetch_depth": sub.get(C.ASYNC_PREFETCH_DEPTH,
                                  C.ASYNC_PREFETCH_DEPTH_DEFAULT),
        "sync_loss_every_step": sub.get(
            C.ASYNC_SYNC_LOSS_EVERY_STEP,
            C.ASYNC_SYNC_LOSS_EVERY_STEP_DEFAULT),
    }


def get_observability_config(param_dict):
    """Unified profiling & telemetry (deepspeed_tpu/profiling/): FLOPs/MFU
    cost profiler, recompile tracking, memory watermarks, trace spans,
    and the JSONL event log tools/obs_report.py renders.

    The legacy top-level ``profiler: {}`` section (jax.profiler trace
    window) is aliased into ``observability.trace``: its keys seed the
    defaults and any explicit ``observability.trace`` key wins — same
    pattern as the compressed_allreduce -> quantized_comm alias.
    """
    legacy_trace = param_dict.get(C.PROFILER, {})
    sub = param_dict.get(C.OBSERVABILITY, {})
    tr = sub.get(C.OBS_TRACE, {})
    trace = {
        "enabled": tr.get(
            C.PROFILER_ENABLED,
            legacy_trace.get(C.PROFILER_ENABLED,
                             C.PROFILER_ENABLED_DEFAULT)),
        "output_path": tr.get(
            C.PROFILER_OUTPUT_PATH,
            legacy_trace.get(C.PROFILER_OUTPUT_PATH,
                             C.PROFILER_OUTPUT_PATH_DEFAULT)),
        "start_step": tr.get(
            C.PROFILER_START_STEP,
            legacy_trace.get(C.PROFILER_START_STEP,
                             C.PROFILER_START_STEP_DEFAULT)),
        "num_steps": tr.get(
            C.PROFILER_NUM_STEPS,
            legacy_trace.get(C.PROFILER_NUM_STEPS,
                             C.PROFILER_NUM_STEPS_DEFAULT)),
    }
    srv = sub.get(C.OBS_SERVE, {}) or {}
    slo = srv.get(C.OBS_SERVE_SLO, {}) or {}
    events_max_mb = sub.get(C.OBS_EVENTS_MAX_MB,
                            C.OBS_EVENTS_MAX_MB_DEFAULT)
    serve_max_mb = srv.get(C.OBS_SERVE_EVENTS_MAX_MB,
                           C.OBS_SERVE_EVENTS_MAX_MB_DEFAULT)
    serve = {
        "enabled": bool(srv.get(C.OBS_SERVE_ENABLED,
                                C.OBS_SERVE_ENABLED_DEFAULT)),
        "slo": {
            "ttft_ms": float(slo.get(C.OBS_SERVE_SLO_TTFT_MS,
                                     C.OBS_SERVE_SLO_TTFT_MS_DEFAULT)),
            "tbt_ms": float(slo.get(C.OBS_SERVE_SLO_TBT_MS,
                                    C.OBS_SERVE_SLO_TBT_MS_DEFAULT)),
        },
        "sample_rate": float(srv.get(C.OBS_SERVE_SAMPLE_RATE,
                                     C.OBS_SERVE_SAMPLE_RATE_DEFAULT)),
        # serving events log inherits the top-level rotation cap
        # unless overridden inside the serve section
        "events_max_mb": float(events_max_mb if serve_max_mb is None
                               else serve_max_mb),
        "replica_id": srv.get(C.OBS_SERVE_REPLICA_ID,
                              C.OBS_SERVE_REPLICA_ID_DEFAULT),
    }
    # validated here (not only in DeepSpeedConfig) because the
    # inference engine parses this section standalone
    if serve["sample_rate"] < 0 or serve["sample_rate"] > 1:
        raise DeepSpeedConfigError(
            f"observability.serve.sample_rate must be in [0, 1], got "
            f"{serve['sample_rate']}")
    if serve["slo"]["ttft_ms"] <= 0 or serve["slo"]["tbt_ms"] <= 0:
        raise DeepSpeedConfigError(
            "observability.serve.slo thresholds must be > 0, got "
            f"{serve['slo']}")
    if float(events_max_mb) < 0:
        raise DeepSpeedConfigError(
            "observability.events_max_mb must be >= 0 (0 disables "
            "rotation)")
    if serve["events_max_mb"] < 0:
        raise DeepSpeedConfigError(
            "observability.serve.events_max_mb must be >= 0 (0 disables "
            "rotation)")
    if serve["replica_id"] is not None:
        serve["replica_id"] = int(serve["replica_id"])
        if serve["replica_id"] < 0:
            raise DeepSpeedConfigError(
                "observability.serve.replica_id must be >= 0, got "
                f"{serve['replica_id']}")
    hl = sub.get(C.OBS_HEALTH, {}) or {}
    det = hl.get(C.OBS_HEALTH_DETECTORS, {}) or {}
    health = {
        "enabled": bool(hl.get(C.OBS_HEALTH_ENABLED,
                               C.OBS_HEALTH_ENABLED_DEFAULT)),
        "ring_events": int(hl.get(C.OBS_HEALTH_RING_EVENTS,
                                  C.OBS_HEALTH_RING_EVENTS_DEFAULT)),
        "stall_timeout_s": float(hl.get(
            C.OBS_HEALTH_STALL_TIMEOUT_S,
            C.OBS_HEALTH_STALL_TIMEOUT_S_DEFAULT)),
        "on_stall": str(hl.get(C.OBS_HEALTH_ON_STALL,
                               C.OBS_HEALTH_ON_STALL_DEFAULT)),
        "flight_path": str(hl.get(C.OBS_HEALTH_FLIGHT_PATH,
                                  C.OBS_HEALTH_FLIGHT_PATH_DEFAULT)),
        "detectors": {
            "enabled": bool(det.get(C.OBS_HEALTH_DET_ENABLED,
                                    C.OBS_HEALTH_DET_ENABLED_DEFAULT)),
            "nonfinite_streak": int(det.get(
                C.OBS_HEALTH_DET_NONFINITE_STREAK,
                C.OBS_HEALTH_DET_NONFINITE_STREAK_DEFAULT)),
            "spike_zscore": float(det.get(
                C.OBS_HEALTH_DET_SPIKE_ZSCORE,
                C.OBS_HEALTH_DET_SPIKE_ZSCORE_DEFAULT)),
            "spike_window": int(det.get(
                C.OBS_HEALTH_DET_SPIKE_WINDOW,
                C.OBS_HEALTH_DET_SPIKE_WINDOW_DEFAULT)),
            "grad_norm_max": float(det.get(
                C.OBS_HEALTH_DET_GRAD_NORM_MAX,
                C.OBS_HEALTH_DET_GRAD_NORM_MAX_DEFAULT)),
            "scale_collapse_below": float(det.get(
                C.OBS_HEALTH_DET_SCALE_COLLAPSE_BELOW,
                C.OBS_HEALTH_DET_SCALE_COLLAPSE_BELOW_DEFAULT)),
            "recompile_storm_count": int(det.get(
                C.OBS_HEALTH_DET_RECOMPILE_STORM_COUNT,
                C.OBS_HEALTH_DET_RECOMPILE_STORM_COUNT_DEFAULT)),
            "recompile_storm_window": int(det.get(
                C.OBS_HEALTH_DET_RECOMPILE_STORM_WINDOW,
                C.OBS_HEALTH_DET_RECOMPILE_STORM_WINDOW_DEFAULT)),
        },
    }
    # validated here for the same standalone-parse reason as serve
    if health["ring_events"] < 1:
        raise DeepSpeedConfigError(
            "observability.health.ring_events must be >= 1, got "
            f"{health['ring_events']}")
    if health["stall_timeout_s"] < 0:
        raise DeepSpeedConfigError(
            "observability.health.stall_timeout_s must be >= 0 (0 "
            f"disables the watchdog), got {health['stall_timeout_s']}")
    if health["on_stall"] not in ("warn", "exit"):
        raise DeepSpeedConfigError(
            "observability.health.on_stall must be 'warn' or 'exit', "
            f"got {health['on_stall']!r}")
    _det = health["detectors"]
    if _det["nonfinite_streak"] < 1 or _det["spike_window"] < 2 or \
            _det["recompile_storm_count"] < 1 or \
            _det["recompile_storm_window"] < 1:
        raise DeepSpeedConfigError(
            "observability.health.detectors window/streak/count knobs "
            f"must be positive, got {_det}")
    if _det["spike_zscore"] <= 0 or _det["grad_norm_max"] <= 0 or \
            _det["scale_collapse_below"] <= 0:
        raise DeepSpeedConfigError(
            "observability.health.detectors thresholds must be > 0, "
            f"got {_det}")
    return {
        "enabled": sub.get(C.OBS_ENABLED, C.OBS_ENABLED_DEFAULT),
        "events_dir": sub.get(C.OBS_EVENTS_DIR, C.OBS_EVENTS_DIR_DEFAULT),
        "events_max_mb": float(events_max_mb),
        "flops_profiler": sub.get(C.OBS_FLOPS_PROFILER,
                                  C.OBS_FLOPS_PROFILER_DEFAULT),
        "memory_watermarks": sub.get(C.OBS_MEMORY_WATERMARKS,
                                     C.OBS_MEMORY_WATERMARKS_DEFAULT),
        "recompile_warn_after": sub.get(C.OBS_RECOMPILE_WARN_AFTER,
                                        C.OBS_RECOMPILE_WARN_AFTER_DEFAULT),
        "chrome_trace_path": sub.get(C.OBS_CHROME_TRACE_PATH,
                                     C.OBS_CHROME_TRACE_PATH_DEFAULT),
        "serve": serve,
        "health": health,
        "trace": trace,
    }


def get_profiler_config(param_dict):
    """Legacy accessor: the jax.profiler trace window, now owned by
    observability.trace (this returns the same aliased dict)."""
    return get_observability_config(param_dict)["trace"]


def get_compile_cache_config(param_dict):
    """Persistent XLA compilation cache (re-runs start hot; see
    constants.py for the knob's rationale)."""
    sub = param_dict.get(C.COMPILE_CACHE, {})
    return {
        "enabled": sub.get(C.COMPILE_CACHE_ENABLED,
                           C.COMPILE_CACHE_ENABLED_DEFAULT),
        "dir": sub.get(C.COMPILE_CACHE_DIR, C.COMPILE_CACHE_DIR_DEFAULT),
        "min_compile_secs": sub.get(C.COMPILE_CACHE_MIN_COMPILE_SECS,
                                    C.COMPILE_CACHE_MIN_COMPILE_SECS_DEFAULT),
    }


def get_checkpoint_config(param_dict):
    """Fault-tolerant checkpointing knobs (atomic commit + verification +
    retention + async snapshot saves + preemption drain/supervisor; see
    runtime/checkpoint.py, runtime/elastic.py, docs/checkpointing.md)."""
    sub = param_dict.get(C.CHECKPOINT, {})
    sup = sub.get(C.CHECKPOINT_SUPERVISOR, {}) or {}
    cfg = {
        "verify_checksums": sub.get(C.CHECKPOINT_VERIFY_CHECKSUMS,
                                    C.CHECKPOINT_VERIFY_CHECKSUMS_DEFAULT),
        "keep_n": sub.get(C.CHECKPOINT_KEEP_N, C.CHECKPOINT_KEEP_N_DEFAULT),
        "io_retries": sub.get(C.CHECKPOINT_IO_RETRIES,
                              C.CHECKPOINT_IO_RETRIES_DEFAULT),
        "io_retry_backoff": sub.get(C.CHECKPOINT_IO_RETRY_BACKOFF,
                                    C.CHECKPOINT_IO_RETRY_BACKOFF_DEFAULT),
        "async_save": bool(sub.get(C.CHECKPOINT_ASYNC_SAVE,
                                   C.CHECKPOINT_ASYNC_SAVE_DEFAULT)),
        "drain_on_preemption": bool(sub.get(
            C.CHECKPOINT_DRAIN_ON_PREEMPTION,
            C.CHECKPOINT_DRAIN_ON_PREEMPTION_DEFAULT)),
        "save_dir": sub.get(C.CHECKPOINT_SAVE_DIR,
                            C.CHECKPOINT_SAVE_DIR_DEFAULT),
        "supervisor": {
            "max_restarts": int(sup.get(
                C.CHECKPOINT_SUPERVISOR_MAX_RESTARTS,
                C.CHECKPOINT_SUPERVISOR_MAX_RESTARTS_DEFAULT)),
            "backoff": float(sup.get(
                C.CHECKPOINT_SUPERVISOR_BACKOFF,
                C.CHECKPOINT_SUPERVISOR_BACKOFF_DEFAULT)),
        },
    }
    if cfg["supervisor"]["max_restarts"] < 0:
        raise DeepSpeedConfigError(
            "checkpoint.supervisor.max_restarts must be >= 0, got "
            f"{cfg['supervisor']['max_restarts']}")
    if cfg["supervisor"]["backoff"] < 0:
        raise DeepSpeedConfigError(
            "checkpoint.supervisor.backoff must be >= 0, got "
            f"{cfg['supervisor']['backoff']}")
    if cfg["save_dir"] is not None and not isinstance(cfg["save_dir"], str):
        raise DeepSpeedConfigError(
            "checkpoint.save_dir must be a path string or null")
    return cfg


def _norm_quantize_weights(v):
    """``inference.quantize_weights``: False | "bf16" | "int8". True is
    a back-compat alias for "bf16" (the historical wire-only behavior);
    the normalized value is what the engine branches on."""
    if isinstance(v, str):
        low = v.lower()
        if low in ("bf16", "int8"):
            return low
        raise DeepSpeedConfigError(
            f"inference.quantize_weights must be false, true (alias for "
            f"'bf16'), 'bf16', or 'int8', got {v!r}")
    return "bf16" if v else False


def get_inference_config(param_dict):
    """Serving-engine knobs (deepspeed_tpu/inference/; docs/inference.md).
    Bucket lists are validated up front — a malformed bucket table would
    otherwise surface as silent steady-state recompiles, the exact
    failure mode the buckets exist to prevent."""
    from deepspeed_tpu.inference.buckets import validate_buckets
    sub = param_dict.get(C.INFERENCE, {})
    cfg = {
        "max_batch_size": int(sub.get(C.INF_MAX_BATCH_SIZE,
                                      C.INF_MAX_BATCH_SIZE_DEFAULT)),
        "prompt_buckets": list(sub.get(C.INF_PROMPT_BUCKETS,
                                       C.INF_PROMPT_BUCKETS_DEFAULT)),
        "batch_buckets": list(sub.get(C.INF_BATCH_BUCKETS,
                                      C.INF_BATCH_BUCKETS_DEFAULT)),
        "max_seq_len": int(sub.get(C.INF_MAX_SEQ_LEN,
                                   C.INF_MAX_SEQ_LEN_DEFAULT)),
        "max_new_tokens": int(sub.get(C.INF_MAX_NEW_TOKENS,
                                      C.INF_MAX_NEW_TOKENS_DEFAULT)),
        "temperature": float(sub.get(C.INF_TEMPERATURE,
                                     C.INF_TEMPERATURE_DEFAULT)),
        "top_k": int(sub.get(C.INF_TOP_K, C.INF_TOP_K_DEFAULT)),
        "eos_token_id": sub.get(C.INF_EOS_TOKEN_ID,
                                C.INF_EOS_TOKEN_ID_DEFAULT),
        "events_dir": sub.get(C.INF_EVENTS_DIR, C.INF_EVENTS_DIR_DEFAULT),
        "quantize_weights": _norm_quantize_weights(
            sub.get(C.INF_QUANTIZE_WEIGHTS,
                    C.INF_QUANTIZE_WEIGHTS_DEFAULT)),
        "quantize_block": int(sub.get(C.INF_QUANTIZE_BLOCK,
                                      C.INF_QUANTIZE_BLOCK_DEFAULT)),
        "admit_lookahead": int(sub.get(C.INF_ADMIT_LOOKAHEAD,
                                       C.INF_ADMIT_LOOKAHEAD_DEFAULT)),
    }
    pk = sub.get(C.INF_PAGED_KV, {}) or {}
    cfg["paged_kv"] = {
        "enabled": bool(pk.get(C.INF_PAGED_ENABLED,
                               C.INF_PAGED_ENABLED_DEFAULT)),
        "page_size": int(pk.get(C.INF_PAGED_PAGE_SIZE,
                                C.INF_PAGED_PAGE_SIZE_DEFAULT)),
        "num_pages": int(pk.get(C.INF_PAGED_NUM_PAGES,
                                C.INF_PAGED_NUM_PAGES_DEFAULT)),
        "prefix_cache": bool(pk.get(C.INF_PAGED_PREFIX_CACHE,
                                    C.INF_PAGED_PREFIX_CACHE_DEFAULT)),
        "attn_kernel": str(pk.get(C.INF_PAGED_ATTN_KERNEL,
                                  C.INF_PAGED_ATTN_KERNEL_DEFAULT)),
        "decode_page_buckets": list(pk.get(
            C.INF_PAGED_DECODE_PAGE_BUCKETS,
            C.INF_PAGED_DECODE_PAGE_BUCKETS_DEFAULT)),
        "kv_dtype": pk.get(C.INF_PAGED_KV_DTYPE,
                           C.INF_PAGED_KV_DTYPE_DEFAULT),
        "kv_quant_block": int(pk.get(C.INF_PAGED_KV_QUANT_BLOCK,
                                     C.INF_PAGED_KV_QUANT_BLOCK_DEFAULT)),
    }
    mesh_sub = sub.get(C.INF_MESH, {}) or {}
    cfg["mesh"] = {"axes": dict(mesh_sub.get(C.INF_MESH_AXES, {}) or {})}
    ck = sub.get(C.INF_CHUNKED_PREFILL, {}) or {}
    cfg["chunked_prefill"] = {
        "enabled": bool(ck.get(C.INF_CHUNK_ENABLED,
                               C.INF_CHUNK_ENABLED_DEFAULT)),
        "chunk_tokens": int(ck.get(C.INF_CHUNK_TOKENS,
                                   C.INF_CHUNK_TOKENS_DEFAULT)),
        "cp_threshold_tokens": int(ck.get(
            C.INF_CHUNK_CP_THRESHOLD,
            C.INF_CHUNK_CP_THRESHOLD_DEFAULT)),
    }
    sd = sub.get(C.INF_SPEC_DECODE, {}) or {}
    cfg["spec_decode"] = {
        "enabled": bool(sd.get(C.INF_SPEC_ENABLED,
                               C.INF_SPEC_ENABLED_DEFAULT)),
        "k": int(sd.get(C.INF_SPEC_K, C.INF_SPEC_K_DEFAULT)),
        "method": str(sd.get(C.INF_SPEC_METHOD,
                             C.INF_SPEC_METHOD_DEFAULT)),
        "ngram_min": int(sd.get(C.INF_SPEC_NGRAM_MIN,
                                C.INF_SPEC_NGRAM_MIN_DEFAULT)),
        "ngram_max": int(sd.get(C.INF_SPEC_NGRAM_MAX,
                                C.INF_SPEC_NGRAM_MAX_DEFAULT)),
        "verify_widths": list(sd.get(C.INF_SPEC_VERIFY_WIDTHS,
                                     C.INF_SPEC_VERIFY_WIDTHS_DEFAULT)),
    }
    dg = sub.get(C.INF_DISAGG, {}) or {}
    dg_mesh = dg.get(C.INF_DISAGG_DECODE_MESH, {}) or {}
    cfg["disagg"] = {
        "enabled": bool(dg.get(C.INF_DISAGG_ENABLED,
                               C.INF_DISAGG_ENABLED_DEFAULT)),
        "separate_pools": dg.get(C.INF_DISAGG_SEPARATE_POOLS,
                                 C.INF_DISAGG_SEPARATE_POOLS_DEFAULT),
        "prefill_pages": int(dg.get(C.INF_DISAGG_PREFILL_PAGES,
                                    C.INF_DISAGG_PREFILL_PAGES_DEFAULT)),
        "decode_mesh": {"axes": dict(
            dg_mesh.get(C.INF_MESH_AXES, {}) or {})},
    }
    fl = sub.get(C.INF_FLEET, {}) or {}
    shed = fl.get(C.INF_FLEET_SLO_SHED, {}) or {}
    swap = fl.get(C.INF_FLEET_SWAP, {}) or {}
    pm = fl.get(C.INF_FLEET_PROCESS_MODE, {}) or {}
    ascale = fl.get(C.INF_FLEET_AUTOSCALE, {}) or {}
    budget = shed.get(C.INF_FLEET_SHED_TTFT_BUDGET_MS,
                      C.INF_FLEET_SHED_TTFT_BUDGET_MS_DEFAULT)
    cfg["fleet"] = {
        "replicas": int(fl.get(C.INF_FLEET_REPLICAS,
                               C.INF_FLEET_REPLICAS_DEFAULT)),
        "routing": str(fl.get(C.INF_FLEET_ROUTING,
                              C.INF_FLEET_ROUTING_DEFAULT)),
        "slo_shed": {
            "enabled": bool(shed.get(C.INF_FLEET_SHED_ENABLED,
                                     C.INF_FLEET_SHED_ENABLED_DEFAULT)),
            "ttft_budget_ms": (float(budget) if budget is not None
                               else None),
            "min_samples": int(shed.get(
                C.INF_FLEET_SHED_MIN_SAMPLES,
                C.INF_FLEET_SHED_MIN_SAMPLES_DEFAULT)),
            "shed_below_priority": int(shed.get(
                C.INF_FLEET_SHED_BELOW_PRIORITY,
                C.INF_FLEET_SHED_BELOW_PRIORITY_DEFAULT)),
            "degrade_factor": float(shed.get(
                C.INF_FLEET_SHED_DEGRADE_FACTOR,
                C.INF_FLEET_SHED_DEGRADE_FACTOR_DEFAULT)),
            "degrade_max_new": int(shed.get(
                C.INF_FLEET_SHED_DEGRADE_MAX_NEW,
                C.INF_FLEET_SHED_DEGRADE_MAX_NEW_DEFAULT)),
        },
        "swap": {
            "verify_integrity": bool(swap.get(
                C.INF_FLEET_SWAP_VERIFY_INTEGRITY,
                C.INF_FLEET_SWAP_VERIFY_INTEGRITY_DEFAULT)),
        },
        "process_mode": {
            "enabled": bool(pm.get(C.INF_FLEET_PM_ENABLED,
                                   C.INF_FLEET_PM_ENABLED_DEFAULT)),
            "rpc_timeout_s": float(pm.get(
                C.INF_FLEET_PM_RPC_TIMEOUT_S,
                C.INF_FLEET_PM_RPC_TIMEOUT_S_DEFAULT)),
            "rpc_retries": int(pm.get(
                C.INF_FLEET_PM_RPC_RETRIES,
                C.INF_FLEET_PM_RPC_RETRIES_DEFAULT)),
            "rpc_backoff_s": float(pm.get(
                C.INF_FLEET_PM_RPC_BACKOFF_S,
                C.INF_FLEET_PM_RPC_BACKOFF_S_DEFAULT)),
            "max_restarts": int(pm.get(
                C.INF_FLEET_PM_MAX_RESTARTS,
                C.INF_FLEET_PM_MAX_RESTARTS_DEFAULT)),
            "restart_backoff_s": float(pm.get(
                C.INF_FLEET_PM_RESTART_BACKOFF_S,
                C.INF_FLEET_PM_RESTART_BACKOFF_S_DEFAULT)),
            "ready_timeout_s": float(pm.get(
                C.INF_FLEET_PM_READY_TIMEOUT_S,
                C.INF_FLEET_PM_READY_TIMEOUT_S_DEFAULT)),
        },
        "autoscale": {
            "enabled": bool(ascale.get(
                C.INF_FLEET_AS_ENABLED,
                C.INF_FLEET_AS_ENABLED_DEFAULT)),
            "min_replicas": int(ascale.get(
                C.INF_FLEET_AS_MIN_REPLICAS,
                C.INF_FLEET_AS_MIN_REPLICAS_DEFAULT)),
            "max_replicas": int(ascale.get(
                C.INF_FLEET_AS_MAX_REPLICAS,
                C.INF_FLEET_AS_MAX_REPLICAS_DEFAULT)),
            "scale_up_patience": int(ascale.get(
                C.INF_FLEET_AS_UP_PATIENCE,
                C.INF_FLEET_AS_UP_PATIENCE_DEFAULT)),
            "scale_down_patience": int(ascale.get(
                C.INF_FLEET_AS_DOWN_PATIENCE,
                C.INF_FLEET_AS_DOWN_PATIENCE_DEFAULT)),
            "cooldown_steps": int(ascale.get(
                C.INF_FLEET_AS_COOLDOWN_STEPS,
                C.INF_FLEET_AS_COOLDOWN_STEPS_DEFAULT)),
        },
    }
    try:
        cfg["prompt_buckets"] = list(validate_buckets(
            cfg["prompt_buckets"], "inference.prompt_buckets"))
        cfg["batch_buckets"] = list(validate_buckets(
            cfg["batch_buckets"], "inference.batch_buckets"))
    except ValueError as e:
        raise DeepSpeedConfigError(str(e))
    if cfg["max_batch_size"] < 1:
        raise DeepSpeedConfigError(
            f"inference.max_batch_size must be >= 1, got "
            f"{cfg['max_batch_size']}")
    if max(cfg["batch_buckets"]) > cfg["max_batch_size"]:
        raise DeepSpeedConfigError(
            f"inference.batch_buckets max ({max(cfg['batch_buckets'])}) "
            f"exceeds max_batch_size ({cfg['max_batch_size']})")
    if max(cfg["prompt_buckets"]) > cfg["max_seq_len"]:
        raise DeepSpeedConfigError(
            f"inference.prompt_buckets max ({max(cfg['prompt_buckets'])}) "
            f"exceeds max_seq_len ({cfg['max_seq_len']})")
    if cfg["max_new_tokens"] < 1 or cfg["top_k"] < 0 or \
            cfg["quantize_block"] < 8:
        raise DeepSpeedConfigError(
            "inference: max_new_tokens >= 1, top_k >= 0 and "
            "quantize_block >= 8 required")
    if cfg["admit_lookahead"] < 0:
        raise DeepSpeedConfigError(
            f"inference.admit_lookahead must be >= 0, got "
            f"{cfg['admit_lookahead']}")
    pkc = cfg["paged_kv"]
    if pkc["page_size"] < 1 or pkc["page_size"] > cfg["max_seq_len"]:
        raise DeepSpeedConfigError(
            f"inference.paged_kv.page_size must be in [1, max_seq_len], "
            f"got {pkc['page_size']}")
    if pkc["num_pages"] < 0 or pkc["num_pages"] == 1:
        # 0 = auto-size; an explicit pool needs >= 2 (null + 1 usable)
        raise DeepSpeedConfigError(
            f"inference.paged_kv.num_pages must be 0 (auto) or >= 2, "
            f"got {pkc['num_pages']}")
    if pkc["attn_kernel"] not in ("pallas", "gather"):
        raise DeepSpeedConfigError(
            f"inference.paged_kv.attn_kernel must be 'pallas' or "
            f"'gather', got {pkc['attn_kernel']!r}")
    if pkc["decode_page_buckets"]:
        try:
            pkc["decode_page_buckets"] = list(validate_buckets(
                pkc["decode_page_buckets"],
                "inference.paged_kv.decode_page_buckets"))
        except ValueError as e:
            raise DeepSpeedConfigError(str(e))
    if pkc["kv_dtype"] is not None:
        pkc["kv_dtype"] = str(pkc["kv_dtype"]).lower()
        if pkc["kv_dtype"] not in ("bf16", "int8"):
            raise DeepSpeedConfigError(
                f"inference.paged_kv.kv_dtype must be null (engine "
                f"dtype), 'bf16', or 'int8', got {pkc['kv_dtype']!r}")
    if pkc["kv_quant_block"] < 0:
        raise DeepSpeedConfigError(
            f"inference.paged_kv.kv_quant_block must be >= 0 (0 = one "
            f"scale per token row), got {pkc['kv_quant_block']}")
    if pkc["kv_quant_block"] and pkc["kv_dtype"] != "int8":
        raise DeepSpeedConfigError(
            "inference.paged_kv.kv_quant_block requires "
            "kv_dtype: 'int8'")
    for where, axes in (("inference.mesh", cfg["mesh"]["axes"]),
                        ("inference.disagg.decode_mesh",
                         cfg["disagg"]["decode_mesh"]["axes"])):
        for name, size in axes.items():
            if name != "model":
                # the serving programs shard params/cache over the
                # 'model' axis only today; an unknown axis would
                # otherwise surface as an opaque jax resource error
                # deep in engine init
                raise DeepSpeedConfigError(
                    f"{where}.axes supports only the 'model' "
                    f"(tensor-parallel) axis, got {name!r}")
            if not isinstance(size, int) or size < 1:
                raise DeepSpeedConfigError(
                    f"{where}.axes entries must be positive ints, "
                    f"got {name}={size!r}")
    ckc = cfg["chunked_prefill"]
    if ckc["enabled"] and not pkc["enabled"]:
        raise DeepSpeedConfigError(
            "inference.chunked_prefill requires paged_kv.enabled (a "
            "chunk is cache_position advancing over the slot's pages)")
    if ckc["enabled"] and (ckc["chunk_tokens"] < 1
                           or ckc["chunk_tokens"] > cfg["max_seq_len"]):
        raise DeepSpeedConfigError(
            f"inference.chunked_prefill.chunk_tokens must be in "
            f"[1, max_seq_len], got {ckc['chunk_tokens']}")
    if ckc["cp_threshold_tokens"] < 0:
        raise DeepSpeedConfigError(
            f"inference.chunked_prefill.cp_threshold_tokens must be "
            f">= 0 (0 = context-parallel off), got "
            f"{ckc['cp_threshold_tokens']}")
    sdc = cfg["spec_decode"]
    if sdc["enabled"] and not pkc["enabled"]:
        raise DeepSpeedConfigError(
            "inference.spec_decode requires paged_kv.enabled (rollback "
            "is a block-table/position edit on the page pool)")
    if sdc["k"] < 1 or sdc["k"] >= cfg["max_seq_len"]:
        raise DeepSpeedConfigError(
            f"inference.spec_decode.k must be in [1, max_seq_len), got "
            f"{sdc['k']}")
    if sdc["method"] not in ("ngram", "callable"):
        raise DeepSpeedConfigError(
            f"inference.spec_decode.method must be 'ngram' or "
            f"'callable', got {sdc['method']!r}")
    if sdc["ngram_min"] < 1 or sdc["ngram_max"] < sdc["ngram_min"]:
        raise DeepSpeedConfigError(
            "inference.spec_decode: 1 <= ngram_min <= ngram_max "
            f"required, got [{sdc['ngram_min']}, {sdc['ngram_max']}]")
    if sdc["verify_widths"]:
        try:
            sdc["verify_widths"] = list(validate_buckets(
                sdc["verify_widths"],
                "inference.spec_decode.verify_widths"))
        except ValueError as e:
            raise DeepSpeedConfigError(str(e))
        if min(sdc["verify_widths"]) < 2:
            # width 1 IS the plain decode program; a verify program
            # only exists to check >= 1 draft token in one dispatch
            raise DeepSpeedConfigError(
                "inference.spec_decode.verify_widths entries must be "
                ">= 2 (width 1 is the plain decode program)")
    dgc = cfg["disagg"]
    if dgc["enabled"] and not pkc["enabled"]:
        raise DeepSpeedConfigError(
            "inference.disagg requires paged_kv.enabled (the handoff "
            "transfers page ownership between worker loops)")
    if dgc["separate_pools"] is not None:
        dgc["separate_pools"] = bool(dgc["separate_pools"])
    if dgc["prefill_pages"] < 0 or dgc["prefill_pages"] == 1:
        raise DeepSpeedConfigError(
            f"inference.disagg.prefill_pages must be 0 (auto) or >= 2, "
            f"got {dgc['prefill_pages']}")
    if dgc["decode_mesh"]["axes"] and not dgc["enabled"]:
        raise DeepSpeedConfigError(
            "inference.disagg.decode_mesh.axes set but disagg.enabled "
            "is false")
    flc = cfg["fleet"]
    if flc["replicas"] < 1:
        raise DeepSpeedConfigError(
            f"inference.fleet.replicas must be >= 1, got "
            f"{flc['replicas']}")
    if flc["routing"] not in C.INF_FLEET_ROUTING_CHOICES:
        raise DeepSpeedConfigError(
            f"inference.fleet.routing must be one of "
            f"{list(C.INF_FLEET_ROUTING_CHOICES)}, got "
            f"{flc['routing']!r}")
    shc = flc["slo_shed"]
    if shc["ttft_budget_ms"] is not None and shc["ttft_budget_ms"] <= 0:
        raise DeepSpeedConfigError(
            f"inference.fleet.slo_shed.ttft_budget_ms must be > 0 (or "
            f"null for the serve SLO), got {shc['ttft_budget_ms']}")
    if shc["min_samples"] < 1 or shc["shed_below_priority"] < 0 or \
            shc["degrade_max_new"] < 0:
        raise DeepSpeedConfigError(
            "inference.fleet.slo_shed: min_samples >= 1, "
            "shed_below_priority >= 0 and degrade_max_new >= 0 required")
    if shc["degrade_factor"] < 1.0:
        raise DeepSpeedConfigError(
            f"inference.fleet.slo_shed.degrade_factor must be >= 1.0 "
            f"(the degrade rung engages above the shed rung), got "
            f"{shc['degrade_factor']}")
    pmc = flc["process_mode"]
    if pmc["rpc_timeout_s"] <= 0 or pmc["ready_timeout_s"] <= 0:
        raise DeepSpeedConfigError(
            f"inference.fleet.process_mode: rpc_timeout_s and "
            f"ready_timeout_s must be > 0, got "
            f"{pmc['rpc_timeout_s']}/{pmc['ready_timeout_s']}")
    if pmc["rpc_retries"] < 0 or pmc["rpc_backoff_s"] < 0 or \
            pmc["max_restarts"] < 0 or pmc["restart_backoff_s"] < 0:
        raise DeepSpeedConfigError(
            "inference.fleet.process_mode: rpc_retries, rpc_backoff_s, "
            "max_restarts and restart_backoff_s must be >= 0")
    asc = flc["autoscale"]
    if asc["min_replicas"] < 1:
        raise DeepSpeedConfigError(
            f"inference.fleet.autoscale.min_replicas must be >= 1, got "
            f"{asc['min_replicas']}")
    if asc["max_replicas"] < asc["min_replicas"]:
        raise DeepSpeedConfigError(
            f"inference.fleet.autoscale.max_replicas must be >= "
            f"min_replicas ({asc['min_replicas']}), got "
            f"{asc['max_replicas']}")
    if asc["scale_up_patience"] < 1 or asc["scale_down_patience"] < 1:
        raise DeepSpeedConfigError(
            "inference.fleet.autoscale: scale_up_patience and "
            "scale_down_patience must be >= 1 (hysteresis — a single "
            "hot or idle step must never flap the fleet)")
    if asc["cooldown_steps"] < 0:
        raise DeepSpeedConfigError(
            f"inference.fleet.autoscale.cooldown_steps must be >= 0, "
            f"got {asc['cooldown_steps']}")
    return cfg


def get_tensorboard_enabled(param_dict):
    if C.TENSORBOARD in param_dict:
        return get_scalar_param(param_dict[C.TENSORBOARD], C.TENSORBOARD_ENABLED,
                                C.TENSORBOARD_ENABLED_DEFAULT)
    return False


def get_tensorboard_output_path(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[C.TENSORBOARD],
                                C.TENSORBOARD_OUTPUT_PATH,
                                C.TENSORBOARD_OUTPUT_PATH_DEFAULT)
    return C.TENSORBOARD_OUTPUT_PATH_DEFAULT


def get_tensorboard_job_name(param_dict):
    if get_tensorboard_enabled(param_dict):
        return get_scalar_param(param_dict[C.TENSORBOARD], C.TENSORBOARD_JOB_NAME,
                                C.TENSORBOARD_JOB_NAME_DEFAULT)
    return C.TENSORBOARD_JOB_NAME_DEFAULT


def get_mesh_axes(param_dict):
    """TPU-native extension: explicit named mesh axes in the JSON config."""
    mesh = param_dict.get(C.MESH, None)
    if mesh is None:
        return None
    return mesh.get(C.MESH_AXES, None)


class DeepSpeedConfig:
    """Parsed view of the JSON config (reference config.py:464)."""

    def __init__(self, json_file_or_dict, mpu=None, world_size: Optional[int] = None):
        if isinstance(json_file_or_dict, dict):
            self._param_dict = json_file_or_dict
        else:
            with open(json_file_or_dict, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)

        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        else:
            self.world_size = int(os.environ.get("DSTPU_DP_WORLD_SIZE", "1"))

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _initialize_params(self, param_dict):
        self.train_batch_size = get_train_batch_size(param_dict)
        self.train_micro_batch_size_per_gpu = get_train_micro_batch_size_per_gpu(param_dict)
        self.gradient_accumulation_steps = get_gradient_accumulation_steps(param_dict)
        self.steps_per_print = get_steps_per_print(param_dict)
        self.dump_state = get_dump_state(param_dict)

        self.disable_allgather = get_disable_allgather(param_dict)
        self.gradient_predivide_factor = get_gradient_predivide_factor(param_dict)
        self.sparse_gradients_enabled = get_sparse_gradients_enabled(param_dict)
        self.sparse_gradients_params = get_sparse_gradients_params(param_dict)

        self.zero_config = DeepSpeedZeroConfig(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.activation_checkpointing_config = \
            DeepSpeedActivationCheckpointingConfig(param_dict)

        self.gradient_clipping = get_gradient_clipping(param_dict)
        self.fp16_enabled = get_fp16_enabled(param_dict)
        self.bf16_enabled = get_bf16_enabled(param_dict)
        self.bf16_master_weights = get_bf16_master_weights(param_dict)
        self.bf16_stochastic_rounding = \
            get_bf16_stochastic_rounding(param_dict)
        self.bf16_sr_seed = get_bf16_sr_seed(param_dict)
        self.loss_scale = get_loss_scale(param_dict)
        self.initial_dynamic_scale = get_initial_dynamic_scale(param_dict)
        self.dynamic_loss_scale_args = get_dynamic_loss_scale_args(param_dict)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and \
                self.optimizer_name.lower() in C.DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_optimizer_legacy_fusion(param_dict)

        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_wall_clock_breakdown(param_dict)
        self.async_pipeline_config = get_async_pipeline_config(param_dict)
        self.observability_config = get_observability_config(param_dict)
        # legacy attribute: the jax.profiler trace window, aliased into
        # observability.trace (scripts written against it keep working)
        self.profiler_config = self.observability_config["trace"]
        self.compile_cache_config = get_compile_cache_config(param_dict)
        self.quantized_comm_config = get_quantized_comm_config(param_dict)
        self.comm_autotune_config = get_comm_autotune_config(param_dict)
        # legacy attribute name, kept for scripts written against it
        self.compressed_allreduce_config = self.quantized_comm_config
        self.memory_breakdown = get_memory_breakdown(param_dict)
        self.checkpoint_config = get_checkpoint_config(param_dict)
        self.inference_config = get_inference_config(param_dict)
        self.tensorboard_enabled = get_tensorboard_enabled(param_dict)
        self.tensorboard_output_path = get_tensorboard_output_path(param_dict)
        self.tensorboard_job_name = get_tensorboard_job_name(param_dict)

        self.sparse_attention = get_sparse_attention(param_dict)
        self.pipeline = get_pipeline_config(param_dict)
        self.prescale_gradients = get_prescale_gradients(param_dict)
        self.mesh_axes = get_mesh_axes(param_dict)

    def _batch_assertion(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self):
        """Solve the batch triangle (reference config.py:562-608)."""
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # all three parameters provided
        if all(x is not None for x in [train_batch, micro_batch, grad_acc]):
            return
        # two parameters provided: derive the third
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        elif micro_batch is not None and grad_acc is not None:
            self.train_batch_size = micro_batch * grad_acc * self.world_size
        # one parameter provided
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

    def _configure_train_batch_size(self):
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self):
        self._do_error_check()
        self._do_warning_check()

    def _do_error_check(self):
        assert self.train_micro_batch_size_per_gpu, \
            f"DeepSpeedConfig: {C.TRAIN_MICRO_BATCH_SIZE_PER_GPU} is not defined"
        assert self.gradient_accumulation_steps, \
            f"DeepSpeedConfig: {C.GRADIENT_ACCUMULATION_STEPS} is not defined"
        if self.zero_enabled:
            assert self.zero_optimization_stage <= MAX_STAGE_ZERO_OPTIMIZATION, (
                f"DeepSpeedConfig: Maximum supported ZeRO stage is "
                f"{MAX_STAGE_ZERO_OPTIMIZATION}")
        if self.fp16_enabled and self.bf16_enabled:
            raise DeepSpeedConfigError(
                "fp16 and bf16 cannot both be enabled; pick one")
        if not self.bf16_master_weights:
            if not self.bf16_enabled:
                raise DeepSpeedConfigError(
                    "bf16.master_weights=false requires bf16.enabled=true "
                    "(params are held in bf16 end-to-end)")
            if not self.bf16_stochastic_rounding:
                raise DeepSpeedConfigError(
                    "bf16.master_weights=false requires "
                    "bf16.stochastic_rounding=true: RNE-cast bf16 updates "
                    "silently drop sub-ulp steps (set it explicitly to "
                    "acknowledge the rounding-mode change)")
        if self.bf16_stochastic_rounding and not self.bf16_enabled:
            raise DeepSpeedConfigError(
                "bf16.stochastic_rounding=true requires bf16.enabled=true")
        if not self.bf16_master_weights and self.zero_enabled and \
                self.zero_config.cpu_offload:
            raise DeepSpeedConfigError(
                "bf16.master_weights=false contradicts ZeRO-Offload: the "
                "offloaded host fp32 copy IS a master copy (drop one of "
                "the two)")
        qc = self.quantized_comm_config
        from deepspeed_tpu.runtime.quantized_collectives import \
            QUANTIZED_ALGOS
        if qc["algo"] not in QUANTIZED_ALGOS:
            raise DeepSpeedConfigError(
                f"quantized_comm.algo must be one of {QUANTIZED_ALGOS}, "
                f"got {qc['algo']!r}")
        if qc["block"] < 8:
            raise DeepSpeedConfigError(
                f"quantized_comm.block must be >= 8, got {qc['block']}")
        if qc["hierarchical"] == 1 or qc["hierarchical"] < 0:
            raise DeepSpeedConfigError(
                "quantized_comm.hierarchical must be 0 (off) or the "
                f"intra-slice size >= 2, got {qc['hierarchical']}")
        if qc["secondary_partition"] and not qc["hierarchical"]:
            raise DeepSpeedConfigError(
                "quantized_comm.secondary_partition (hpZ) needs "
                "quantized_comm.hierarchical >= 2: the secondary shard IS "
                "the intra-slice copy")
        if qc["enabled"] and qc["hierarchical"]:
            if qc["algo"] != "twohop":
                raise DeepSpeedConfigError(
                    "quantized_comm.hierarchical requires algo='twohop' "
                    f"(got {qc['algo']!r}: the legacy allgather exchange "
                    "has no 2D form)")
            if self.sparse_gradients_enabled:
                raise DeepSpeedConfigError(
                    "quantized_comm.hierarchical does not compose with "
                    "sparse_gradients (the CSR exchange is written "
                    "against the flat 'data' axis)")
            if self.optimizer_name and \
                    "onebit" in self.optimizer_name.lower().replace("_", ""):
                raise DeepSpeedConfigError(
                    "quantized_comm.hierarchical does not compose with "
                    "OnebitAdam (its compressed exchange is written "
                    "against the flat 'data' axis)")
        ca = self.comm_autotune_config
        if ca["overlap"] not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                "comm_autotune.overlap must be true, false or \"auto\", "
                f"got {ca['overlap']!r}")
        if ca["intra_size"] == 1 or ca["intra_size"] < 0:
            raise DeepSpeedConfigError(
                "comm_autotune.intra_size must be 0 (infer) or the "
                f"fast-wire extent >= 2, got {ca['intra_size']}")
        if ca["intra_gbps"] <= 0 or ca["inter_gbps"] <= 0:
            raise DeepSpeedConfigError(
                "comm_autotune bandwidths must be > 0 GBit/s, got "
                f"intra={ca['intra_gbps']} inter={ca['inter_gbps']}")
        if ca["intra_latency_us"] < 0 or ca["inter_latency_us"] < 0:
            raise DeepSpeedConfigError(
                "comm_autotune latencies must be >= 0 us")
        if not ca["block_candidates"] or \
                any(int(b) < 8 for b in ca["block_candidates"]):
            raise DeepSpeedConfigError(
                "comm_autotune.block_candidates must be a non-empty "
                f"list of ints >= 8, got {ca['block_candidates']}")
        if ca["enabled"] and not qc["enabled"]:
            logger.warning(
                "comm_autotune.enabled has no exchange to tune: "
                "quantized_comm is disabled (the dense GSPMD allreduce "
                "is compiler-scheduled); enable quantized_comm or drop "
                "the section")
        ap = self.async_pipeline_config
        if not isinstance(ap["prefetch_depth"], int) or \
                ap["prefetch_depth"] < 0:
            raise DeepSpeedConfigError(
                "async_pipeline.prefetch_depth must be an int >= 0 "
                f"(0 disables prefetching), got {ap['prefetch_depth']!r}")
        obs = self.observability_config
        if int(obs["recompile_warn_after"]) < 0:
            raise DeepSpeedConfigError(
                "observability.recompile_warn_after must be >= 0, got "
                f"{obs['recompile_warn_after']}")
        if obs["enabled"] and not isinstance(obs["events_dir"], str):
            raise DeepSpeedConfigError(
                "observability.events_dir must be a path string, got "
                f"{type(obs['events_dir']).__name__}")
        if obs["trace"]["enabled"] and int(obs["trace"]["num_steps"]) < 1:
            raise DeepSpeedConfigError(
                "observability.trace.num_steps must be >= 1 when the "
                "trace window is enabled")
        if qc["quantize_weights"] and not self.zero_enabled:
            logger.warning(
                "quantized_comm.quantize_weights has no effect at ZeRO "
                "stage 0: params are replicated, there is no gather to "
                "compress")

    def _do_warning_check(self):
        if self.bf16_stochastic_rounding and self.bf16_master_weights:
            logger.warning(
                "DeepSpeedConfig: bf16.stochastic_rounding has no effect "
                "while master_weights=true (updates land on the fp32 "
                "master); set bf16.master_weights=false for "
                "master-weight-free bf16 training")
        fp16_enabled = self.fp16_enabled or self.zero_enabled
        vocabulary_size = get_scalar_param(self._param_dict, C.VOCABULARY_SIZE,
                                           C.VOCABULARY_SIZE_DEFAULT)
        if vocabulary_size and vocabulary_size % 8 != 0:
            logger.warning(
                "DeepSpeedConfig: vocabulary size should be aligned to 8 "
                "(128 on TPU for best MXU tiling)")
        if self.optimizer_params is not None and \
                C.MAX_GRAD_NORM in self.optimizer_params and \
                self.optimizer_params[C.MAX_GRAD_NORM] > 0:
            if fp16_enabled:
                logger.warning(
                    f"DeepSpeedConfig: In FP16 mode, DeepSpeed-TPU will pass "
                    f"{C.MAX_GRAD_NORM}:"
                    f"{self.optimizer_params[C.MAX_GRAD_NORM]} to FP16 wrapper")
            else:
                logger.warning(
                    f"DeepSpeedConfig: In FP32 mode, DeepSpeed-TPU does not "
                    f"permit MAX_GRAD_NORM; set gradient_clipping instead")
                self.optimizer_params[C.MAX_GRAD_NORM] = 0.0

    def print(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                logger.info(f"  {arg} {getattr(self, arg)}")
        logger.info("  json = {}".format(
            json.dumps(self._param_dict, sort_keys=True, indent=4, default=str)))
