"""Multi-node transport runners for the ``dstpu`` launcher.

Reference: ``deepspeed/launcher/multinode_runner.py`` (``PDSHRunner:35``,
``OpenMPIRunner:78``, ``MVAPICHRunner:118``) — each wraps a remote-execution
transport and renders the per-node command.

TPU differences: one process per HOST (JAX is multi-controller; chips are
local to the process), rendezvous via ``jax.distributed.initialize`` driven
by ``DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` env (the reference wires
RANK/WORLD_SIZE/MASTER_* per GPU process instead). MVAPICH (CUDA-specific)
has no TPU analog; the MPI runner targets any mpirun.
"""

import os
import shlex
import shutil
import sys
from typing import Dict, List

__all__ = ["MultiNodeRunner", "SSHRunner", "PDSHRunner", "OpenMPIRunner",
           "make_runner"]


class MultiNodeRunner:
    """Base: renders the command that runs ``process_id`` on ``host``."""

    name = "base"

    def __init__(self, args, world_info: Dict[str, List[int]]):
        self.args = args
        self.world_info = world_info

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def _remote_shell_line(self, process_id: int, num_processes: int,
                           coordinator: str,
                           exports: Dict[str, str]) -> str:
        env_parts = [f"{k}={shlex.quote(v)}"
                     for k, v in sorted(exports.items())]
        env_parts += [
            f"DSTPU_COORDINATOR={coordinator}",
            f"DSTPU_NUM_PROCESSES={num_processes}",
            f"DSTPU_PROCESS_ID={process_id}",
        ]
        return (f"cd {shlex.quote(os.getcwd())} && "
                + " ".join(env_parts)
                + f" {shlex.quote(sys.executable)} -u "
                + shlex.quote(self.args.user_script) + " "
                + " ".join(map(shlex.quote, self.args.user_args)))

    def get_cmd(self, host: str, process_id: int, num_processes: int,
                coordinator: str, exports: Dict[str, str]) -> List[str]:
        raise NotImplementedError


class SSHRunner(MultiNodeRunner):
    """Plain ssh per host (the default; the reference's pdsh minus the
    fan-out dependency)."""

    name = "ssh"

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, host, process_id, num_processes, coordinator, exports):
        line = self._remote_shell_line(process_id, num_processes,
                                       coordinator, exports)
        if host in ("localhost", "127.0.0.1"):
            return ["/bin/sh", "-c", line]
        return ["ssh", "-o", "StrictHostKeyChecking=no", host, line]


class PDSHRunner(MultiNodeRunner):
    """pdsh transport (reference ``PDSHRunner:35``). Note pdsh renders one
    command per host here (per-host env differs), not one fan-out."""

    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, host, process_id, num_processes, coordinator, exports):
        line = self._remote_shell_line(process_id, num_processes,
                                       coordinator, exports)
        if host in ("localhost", "127.0.0.1"):
            return ["/bin/sh", "-c", line]
        return ["pdsh", "-R", "ssh", "-w", host, line]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun transport (reference ``OpenMPIRunner:78``): ONE command that
    launches every process; per-process identity comes from
    OMPI_COMM_WORLD_RANK, which init_distributed maps to DSTPU_PROCESS_ID
    via the ``--use_mpi_rank`` shim env."""

    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd_all(self, hosts: List[str], coordinator: str,
                    exports: Dict[str, str]) -> List[str]:
        cmd = ["mpirun", "-np", str(len(hosts)),
               "--host", ",".join(hosts),
               "--allow-run-as-root",
               "-wdir", os.getcwd()]  # ssh/pdsh runners 'cd' instead
        for k, v in sorted(exports.items()):
            if k == "DSTPU_PROCESS_ID":
                # a stale per-rank id from the operator's shell would
                # shadow OMPI_COMM_WORLD_RANK on every rank
                continue
            cmd += ["-x", f"{k}={v}"]
        cmd += ["-x", f"DSTPU_COORDINATOR={coordinator}",
                "-x", f"DSTPU_NUM_PROCESSES={len(hosts)}",
                "-x", "DSTPU_PROCESS_ID_FROM_MPI=1"]
        cmd += [sys.executable, "-u", self.args.user_script]
        cmd += self.args.user_args
        return cmd

    def get_cmd(self, host, process_id, num_processes, coordinator, exports):
        raise RuntimeError("OpenMPIRunner launches all processes in one "
                           "mpirun; use get_cmd_all")


def make_runner(launcher: str, args, world_info) -> MultiNodeRunner:
    runners = {"ssh": SSHRunner, "pdsh": PDSHRunner, "openmpi": OpenMPIRunner}
    if launcher not in runners:
        raise ValueError(f"unknown launcher {launcher!r}; "
                         f"choose from {sorted(runners)}")
    return runners[launcher](args, world_info)
