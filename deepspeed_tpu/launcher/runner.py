"""``dstpu`` — multi-host launcher.

TPU-native analog of the reference launcher stack
(``deepspeed/launcher/runner.py`` main :251, hostfile parse fetch_hostfile
:115, include/exclude filter parse_resource_filter :143;
``launcher/launch.py`` per-node spawner; ``launcher/multinode_runner.py``
PDSH/MPI runners; shell entrypoints ``bin/deepspeed``/``bin/ds``).

Key difference from the reference: on GPU, one *process per device* had to be
spawned and wired into NCCL via RANK/WORLD_SIZE env. On TPU, JAX is
multi-controller: exactly one process per *host*, each seeing its local
chips; ``jax.distributed.initialize()`` handles rendezvous. So the launcher's
job shrinks to (1) enumerating hosts, (2) running one copy of the user script
per host with coordinator env vars, (3) propagating ``.deepspeed_env``.

Single host:  dstpu train.py --deepspeed_config ds.json
Multi host:   dstpu --hostfile /job/hostfile train.py ...

Preemption supervision (``--supervise``; ISSUE 10): the training process
exits with the distinguished resumable code
(``runtime/elastic.py RESUMABLE_EXIT_CODE``, 85) after a graceful
preemption drain — the supervisor loop relaunches it with exponential
backoff, exporting ``DSTPU_RESTART_COUNT`` so the child's telemetry can
report how many lives it has used. Any OTHER nonzero exit is a genuine
failure the supervisor gives up on immediately, and ``--max_restarts``
bounds how many preemptions a run survives unattended.
"""

import argparse
import base64
import json
import os
import subprocess
import sys
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.runtime.elastic import (RESTART_COUNT_ENV,
                                           RESUMABLE_EXIT_CODE)
from deepspeed_tpu.utils.health import STALL_EXIT_CODE
from deepspeed_tpu.utils.logging import logger

#: exit codes the supervisor relaunches on: the graceful preemption
#: drain (85) and the hang watchdog's distinguished ``os._exit`` (87) —
#: a hung-then-killed job is exactly the preemption-shaped failure the
#: supervisor exists for (ISSUE 16 satellite). Anything else is a
#: genuine failure: give up immediately.
RESTARTABLE_EXIT_CODES = (RESUMABLE_EXIT_CODE, STALL_EXIT_CODE)


def restart_eligible(rc: Optional[int]) -> bool:
    """True when exit code ``rc`` should be answered with a relaunch
    (shared by :func:`supervise` and the serving fleet's replica
    supervision in ``inference/fleet.py``)."""
    return rc in RESTARTABLE_EXIT_CODES


DLTS_HOSTFILE = "/job/hostfile"
ENV_FILE = ".deepspeed_env"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "TPU_", "JAX_", "XLA_",
               "DSTPU_"]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher: run a training script across "
                    "TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of '<hostname> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Host filter, e.g. 'worker-0@worker-1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Host exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit number of hosts")
    parser.add_argument("--master_port", type=int, default=29500,
                        help="Coordinator port for jax.distributed")
    parser.add_argument("--master_addr", type=str, default="",
                        help="Coordinator address (default: first host)")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "local"],
                        help="Multi-node transport (reference supports "
                             "pdsh/openmpi/mvapich, multinode_runner.py)")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat as multi-node even for one host")
    parser.add_argument("--supervise", action="store_true",
                        help="Relaunch the job (with exponential backoff) "
                             "whenever it exits with the resumable "
                             f"preemption code {RESUMABLE_EXIT_CODE} or "
                             f"the hang-watchdog code {STALL_EXIT_CODE} "
                             "(checkpoint.drain_on_preemption / "
                             "observability.health.watchdog)")
    parser.add_argument("--max_restarts", type=int, default=3,
                        help="Supervisor: give up after this many "
                             "resumable restarts (default 3)")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="Supervisor: base backoff seconds before a "
                             "relaunch; doubles per restart (default 1.0)")
    parser.add_argument("user_script", type=str,
                        help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def supervise(run_once: Callable[[int], int], max_restarts: int = 3,
              backoff: float = 1.0, sleep: Callable[[float], None] = None
              ) -> int:
    """Relaunch-on-preemption loop (the launcher's elastic half).

    ``run_once(restart_count)`` launches the job and returns its exit
    code. The loop relaunches ONLY on :data:`RESTARTABLE_EXIT_CODES` —
    the graceful preemption drain (85: the run left a committed
    checkpoint and asked to be resumed) and the hang watchdog's
    distinguished kill (87: a wedged run ``os._exit``-ed itself; the
    committed checkpoint chain makes a relaunch exactly as safe as a
    preemption resume) — sleeping ``backoff * 2**restart`` seconds
    between lives; any other nonzero code is a genuine failure returned
    immediately, and after ``max_restarts`` restartable exits the code
    is returned for the operator to act on. Returns the final exit
    code.
    """
    sleep = time.sleep if sleep is None else sleep
    restarts = 0
    while True:
        rc = run_once(restarts)
        if not restart_eligible(rc):
            if rc != 0:
                logger.error(f"dstpu supervisor: job failed (exit {rc}); "
                             "not a preemption — giving up")
            return rc
        if restarts >= max_restarts:
            logger.error(
                f"dstpu supervisor: restartable exit but max_restarts="
                f"{max_restarts} exhausted; giving up with exit {rc}")
            return rc
        delay = backoff * (2 ** restarts)
        restarts += 1
        kind = "preemption drain" if rc == RESUMABLE_EXIT_CODE \
            else "watchdog kill"
        logger.warning(
            f"dstpu supervisor: {kind} (exit {rc}); relaunch "
            f"{restarts}/{max_restarts} in {delay:.1f}s")
        sleep(delay)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse '<hostname> slots=<n>' lines (reference runner.py:115)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile, will proceed with training "
                       f"with local resources only: {hostfile_path}")
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected slots=<n>, got {slots}")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"Hostfile is not formatted correctly, unable to "
                             f"proceed with training: '{line}'")
                raise ValueError(f"bad hostfile line: '{line}'")
            if hostname in resource_pool:
                logger.error(f"Hostfile contains duplicate hosts, unable to "
                             f"proceed with training: {hostname}")
                raise ValueError(f"duplicate host: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_filter_str(s: str) -> Dict[str, Optional[List[int]]]:
    """Parse 'host1@host2:0,2' style filters (reference runner.py:143).

    Returns host -> list of slot indices (None = all slots).
    """
    out: "OrderedDict[str, Optional[List[int]]]" = OrderedDict()
    if not s:
        return out
    for term in s.split("@"):
        term = term.strip()
        if ":" in term:
            host, slot_str = term.split(":")
            slots = [int(x) for x in slot_str.split(",")]
            out[host] = slots
        else:
            out[term] = None
    return out


def parse_resource_filter(host_info: Dict[str, int], include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Apply include/exclude filters to the host pool."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")

    full = OrderedDict(
        (host, list(range(slots))) for host, slots in host_info.items())

    if include_str:
        inc = _parse_filter_str(include_str)
        filtered = OrderedDict()
        for host, slots in inc.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            use = slots if slots is not None else full[host]
            for s in use:
                if s not in full[host]:
                    raise ValueError(f"include slot {host}:{s} does not exist")
            filtered[host] = use
        return filtered

    if exclude_str:
        exc = _parse_filter_str(exclude_str)
        for host, slots in exc.items():
            if host not in full:
                raise ValueError(f"exclude host {host} not in hostfile")
            if slots is not None:
                for s in slots:
                    if s not in full[host]:
                        raise ValueError(
                            f"exclude slot {host}:{s} does not exist")
        filtered = OrderedDict()
        for host, slots in full.items():
            if host in exc:
                if exc[host] is None:
                    continue  # exclude whole host
                keep = [s for s in slots if s not in exc[host]]
                if keep:
                    filtered[host] = keep
            else:
                filtered[host] = slots
        return filtered

    return full


def encode_world_info(resource_pool: Dict[str, List[int]]) -> str:
    """Base64-encode the host->slots map for env transport
    (reference runner.py:245)."""
    world_info = json.dumps(resource_pool)
    return base64.urlsafe_b64encode(world_info.encode("utf-8")).decode("utf-8")


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded).decode("utf-8"))


def collect_env_exports() -> Dict[str, str]:
    """Env vars to propagate to remote hosts, plus .deepspeed_env overrides
    (reference runner.py:345-351)."""
    exports = {}
    for var, val in os.environ.items():
        if any(var == v or (v.endswith("_") and var.startswith(v))
               for v in EXPORT_ENVS):
            exports[var] = val
    env_file = os.path.join(os.path.expanduser("~"), ENV_FILE)
    for candidate in [ENV_FILE, env_file]:
        if os.path.isfile(candidate):
            with open(candidate) as f:
                for line in f:
                    line = line.strip()
                    if "=" in line and not line.startswith("#"):
                        key, val = line.split("=", 1)
                        exports[key.strip()] = val.strip()
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool is None and args.force_multi:
        # single-host multi-controller: run the coordinator env path against
        # localhost so jax.distributed still initializes
        resource_pool = OrderedDict(localhost=1)

    if resource_pool is None or args.launcher == "local":
        # single host: exec in-place; jax.distributed is a no-op single
        # process and local chips are auto-discovered.
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        logger.info(f"dstpu local launch: {' '.join(cmd)}")

        def run_local(restarts: int) -> int:
            env = os.environ.copy()
            env[RESTART_COUNT_ENV] = str(restarts)
            proc = subprocess.Popen(cmd, env=env)
            proc.wait()
            return proc.returncode

        if args.supervise:
            rc = supervise(run_local, max_restarts=args.max_restarts,
                           backoff=args.restart_backoff)
        else:
            rc = run_local(0)
        # propagate first failing exit code (reference runner.py:356)
        if rc != 0:
            sys.exit(rc)
        return

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])

    hosts = list(active.keys())
    coordinator_addr = args.master_addr or hosts[0]
    coordinator = f"{coordinator_addr}:{args.master_port}"
    exports = collect_env_exports()
    exports["DSTPU_WORLD_INFO"] = encode_world_info(active)

    from deepspeed_tpu.launcher.multinode_runner import make_runner
    runner = make_runner(args.launcher, args, active)
    nonlocal_hosts = [h for h in hosts
                      if h not in ("localhost", "127.0.0.1")]
    # ssh/pdsh have a /bin/sh shortcut for local hosts; mpirun is needed
    # even for a localhost-only pool
    if (nonlocal_hosts or args.launcher == "openmpi") and \
            not runner.backend_exists():
        raise RuntimeError(
            f"launcher backend '{args.launcher}' not found on PATH "
            f"(hosts: {hosts})")

    def run_wave(restarts: int) -> int:
        """One multi-host launch wave; returns the first failing exit
        code — RESUMABLE_EXIT_CODE wins over other nonzero codes so one
        drained host plus N killed-mid-drain hosts still reads as a
        preemption to the supervisor."""
        exports[RESTART_COUNT_ENV] = str(restarts)
        procs = []
        if args.launcher == "openmpi":
            cmd = runner.get_cmd_all(hosts, coordinator, exports)
            logger.info(f"dstpu mpirun launch: {' '.join(cmd[:8])} ...")
            procs.append(subprocess.Popen(cmd))
        else:
            for pid, host in enumerate(hosts):
                cmd = runner.get_cmd(host, pid, len(hosts), coordinator,
                                     exports)
                logger.info(
                    f"dstpu launching on {host}: process {pid}/{len(hosts)}")
                procs.append(subprocess.Popen(cmd))
        exit_code = 0
        for p in procs:
            p.wait()
            if p.returncode == RESUMABLE_EXIT_CODE:
                exit_code = RESUMABLE_EXIT_CODE
            elif p.returncode != 0 and exit_code == 0:
                exit_code = p.returncode
        return exit_code

    if args.supervise:
        exit_code = supervise(run_wave, max_restarts=args.max_restarts,
                              backoff=args.restart_backoff)
    else:
        exit_code = run_wave(0)
    if exit_code != 0:
        sys.exit(exit_code)


if __name__ == "__main__":
    main()
