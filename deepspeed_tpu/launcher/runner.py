"""``dstpu`` — multi-host launcher.

TPU-native analog of the reference launcher stack
(``deepspeed/launcher/runner.py`` main :251, hostfile parse fetch_hostfile
:115, include/exclude filter parse_resource_filter :143;
``launcher/launch.py`` per-node spawner; ``launcher/multinode_runner.py``
PDSH/MPI runners; shell entrypoints ``bin/deepspeed``/``bin/ds``).

Key difference from the reference: on GPU, one *process per device* had to be
spawned and wired into NCCL via RANK/WORLD_SIZE env. On TPU, JAX is
multi-controller: exactly one process per *host*, each seeing its local
chips; ``jax.distributed.initialize()`` handles rendezvous. So the launcher's
job shrinks to (1) enumerating hosts, (2) running one copy of the user script
per host with coordinator env vars, (3) propagating ``.deepspeed_env``.

Single host:  dstpu train.py --deepspeed_config ds.json
Multi host:   dstpu --hostfile /job/hostfile train.py ...
"""

import argparse
import base64
import json
import os
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
ENV_FILE = ".deepspeed_env"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "TPU_", "JAX_", "XLA_",
               "DSTPU_"]


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeed-TPU launcher: run a training script across "
                    "TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of '<hostname> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Host filter, e.g. 'worker-0@worker-1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Host exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit number of hosts")
    parser.add_argument("--master_port", type=int, default=29500,
                        help="Coordinator port for jax.distributed")
    parser.add_argument("--master_addr", type=str, default="",
                        help="Coordinator address (default: first host)")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "local"],
                        help="Multi-node transport (reference supports "
                             "pdsh/openmpi/mvapich, multinode_runner.py)")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat as multi-node even for one host")
    parser.add_argument("user_script", type=str,
                        help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse '<hostname> slots=<n>' lines (reference runner.py:115)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile, will proceed with training "
                       f"with local resources only: {hostfile_path}")
        return None
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected slots=<n>, got {slots}")
                slot_count = int(slot_count)
            except ValueError:
                logger.error(f"Hostfile is not formatted correctly, unable to "
                             f"proceed with training: '{line}'")
                raise ValueError(f"bad hostfile line: '{line}'")
            if hostname in resource_pool:
                logger.error(f"Hostfile contains duplicate hosts, unable to "
                             f"proceed with training: {hostname}")
                raise ValueError(f"duplicate host: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_filter_str(s: str) -> Dict[str, Optional[List[int]]]:
    """Parse 'host1@host2:0,2' style filters (reference runner.py:143).

    Returns host -> list of slot indices (None = all slots).
    """
    out: "OrderedDict[str, Optional[List[int]]]" = OrderedDict()
    if not s:
        return out
    for term in s.split("@"):
        term = term.strip()
        if ":" in term:
            host, slot_str = term.split(":")
            slots = [int(x) for x in slot_str.split(",")]
            out[host] = slots
        else:
            out[term] = None
    return out


def parse_resource_filter(host_info: Dict[str, int], include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Apply include/exclude filters to the host pool."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")

    full = OrderedDict(
        (host, list(range(slots))) for host, slots in host_info.items())

    if include_str:
        inc = _parse_filter_str(include_str)
        filtered = OrderedDict()
        for host, slots in inc.items():
            if host not in full:
                raise ValueError(f"include host {host} not in hostfile")
            use = slots if slots is not None else full[host]
            for s in use:
                if s not in full[host]:
                    raise ValueError(f"include slot {host}:{s} does not exist")
            filtered[host] = use
        return filtered

    if exclude_str:
        exc = _parse_filter_str(exclude_str)
        for host, slots in exc.items():
            if host not in full:
                raise ValueError(f"exclude host {host} not in hostfile")
            if slots is not None:
                for s in slots:
                    if s not in full[host]:
                        raise ValueError(
                            f"exclude slot {host}:{s} does not exist")
        filtered = OrderedDict()
        for host, slots in full.items():
            if host in exc:
                if exc[host] is None:
                    continue  # exclude whole host
                keep = [s for s in slots if s not in exc[host]]
                if keep:
                    filtered[host] = keep
            else:
                filtered[host] = slots
        return filtered

    return full


def encode_world_info(resource_pool: Dict[str, List[int]]) -> str:
    """Base64-encode the host->slots map for env transport
    (reference runner.py:245)."""
    world_info = json.dumps(resource_pool)
    return base64.urlsafe_b64encode(world_info.encode("utf-8")).decode("utf-8")


def decode_world_info(encoded: str) -> Dict[str, List[int]]:
    return json.loads(base64.urlsafe_b64decode(encoded).decode("utf-8"))


def collect_env_exports() -> Dict[str, str]:
    """Env vars to propagate to remote hosts, plus .deepspeed_env overrides
    (reference runner.py:345-351)."""
    exports = {}
    for var, val in os.environ.items():
        if any(var == v or (v.endswith("_") and var.startswith(v))
               for v in EXPORT_ENVS):
            exports[var] = val
    env_file = os.path.join(os.path.expanduser("~"), ENV_FILE)
    for candidate in [ENV_FILE, env_file]:
        if os.path.isfile(candidate):
            with open(candidate) as f:
                for line in f:
                    line = line.strip()
                    if "=" in line and not line.startswith("#"):
                        key, val = line.split("=", 1)
                        exports[key.strip()] = val.strip()
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool is None and args.force_multi:
        # single-host multi-controller: run the coordinator env path against
        # localhost so jax.distributed still initializes
        resource_pool = OrderedDict(localhost=1)

    if resource_pool is None or args.launcher == "local":
        # single host: exec in-place; jax.distributed is a no-op single
        # process and local chips are auto-discovered.
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        logger.info(f"dstpu local launch: {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        # propagate first failing exit code (reference runner.py:356)
        if result.returncode != 0:
            sys.exit(result.returncode)
        return

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = OrderedDict(list(active.items())[:args.num_nodes])

    hosts = list(active.keys())
    coordinator_addr = args.master_addr or hosts[0]
    coordinator = f"{coordinator_addr}:{args.master_port}"
    exports = collect_env_exports()
    exports["DSTPU_WORLD_INFO"] = encode_world_info(active)

    from deepspeed_tpu.launcher.multinode_runner import make_runner
    runner = make_runner(args.launcher, args, active)
    nonlocal_hosts = [h for h in hosts
                      if h not in ("localhost", "127.0.0.1")]
    # ssh/pdsh have a /bin/sh shortcut for local hosts; mpirun is needed
    # even for a localhost-only pool
    if (nonlocal_hosts or args.launcher == "openmpi") and \
            not runner.backend_exists():
        raise RuntimeError(
            f"launcher backend '{args.launcher}' not found on PATH "
            f"(hosts: {hosts})")

    procs = []
    if args.launcher == "openmpi":
        cmd = runner.get_cmd_all(hosts, coordinator, exports)
        logger.info(f"dstpu mpirun launch: {' '.join(cmd[:8])} ...")
        procs.append(subprocess.Popen(cmd))
    else:
        for pid, host in enumerate(hosts):
            cmd = runner.get_cmd(host, pid, len(hosts), coordinator, exports)
            logger.info(
                f"dstpu launching on {host}: process {pid}/{len(hosts)}")
            procs.append(subprocess.Popen(cmd))
    exit_code = 0
    for p in procs:
        p.wait()
        if p.returncode != 0 and exit_code == 0:
            exit_code = p.returncode
    if exit_code != 0:
        sys.exit(exit_code)


if __name__ == "__main__":
    main()
