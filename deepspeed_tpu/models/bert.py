"""BERT model family — the bing_bert workload.

Recreates the reference's BERT pretraining workload (BASELINE.md: BERT-large
+ fused transformer kernel; tests/unit/modeling.py + modelingpreln.py were
its post-LN/pre-LN reference implementations) on the DeepSpeedTransformerLayer
stack: embeddings (token+position+type) → N layers → MLM head.
"""

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, init_transformer_params,
    transformer_layer_forward)


class BertConfig(NamedTuple):
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attn_dropout: float = 0.1
    initializer_range: float = 0.02
    pre_layer_norm: bool = True     # modelingpreln.py variant (default for
    #                                 the reference's fused kernel training)
    # stacked layers + lax.scan encoder: the layer compiles once instead
    # of num_layers times (see GPT2Config.scan_layers)
    scan_layers: bool = False


BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                        intermediate_size=4096)


def layer_config(config: BertConfig, training: bool = True,
                 dtype=jnp.bfloat16) -> DeepSpeedTransformerConfig:
    return DeepSpeedTransformerConfig(
        bf16=(dtype == jnp.bfloat16),
        fp16=(dtype == jnp.float16),
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        heads=config.num_heads,
        attn_dropout_ratio=config.attn_dropout,
        hidden_dropout_ratio=config.hidden_dropout,
        num_hidden_layers=config.num_layers,
        initializer_range=config.initializer_range,
        pre_layer_norm=config.pre_layer_norm,
        training=training)


def init_bert_params(config: BertConfig, key) -> Dict[str, Any]:
    h = config.hidden_size
    rng = config.initializer_range
    lcfg = layer_config(config)
    keys = jax.random.split(key, 4 + config.num_layers)
    params: Dict[str, Any] = {
        "tok_emb": jax.random.normal(keys[0], (config.vocab_size, h),
                                     jnp.float32) * rng,
        "pos_emb": jax.random.normal(keys[1],
                                     (config.max_position_embeddings, h),
                                     jnp.float32) * rng,
        "type_emb": jax.random.normal(keys[2], (config.type_vocab_size, h),
                                      jnp.float32) * rng,
        "emb_ln": {"w": jnp.ones((h,), jnp.float32),
                   "b": jnp.zeros((h,), jnp.float32)},
        "mlm_dense": {"w": jax.random.normal(keys[3], (h, h),
                                             jnp.float32) * rng,
                      "b": jnp.zeros((h,), jnp.float32)},
        "mlm_ln": {"w": jnp.ones((h,), jnp.float32),
                   "b": jnp.zeros((h,), jnp.float32)},
        "mlm_bias": jnp.zeros((config.vocab_size,), jnp.float32),
    }
    layers = [init_transformer_params(lcfg, keys[4 + i], i)
              for i in range(config.num_layers)]
    if config.scan_layers:
        params["layers"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *layers)
    else:
        for i, lp in enumerate(layers):
            params[f"layer_{i}"] = lp
    return params


def bert_param_specs(config: BertConfig):
    """Megatron-style tensor-parallel PartitionSpecs over the 'model'
    axis for the BERT family (column-parallel qkv/inter, row-parallel
    out/output; embeddings vocab-sharded) — pass as
    ``deepspeed_tpu.initialize(param_specs=...)``. Mirrors
    models/gpt2.gpt2_param_specs; the reference delegated this to the
    client's Megatron mpu (SURVEY §2.3 TP row)."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "qkvw": P(None, "model"), "qkvb": P("model"),
        "ow": P("model", None), "ob": P(),
        "attn_nw": P(), "attn_nb": P(),
        "inter_w": P(None, "model"), "inter_b": P("model"),
        "output_w": P("model", None), "output_b": P(),
        "norm_w": P(), "norm_b": P(),
    }
    specs = {
        "tok_emb": P("model", None),
        "pos_emb": P(), "type_emb": P(),
        "emb_ln": {"w": P(), "b": P()},
        "mlm_dense": {"w": P(), "b": P()},
        "mlm_ln": {"w": P(), "b": P()},
        "mlm_bias": P("model"),
    }
    if config.scan_layers:
        specs["layers"] = jax.tree_util.tree_map(
            lambda p: P(None, *p), layer,
            is_leaf=lambda x: isinstance(x, P))
    else:
        for i in range(config.num_layers):
            specs[f"layer_{i}"] = layer
    return specs


from deepspeed_tpu.ops.functional import (
    layer_norm as _ln_wb, matmul_bf16_accum_fp32)


def _ln(x, p, eps=1e-12):
    return _ln_wb(x, p["w"], p["b"], eps)


def bert_encoder(params, config: BertConfig, input_ids, attention_mask=None,
                 token_type_ids=None, rng=None, deterministic: bool = True,
                 dtype=jnp.bfloat16, remat: bool = False,
                 sparsity_config=None):
    """Sequence output (B, S, H). attention_mask: (B, S) with 1=keep.

    ``sparsity_config``: a SparsityConfig — the layers' core attention is
    swapped for block-sparse attention (what the reference's
    SparseAttentionUtils module surgery achieves,
    sparse_attention_utils.py:85); QKV/output projections and all other
    params are reused unchanged. seq_len must be a multiple of the sparsity
    block (use SparseAttentionUtils.pad_to_block_size).
    """
    B, S = input_ids.shape
    lcfg = layer_config(config, training=not deterministic, dtype=dtype)
    pos = jnp.arange(S)[None, :]
    tt = token_type_ids if token_type_ids is not None else \
        jnp.zeros_like(input_ids)
    x = (params["tok_emb"][input_ids] + params["pos_emb"][pos] +
         params["type_emb"][tt])
    x = _ln(x, params["emb_ln"]).astype(dtype)

    add_mask = None
    if attention_mask is not None:
        add_mask = ((1.0 - attention_mask[:, None, None, :].astype(
            jnp.float32)) * -1e9)

    attention_fn = None
    if sparsity_config is not None:
        from deepspeed_tpu.ops.sparse_attention import SparseSelfAttention
        # 'mul' mode: our (B, S) mask is 1=keep/0=pad — _to_additive turns
        # zeros into -inf (the default 'add' mode would add the raw 1/0
        # values as biases and leave padding unmasked)
        sparse_attn = SparseSelfAttention(sparsity_config,
                                          key_padding_mask_mode="mul")
        kpm = attention_mask  # (B, S), 1=keep

        def attention_fn(q, k, v, _add_mask):
            return sparse_attn(q, k, v, key_padding_mask=kpm)

    fwd = transformer_layer_forward
    if remat:
        # use_flash (6) and attention_fn (7) are static: plain callables,
        # not pytrees
        fwd = jax.checkpoint(transformer_layer_forward,
                             static_argnums=(1, 5, 6, 7))
    if config.scan_layers:
        if rng is not None:
            layer_rngs = jax.random.split(rng, config.num_layers)

            def body(x, inp):
                lp, r = inp
                return fwd(lp, lcfg, x, add_mask, r, deterministic,
                           True, attention_fn), None
            x, _ = jax.lax.scan(body, x, (params["layers"], layer_rngs))
        else:
            def body(x, lp):
                return fwd(lp, lcfg, x, add_mask, None, deterministic,
                           True, attention_fn), None
            x, _ = jax.lax.scan(body, x, params["layers"])
        return x
    for i in range(config.num_layers):
        if rng is not None:
            rng, r = jax.random.split(rng)
        else:
            r = None
        x = fwd(params[f"layer_{i}"], lcfg, x, add_mask, r, deterministic,
                True, attention_fn)
    return x


def bert_mlm_sp_loss_fn(config: BertConfig, mesh, dtype=jnp.bfloat16,
                        deterministic: bool = False):
    """Sequence-parallel BERT MLM over the ``seq`` mesh axis: every
    activation lives (B, S/P, H) on its shard; bidirectional ring
    attention (ops/attention/ring.py — no causal waste) crosses shards
    with the padding mask riding alongside its K/V chunk; the MLM head
    and masked-token loss are token-local with fp32 psums for the global
    sum/count. Engine contract: batch = {'input_ids', 'labels',
    'attention_mask'?} each (B, S), S divisible by the seq-axis size.
    """
    from deepspeed_tpu.ops.attention.ring import ring_attention
    from deepspeed_tpu.parallel.mesh import axis_size
    from jax.sharding import PartitionSpec as PS
    if "seq" not in mesh.axis_names:
        raise ValueError("bert_mlm_sp_loss_fn requires a 'seq' mesh axis")
    assert not config.scan_layers, \
        "bert_mlm_sp_loss_fn uses the layer_{i} layout (scan_layers=False)"
    Pn = axis_size(mesh, "seq")
    manual = frozenset(a for a in ("seq", "data") if a in mesh.axis_names)
    lcfg = layer_config(config, training=not deterministic, dtype=dtype)

    def per_device(params, batch, rng):
        idx = jax.lax.axis_index("seq")
        ids_full = batch["input_ids"]              # (B_l, S) replicated/seq
        B, S = ids_full.shape
        assert S % Pn == 0, (S, Pn)
        sl = S // Pn
        sl_ids = jax.lax.dynamic_slice_in_dim(ids_full, idx * sl, sl, 1)
        labels = jax.lax.dynamic_slice_in_dim(batch["labels"], idx * sl,
                                              sl, 1)
        am_full = batch.get("attention_mask")
        if am_full is not None:
            am_l = jax.lax.dynamic_slice_in_dim(am_full, idx * sl, sl, 1)
            kpm = ((1.0 - am_l[:, None, None, :].astype(jnp.float32))
                   * -1e9)                          # additive (B,1,1,sl)
        else:
            kpm = None
        pos = idx * sl + jnp.arange(sl)
        x = (params["tok_emb"][sl_ids] +
             jax.lax.dynamic_slice_in_dim(params["pos_emb"], idx * sl, sl,
                                          0)[None] +
             params["type_emb"][jnp.zeros_like(sl_ids)])
        x = _ln(x, params["emb_ln"]).astype(dtype)
        del pos

        def attention_fn(q, k, v, _add_mask):
            return ring_attention(q, k, v, axis_name="seq", causal=False,
                                  key_padding_mask=kpm)

        for i in range(config.num_layers):
            if rng is not None and not deterministic:
                rng, r = jax.random.split(rng)
                r = jax.random.fold_in(r, idx)
            else:
                r = None
            x = transformer_layer_forward(params[f"layer_{i}"], lcfg, x,
                                          None, r, deterministic, True,
                                          attention_fn)
        mh = x @ params["mlm_dense"]["w"].astype(dtype) + \
            params["mlm_dense"]["b"].astype(dtype)
        mh = jax.nn.gelu(mh, approximate=False)
        mh = _ln(mh, params["mlm_ln"])
        logits = matmul_bf16_accum_fp32(mh, params["tok_emb"]) + \
            params["mlm_bias"]
        mask = (labels != -100)
        safe = jnp.where(mask, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        # fp32 psums only (bf16 psum trips the XLA partitioner with auto
        # axes in the mesh — runtime/pipe/spmd._psum_act). Sum AND count
        # reduce over every manual axis before the division: dividing
        # per-data-shard and averaging would weight shards with fewer
        # masked tokens more (mean-of-means != global masked mean).
        axes = tuple(sorted(manual))
        total = jax.lax.psum(
            jnp.sum(jnp.where(mask, ll, 0.0)).astype(jnp.float32), axes)
        count = jax.lax.psum(jnp.sum(mask).astype(jnp.float32), axes)
        return -total / jnp.maximum(count, 1.0)

    def loss_fn(params, batch, rng):
        param_specs = jax.tree_util.tree_map(lambda _: PS(), params)
        batch_specs = jax.tree_util.tree_map(
            lambda _: PS("data") if "data" in manual else PS(), batch)
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(param_specs, batch_specs, PS()),
            out_specs=PS(), axis_names=manual,
            check_vma=False)(params, batch, rng)

    loss_fn.owns_cast = True   # per-use casts; grad psums stay fp32
    return loss_fn


def bert_mlm_loss_fn(config: BertConfig, dtype=jnp.bfloat16,
                     remat: bool = False, deterministic: bool = False,
                     sparsity_config=None):
    """Engine-contract MLM loss. batch: input_ids (B,S), labels (B,S) with
    -100 = unmasked (ignored), attention_mask (B,S) optional.
    sparsity_config: optional SparsityConfig — block-sparse attention in
    every layer (see bert_encoder; build one from the JSON
    ``sparse_attention`` section with ``sparsity_config_from_dict``)."""
    def loss_fn(params, batch, rng):
        x = bert_encoder(params, config, batch["input_ids"],
                         attention_mask=batch.get("attention_mask"),
                         token_type_ids=batch.get("token_type_ids"),
                         rng=rng, deterministic=deterministic, dtype=dtype,
                         remat=remat, sparsity_config=sparsity_config)
        # MLM head: dense+gelu+LN then decode against tied embeddings
        mh = x @ params["mlm_dense"]["w"].astype(dtype) + \
            params["mlm_dense"]["b"].astype(dtype)
        mh = jax.nn.gelu(mh, approximate=False)
        mh = _ln(mh, params["mlm_ln"])
        # bf16 operands / fp32 accumulation for the vocab GEMM (MXU fast
        # path, same pattern as gpt2_forward)
        logits = matmul_bf16_accum_fp32(mh, params["tok_emb"]) + \
            params["mlm_bias"]
        labels = batch["labels"]
        mask = (labels != -100)
        safe_labels = jnp.where(mask, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(mask), 1)
        return -jnp.sum(jnp.where(mask, ll, 0.0)) / denom
    return loss_fn
