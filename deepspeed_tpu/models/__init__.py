from deepspeed_tpu.models.gpt2 import (
    GPT2Config, GPT2_SMALL, GPT2_MEDIUM, GPT2_LARGE, GPT2_XL,
    causal_cache_mask, gpt2_forward, gpt2_loss_fn, gpt2_param_specs,
    gpt2_pipeline_spec, gpt2_sp_loss_fn, init_gpt2_params, count_params,
    write_kv_cache)
from deepspeed_tpu.models.bert import (
    BertConfig, BERT_BASE, BERT_LARGE, bert_encoder, bert_mlm_loss_fn,
    bert_mlm_sp_loss_fn, bert_param_specs, init_bert_params)
from deepspeed_tpu.models.llama import (
    LlamaConfig, init_llama_params, llama_forward, llama_generate,
    llama_loss_fn, llama_param_specs)
