"""Llama-style decoder family: RoPE + RMSNorm + SwiGLU + GQA.

A beyond-reference model family (the reference snapshot predates this
architecture) demonstrating the framework on the modern decoder recipe:
rotary position embeddings (no learned positions), RMSNorm pre-norm,
SwiGLU MLP, and grouped-query attention served NATIVELY by the Pallas
flash kernels (ops/attention/flash.py — kv_heads < heads share K/V rows
via block index maps / DMA row select; K/V never expand to the full head
count). First-class Megatron-style tensor-parallel PartitionSpecs and
the stacked ``scan_layers`` layout ship like the GPT-2/BERT families'.
"""

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash import NEG_INF, flash_attention
from deepspeed_tpu.ops.functional import rms_norm


class LlamaConfig(NamedTuple):
    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 8
    num_heads: int = 8
    num_kv_heads: int = 0          # 0 => num_heads (MHA); 1 = MQA
    intermediate_size: int = 0     # 0 => the llama 8/3 * hidden, 128-aligned
    max_position_embeddings: int = 2048
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    scan_layers: bool = False

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def inter(self):
        if self.intermediate_size:
            return self.intermediate_size
        raw = int(self.hidden_size * 8 / 3)
        return (raw + 127) // 128 * 128


def init_llama_params(config: LlamaConfig, key) -> Dict[str, Any]:
    h, hd = config.hidden_size, config.head_dim
    hkv, inter = config.kv_heads, config.inter
    rng = config.initializer_range
    out_rng = rng / np.sqrt(2.0 * config.num_layers)
    keys = jax.random.split(key, 2 + 7 * config.num_layers)
    params: Dict[str, Any] = {
        "tok_emb": jax.random.normal(keys[0], (config.vocab_size, h),
                                     jnp.float32) * rng,
        "ln_f": {"w": jnp.ones((h,), jnp.float32)},
        # untied output head, stored (V, H) like a tied embedding so the
        # chunked fused head (gpt2._tied_xent_chunked) applies unchanged
        "lm_head": jax.random.normal(keys[1], (config.vocab_size, h),
                                     jnp.float32) * rng,
    }
    layers = []
    for i in range(config.num_layers):
        k = keys[2 + 7 * i: 9 + 7 * i]
        layers.append({
            "ln_1": {"w": jnp.ones((h,), jnp.float32)},
            "attn": {
                "wq": jax.random.normal(k[0], (h, h), jnp.float32) * rng,
                "wk": jax.random.normal(k[1], (h, hkv * hd),
                                       jnp.float32) * rng,
                "wv": jax.random.normal(k[2], (h, hkv * hd),
                                       jnp.float32) * rng,
                "wo": jax.random.normal(k[3], (h, h), jnp.float32) * out_rng,
            },
            "ln_2": {"w": jnp.ones((h,), jnp.float32)},
            "mlp": {
                "w_gate": jax.random.normal(k[4], (h, inter),
                                            jnp.float32) * rng,
                "w_up": jax.random.normal(k[5], (h, inter),
                                          jnp.float32) * rng,
                "w_down": jax.random.normal(k[6], (inter, h),
                                            jnp.float32) * out_rng,
            },
        })
    if config.scan_layers:
        params["h"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *layers)
    else:
        for i, lp in enumerate(layers):
            params[f"h_{i}"] = lp
    return params


def llama_param_specs(config: LlamaConfig) -> Dict[str, Any]:
    """Megatron column/row TP over the ``model`` axis: wq/wk/wv/gate/up
    column-parallel (output dim = heads — shard cleanly when num_heads
    and kv_heads divide the axis), wo/down row-parallel; embeddings and
    head vocab-sharded."""
    layer = {
        "ln_1": {"w": P()},
        "attn": {"wq": P(None, "model"), "wk": P(None, "model"),
                 "wv": P(None, "model"), "wo": P("model", None)},
        "ln_2": {"w": P()},
        "mlp": {"w_gate": P(None, "model"), "w_up": P(None, "model"),
                "w_down": P("model", None)},
    }
    specs: Dict[str, Any] = {
        "tok_emb": P("model", None),
        "ln_f": {"w": P()},
        "lm_head": P("model", None),
    }
    if config.scan_layers:
        specs["h"] = jax.tree_util.tree_map(
            lambda p: P(None, *p), layer,
            is_leaf=lambda x: isinstance(x, P))
    else:
        for i in range(config.num_layers):
            specs[f"h_{i}"] = layer
    return specs


from deepspeed_tpu.models.gpt2 import count_params  # noqa: E402 (reuse)


def rope_cos_sin(seq_len: int, head_dim: int, theta: float,
                 dtype=jnp.float32):
    """(S, hd/2) cos/sin tables for rotary embedding."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2,
                                     dtype=np.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, jnp.asarray(inv))           # (S, hd/2)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin):
    """Rotate (B, H, S, hd) by per-position angles.

    ``cos``/``sin`` are either the shared (S, hd/2) tables (training —
    every row sees positions 0..S-1) or per-row (B, S, hd/2) gathers
    (KV-cache serving — continuous-batching slots sit at different
    absolute positions). Pair layout is (x[..., :hd/2], x[..., hd/2:])
    — the "rotate_half" convention; consistent across q and k so
    relative phases match.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if cos.ndim == 3:            # (B, S, hd/2): broadcast over heads only
        c = cos[:, None].astype(x.dtype)
        s = sin[:, None].astype(x.dtype)
    else:                        # (S, hd/2): broadcast over batch + heads
        c = cos[None, None].astype(x.dtype)
        s = sin[None, None].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


def llama_block(block_params, config: LlamaConfig, x, cos, sin, dtype,
                attention_fn=None):
    """``attention_fn(q, k, v) -> ctx`` optionally replaces causal GQA
    flash attention (q post-RoPE (B, H, S, hd); k/v (B, kv_heads, S,
    hd), k post-RoPE) — the KV-cache decode hook."""
    B, S, h = x.shape
    H, hkv, hd = config.num_heads, config.kv_heads, config.head_dim

    from deepspeed_tpu.models.gpt2 import _wd
    a_in = rms_norm(x, block_params["ln_1"]["w"], config.rms_norm_eps)
    ap = block_params["attn"]
    q = (a_in @ _wd(ap["wq"], dtype)).reshape(B, S, H, hd)
    k = (a_in @ _wd(ap["wk"], dtype)).reshape(B, S, hkv, hd)
    v = (a_in @ _wd(ap["wv"], dtype)).reshape(B, S, hkv, hd)
    q = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)
    k = apply_rope(k.transpose(0, 2, 1, 3), cos, sin)
    v = v.transpose(0, 2, 1, 3)
    if attention_fn is not None:
        ctx = attention_fn(q, k, v)
    else:
        ctx = flash_attention(q, k, v, causal=True)  # native GQA
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, h)
    x = x + ctx @ _wd(ap["wo"], dtype)

    m_in = rms_norm(x, block_params["ln_2"]["w"], config.rms_norm_eps)
    mp = block_params["mlp"]
    gate = jax.nn.silu(m_in @ _wd(mp["w_gate"], dtype))
    up = m_in @ _wd(mp["w_up"], dtype)
    return x + (gate * up) @ _wd(mp["w_down"], dtype)


def _llama_trunk(params, config: LlamaConfig, input_ids,
                 dtype=jnp.bfloat16, remat: bool = False):
    B, S = input_ids.shape
    assert S <= config.max_position_embeddings, (
        "sequence length exceeds max_position_embeddings — RoPE would "
        "silently extrapolate", S, config.max_position_embeddings)
    from deepspeed_tpu.models.gpt2 import _emb_rows
    x = _emb_rows(params["tok_emb"], input_ids, dtype)
    cos, sin = rope_cos_sin(S, config.head_dim, config.rope_theta)

    block = llama_block
    if remat:
        block = jax.checkpoint(llama_block, static_argnums=(1, 5, 6))

    if config.scan_layers:
        def body(x, lp):
            return block(lp, config, x, cos, sin, dtype, None), None
        x, _ = jax.lax.scan(body, x, params["h"])
    else:
        for i in range(config.num_layers):
            x = block(params[f"h_{i}"], config, x, cos, sin, dtype, None)
    return rms_norm(x, params["ln_f"]["w"], config.rms_norm_eps)


def _gqa_offset_cache_attention(kcache, vcache, cache_position, out_box):
    """attention_fn for the cached llama forward (prefill-into-cache and
    decode alike): write this call's post-RoPE K/V into the hkv-head
    cache at each row's own offset, attend group-wise over all cache
    slots <= each query's absolute position (the shared
    ``causal_cache_mask``). The cache stays kv_heads-sized — GQA's
    serving payoff. Updated caches return through ``out_box``."""
    from deepspeed_tpu.models.gpt2 import causal_cache_mask, write_kv_cache

    def attn(q, k, v):
        kc = write_kv_cache(kcache, k, cache_position)
        vc = write_kv_cache(vcache, v, cache_position)
        out_box.append((kc, vc))
        B, H, S, hd = q.shape
        hkv = kc.shape[1]
        qg = q.reshape(B, hkv, H // hkv, S, hd)
        scores = jnp.einsum("bkgsd,bkld->bkgsl", qg.astype(jnp.float32),
                            kc.astype(jnp.float32)) / np.sqrt(hd)
        mask = causal_cache_mask(cache_position, S, kc.shape[2])
        scores = jnp.where(mask[:, :, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgsl,bkld->bkgsd", probs,
                         vc.astype(jnp.float32))
        return ctx.reshape(B, H, S, hd).astype(q.dtype)
    return attn


def _gqa_paged_cache_attention(kpool, vpool, block_table, cache_position,
                               out_box, attn_kernel: str = "gather",
                               kscale_pool=None, vscale_pool=None):
    """Paged attention_fn for the cached llama forward: scatter this
    call's post-RoPE K/V into the kv_heads-sized page pool via the block
    table (``gpt2.write_paged_kv_cache``), then attend. Single-query
    calls with ``attn_kernel="pallas"`` run the fused paged-decode
    kernel, which serves GQA natively — the q_heads/kv_heads query rows
    of each group share their kv head's page stream inside the kernel,
    so no head replication ever materializes. Otherwise gather each
    row's logical stripe back and attend group-wise under the shared
    ``causal_cache_mask`` (the oracle/fallback). Updated pools return
    through ``out_box``. ``kscale_pool``/``vscale_pool`` select the int8
    pool (see ``gpt2._paged_cache_attention``): writes quantize per
    token row, reads dequantize, ``out_box`` carries the 4-tuple."""
    from deepspeed_tpu.models.gpt2 import (causal_cache_mask,
                                           gather_paged_kv,
                                           paged_decode_ctx,
                                           write_paged_kv_cache)
    quantized = kscale_pool is not None

    def attn(q, k, v):
        if quantized:
            from deepspeed_tpu.ops.attention.paged import (dequantize_pool,
                                                           quantize_kv)
            nb = kscale_pool.shape[-1]
            k_q, k_s = quantize_kv(k, nb)
            v_q, v_s = quantize_kv(v, nb)
            kp = write_paged_kv_cache(kpool, k_q, block_table,
                                      cache_position)
            vp = write_paged_kv_cache(vpool, v_q, block_table,
                                      cache_position)
            ksp = write_paged_kv_cache(kscale_pool, k_s, block_table,
                                       cache_position)
            vsp = write_paged_kv_cache(vscale_pool, v_s, block_table,
                                       cache_position)
            out_box.append((kp, vp, ksp, vsp))
        else:
            kp = write_paged_kv_cache(kpool, k, block_table,
                                      cache_position)
            vp = write_paged_kv_cache(vpool, v, block_table,
                                      cache_position)
            ksp = vsp = None
            out_box.append((kp, vp))
        if attn_kernel == "pallas" and q.shape[2] == 1:
            return paged_decode_ctx(q, kp, vp, block_table,
                                    cache_position, k_scales=ksp,
                                    v_scales=vsp)
        kc = gather_paged_kv(kp, block_table)
        vc = gather_paged_kv(vp, block_table)
        if quantized:
            kc = dequantize_pool(kc, gather_paged_kv(ksp, block_table))
            vc = dequantize_pool(vc, gather_paged_kv(vsp, block_table))
        if q.shape[2] > 1:
            # context-parallel chunked prefill (ISSUE 19): ring over
            # the serving mesh; GQA folds group-wise inside the ring
            # exactly like the dense fallback below
            from deepspeed_tpu.parallel.pallas_shard import \
                current_cp_mesh
            cp = current_cp_mesh()
            if cp is not None:
                from deepspeed_tpu.ops.attention.ring import \
                    ring_prefill_attention
                return ring_prefill_attention(q, kc, vc, cache_position,
                                              cp.mesh, cp.axis)
        B, H, S, hd = q.shape
        hkv = kc.shape[1]
        qg = q.reshape(B, hkv, H // hkv, S, hd)
        scores = jnp.einsum("bkgsd,bkld->bkgsl", qg.astype(jnp.float32),
                            kc.astype(jnp.float32)) / np.sqrt(hd)
        mask = causal_cache_mask(cache_position, S, kc.shape[2])
        scores = jnp.where(mask[:, :, None], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bkgsl,bkld->bkgsd", probs,
                         vc.astype(jnp.float32))
        return ctx.reshape(B, H, S, hd).astype(q.dtype)
    return attn


def _llama_trunk_cached(params, config: LlamaConfig, input_ids, kv_cache,
                        cache_position, dtype, block_tables=None,
                        paged_attn_kernel: str = "gather"):
    """Cache-carrying trunk (see gpt2._gpt2_trunk_cached): one code path
    for prefill-into-cache and decode, through the SAME llama_block as
    training. RoPE angles are gathered per row at each token's absolute
    position. Returns (hidden states after ln_f, updated kv_cache).
    ``block_tables`` switches to the paged pool pair (each
    (layers, num_pages, kv_heads, page_size, hd)); an int8-quantized
    pool arrives as the 4-tuple ``(kc, vc, kscale, vscale)``;
    ``paged_attn_kernel`` picks the fused Pallas decode kernel or the
    gather oracle for seq-1 queries."""
    from deepspeed_tpu.models.gpt2 import _emb_rows, layer_params
    kc, vc = kv_cache[0], kv_cache[1]
    kscale, vscale = (kv_cache[2], kv_cache[3]) if len(kv_cache) == 4 \
        else (None, None)
    B, S = input_ids.shape
    if block_tables is not None:
        max_len = block_tables.shape[1] * kc.shape[3]  # pages x page_size
    else:
        max_len = kc.shape[3]
    pos = cache_position[:, None] + jnp.arange(S)[None, :]
    cos_full, sin_full = rope_cos_sin(max_len, config.head_dim,
                                      config.rope_theta)
    cos_b, sin_b = cos_full[pos], sin_full[pos]        # (B, S, hd/2)
    x = _emb_rows(params["tok_emb"], input_ids, dtype)
    new_caches = []
    for i in range(config.num_layers):
        box = []
        if block_tables is not None:
            attn = _gqa_paged_cache_attention(
                kc[i], vc[i], block_tables, cache_position, box,
                attn_kernel=paged_attn_kernel,
                kscale_pool=None if kscale is None else kscale[i],
                vscale_pool=None if vscale is None else vscale[i])
        else:
            attn = _gqa_offset_cache_attention(kc[i], vc[i],
                                               cache_position, box)
        x = llama_block(layer_params(params, config, i), config, x,
                        cos_b, sin_b, dtype, attention_fn=attn)
        new_caches.append(box[0])
    x = rms_norm(x, params["ln_f"]["w"], config.rms_norm_eps)
    return x, tuple(jnp.stack(leaf) for leaf in zip(*new_caches))


def llama_forward(params, config: LlamaConfig, input_ids,
                  dtype=jnp.bfloat16, remat: bool = False,
                  kv_cache=None, cache_position=None, block_tables=None,
                  paged_attn_kernel: str = "gather"):
    """Logits (B, S, vocab).

    KV-cache mode (serving): with ``kv_cache=(kc, vc)`` (each
    ``(layers, B, kv_heads, max_len, hd)``) and ``cache_position``
    ((B,) int32), writes this call's K/V at each row's offset and
    returns ``(logits, updated_cache)`` — same contract as
    :func:`deepspeed_tpu.models.gpt2.gpt2_forward`, including the
    paged-pool interpretation under ``block_tables`` and the
    ``paged_attn_kernel`` fused-decode switch. Training call signature
    unchanged."""
    from deepspeed_tpu.models.gpt2 import _tied_logits
    if kv_cache is not None:
        if cache_position is None:
            cache_position = jnp.zeros((input_ids.shape[0],), jnp.int32)
        x, cache = _llama_trunk_cached(params, config, input_ids,
                                       kv_cache, cache_position, dtype,
                                       block_tables=block_tables,
                                       paged_attn_kernel=paged_attn_kernel)
        return _tied_logits(x, params["lm_head"], dtype), cache
    x = _llama_trunk(params, config, input_ids, dtype=dtype, remat=remat)
    return _tied_logits(x, params["lm_head"], dtype)


def _gqa_cached_attention(kcache, vcache, pos, out_box):
    """Single-position decode hook (llama_generate's scan): every row
    writes/attends at the same scalar ``pos`` — the offset-cache GQA
    attention with a broadcast position vector (one copy of the cache
    attention math; the cache stays kv_heads-sized)."""
    B = kcache.shape[0]
    return _gqa_offset_cache_attention(
        kcache, vcache, jnp.full((B,), pos, jnp.int32), out_box)


def llama_generate(params, config: LlamaConfig, prompt_ids,
                   max_new_tokens, rng=None, temperature: float = 1.0,
                   top_k: int = 0, dtype=jnp.bfloat16):
    """Autoregressive sampling with a kv_heads-sized KV cache (GQA's
    inference payoff: cache memory is kv_heads/heads of the MHA cache).
    Same contract as :func:`deepspeed_tpu.models.gpt2.gpt2_generate`;
    decode is one ``lax.scan``."""
    from deepspeed_tpu.models.gpt2 import (_tied_logits, layer_params,
                                           make_token_sampler,
                                           run_decode_scan)
    B, Pl = prompt_ids.shape
    if max_new_tokens <= 0:
        return prompt_ids
    L = Pl + max_new_tokens
    assert L <= config.max_position_embeddings, (
        L, config.max_position_embeddings)
    hkv, hd = config.kv_heads, config.head_dim
    nl = config.num_layers
    greedy = rng is None or temperature == 0.0
    sample = make_token_sampler(config.vocab_size, temperature, top_k,
                                greedy)
    cos_full, sin_full = rope_cos_sin(L, hd, config.rope_theta)

    # ---- prefill: full forward over the prompt, capturing post-RoPE K/V
    x = params["tok_emb"][prompt_ids].astype(dtype)
    kc = jnp.zeros((nl, B, hkv, L, hd), dtype)
    vc = jnp.zeros((nl, B, hkv, L, hd), dtype)
    captured = {}

    def capture_attn(i):
        def attn(q, k, v):
            captured[i] = (k, v)
            return flash_attention(q, k, v, causal=True)
        return attn

    cos_p, sin_p = cos_full[:Pl], sin_full[:Pl]
    for i in range(nl):
        x = llama_block(layer_params(params, config, i), config, x,
                        cos_p, sin_p, dtype, attention_fn=capture_attn(i))
        k, v = captured.pop(i)
        kc = kc.at[i, :, :, :Pl].set(k.astype(dtype))
        vc = vc.at[i, :, :, :Pl].set(v.astype(dtype))
    x = rms_norm(x, params["ln_f"]["w"], config.rms_norm_eps)
    last_logits = _tied_logits(x[:, -1:], params["lm_head"], dtype)[:, 0]

    if rng is None:
        rng = jax.random.PRNGKey(0)
    first_tok = sample(last_logits, jax.random.fold_in(rng, 0))

    def step_logits(tok, t, caches):
        kc, vc = caches
        pos = Pl + t                      # position of `tok` in the stream
        x = params["tok_emb"][tok[:, None]].astype(dtype)
        cos_t = jax.lax.dynamic_slice_in_dim(cos_full, pos, 1, 0)
        sin_t = jax.lax.dynamic_slice_in_dim(sin_full, pos, 1, 0)
        new_kc, new_vc = [], []
        for i in range(nl):
            box = []
            x = llama_block(layer_params(params, config, i), config, x,
                            cos_t, sin_t, dtype,
                            attention_fn=_gqa_cached_attention(
                                kc[i], vc[i], pos, box))
            ki, vi = box[0]
            new_kc.append(ki)
            new_vc.append(vi)
        x = rms_norm(x, params["ln_f"]["w"], config.rms_norm_eps)
        logits = _tied_logits(x, params["lm_head"], dtype)[:, 0]
        return logits, (jnp.stack(new_kc), jnp.stack(new_vc))

    gen = run_decode_scan(step_logits, sample, first_tok, (kc, vc),
                          max_new_tokens, rng)
    return jnp.concatenate([prompt_ids, gen], axis=1)


def llama_loss_fn(config: LlamaConfig, dtype=jnp.bfloat16,
                  remat: bool = False, deterministic: bool = True):
    """Engine-contract loss: batch = {'input_ids': (B, S+1) int32} —
    next-token cross entropy via the chunked fused head. The family has
    no dropout (llama recipe), so ``deterministic`` is accepted for
    engine-contract parity and ignored."""
    from deepspeed_tpu.models.gpt2 import _tied_xent_chunked

    def loss_fn(params, batch, rng):
        del rng
        ids = batch["input_ids"]
        inputs, targets = ids[:, :-1], ids[:, 1:]
        x = _llama_trunk(params, config, inputs, dtype=dtype, remat=remat)
        return _tied_xent_chunked(x, params["lm_head"], targets, dtype)
    return loss_fn
