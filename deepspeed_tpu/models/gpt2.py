"""GPT-2 model family — the flagship workload.

Recreates the Megatron-GPT2 workload the reference trained through
DeepSpeedExamples (BASELINE.md: GPT-2 345M + ZeRO-2, GPT-2 1.5B 3D-parallel)
as a native model of this framework: causal flash attention, bf16 compute,
and first-class tensor-parallel PartitionSpecs (Megatron column/row sharding
over the ``model`` mesh axis — what the reference delegated to the client's
mpu, SURVEY §2.3).
"""

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash import (NEG_INF,
                                               flash_attention)


class GPT2Config(NamedTuple):
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0      # 0 => 4*hidden
    embd_dropout: float = 0.1
    attn_dropout: float = 0.1
    resid_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    # Stack the (homogeneous) blocks into leading-dim-L params and run
    # the trunk as one lax.scan: the block compiles ONCE instead of
    # num_layers times (BERT-large/GPT-2 first-compile drops ~20x; the
    # standard JAX LLM layout, cf. T5X/MaxText). Numerics are identical
    # to the unrolled trunk (same per-layer init keys); only the
    # per-layer dropout streams differ. Dense family only.
    scan_layers: bool = False

    @property
    def inter(self):
        return self.intermediate_size or 4 * self.hidden_size


# canonical sizes (Megatron/GPT-2 papers)
GPT2_SMALL = GPT2Config()                                          # 124M
GPT2_MEDIUM = GPT2Config(hidden_size=1024, num_layers=24,
                         num_heads=16)                             # 345M
GPT2_LARGE = GPT2Config(hidden_size=1280, num_layers=36,
                        num_heads=20)                              # 774M
GPT2_XL = GPT2Config(hidden_size=1600, num_layers=48,
                     num_heads=25)                                 # 1.5B


def init_gpt2_params(config: GPT2Config, key) -> Dict[str, Any]:
    h, inter = config.hidden_size, config.inter
    rng = config.initializer_range
    out_rng = rng / np.sqrt(2.0 * config.num_layers)
    keys = jax.random.split(key, 2 + 4 * config.num_layers)
    params: Dict[str, Any] = {
        "wte": jax.random.normal(keys[0], (config.vocab_size, h),
                                 jnp.float32) * rng,
        "wpe": jax.random.normal(keys[1], (config.max_position_embeddings, h),
                                 jnp.float32) * rng,
        "ln_f": {"w": jnp.ones((h,), jnp.float32),
                 "b": jnp.zeros((h,), jnp.float32)},
    }
    layers = []
    for i in range(config.num_layers):
        k = keys[2 + 4 * i: 6 + 4 * i]
        layers.append({
            "ln_1": {"w": jnp.ones((h,), jnp.float32),
                     "b": jnp.zeros((h,), jnp.float32)},
            "attn": {
                "qkvw": jax.random.normal(k[0], (h, 3 * h), jnp.float32) * rng,
                "qkvb": jnp.zeros((3 * h,), jnp.float32),
                "ow": jax.random.normal(k[1], (h, h), jnp.float32) * out_rng,
                "ob": jnp.zeros((h,), jnp.float32),
            },
            "ln_2": {"w": jnp.ones((h,), jnp.float32),
                     "b": jnp.zeros((h,), jnp.float32)},
            "mlp": {
                "fc_w": jax.random.normal(k[2], (h, inter), jnp.float32) * rng,
                "fc_b": jnp.zeros((inter,), jnp.float32),
                "proj_w": jax.random.normal(k[3], (inter, h),
                                            jnp.float32) * out_rng,
                "proj_b": jnp.zeros((h,), jnp.float32),
            },
        })
    if config.scan_layers:
        params["h"] = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *layers)
    else:
        for i, lp in enumerate(layers):
            params[f"h_{i}"] = lp
    return params


def layer_params(params, config: GPT2Config, i: int):
    """Block i's param pytree under either layout (``h_{i}`` keys, or the
    ``scan_layers`` stacked ``h``)."""
    if config.scan_layers:
        return jax.tree_util.tree_map(lambda a: a[i], params["h"])
    return params[f"h_{i}"]


def gpt2_param_specs(config: GPT2Config) -> Dict[str, Any]:
    """Megatron-style tensor-parallel shardings over the 'model' axis:
    column-parallel qkv/fc (shard output dim), row-parallel proj/ow (shard
    input dim); embeddings sharded over vocab."""
    layer = {
        "ln_1": {"w": P(), "b": P()},
        "attn": {"qkvw": P(None, "model"), "qkvb": P("model"),
                 "ow": P("model", None), "ob": P()},
        "ln_2": {"w": P(), "b": P()},
        "mlp": {"fc_w": P(None, "model"), "fc_b": P("model"),
                "proj_w": P("model", None), "proj_b": P()},
    }
    specs: Dict[str, Any] = {
        "wte": P("model", None),
        "wpe": P(),
        "ln_f": {"w": P(), "b": P()},
    }
    if config.scan_layers:
        # stacked layout: same shardings with an unsharded leading L dim
        specs["h"] = jax.tree_util.tree_map(
            lambda p: P(None, *p), layer,
            is_leaf=lambda x: isinstance(x, P))
    else:
        for i in range(config.num_layers):
            specs[f"h_{i}"] = layer
    return specs


from deepspeed_tpu.ops.functional import dropout as _dropout
from deepspeed_tpu.ops.functional import layer_norm as _ln_wb


def _layer_norm(x, p, eps):
    return _ln_wb(x, p["w"], p["b"], eps)


def _wd(leaf, dtype):
    """Weight at its use site: int8-resident leaves (serving under
    ``inference.quantize_weights: "int8"`` — runtime/quantized_params)
    dequantize per block RIGHT HERE, inside the compiled program, so
    the resident HBM copy stays int8; dense leaves just cast. The
    isinstance test is trace-time — training trees never carry
    QuantizedParam leaves, so the training path compiles unchanged."""
    from deepspeed_tpu.runtime.quantized_params import (QuantizedParam,
                                                        dequantize_param)
    if isinstance(leaf, QuantizedParam):
        return dequantize_param(leaf, dtype)
    return leaf.astype(dtype)


def _emb_rows(leaf, ids, dtype):
    """Embedding-table row gather for dense or int8-resident tables:
    quantized tables gather the int8 rows AND their per-block scales,
    dequantizing only the gathered rows — the full-vocab table is never
    materialized at the model dtype."""
    from deepspeed_tpu.runtime.quantized_params import QuantizedParam
    if isinstance(leaf, QuantizedParam):
        q = leaf.q[ids]
        s = jnp.repeat(leaf.scale[ids], leaf.block, axis=-1)
        return (q.astype(jnp.float32) * s[..., :q.shape[-1]]
                ).astype(dtype)
    return leaf[ids].astype(dtype)


def _embed(wte, wpe, ids, dtype):
    """Token + position embedding (shared by flat and pipelined forms)."""
    from deepspeed_tpu.runtime.quantized_params import QuantizedParam
    pos = jnp.arange(ids.shape[1])[None, :]
    if isinstance(wte, QuantizedParam) or isinstance(wpe, QuantizedParam):
        return (_emb_rows(wte, ids, jnp.float32)
                + _emb_rows(wpe, pos, jnp.float32)).astype(dtype)
    return (wte[ids] + wpe[pos]).astype(dtype)


def _tied_logits(x, wte, dtype):
    """LM head tied to the embedding: bf16 operands, fp32 accumulation —
    keeps the vocab GEMM on the MXU's fast path while the downstream
    softmax stays fp32."""
    return jax.lax.dot_general(
        x.astype(dtype), _wd(wte, dtype),
        (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32)


def _next_token_xent(logits, targets):
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def _tied_xent_chunked(x, wte, targets, dtype, chunk_tokens: int = 2048,
                       mean: bool = True, weights=None):
    """Fused tied-LM-head + next-token cross entropy, chunked over tokens.

    The naive path materializes fp32 logits (B·S, V) plus a log_softmax
    copy — multi-GB of HBM traffic at V≈50k that makes the step
    bandwidth-bound (and *worse* at larger batch). Here the head GEMM +
    logsumexp run per token-chunk under ``jax.checkpoint``: peak extra
    memory is one (chunk, V) fp32 tile and the backward recomputes it —
    ~10% more MXU flops for a large cut in HBM traffic. The scan carries
    only the scalar loss.
    """
    B, S, H = x.shape
    n = B * S
    xf = x.reshape(n, H)
    tf = targets.reshape(n)
    c = min(chunk_tokens, n)
    # pad to a multiple of c (weight-masked) rather than shrinking the
    # chunk — a prime n would otherwise degrade to c=1 and a scan of
    # thousands of single-token GEMMs. ``weights``: optional per-token
    # loss weights (e.g. 0 for positions past a ragged sequence end)
    pad = (-n) % c
    wf = (jnp.ones((n,), jnp.float32) if weights is None
          else weights.reshape(n).astype(jnp.float32))
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad, H), xf.dtype)])
        tf = jnp.concatenate([tf, jnp.zeros((pad,), tf.dtype)])
        wf = jnp.concatenate([wf, jnp.zeros((pad,), jnp.float32)])
    m = (n + pad) // c
    wte_d = wte.astype(dtype)

    def body(xs_c, ts_c, ws_c):
        logits = jax.lax.dot_general(
            xs_c.astype(dtype), wte_d, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (c, V) fp32
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, ts_c[:, None], axis=-1)[:, 0]
        return ((lse - picked) * ws_c).sum()

    body = jax.checkpoint(body)

    def scan_body(acc, inp):
        xs_c, ts_c, ws_c = inp
        return acc + body(xs_c, ts_c, ws_c), None

    total, _ = jax.lax.scan(
        scan_body, jnp.zeros((), jnp.float32),
        (xf.reshape(m, c, H), tf.reshape(m, c), wf.reshape(m, c)))
    return total / n if mean else total


def gpt2_block(block_params, config: GPT2Config, x, rng, deterministic,
               dtype, attention_fn=None, mlp_fn=None):
    """One pre-LN transformer block. ``attention_fn(q, k, v, rate, rng)``
    optionally replaces causal flash attention (e.g. ring attention for
    sequence parallelism). ``mlp_fn(mlp_params, m_in) -> (m_out, aux)``
    optionally replaces the dense MLP (e.g. a MoE FFN) — the block then
    returns ``(x, aux)`` instead of ``x``."""
    B, S, h = x.shape
    heads = config.num_heads
    hd = h // heads
    if rng is not None:
        r1, r2 = jax.random.split(rng)
    else:
        r1 = r2 = None

    # attention (pre-LN)
    a_in = _layer_norm(x, block_params["ln_1"], config.layer_norm_eps)
    ap = block_params["attn"]
    qkv = a_in @ _wd(ap["qkvw"], dtype) + _wd(ap["qkvb"], dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, S, heads, hd).transpose(0, 2, 1, 3)
    drop = (config.attn_dropout
            if not deterministic and rng is not None else 0.0)
    if drop > 0.0:
        r1, r_attn = jax.random.split(r1)
    else:
        r_attn = None
    if attention_fn is not None:
        ctx = attention_fn(q, k, v, drop, r_attn)
    elif drop > 0.0:
        # attention dropout runs inside the Pallas kernel (counter-based
        # hash mask regenerated in fwd and bwd — no (S, S) mask in HBM)
        ctx = flash_attention(q, k, v, causal=True, dropout_rate=drop,
                              dropout_rng=r_attn)
    else:
        ctx = flash_attention(q, k, v, causal=True)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, h)
    attn_out = ctx @ _wd(ap["ow"], dtype) + _wd(ap["ob"], dtype)
    x = x + _dropout(attn_out, config.resid_dropout, r1, deterministic)

    # mlp
    m_in = _layer_norm(x, block_params["ln_2"], config.layer_norm_eps)
    mp = block_params["mlp"]
    if mlp_fn is not None:
        m_out, aux = mlp_fn(mp, m_in)
        x = x + _dropout(m_out.astype(dtype), config.resid_dropout, r2,
                         deterministic)
        return x, aux
    hmid = m_in @ _wd(mp["fc_w"], dtype) + _wd(mp["fc_b"], dtype)
    hmid = jax.nn.gelu(hmid, approximate=True)
    m_out = hmid @ _wd(mp["proj_w"], dtype) + _wd(mp["proj_b"], dtype)
    x = x + _dropout(m_out, config.resid_dropout, r2, deterministic)
    return x


def _gpt2_trunk(params, config: GPT2Config, input_ids, rng=None,
                deterministic: bool = True, dtype=jnp.bfloat16,
                remat: bool = False, mlp_fns=None):
    """Final hidden states (B, S, H) after ln_f (no LM head).

    ``mlp_fns``: optional {layer_index: mlp_fn} replacing that block's
    dense MLP (e.g. MoE); when given, returns ``(x, aux_loss_total)``."""
    x = _embed(params["wte"], params["wpe"], input_ids, dtype)
    if rng is not None:
        rng, r_emb = jax.random.split(rng)
        x = _dropout(x, config.embd_dropout, r_emb, deterministic)

    block = gpt2_block
    if remat:
        # attention_fn/mlp_fn are callables -> static under checkpoint
        block = jax.checkpoint(gpt2_block,
                               static_argnums=(1, 4, 5, 6, 7))
    aux_total = jnp.zeros((), jnp.float32)
    if config.scan_layers:
        assert mlp_fns is None, \
            "scan_layers supports the homogeneous dense family only"
        # one compiled block, scanned over the stacked layer params
        if rng is not None:
            layer_rngs = jax.random.split(rng, config.num_layers)

            def body(x, inp):
                lp, r = inp
                return block(lp, config, x, r, deterministic,
                             dtype, None, None), None
            x, _ = jax.lax.scan(body, x, (params["h"], layer_rngs))
        else:
            def body(x, lp):
                return block(lp, config, x, None, deterministic,
                             dtype, None, None), None
            x, _ = jax.lax.scan(body, x, params["h"])
    else:
        for i in range(config.num_layers):
            if rng is not None:
                rng, r = jax.random.split(rng)
            else:
                r = None
            mlp_fn = None if mlp_fns is None else mlp_fns.get(i)
            if mlp_fn is not None:
                x, aux = block(params[f"h_{i}"], config, x, r, deterministic,
                               dtype, None, mlp_fn)
                aux_total = aux_total + aux
            else:
                x = block(params[f"h_{i}"], config, x, r, deterministic,
                          dtype, None, None)

    x = _layer_norm(x, params["ln_f"], config.layer_norm_eps)
    if mlp_fns is not None:
        return x, aux_total
    return x


def _gpt2_trunk_cached(params, config: GPT2Config, input_ids, kv_cache,
                       cache_position, dtype, block_tables=None,
                       paged_attn_kernel: str = "gather"):
    """Cache-carrying trunk: run ``input_ids`` (B, S) through the SAME
    gpt2_block as training with attention over the provided KV cache
    (``kv_cache = (kc, vc)``, each (layers, B, heads, max_len, hd)),
    writing this call's K/V at each row's ``cache_position`` offset.
    Returns (final hidden states after ln_f, updated kv_cache). Serves
    prefill (S = padded prompt, cache_position = 0) and decode (S = 1,
    per-slot positions) with one code path — no second copy of the
    block math to drift.

    With ``block_tables`` ((B, pages_per_seq) int32) the cache is the
    PAGED pool pair (each (layers, num_pages, heads, page_size, hd)) and
    attention runs the paged path (:func:`_paged_cache_attention`) —
    same block, same mask; ``paged_attn_kernel`` picks the fused Pallas
    decode kernel ("pallas") or the gather oracle ("gather") for seq-1
    queries. An int8-quantized pool arrives as the 4-tuple
    ``(kc, vc, kscale, vscale)`` (scale pools
    (layers, num_pages, heads, page_size, nb) fp32) — writes quantize
    per token row, reads dequantize at the attention site."""
    kc, vc = kv_cache[0], kv_cache[1]
    kscale, vscale = (kv_cache[2], kv_cache[3]) if len(kv_cache) == 4 \
        else (None, None)
    B, S = input_ids.shape
    pos = cache_position[:, None] + jnp.arange(S)[None, :]
    x = (_emb_rows(params["wte"], input_ids, jnp.float32)
         + _emb_rows(params["wpe"], pos, jnp.float32)).astype(dtype)
    new_caches = []
    for i in range(config.num_layers):
        box = []
        if block_tables is not None:
            attn = _paged_cache_attention(
                kc[i], vc[i], block_tables, cache_position, box,
                attn_kernel=paged_attn_kernel,
                kscale_pool=None if kscale is None else kscale[i],
                vscale_pool=None if vscale is None else vscale[i])
        else:
            attn = _offset_cache_attention(kc[i], vc[i], cache_position,
                                           box)
        x = gpt2_block(layer_params(params, config, i), config, x, None,
                       True, dtype, attention_fn=attn)
        new_caches.append(box[0])
    x = _layer_norm(x, params["ln_f"], config.layer_norm_eps)
    return x, tuple(jnp.stack(leaf) for leaf in zip(*new_caches))


def gpt2_forward(params, config: GPT2Config, input_ids, rng=None,
                 deterministic: bool = True, dtype=jnp.bfloat16,
                 remat: bool = False, kv_cache=None, cache_position=None,
                 block_tables=None, paged_attn_kernel: str = "gather"):
    """Logits (B, S, vocab). Embedding output layer is tied to wte.

    KV-cache mode (serving): with ``kv_cache=(kc, vc)`` (each
    ``(layers, B, heads, max_len, hd)``) and ``cache_position`` ((B,)
    int32 — tokens already in each row's cache), the forward writes this
    call's K/V into the cache at each row's offset, attends with
    :func:`causal_cache_mask`, and returns ``(logits, updated_cache)``
    instead of bare logits. ``block_tables`` ((B, pages_per_seq) int32)
    switches the cache interpretation to the paged pool pair (each
    ``(layers, num_pages, heads, page_size, hd)``);
    ``paged_attn_kernel="pallas"`` routes seq-1 queries through the
    fused Pallas paged-decode kernel instead of the stripe gather. The
    training call signature is unchanged (the serving arguments all
    default off)."""
    if kv_cache is not None:
        if cache_position is None:
            cache_position = jnp.zeros((input_ids.shape[0],), jnp.int32)
        x, cache = _gpt2_trunk_cached(params, config, input_ids, kv_cache,
                                      cache_position, dtype,
                                      block_tables=block_tables,
                                      paged_attn_kernel=paged_attn_kernel)
        return _tied_logits(x, params["wte"], dtype), cache
    x = _gpt2_trunk(params, config, input_ids, rng=rng,
                    deterministic=deterministic, dtype=dtype, remat=remat)
    return _tied_logits(x, params["wte"], dtype)


def gpt2_loss_fn(config: GPT2Config, dtype=jnp.bfloat16, remat: bool = False,
                 deterministic: bool = False):
    """Engine-contract loss: batch = {'input_ids': (B, S+1) int32} —
    next-token cross entropy on shifted ids."""
    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        inputs, targets = ids[:, :-1], ids[:, 1:]
        # run the trunk, then the fused chunked head+loss (skips the full
        # (B,S,V) fp32 logits materialization of gpt2_forward)
        x = _gpt2_trunk(params, config, inputs, rng=rng,
                        deterministic=deterministic, dtype=dtype,
                        remat=remat)
        return _tied_xent_chunked(x, params["wte"], targets, dtype)
    return loss_fn


# --------------------------------------------------------------------- #
# generation (KV-cache decode) — beyond-reference extension: the v0.3.0
# snapshot is training-only; sampling here is the natural flip side of
# the GPT-2 family. TPU-first shape discipline: the cache is a static
# (B, heads, max_len, hd) buffer per layer, prefill is ONE full forward
# (flash attention) that also writes the cache, and decode is a
# lax.scan over positions — a single compiled step per token, no
# Python-loop retracing, no dynamic shapes. Both phases run the SAME
# gpt2_block as training, with the attention swapped via its
# attention_fn hook (prefill captures K/V; decode attends to the cache)
# — no second copy of the block math to drift.
# --------------------------------------------------------------------- #
def make_token_sampler(vocab_size: int, temperature: float, top_k: int,
                       greedy: bool):
    """Shared decode-step sampler (gpt2_generate / llama_generate): greedy
    argmax, or temperature + optional top-k filtering + categorical. One
    home so sampling semantics cannot drift between model families."""
    eff_k = min(top_k, vocab_size)

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        t = logits / jnp.maximum(temperature, 1e-6)
        if eff_k > 0:
            kth = jax.lax.top_k(t, eff_k)[0][:, -1][:, None]
            t = jnp.where(t < kth, NEG_INF, t)
        return jax.random.categorical(key, t, axis=-1).astype(jnp.int32)
    return sample


def run_decode_scan(step_logits, sample, first_tok, caches,
                    max_new_tokens, rng):
    """Shared decode loop (gpt2_generate / llama_generate): one
    ``lax.scan`` over ``step_logits(tok, t, caches) -> (logits, caches)``.
    Owns the carry shape, the max_new_tokens-1 step count (`first_tok`
    was already sampled from the prefill logits), and the
    ``[toks.T | last]`` assembly — one home so the off-by-one contract
    cannot drift between model families. Returns (B, max_new_tokens)."""
    def step(carry, t):
        tok, caches = carry
        logits, caches = step_logits(tok, t, caches)
        nxt = sample(logits, jax.random.fold_in(rng, t + 1))
        return (nxt, caches), tok

    (last, _), toks = jax.lax.scan(
        step, (first_tok, caches), jnp.arange(max_new_tokens - 1))
    return jnp.concatenate([toks.T, last[:, None]], axis=1)


def causal_cache_mask(cache_position, q_len: int, kv_len: int):
    """Causal mask over a KV cache that respects per-row cache offsets.

    ``cache_position``: (B,) int32 — absolute position of each row's
    FIRST query token in its stream (the number of tokens already in
    that row's cache). Query j of row b therefore sits at position
    ``cache_position[b] + j`` and may attend exactly the cache slots
    ``<= `` that position: everything written before it plus the slots
    this same call writes at/before its own position. Returns a bool
    (B, 1, q_len, kv_len) mask (broadcasts over heads). The shared
    offset-mask home for the cached prefill/decode paths of every model
    family — the serving engine's bucketed programs pin their numerics
    on it.
    """
    q_pos = cache_position[:, None] + jnp.arange(q_len)[None, :]
    k_idx = jnp.arange(kv_len)
    return k_idx[None, None, None, :] <= q_pos[:, None, :, None]


def write_kv_cache(cache, new, cache_position):
    """Write ``new`` (B, heads, S, hd) into ``cache`` (B, heads, max_len,
    hd) starting at per-row position ``cache_position`` (B,) — a
    ``lax.dynamic_update_slice`` vmapped over the batch so every serving
    slot advances at its own offset (continuous batching: slots are at
    different sequence lengths)."""
    return jax.vmap(
        lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (0, s, 0))
    )(cache, new.astype(cache.dtype), cache_position)


def write_paged_kv_cache(pool, new, block_table, cache_position):
    """Scatter ``new`` (B, heads, S, hd) into a paged pool
    ``(num_pages, heads, page_size, hd)``: row b's token j lands in page
    ``block_table[b, (cache_position[b]+j) // page_size]`` at offset
    ``(cache_position[b]+j) % page_size``. Positions past the table's
    logical extent — and unreserved table entries, which the host
    allocator leaves at 0 — land in the reserved null page 0, whose
    garbage ``causal_cache_mask`` keeps unread. One scatter per call,
    static shapes throughout: the serving paged programs never reshape.
    """
    B, H, S, hd = new.shape
    P = block_table.shape[1]
    ps = pool.shape[2]
    pos = cache_position[:, None] + jnp.arange(S)[None, :]       # (B, S)
    slot = pos // ps
    page = jnp.where(
        slot < P,
        jnp.take_along_axis(block_table, jnp.minimum(slot, P - 1), axis=1),
        0)
    vals = new.astype(pool.dtype).transpose(0, 2, 1, 3).reshape(
        B * S, H, hd)
    return pool.at[page.reshape(-1), :, (pos % ps).reshape(-1)].set(vals)


def gather_paged_kv(pool, block_table):
    """Assemble each row's logical K or V stripe from the paged pool:
    ``(B, pages_per_seq)`` block table over ``(num_pages, heads,
    page_size, hd)`` -> ``(B, heads, pages_per_seq * page_size, hd)``.
    Gathered position ``t * page_size + o`` is the row's absolute cache
    position, so :func:`causal_cache_mask` applies unchanged — unmapped
    table entries surface the null page, always masked.

    NB: this materializes each row's full logical stripe (every table
    entry it is handed) each call — per-step decode reads are bounded
    by the TABLE WIDTH, not the tokens actually live. It serves as the
    paged paths' numerics oracle and as the fallback where the fused
    Pallas decode kernel (``ops/attention/paged.py`` — reads only live
    pages, O(live tokens)) can't run; the serving engine additionally
    clamps the decode table width to the batch's live page bucket so
    even this fallback stops paying full ``max_len`` bandwidth
    (``inference.paged_kv.decode_page_buckets``)."""
    B, P = block_table.shape
    _, H, ps, hd = pool.shape
    return pool[block_table].transpose(0, 2, 1, 3, 4).reshape(
        B, H, P * ps, hd)


def paged_decode_ctx(q, kpool, vpool, block_table, cache_position,
                     k_scales=None, v_scales=None):
    """The seq-1 fused-kernel dispatch both families share: run
    :func:`deepspeed_tpu.ops.attention.paged.paged_decode_attention`
    against the (already-written) pool and restore the (B, H, 1, hd)
    context layout. One home so the kernel call contract cannot drift
    between gpt2 and llama. ``k_scales``/``v_scales`` select the int8
    pool arity — the per-page scale tiles stream into the kernel and
    dequant happens in VMEM.

    Under a serving mesh the engine traces its compiled programs inside
    ``parallel/pallas_shard.pallas_kernel_mesh``; consulting that
    context here wraps the kernel in shard_map over the mesh's head
    axis (pools stay sharded over kv heads — the O(live tokens) read
    survives GSPMD instead of falling back to gather)."""
    from deepspeed_tpu.ops.attention.paged import paged_decode_attention
    from deepspeed_tpu.parallel.pallas_shard import (current_kernel_mesh,
                                                     sharded_paged_decode)
    km = current_kernel_mesh()
    if km is not None:
        out = sharded_paged_decode(q[:, :, 0], kpool, vpool, block_table,
                                   cache_position, mesh=km.mesh,
                                   axis=km.axis, k_scales=k_scales,
                                   v_scales=v_scales)
    else:
        out = paged_decode_attention(q[:, :, 0], kpool, vpool,
                                     block_table, cache_position,
                                     k_scales=k_scales,
                                     v_scales=v_scales)
    return out[:, :, None, :]


def _paged_cache_attention(kpool, vpool, block_table, cache_position,
                           out_box, attn_kernel: str = "gather",
                           kscale_pool=None, vscale_pool=None):
    """attention_fn for the paged cached forward (prefill-into-pages and
    paged decode alike): scatter this call's K/V into the page pool via
    the block table, then attend. Single-query calls (decode — and any
    seq-1 prefill bucket) with ``attn_kernel="pallas"`` run the fused
    paged-attention kernel straight against the pool
    (:func:`paged_decode_ctx` — only live pages are read); everything
    else gathers each row's logical stripe back and attends under the
    shared ``causal_cache_mask`` (the numerics oracle / fallback).
    Updated pools return through ``out_box``.

    With ``kscale_pool``/``vscale_pool`` the pool is int8: this call's
    K/V quantize per token row (``ops.attention.paged.quantize_kv``)
    before the scatter — payload and scales land through the SAME
    block-table scatter — and every read path dequantizes (in-kernel
    for pallas, post-gather for the oracle). ``out_box`` then carries
    the 4-tuple ``(kp, vp, ksp, vsp)``."""
    quantized = kscale_pool is not None

    def attn(q, k, v, rate, rng):
        del rate, rng                  # cached forward is deterministic
        if quantized:
            from deepspeed_tpu.ops.attention.paged import (dequantize_pool,
                                                           quantize_kv)
            nb = kscale_pool.shape[-1]
            k_q, k_s = quantize_kv(k, nb)
            v_q, v_s = quantize_kv(v, nb)
            kp = write_paged_kv_cache(kpool, k_q, block_table,
                                      cache_position)
            vp = write_paged_kv_cache(vpool, v_q, block_table,
                                      cache_position)
            ksp = write_paged_kv_cache(kscale_pool, k_s, block_table,
                                       cache_position)
            vsp = write_paged_kv_cache(vscale_pool, v_s, block_table,
                                       cache_position)
            out_box.append((kp, vp, ksp, vsp))
        else:
            kp = write_paged_kv_cache(kpool, k, block_table,
                                      cache_position)
            vp = write_paged_kv_cache(vpool, v, block_table,
                                      cache_position)
            ksp = vsp = None
            out_box.append((kp, vp))
        if attn_kernel == "pallas" and q.shape[2] == 1:
            return paged_decode_ctx(q, kp, vp, block_table,
                                    cache_position, k_scales=ksp,
                                    v_scales=vsp)
        kc = gather_paged_kv(kp, block_table)
        vc = gather_paged_kv(vp, block_table)
        if quantized:
            kc = dequantize_pool(kc, gather_paged_kv(ksp, block_table))
            vc = dequantize_pool(vc, gather_paged_kv(vsp, block_table))
        if q.shape[2] > 1:
            # context-parallel chunked prefill (ISSUE 19): under the
            # engine's CP trace context, the chunk's sequence axis runs
            # ring-sharded over the serving mesh — same stripe, same
            # absolute-position causal rule
            from deepspeed_tpu.parallel.pallas_shard import \
                current_cp_mesh
            cp = current_cp_mesh()
            if cp is not None:
                from deepspeed_tpu.ops.attention.ring import \
                    ring_prefill_attention
                return ring_prefill_attention(q, kc, vc, cache_position,
                                              cp.mesh, cp.axis)
        hd = q.shape[-1]
        scores = jnp.einsum("bhqd,bhld->bhql", q.astype(jnp.float32),
                            kc.astype(jnp.float32)) / np.sqrt(hd)
        mask = causal_cache_mask(cache_position, q.shape[2], kc.shape[2])
        scores = jnp.where(mask, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhql,bhld->bhqd", probs,
                          vc.astype(jnp.float32)).astype(q.dtype)
    return attn


def _offset_cache_attention(kcache, vcache, cache_position, out_box):
    """attention_fn for the cached forward (prefill-into-cache and
    decode alike): write this call's K/V into the cache at each row's
    own offset, attend every query to all cache slots <= its absolute
    position (``causal_cache_mask``). Updated caches return through
    ``out_box`` (gpt2_block's hook only returns the context)."""
    def attn(q, k, v, rate, rng):
        del rate, rng                      # cached forward is deterministic
        kc = write_kv_cache(kcache, k, cache_position)
        vc = write_kv_cache(vcache, v, cache_position)
        out_box.append((kc, vc))
        hd = q.shape[-1]
        scores = jnp.einsum("bhqd,bhld->bhql", q.astype(jnp.float32),
                            kc.astype(jnp.float32)) / np.sqrt(hd)
        mask = causal_cache_mask(cache_position, q.shape[2], kc.shape[2])
        scores = jnp.where(mask, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhql,bhld->bhqd", probs,
                          vc.astype(jnp.float32)).astype(q.dtype)
    return attn


def _cached_attention(kcache, vcache, pos, out_box):
    """Single-position decode hook (gpt2_generate's scan): every row
    writes/attends at the same scalar ``pos`` — the offset-cache
    attention with a broadcast position vector."""
    B = kcache.shape[0]
    return _offset_cache_attention(
        kcache, vcache, jnp.full((B,), pos, jnp.int32), out_box)


def gpt2_generate(params, config: GPT2Config, prompt_ids, max_new_tokens,
                  rng=None, temperature: float = 1.0, top_k: int = 0,
                  dtype=jnp.bfloat16):
    """Autoregressive sampling with a KV cache.

    prompt_ids: (B, P) int32. Returns (B, P + max_new_tokens) int32.
    temperature=0 (or rng=None) decodes greedily; top_k > 0 restricts
    sampling to the k most likely tokens. Dense GPT-2 family only (MoE
    params are rejected). The whole decode loop is one ``lax.scan`` —
    compile once, generate any prompt of length P.
    """
    B, P = prompt_ids.shape
    if max_new_tokens <= 0:
        return prompt_ids
    L = P + max_new_tokens
    assert L <= config.max_position_embeddings, (
        L, config.max_position_embeddings)
    if config.scan_layers:
        # stacked layout is structurally dense (init_gpt2_moe_params
        # rejects it); one key check, no per-layer slicing
        if "fc_w" not in params["h"]["mlp"]:
            raise ValueError("gpt2_generate supports the dense GPT-2 "
                             "family only")
    else:
        for i in range(config.num_layers):
            if "fc_w" not in params[f"h_{i}"]["mlp"]:
                raise ValueError(
                    "gpt2_generate supports the dense GPT-2 family only; "
                    f"block h_{i} carries MoE expert params")
    heads = config.num_heads
    hd = config.hidden_size // heads
    nl = config.num_layers
    greedy = rng is None or temperature == 0.0
    sample = make_token_sampler(config.vocab_size, temperature, top_k,
                                greedy)

    # ---- prefill: one full forward over the prompt through gpt2_block,
    # the attention hook capturing each layer's K/V into the cache
    x = _embed(params["wte"], params["wpe"], prompt_ids, dtype)
    kc = jnp.zeros((nl, B, heads, L, hd), dtype)
    vc = jnp.zeros((nl, B, heads, L, hd), dtype)
    captured = {}

    def capture_attn(i):
        def attn(q, k, v, rate, rng_):
            del rate, rng_
            captured[i] = (k, v)
            return flash_attention(q, k, v, causal=True)
        return attn

    for i in range(nl):
        x = gpt2_block(layer_params(params, config, i), config, x, None,
                       True, dtype, attention_fn=capture_attn(i))
        k, v = captured.pop(i)
        kc = kc.at[i, :, :, :P].set(k.astype(dtype))
        vc = vc.at[i, :, :, :P].set(v.astype(dtype))
    x = _layer_norm(x, params["ln_f"], config.layer_norm_eps)
    last_logits = _tied_logits(x[:, -1:], params["wte"], dtype)[:, 0]

    if rng is None:
        rng = jax.random.PRNGKey(0)
    first_tok = sample(last_logits, jax.random.fold_in(rng, 0))

    def step_logits(tok, t, caches):
        kc, vc = caches
        pos = P + t                       # position of `tok` in the stream
        x = (params["wte"][tok[:, None]]
             + params["wpe"][pos][None, None]).astype(dtype)
        new_kc, new_vc = [], []
        for i in range(nl):
            box = []
            x = gpt2_block(layer_params(params, config, i), config, x,
                           None, True, dtype,
                           attention_fn=_cached_attention(kc[i], vc[i],
                                                          pos, box))
            ki, vi = box[0]
            new_kc.append(ki)
            new_vc.append(vi)
        x = _layer_norm(x, params["ln_f"], config.layer_norm_eps)
        logits = _tied_logits(x, params["wte"], dtype)[:, 0]
        return logits, (jnp.stack(new_kc), jnp.stack(new_vc))

    gen = run_decode_scan(step_logits, sample, first_tok, (kc, vc),
                          max_new_tokens, rng)
    return jnp.concatenate([prompt_ids, gen], axis=1)


def _is_moe_block(i: int, moe_every: int) -> bool:
    # blocks moe_every-1, 2*moe_every-1, ... — moe_every=1 means every
    # block; the single predicate keeps init and loss_fn in lockstep
    return i % moe_every == moe_every - 1


def init_gpt2_moe_params(config: GPT2Config, moe_config, key,
                         moe_every: int = 2):
    """GPT-2 params with the dense MLP of every ``moe_every``-th block
    (blocks moe_every-1, 2*moe_every-1, ...) replaced by a MoE expert
    bank; ``moe_every=1`` converts every block."""
    from deepspeed_tpu.ops.moe import init_moe_params
    assert not config.scan_layers, \
        "MoE blocks are heterogeneous; use the h_{i} layout"
    params = init_gpt2_params(config, key)
    for i in range(config.num_layers):
        if _is_moe_block(i, moe_every):
            key, km = jax.random.split(key)
            params[f"h_{i}"]["mlp"] = init_moe_params(moe_config, km)
    return params


def gpt2_moe_param_specs(config: GPT2Config, moe_every: int = 2):
    """PartitionSpecs for the MoE GPT-2: dense blocks keep the Megatron
    column/row TP specs; MoE blocks shard their expert banks over the
    ``expert`` mesh axis (true expert parallelism — each device OWNS
    E/ep experts' weights and optimizer state, it does not just
    constrain activations). Router stays replicated (tiny, every token
    needs it)."""
    specs = gpt2_param_specs(config)
    moe_mlp = {
        "router": P(),
        "wi": P("expert", None, None),
        "wo": P("expert", None, None),
    }
    for i in range(config.num_layers):
        if _is_moe_block(i, moe_every):
            specs[f"h_{i}"] = dict(specs[f"h_{i}"], mlp=moe_mlp)
    return specs


def gpt2_moe_loss_fn(config: GPT2Config, moe_config, mesh=None,
                     moe_every: int = 2, dtype=jnp.bfloat16,
                     remat: bool = False, deterministic: bool = False):
    """Engine-contract loss for a MoE GPT-2: next-token cross entropy plus
    the routers' load-balance/z aux losses. Blocks selected by
    ``_is_moe_block`` (moe_every=1 -> every block) carry a MoE FFN
    (params from :func:`init_gpt2_moe_params`); experts shard over the
    ``expert`` mesh axis when ``mesh`` has one.

    Beyond-reference extension (no MoE in the v0.3.0 snapshot): the
    sparse-FFN scaling axis on the same engine contract as the dense
    family."""
    from deepspeed_tpu.ops.moe import moe_layer

    expert_axis = ("expert" if mesh is not None
                   and "expert" in mesh.axis_names else None)

    def mlp_fn(mp, m_in):
        return moe_layer(mp, moe_config, m_in, expert_axis=expert_axis,
                         mesh=mesh, dtype=dtype)

    mlp_fns = {i: mlp_fn for i in range(config.num_layers)
               if _is_moe_block(i, moe_every)}

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        inputs, targets = ids[:, :-1], ids[:, 1:]
        x, aux_total = _gpt2_trunk(params, config, inputs, rng=rng,
                                   deterministic=deterministic,
                                   dtype=dtype, remat=remat,
                                   mlp_fns=mlp_fns)
        return (_tied_xent_chunked(x, params["wte"], targets, dtype)
                + aux_total)
    return loss_fn


def gpt2_sp_loss_fn(config: GPT2Config, mesh, dtype=jnp.bfloat16,
                    remat: bool = False, deterministic: bool = False,
                    zigzag: bool = False):
    """Sequence-parallel (context-parallel) GPT-2 loss over the ``seq``
    mesh axis — long-context training beyond one chip's activation
    memory (a TPU-native extension past the reference's block-sparse
    answer; SURVEY §5 long-context).

    Every activation tensor lives sharded (B, S/P, H) on its sequence
    shard: embeddings, LN, and MLP are token-local; attention crosses
    shards through :func:`deepspeed_tpu.ops.attention.ring.ring_attention`
    (K/V rotating over ICI); the chunked tied-head loss sums per-shard
    and psums in fp32. Engine-contract: batch = {'input_ids': (B, S+1)}
    with S divisible by the seq-axis size; batch rows shard over 'data'
    if present.
    """
    from deepspeed_tpu.ops.attention.ring import ring_attention
    from deepspeed_tpu.parallel.mesh import axis_size
    if "seq" not in mesh.axis_names:
        raise ValueError("gpt2_sp_loss_fn requires a 'seq' mesh axis")
    assert not config.scan_layers, \
        "gpt2_sp_loss_fn uses the h_{i} layout (set scan_layers=False)"
    Pn = axis_size(mesh, "seq")
    manual = frozenset(a for a in ("seq", "data") if a in mesh.axis_names)

    def attention_fn(q, k, v, rate, rng):
        return ring_attention(q, k, v, axis_name="seq", causal=True,
                              dropout_rate=rate, dropout_rng=rng,
                              zigzag=zigzag)

    block = gpt2_block
    if remat:
        block = jax.checkpoint(gpt2_block, static_argnums=(1, 4, 5, 6))

    def per_device(params, batch, rng):
        idx = jax.lax.axis_index("seq")
        ids = batch["input_ids"]                   # (B_l, S+1) replicated
        S = ids.shape[1] - 1
        assert S % Pn == 0, (S, Pn)
        sl = S // Pn
        if zigzag:
            # load-balanced causal layout: this shard owns global chunks
            # (idx, 2P-1-idx) of 2P (ring.zigzag_layout_indices); all
            # token-local math is position-gathered, so only the window
            # selection changes
            lc = sl // 2
            starts = (idx * lc, (2 * Pn - 1 - idx) * lc)
            wins = [jax.lax.dynamic_slice_in_dim(ids, st, lc + 1, axis=1)
                    for st in starts]
            inputs = jnp.concatenate([w[:, :-1] for w in wins], axis=1)
            targets = jnp.concatenate([w[:, 1:] for w in wins], axis=1)
            pos_emb = jnp.concatenate(
                [jax.lax.dynamic_slice_in_dim(params["wpe"], st, lc,
                                              axis=0) for st in starts],
                axis=0)
        else:
            # this shard's token window [idx*sl, idx*sl+sl] (+1 targets)
            win = jax.lax.dynamic_slice_in_dim(ids, idx * sl, sl + 1,
                                               axis=1)
            inputs, targets = win[:, :-1], win[:, 1:]
            pos_emb = jax.lax.dynamic_slice_in_dim(params["wpe"],
                                                   idx * sl, sl, axis=0)
        x = (params["wte"][inputs] + pos_emb[None]).astype(dtype)
        if rng is not None and not deterministic:
            rng = jax.random.fold_in(rng, 0)
            rng, r_emb = jax.random.split(rng)
            # per-shard stream for the token-local dropouts
            x = _dropout(x, config.embd_dropout,
                         jax.random.fold_in(r_emb, idx), deterministic)
        for i in range(config.num_layers):
            if rng is not None and not deterministic:
                rng, r = jax.random.split(rng)
                r = jax.random.fold_in(r, idx)
            else:
                r = None
            x = block(params[f"h_{i}"], config, x, r, deterministic, dtype,
                      attention_fn)
        x = _layer_norm(x, params["ln_f"], config.layer_norm_eps)
        local = _tied_xent_chunked(x, params["wte"], targets, dtype,
                                   mean=False)
        # fp32 psums only (bf16 psum trips the XLA partitioner when auto
        # axes share the mesh — see runtime/pipe/spmd._psum_act)
        total = jax.lax.psum(local.astype(jnp.float32), "seq")
        if "data" in manual:
            total = jax.lax.pmean(total, "data")
        B = ids.shape[0]
        return total / (B * S)

    PS = P
    def loss_fn(params, batch, rng):
        param_specs = jax.tree_util.tree_map(lambda _: PS(), params)
        batch_specs = jax.tree_util.tree_map(
            lambda _: PS("data") if "data" in manual else PS(), batch)
        return jax.shard_map(
            per_device, mesh=mesh,
            in_specs=(param_specs, batch_specs, PS()),
            out_specs=PS(), axis_names=manual,
            check_vma=False)(params, batch, rng)

    # fp32 master params flow in directly; every weight is cast at its use
    # site, so the shard_map-transposed gradient psums stay fp32 (the
    # engine skips its up-front cast — same policy as ZeRO stage 3)
    loss_fn.owns_cast = True
    return loss_fn


def count_params(params) -> int:
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params))


def gpt2_pipeline_spec(config: GPT2Config, num_stages: int,
                       dtype=None, deterministic: bool = True):
    """GPT-2 as a PipelineSpec for the compiled SPMD pipeline
    (runtime/pipe/spmd.py) — the 3D-parallel (pipe × data × model)
    flagship workload (BASELINE.md: GPT-2 1.5B 3D-parallel; reference ran
    it via PipelineModule + Megatron mpu).

    - pre: token+position embedding (stage-0 slot, wte/wpe replicated over
      'pipe', vocab-sharded over 'model');
    - stages: ``num_layers/num_stages`` blocks each, params stacked
      ``(S, L/S, ...)``, applied via ``lax.scan`` over the layer dim;
    - post: final LN + logits tied to wte (TiedLayerSpec semantics) +
      next-token cross entropy.

    Micro-batch contract: ``{"input_ids": (mb, seq+1) int32}``.

    ``dtype=None`` (default) inherits the engine's configured compute dtype
    — the pipeline loss fn casts params inside the mapped program
    (spmd.py ``compute_dtype``), and these fns read the dtype off the cast
    param leaves, so an fp16 config really computes fp16.
    """
    from deepspeed_tpu.runtime.pipe.spmd import PipelineSpec

    assert not config.scan_layers, \
        "the pipeline spec stage-stacks layers itself (scan_layers=False)"
    L = config.num_layers
    # uneven partitions supported: stages hold ceil(L/S) slots, short
    # stages pad with zero blocks masked out in stage_apply (data-masked,
    # never branched — reference parameters-balanced partitions,
    # module.py:348, composed with the SPMD uniformity invariant)
    lps = -(-L // num_stages)  # ceil
    stage_counts = [min(lps, max(0, L - s * lps))
                    for s in range(num_stages)]
    if min(stage_counts) <= 0:
        raise ValueError(f"num_layers {L} too few for {num_stages} stages "
                         f"(an entire stage would be empty)")
    even_stages = (L % num_stages == 0)

    def init(key):
        full = init_gpt2_params(config, key)
        per_stage = []
        zero_block = jax.tree_util.tree_map(jnp.zeros_like, full["h_0"])
        for s in range(num_stages):
            blocks = [full[f"h_{s * lps + j}"]
                      for j in range(stage_counts[s])]
            blocks += [zero_block] * (lps - stage_counts[s])
            per_stage.append(jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *blocks))
        stages = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_stage)
        return {"pre": {"wte": full["wte"], "wpe": full["wpe"]},
                "stages": stages,
                "post": {"ln_f": full["ln_f"]}}

    def _dtype_of(leaf):
        return dtype if dtype is not None else leaf.dtype

    def pre_apply(pre_p, micro, rng):
        ids = micro["input_ids"][:, :-1]
        x = _embed(pre_p["wte"], pre_p["wpe"], ids, _dtype_of(pre_p["wte"]))
        if not deterministic and rng is not None:
            x = _dropout(x, config.embd_dropout, rng, deterministic)
        return x

    counts_arr = jnp.asarray(stage_counts, jnp.int32)

    def stage_apply(st_p, act, rng):
        # st_p leaves: (lps, ...) — scan the layer dim; padded slots of an
        # uneven partition pass x through via where (uniform execution)
        cnt = None if even_stages else \
            counts_arr[jax.lax.axis_index("pipe")]
        def body(x, inp):
            j, lp = inp
            r = jax.random.fold_in(rng, j) if rng is not None else None
            y = gpt2_block(lp, config, x, r, deterministic, _dtype_of(act))
            if cnt is not None:
                y = jnp.where(j < cnt, y, x)
            return y, None
        out, _ = jax.lax.scan(body, act, (jnp.arange(lps), st_p))
        return out

    def post_apply(post_p, pre_p, act, micro):
        # fused chunked head+xent: never materializes the (mb, S, V) fp32
        # logits (the same head the non-pipelined loss uses; the naive
        # full-logits path is exactly what it exists to avoid)
        targets = micro["input_ids"][:, 1:]
        x = _layer_norm(act, post_p["ln_f"], config.layer_norm_eps)
        return _tied_xent_chunked(x, pre_p["wte"], targets, _dtype_of(act))

    def post_shard_apply(post_p, pre_p, act_slice, micro, start):
        # sequence-chunk of the head for the cooperative pipeline head
        # (spmd.py): positions [start, start+len) of the micro-batch;
        # per-token xent decomposes, so a SUM over the slice is exact.
        # Targets come via static shift + one-hot block select — a traced
        # `start` dynamic_slice here trips the XLA partitioner under auto
        # mesh axes (see spmd.seq_chunk_select). Ragged sequences
        # (seq %% S != 0): the executor pads the exit activation to
        # S*ceil(seq/S); targets pad with zeros and the pad positions are
        # weight-masked out of the loss.
        from deepspeed_tpu.runtime.pipe.spmd import seq_chunk_select
        length = act_slice.shape[1]
        shifted = micro["input_ids"][:, 1:]            # (mb, seq) next-token
        seq = shifted.shape[1]
        S = -(-seq // length)
        weights = None
        if S * length != seq:
            shifted = jnp.pad(shifted,
                              ((0, 0), (0, S * length - seq)))
            j = jax.lax.iota(jnp.int32, length)
            weights = jnp.broadcast_to(
                (start + j < seq)[None, :].astype(jnp.float32),
                act_slice.shape[:2])
        targets = seq_chunk_select(shifted, start // length, S, axis=1)
        x = _layer_norm(act_slice, post_p["ln_f"], config.layer_norm_eps)
        return _tied_xent_chunked(x, pre_p["wte"], targets,
                                  _dtype_of(act_slice), mean=False,
                                  weights=weights)

    block_specs = gpt2_param_specs(config)["h_0"]
    # stacked stage leaves carry (lps, ...) — shift TP specs right one dim
    stage_specs = jax.tree_util.tree_map(
        lambda s: P(None, *tuple(s)), block_specs,
        is_leaf=lambda x: isinstance(x, P))

    return PipelineSpec(
        init=init, pre_apply=pre_apply, stage_apply=stage_apply,
        post_apply=post_apply, num_stages=num_stages,
        pre_specs={"wte": P("model", None), "wpe": P()},
        stage_specs=stage_specs,
        post_specs={"ln_f": {"w": P(), "b": P()}},
        post_shard_apply=post_shard_apply)
