// Host-side vectorized Adam for ZeRO-Offload, TPU-native build.
//
// Re-implements the capability of the reference's csrc/adam/cpu_adam.cpp
// (Adam_Optimizer::Step / Step_4 / Step_8: AVX512/AVX2 SIMD + OpenMP over
// the fp32 master partition, with a fused cast+copy of updated params back
// to the device dtype). Differences by design:
//  - C API (extern "C") consumed via ctypes — no pybind11 in this image.
//  - The device-bound output is bfloat16 (TPU parameter dtype), produced
//    on the host by round-to-nearest-even truncation; the reference wrote
//    fp16 via a CUDA kernel (custom_cuda_kernel.cu param_update_kernel).
//  - Stateless bias correction: the step count is an argument and
//    beta^t is computed per call, so the same optimizer handle can serve
//    many parameter leaves (the reference tracks _betta1_t incrementally).
//
// Build: make -C csrc  →  libdstpu_adam.so

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

struct AdamConfig {
    float alpha;
    float beta1;
    float beta2;
    float eps;
    float weight_decay;
    int adamw_mode;      // 1: decoupled decay (AdamW), 0: L2 into grad
    int bias_correction; // 1: apply 1/(1-beta^t) corrections
};

std::unordered_map<int, AdamConfig>& registry() {
    static std::unordered_map<int, AdamConfig> r;
    return r;
}
std::mutex reg_mu;

inline uint16_t f32_to_bf16(float v) {
    uint32_t bits;
    std::memcpy(&bits, &v, sizeof(bits));
    // round-to-nearest-even on the truncated mantissa
    uint32_t rounding = 0x7fffu + ((bits >> 16) & 1u);
    return static_cast<uint16_t>((bits + rounding) >> 16);
}

}  // namespace

extern "C" {

int ds_adam_create(int id, float alpha, float beta1, float beta2, float eps,
                   float weight_decay, int adamw_mode, int bias_correction) {
    std::lock_guard<std::mutex> lock(reg_mu);
    registry()[id] = AdamConfig{alpha, beta1,         beta2,          eps,
                                weight_decay, adamw_mode, bias_correction};
    return 0;
}

int ds_adam_destroy(int id) {
    std::lock_guard<std::mutex> lock(reg_mu);
    return registry().erase(id) ? 0 : -1;
}

// One Adam step over a flat fp32 leaf. `step` is 1-based. When
// `out_bf16` is non-null the updated params are also written there in
// bfloat16 (the H2D payload for the TPU copy). Returns 0, or -1 for an
// unknown optimizer id.
int ds_adam_step(int id, long long step, float lr_in, float* params,
                 const float* grads, float* exp_avg, float* exp_avg_sq,
                 long long n, uint16_t* out_bf16) {
    AdamConfig cfg;
    {
        std::lock_guard<std::mutex> lock(reg_mu);
        auto it = registry().find(id);
        if (it == registry().end()) return -1;
        cfg = it->second;
    }
    const float lr = (lr_in > 0.f) ? lr_in : cfg.alpha;
    const float b1 = cfg.beta1, b2 = cfg.beta2;
    const float one_m_b1 = 1.f - b1, one_m_b2 = 1.f - b2;
    float bc1 = 1.f, inv_sqrt_bc2 = 1.f;
    if (cfg.bias_correction) {
        bc1 = 1.f - std::pow(b1, static_cast<float>(step));
        inv_sqrt_bc2 =
            1.f / std::sqrt(1.f - std::pow(b2, static_cast<float>(step)));
    }
    const float step_size = -lr / bc1;
    const float wd = cfg.weight_decay;
    const int adamw = cfg.adamw_mode;
    const float eps = cfg.eps;

    long long vec_end = 0;

#if defined(__AVX2__)
    const __m256 v_b1 = _mm256_set1_ps(b1);
    const __m256 v_b2 = _mm256_set1_ps(b2);
    const __m256 v_1mb1 = _mm256_set1_ps(one_m_b1);
    const __m256 v_1mb2 = _mm256_set1_ps(one_m_b2);
    const __m256 v_eps = _mm256_set1_ps(eps);
    const __m256 v_step = _mm256_set1_ps(step_size);
    const __m256 v_isbc2 = _mm256_set1_ps(inv_sqrt_bc2);
    const __m256 v_wd = _mm256_set1_ps(wd);
    const __m256 v_neg_lr_wd = _mm256_set1_ps(-lr * wd);
    vec_end = n - (n % 8);
#pragma omp parallel for schedule(static)
    for (long long i = 0; i < vec_end; i += 8) {
        __m256 g = _mm256_loadu_ps(grads + i);
        __m256 p = _mm256_loadu_ps(params + i);
        __m256 m = _mm256_loadu_ps(exp_avg + i);
        __m256 v = _mm256_loadu_ps(exp_avg_sq + i);

        if (wd > 0.f && !adamw) g = _mm256_fmadd_ps(p, v_wd, g);

        m = _mm256_mul_ps(m, v_b1);
        m = _mm256_fmadd_ps(g, v_1mb1, m);
        v = _mm256_mul_ps(v, v_b2);
        v = _mm256_fmadd_ps(_mm256_mul_ps(g, g), v_1mb2, v);

        __m256 denom =
            _mm256_fmadd_ps(_mm256_sqrt_ps(v), v_isbc2, v_eps);
        __m256 upd = _mm256_div_ps(m, denom);
        if (wd > 0.f && adamw) p = _mm256_fmadd_ps(p, v_neg_lr_wd, p);
        p = _mm256_fmadd_ps(upd, v_step, p);

        _mm256_storeu_ps(params + i, p);
        _mm256_storeu_ps(exp_avg + i, m);
        _mm256_storeu_ps(exp_avg_sq + i, v);
        if (out_bf16) {
            alignas(32) float tmp[8];
            _mm256_store_ps(tmp, p);
            for (int k = 0; k < 8; ++k) out_bf16[i + k] = f32_to_bf16(tmp[k]);
        }
    }
#endif

    // scalar tail (and full path on non-AVX2 builds)
#pragma omp parallel for schedule(static)
    for (long long i = vec_end; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        float m = exp_avg[i];
        float v = exp_avg_sq[i];
        if (wd > 0.f && !adamw) g += wd * p;
        m = b1 * m + one_m_b1 * g;
        v = b2 * v + one_m_b2 * g * g;
        float denom = std::sqrt(v) * inv_sqrt_bc2 + eps;
        float upd = m / denom;
        if (wd > 0.f && adamw) p -= lr * wd * p;
        p += step_size * upd;
        params[i] = p;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        if (out_bf16) out_bf16[i] = f32_to_bf16(p);
    }
    return 0;
}

// simd width the build actually uses (for tests / introspection)
int ds_adam_simd_width() {
#if defined(__AVX2__)
    return 8;
#else
    return 1;
#endif
}

}  // extern "C"
