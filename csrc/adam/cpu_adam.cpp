// Host-side vectorized Adam for ZeRO-Offload, TPU-native build.
//
// Re-implements the capability of the reference's csrc/adam/cpu_adam.cpp
// (Adam_Optimizer::Step / Step_4 / Step_8: AVX512/AVX2 SIMD + OpenMP over
// the fp32 master partition, with a fused cast+copy of updated params back
// to the device dtype). Differences by design:
//  - C API (extern "C") consumed via ctypes — no pybind11 in this image.
//  - The device-bound output is bfloat16 (TPU parameter dtype), produced
//    on the host by round-to-nearest-even truncation; the reference wrote
//    fp16 via a CUDA kernel (custom_cuda_kernel.cu param_update_kernel).
//  - Stateless bias correction: the step count is an argument and
//    beta^t is computed per call, so the same optimizer handle can serve
//    many parameter leaves (the reference tracks _betta1_t incrementally).
//  - Runtime SIMD dispatch: the file is compiled WITHOUT -mavx*; the
//    AVX-512 (16-lane) and AVX2 (8-lane) paths are target-attributed
//    multiversioned functions selected via __builtin_cpu_supports, so the
//    same .so is safe on any x86-64 host (the reference selects
//    AVX512/AVX2 at compile time; its SIMD_WIDTH tiers are mirrored).
//
// Build: make -C csrc  →  libdstpu_adam.so

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <unordered_map>

#if defined(__x86_64__) || defined(_M_X64)
#define DS_X86 1
#include <immintrin.h>
#else
#define DS_X86 0
#endif

namespace {

struct AdamConfig {
    float alpha;
    float beta1;
    float beta2;
    float eps;
    float weight_decay;
    int adamw_mode;      // 1: decoupled decay (AdamW), 0: L2 into grad
    int bias_correction; // 1: apply 1/(1-beta^t) corrections
};

struct StepScalars {
    float lr, b1, b2, one_m_b1, one_m_b2, eps, step_size, inv_sqrt_bc2, wd;
    int adamw;
};

std::unordered_map<int, AdamConfig>& registry() {
    static std::unordered_map<int, AdamConfig> r;
    return r;
}
std::mutex reg_mu;

inline uint16_t f32_to_bf16(float v) {
    uint32_t bits;
    std::memcpy(&bits, &v, sizeof(bits));
    // NaN guard: the rounding add below can carry through the mantissa
    // into the exponent, turning a NaN into +/-Inf (masking a diverged
    // state as a huge-but-finite weight). Return a quiet NaN preserving
    // the sign instead.
    if ((bits & 0x7fffffffu) > 0x7f800000u) {
        return static_cast<uint16_t>((bits >> 16) | 0x0040u);
    }
    // round-to-nearest-even on the truncated mantissa
    uint32_t rounding = 0x7fffu + ((bits >> 16) & 1u);
    return static_cast<uint16_t>((bits + rounding) >> 16);
}

inline void step_scalar_range(const StepScalars& s, float* params,
                              const float* grads, float* exp_avg,
                              float* exp_avg_sq, long long lo, long long hi,
                              uint16_t* out_bf16) {
    // if-clause: skip the fork/join for tiny ranges (e.g. the <8-element
    // tail the AVX2 path hands us per leaf)
#pragma omp parallel for schedule(static) if (hi - lo >= 4096)
    for (long long i = lo; i < hi; ++i) {
        float g = grads[i];
        float p = params[i];
        float m = exp_avg[i];
        float v = exp_avg_sq[i];
        if (s.wd > 0.f && !s.adamw) g += s.wd * p;
        m = s.b1 * m + s.one_m_b1 * g;
        v = s.b2 * v + s.one_m_b2 * g * g;
        float denom = std::sqrt(v) * s.inv_sqrt_bc2 + s.eps;
        float upd = m / denom;
        if (s.wd > 0.f && s.adamw) p -= s.lr * s.wd * p;
        p += s.step_size * upd;
        params[i] = p;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        if (out_bf16) out_bf16[i] = f32_to_bf16(p);
    }
}

#if DS_X86
__attribute__((target("avx2,fma")))
void step_avx2(const StepScalars& s, float* params, const float* grads,
               float* exp_avg, float* exp_avg_sq, long long n,
               uint16_t* out_bf16) {
    const __m256 v_b1 = _mm256_set1_ps(s.b1);
    const __m256 v_b2 = _mm256_set1_ps(s.b2);
    const __m256 v_1mb1 = _mm256_set1_ps(s.one_m_b1);
    const __m256 v_1mb2 = _mm256_set1_ps(s.one_m_b2);
    const __m256 v_eps = _mm256_set1_ps(s.eps);
    const __m256 v_step = _mm256_set1_ps(s.step_size);
    const __m256 v_isbc2 = _mm256_set1_ps(s.inv_sqrt_bc2);
    const __m256 v_wd = _mm256_set1_ps(s.wd);
    const __m256 v_neg_lr_wd = _mm256_set1_ps(-s.lr * s.wd);
    const long long vec_end = n - (n % 8);
#pragma omp parallel for schedule(static)
    for (long long i = 0; i < vec_end; i += 8) {
        __m256 g = _mm256_loadu_ps(grads + i);
        __m256 p = _mm256_loadu_ps(params + i);
        __m256 m = _mm256_loadu_ps(exp_avg + i);
        __m256 v = _mm256_loadu_ps(exp_avg_sq + i);

        if (s.wd > 0.f && !s.adamw) g = _mm256_fmadd_ps(p, v_wd, g);

        m = _mm256_mul_ps(m, v_b1);
        m = _mm256_fmadd_ps(g, v_1mb1, m);
        v = _mm256_mul_ps(v, v_b2);
        v = _mm256_fmadd_ps(_mm256_mul_ps(g, g), v_1mb2, v);

        __m256 denom = _mm256_fmadd_ps(_mm256_sqrt_ps(v), v_isbc2, v_eps);
        __m256 upd = _mm256_div_ps(m, denom);
        if (s.wd > 0.f && s.adamw) p = _mm256_fmadd_ps(p, v_neg_lr_wd, p);
        p = _mm256_fmadd_ps(upd, v_step, p);

        _mm256_storeu_ps(params + i, p);
        _mm256_storeu_ps(exp_avg + i, m);
        _mm256_storeu_ps(exp_avg_sq + i, v);
        if (out_bf16) {
            alignas(32) float tmp[8];
            _mm256_store_ps(tmp, p);
            for (int k = 0; k < 8; ++k) out_bf16[i + k] = f32_to_bf16(tmp[k]);
        }
    }
    step_scalar_range(s, params, grads, exp_avg, exp_avg_sq, vec_end, n,
                      out_bf16);
}

// GCC 12 false positive: _mm512_sqrt_ps's undef passthrough operand
// trips -Wmaybe-uninitialized when inlined under OpenMP
#pragma GCC diagnostic push
#pragma GCC diagnostic ignored "-Wmaybe-uninitialized"
__attribute__((target("avx512f,avx512bw")))
void step_avx512(const StepScalars& s, float* params, const float* grads,
                 float* exp_avg, float* exp_avg_sq, long long n,
                 uint16_t* out_bf16) {
    const __m512 v_b1 = _mm512_set1_ps(s.b1);
    const __m512 v_b2 = _mm512_set1_ps(s.b2);
    const __m512 v_1mb1 = _mm512_set1_ps(s.one_m_b1);
    const __m512 v_1mb2 = _mm512_set1_ps(s.one_m_b2);
    const __m512 v_eps = _mm512_set1_ps(s.eps);
    const __m512 v_step = _mm512_set1_ps(s.step_size);
    const __m512 v_isbc2 = _mm512_set1_ps(s.inv_sqrt_bc2);
    const __m512 v_wd = _mm512_set1_ps(s.wd);
    const __m512 v_neg_lr_wd = _mm512_set1_ps(-s.lr * s.wd);
    const long long vec_end = n - (n % 16);
#pragma omp parallel for schedule(static)
    for (long long i = 0; i < vec_end; i += 16) {
        __m512 g = _mm512_loadu_ps(grads + i);
        __m512 p = _mm512_loadu_ps(params + i);
        __m512 m = _mm512_loadu_ps(exp_avg + i);
        __m512 v = _mm512_loadu_ps(exp_avg_sq + i);

        if (s.wd > 0.f && !s.adamw) g = _mm512_fmadd_ps(p, v_wd, g);

        m = _mm512_mul_ps(m, v_b1);
        m = _mm512_fmadd_ps(g, v_1mb1, m);
        v = _mm512_mul_ps(v, v_b2);
        v = _mm512_fmadd_ps(_mm512_mul_ps(g, g), v_1mb2, v);

        __m512 denom = _mm512_fmadd_ps(_mm512_sqrt_ps(v), v_isbc2, v_eps);
        __m512 upd = _mm512_div_ps(m, denom);
        if (s.wd > 0.f && s.adamw) p = _mm512_fmadd_ps(p, v_neg_lr_wd, p);
        p = _mm512_fmadd_ps(upd, v_step, p);

        _mm512_storeu_ps(params + i, p);
        _mm512_storeu_ps(exp_avg + i, m);
        _mm512_storeu_ps(exp_avg_sq + i, v);
        if (out_bf16) {
            // same RNE+NaN-guard semantics as the scalar path (the bf16
            // output is pinned BIT-EXACT against ml_dtypes by tests)
            alignas(64) float tmp[16];
            _mm512_store_ps(tmp, p);
            for (int k = 0; k < 16; ++k)
                out_bf16[i + k] = f32_to_bf16(tmp[k]);
        }
    }
    step_scalar_range(s, params, grads, exp_avg, exp_avg_sq, vec_end, n,
                      out_bf16);
}
#pragma GCC diagnostic pop

bool cpu_has_avx512() {
    __builtin_cpu_init();
    return __builtin_cpu_supports("avx512f") &&
           __builtin_cpu_supports("avx512bw");
}

bool cpu_has_avx2() {
    __builtin_cpu_init();
    return __builtin_cpu_supports("avx2") && __builtin_cpu_supports("fma");
}
#endif  // DS_X86

}  // namespace

extern "C" {

int ds_adam_create(int id, float alpha, float beta1, float beta2, float eps,
                   float weight_decay, int adamw_mode, int bias_correction) {
    std::lock_guard<std::mutex> lock(reg_mu);
    registry()[id] = AdamConfig{alpha, beta1,         beta2,          eps,
                                weight_decay, adamw_mode, bias_correction};
    return 0;
}

int ds_adam_destroy(int id) {
    std::lock_guard<std::mutex> lock(reg_mu);
    return registry().erase(id) ? 0 : -1;
}

// One Adam step over a flat fp32 leaf. `step` is 1-based. `lr_in` is the
// learning rate to use; pass a NEGATIVE value to fall back to the
// construction-time alpha (0 is a legitimate rate — warmup schedules start
// there). When `out_bf16` is non-null the updated params are also written
// there in bfloat16 (the H2D payload for the TPU copy). Returns 0, or -1
// for an unknown optimizer id.
int ds_adam_step(int id, long long step, float lr_in, float* params,
                 const float* grads, float* exp_avg, float* exp_avg_sq,
                 long long n, uint16_t* out_bf16) {
    AdamConfig cfg;
    {
        std::lock_guard<std::mutex> lock(reg_mu);
        auto it = registry().find(id);
        if (it == registry().end()) return -1;
        cfg = it->second;
    }
    StepScalars s;
    s.lr = (lr_in < 0.f) ? cfg.alpha : lr_in;
    s.b1 = cfg.beta1;
    s.b2 = cfg.beta2;
    s.one_m_b1 = 1.f - s.b1;
    s.one_m_b2 = 1.f - s.b2;
    s.eps = cfg.eps;
    s.wd = cfg.weight_decay;
    s.adamw = cfg.adamw_mode;
    float bc1 = 1.f;
    s.inv_sqrt_bc2 = 1.f;
    if (cfg.bias_correction) {
        bc1 = 1.f - std::pow(s.b1, static_cast<float>(step));
        s.inv_sqrt_bc2 =
            1.f / std::sqrt(1.f - std::pow(s.b2, static_cast<float>(step)));
    }
    s.step_size = -s.lr / bc1;

#if DS_X86
    static const bool use_avx512 = cpu_has_avx512();
    static const bool use_avx2 = cpu_has_avx2();
    if (use_avx512) {
        step_avx512(s, params, grads, exp_avg, exp_avg_sq, n, out_bf16);
        return 0;
    }
    if (use_avx2) {
        step_avx2(s, params, grads, exp_avg, exp_avg_sq, n, out_bf16);
        return 0;
    }
#endif
    step_scalar_range(s, params, grads, exp_avg, exp_avg_sq, 0, n, out_bf16);
    return 0;
}

// simd width actually used at runtime (for tests / introspection)
int ds_adam_simd_width() {
#if DS_X86
    return cpu_has_avx512() ? 16 : (cpu_has_avx2() ? 8 : 1);
#else
    return 1;
#endif
}

}  // extern "C"
