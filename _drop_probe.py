import time, numpy as np
import jax, jax.numpy as jnp
import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, count_params, gpt2_loss_fn, init_gpt2_params
from jax.sharding import NamedSharding, PartitionSpec

def run(embd, attn, resid, steps=8):
    cfg = GPT2Config(vocab_size=50304, max_position_embeddings=1024,
                     hidden_size=1024, num_layers=24, num_heads=16,
                     embd_dropout=embd, attn_dropout=attn, resid_dropout=resid)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    det = (embd == attn == resid == 0.0)
    loss_fn = gpt2_loss_fn(cfg, dtype=jnp.bfloat16, deterministic=det)
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "gradient_accumulation_steps": 1,
                "bf16": {"enabled": True}, "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}}})
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 1025)).astype(np.int32)
    b = {"input_ids": jax.device_put(ids, NamedSharding(engine.mesh, PartitionSpec()))}
    loss = engine.train_batch(iter([b])); np.asarray(loss)
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(iter([b]))
        np.asarray(loss)
        w = (time.perf_counter()-t0)/steps
        best = w if best is None else min(best, w)
    return best*1e3

for name, e, a, r in [("none",0.0,0.0,0.0), ("attn_only",0.0,0.1,0.0),
                      ("resid_only",0.0,0.0,0.1), ("embd_only",0.1,0.0,0.0)]:
    try:
        print(f"{name}: {run(e,a,r):.1f} ms/step", flush=True)
    except Exception as ex:
        print(f"{name}: FAIL {ex!r}", flush=True)
