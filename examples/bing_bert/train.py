"""BERT pretraining workload — "bing_bert" (BASELINE.md ladder item 2;
recreates the reference's DeepSpeedExamples/bing_bert MLM pretraining with
the fused transformer-layer stack).

Synthetic MLM data by default (shape-realistic); swap in a real corpus by
feeding {"input_ids", "attention_mask", "mlm_labels"} batches.

    python examples/bing_bert/train.py --model base|large \
        [--deepspeed_config ds_config.json]
"""

import argparse
import json
import os

import jax

from deepspeed_tpu.utils.platform import apply_platform_env

apply_platform_env()  # honor DSTPU_PLATFORM/DSTPU_HOST_DEVICES (CLI tests)
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models.bert import (BERT_BASE, BERT_LARGE,
                                       bert_mlm_loss_fn, init_bert_params)


def synthetic_mlm_batches(cfg, n, batch_size, seq, mask_prob=0.15, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ids = rng.randint(0, cfg.vocab_size, (batch_size, seq))
        labels = np.full((batch_size, seq), -100, np.int32)
        mask = rng.rand(batch_size, seq) < mask_prob
        labels[mask] = ids[mask]
        ids = ids.copy()
        ids[mask] = 103  # [MASK]
        yield {"input_ids": ids.astype(np.int32),
               "attention_mask": np.ones((batch_size, seq), np.int32),
               "labels": labels}


def main():
    parser = argparse.ArgumentParser()
    ds.add_config_arguments(parser)
    parser.add_argument("--model", choices=["tiny", "base", "large"],
                        default="base")
    parser.add_argument("--mode", choices=["dense", "sp", "sparse"],
                        default="dense",
                        help="sp: sequence-parallel over the 'seq' mesh "
                             "axis; sparse: block-sparse attention from "
                             "the config's sparse_attention section")
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()

    if args.model == "large":
        cfg = BERT_LARGE
    elif args.model == "tiny":  # CPU smoke runs
        cfg = BERT_BASE._replace(vocab_size=2048, hidden_size=128,
                                 num_layers=2, num_heads=2,
                                 intermediate_size=256,
                                 max_position_embeddings=128)
    else:
        cfg = BERT_BASE
    config = args.deepspeed_config or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ds_config.json")
    with open(config) as f:
        config = json.load(f)

    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    if args.mode == "sp":
        from deepspeed_tpu.models.bert import bert_mlm_sp_loss_fn
        from deepspeed_tpu.parallel.mesh import build_mesh
        mesh = build_mesh(config["mesh"]["axes"])
        loss_fn = bert_mlm_sp_loss_fn(cfg, mesh)
    elif args.mode == "sparse":
        # block-sparse attention driven purely by the JSON config (the
        # reference's bing_bert + sparse_attention configuration; its
        # BERT sparse runs used `fixed` sparsity)
        from deepspeed_tpu.ops.sparse_attention import (
            sparsity_config_from_dict)
        from deepspeed_tpu.runtime.config import get_sparse_attention
        # parse first: the JSON schema's defaults (e.g. block=16) and
        # per-mode key filtering live in get_sparse_attention
        sa = get_sparse_attention(config)
        if sa is None:
            raise SystemExit("--mode sparse requires a sparse_attention "
                             "section in the deepspeed config")
        sc = sparsity_config_from_dict(sa, num_heads=cfg.num_heads)
        if args.seq % sc.block:
            raise SystemExit(f"--seq {args.seq} must be a multiple of the "
                             f"sparsity block ({sc.block}); see "
                             "SparseAttentionUtils.pad_to_block_size")
        loss_fn = bert_mlm_loss_fn(cfg, sparsity_config=sc)
    else:
        loss_fn = bert_mlm_loss_fn(cfg)
    engine, _, _, _ = ds.initialize(model=loss_fn, model_parameters=params,
                                    config=config)
    bs = engine.train_batch_size()
    ga = engine.gradient_accumulation_steps
    micro = bs // ga if ga else bs
    data = synthetic_mlm_batches(cfg, args.steps * ga, micro, args.seq)
    for step in range(args.steps):
        loss = engine.train_batch(data)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: mlm loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
