"""Megatron-GPT2 workload (BASELINE.md ladder items 3-4): GPT-2 345M with
ZeRO-2 data parallelism, or GPT-2 with 3D (pipe x data x model) parallelism
via the compiled SPMD pipeline. Recreates the reference's
tests/model/Megatron_GPT2 harness workloads as native examples.

    # 345M + ZeRO-2 (config ds_config_zero2.json)
    python examples/megatron_gpt2/train.py --mode zero2

    # 3D-parallel pipeline (config ds_config_3d.json; needs >=8 devices —
    # on CPU: XLA_FLAGS=--xla_force_host_platform_device_count=8)
    python examples/megatron_gpt2/train.py --mode 3d
"""

import argparse
import json
import os

import jax

from deepspeed_tpu.utils.platform import apply_platform_env

apply_platform_env()  # honor DSTPU_PLATFORM/DSTPU_HOST_DEVICES (CLI tests)
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import (GPT2Config, count_params,
                                       gpt2_loss_fn, gpt2_pipeline_spec,
                                       gpt2_sp_loss_fn, init_gpt2_params)

GPT2_345M = dict(vocab_size=50304, max_position_embeddings=1024,
                 hidden_size=1024, num_layers=24, num_heads=16)
# GPT-2 XL (1.5B): the BASELINE ladder's 3D-parallel / ZeRO-Offload
# scale point (reference megatron tutorial's 1.5B config)
GPT2_XL = dict(vocab_size=50304, max_position_embeddings=1024,
               hidden_size=1600, num_layers=48, num_heads=25)
# 2.1B: the single-chip ZeRO-Offload flagship (reference ZeRO-Offload
# claim: 13B on one 32 GB V100, docs/_posts/2020-09-09-ZeRO-Offload.md
# :10). On a 16 GB v5e the offload recipe — bf16 params in HBM, grads
# as a direct compute-dtype output (no accumulator), fp32 master +
# Adam moments in host RAM, scan_layers + remat — fits 2.1B under the
# CONSERVATIVE compiler memory proof in tests/unit/test_offload_memory
# .py (no buffer-alias credit; with the alias XLA actually performs,
# ~2.5B fits). Heads of 128 (2048/16) keep flash on tuned block shapes.
GPT2_2B = dict(vocab_size=50304, max_position_embeddings=1024,
               hidden_size=2048, num_layers=40, num_heads=16)
GPT2_TINY = dict(vocab_size=512, max_position_embeddings=128,
                 hidden_size=64, num_layers=4, num_heads=4)


def main():
    parser = argparse.ArgumentParser()
    ds.add_config_arguments(parser)
    parser.add_argument("--mode",
                        choices=["zero2", "3d", "sp", "offload", "moe"],
                        default="zero2")
    parser.add_argument("--tiny", action="store_true",
                        help="Tiny model for smoke runs")
    parser.add_argument("--size", choices=["tiny", "345m", "xl", "2b"],
                        default=None,
                        help="model size (xl = GPT-2 1.5B; 2b = the "
                             "single-chip offload flagship; --tiny wins)")
    parser.add_argument("--seq", type=int, default=0)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--save_dir", type=str, default=None,
                        help="save a checkpoint every --save_interval steps")
    parser.add_argument("--save_interval", type=int, default=0)
    parser.add_argument("--load_dir", type=str, default=None,
                        help="resume from the latest checkpoint here")
    parser.add_argument("--generate", type=int, default=0, metavar="N",
                        help="after training, sample N tokens from the "
                             "trained weights (dense zero2/offload modes)")
    args = parser.parse_args()

    here = os.path.dirname(os.path.abspath(__file__))
    config = args.deepspeed_config or os.path.join(
        here, f"ds_config_{args.mode}.json")
    with open(config) as f:
        config = json.load(f)

    sizes = {"tiny": GPT2_TINY, "345m": GPT2_345M, "xl": GPT2_XL,
             "2b": GPT2_2B}
    size = GPT2_TINY if args.tiny else sizes[args.size or "345m"]
    # billion-scale single-chip offload needs the memory recipe:
    # stacked-layer scan (one compiled block) + rematerialized blocks
    big_offload = args.mode == "offload" and \
        (args.size or "") in ("xl", "2b")
    cfg = GPT2Config(embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0,
                     scan_layers=big_offload, **size)
    seq = args.seq or min(cfg.max_position_embeddings, 1024)

    rng = np.random.RandomState(0)
    if big_offload:
        # one micro per boundary: the engine then allocates no grad
        # accumulator at all — grads leave the step as a compute-dtype
        # output (test_offload_memory.py). Pinned BEFORE reading the
        # batch geometry below.
        config = dict(config, gradient_accumulation_steps=1,
                      train_micro_batch_size_per_gpu=1)
    micro = config["train_micro_batch_size_per_gpu"]
    ga = config.get("gradient_accumulation_steps", 1)

    if args.mode == "moe":
        # sparse-FFN scaling: every other block carries a MoE expert bank;
        # experts shard over the 'expert' mesh axis (docs/moe.md)
        from deepspeed_tpu.models.gpt2 import (gpt2_moe_loss_fn,
                                               init_gpt2_moe_params)
        from deepspeed_tpu.ops.moe import MoEConfig
        from deepspeed_tpu.parallel.mesh import build_mesh
        moe_cfg = MoEConfig(hidden_size=cfg.hidden_size,
                            intermediate_size=cfg.inter,
                            num_experts=8, top_k=2)
        params = init_gpt2_moe_params(cfg, moe_cfg, jax.random.PRNGKey(0))
        print(f"params: {count_params(params)/1e6:.0f}M (MoE)")
        mesh = build_mesh(config["mesh"]["axes"])  # == the engine's mesh
        loss_fn = gpt2_moe_loss_fn(cfg, moe_cfg, mesh=mesh,
                                   deterministic=True)
        engine, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                                   config=config)
        bs = engine.train_batch_size() // ga

        def micro_batches():
            while True:
                yield {"input_ids": rng.randint(
                    0, cfg.vocab_size, (bs, seq + 1)).astype(np.int32)}
        it = micro_batches()
    elif args.mode == "sp":
        # sequence/context parallelism: ring attention over the 'seq'
        # mesh axis — each device holds a (B, S/P, H) activation shard
        from deepspeed_tpu.parallel.mesh import build_mesh
        mesh = build_mesh(config["mesh"]["axes"])
        params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
        print(f"params: {count_params(params)/1e6:.0f}M")
        loss_fn = gpt2_sp_loss_fn(cfg, mesh, deterministic=True)
        engine, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                                   config=config)
        bs = micro * config["mesh"]["axes"].get("data", 1)
        seq_par = config["mesh"]["axes"]["seq"]
        assert seq % seq_par == 0, (seq, seq_par)

        def micro_batches():
            while True:
                yield {"input_ids": rng.randint(
                    0, cfg.vocab_size, (bs, seq + 1)).astype(np.int32)}
        it = micro_batches()
    elif args.mode in ("zero2", "offload"):
        # offload: same data path; the config moves the fp32 master state
        # + Adam to host memory (reference ZeRO-Offload: 13B on one GPU —
        # here GPT-2 XL 1.5B trains on one v5e chip: bf16 params + grads
        # in HBM, fp32 master + moments in host RAM, AVX2 host Adam
        # overlapped under the next window's compute)
        params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
        print(f"params: {count_params(params)/1e6:.0f}M")
        loss_fn = gpt2_loss_fn(cfg, deterministic=True, remat=big_offload)
        engine, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                                   config=config)
        bs = engine.train_batch_size() // ga

        def micro_batches():
            while True:
                yield {"input_ids": rng.randint(
                    0, cfg.vocab_size, (bs, seq + 1)).astype(np.int32)}
        it = micro_batches()
    else:
        stages = (config["mesh"]["axes"]["pipe"]
                  * config.get("pipeline", {}).get("virtual_stages", 1))
        spec = gpt2_pipeline_spec(cfg, num_stages=stages)
        engine, *_ = ds.initialize(model=spec, config=config)
        data_par = config["mesh"]["axes"].get("data", 1)
        global_mb = micro * data_par

        def micro_batches():
            while True:
                yield {"input_ids": rng.randint(
                    0, cfg.vocab_size,
                    (global_mb, seq + 1)).astype(np.int32)}
        it = micro_batches()

    start_step = 0
    if args.load_dir:
        path, _ = engine.load_checkpoint(args.load_dir)
        if path is not None:
            start_step = engine.global_steps
            print(f"resumed from {path} at step {start_step}")
            # deterministic data stream: fast-forward past consumed micros
            per_step = getattr(engine, "micro_batches",
                               engine.gradient_accumulation_steps)
            for _ in range(start_step * per_step):
                next(it)

    for step in range(start_step, args.steps):
        loss = engine.train_batch(it)
        print(f"step {step}: lm loss {float(loss):.4f}")
        if args.save_dir and args.save_interval and \
                (step + 1) % args.save_interval == 0:
            engine.save_checkpoint(args.save_dir)

    if args.generate:
        if args.mode not in ("zero2", "offload"):
            print(f"--generate: not supported for --mode {args.mode} "
                  "(dense zero2/offload only); skipping")
        else:
            # sample from the just-trained weights (KV-cache decode);
            # drain any in-flight offloaded host-Adam update first
            engine.synchronize()
            from deepspeed_tpu.models.gpt2 import gpt2_generate
            prompt = rng.randint(0, cfg.vocab_size, (1, 4)).astype(np.int32)
            out = gpt2_generate(engine.module_params, cfg,
                                jax.numpy.asarray(prompt), args.generate,
                                rng=jax.random.PRNGKey(0), temperature=0.9,
                                top_k=40)
            print("sampled:", np.asarray(out)[0].tolist())
    print("done")


if __name__ == "__main__":
    main()
