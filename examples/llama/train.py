"""Llama-family workload (beyond the reference ladder): RoPE + RMSNorm +
SwiGLU decoder with grouped-query attention served natively by the flash
kernels, trained through the engine with ZeRO-2 or tensor parallelism.

    # ZeRO-2 data parallel (config ds_config_zero2.json)
    python examples/llama/train.py --mode zero2

    # data x model tensor parallel (config ds_config_tp.json)
    python examples/llama/train.py --mode tp

    # stacked-layer scan trunk (compiles the block once)
    python examples/llama/train.py --mode zero2 --scan-layers

    # sample from the trained weights (kv_heads-sized KV cache)
    python examples/llama/train.py --mode zero2 --generate 32
"""

import argparse
import json
import os

import jax

from deepspeed_tpu.utils.platform import apply_platform_env

apply_platform_env()  # honor DSTPU_PLATFORM/DSTPU_HOST_DEVICES (CLI tests)
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models.llama import (LlamaConfig, count_params,
                                        init_llama_params, llama_generate,
                                        llama_loss_fn, llama_param_specs)

# ~1B-class config (llama-style ratios, GQA 4:1)
LLAMA_1B = dict(vocab_size=32128, hidden_size=2048, num_layers=16,
                num_heads=32, num_kv_heads=8,
                max_position_embeddings=2048)
LLAMA_TINY = dict(vocab_size=512, hidden_size=64, num_layers=4,
                  num_heads=4, num_kv_heads=2,
                  max_position_embeddings=128)


def main():
    parser = argparse.ArgumentParser()
    ds.add_config_arguments(parser)
    parser.add_argument("--mode", choices=["zero2", "tp"], default="zero2")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--scan-layers", action="store_true",
                        help="stacked layers + lax.scan trunk "
                             "(~num_layers x faster first compile)")
    parser.add_argument("--seq", type=int, default=0)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--generate", type=int, default=0, metavar="N")
    args = parser.parse_args()

    here = os.path.dirname(os.path.abspath(__file__))
    config = args.deepspeed_config or os.path.join(
        here, f"ds_config_{args.mode}.json")
    with open(config) as f:
        config = json.load(f)

    size = LLAMA_TINY if args.tiny else LLAMA_1B
    cfg = LlamaConfig(scan_layers=args.scan_layers, **size)
    seq = args.seq or min(cfg.max_position_embeddings, 1024)

    params = init_llama_params(cfg, jax.random.PRNGKey(0))
    print(f"params: {count_params(params)/1e6:.0f}M "
          f"(GQA {cfg.num_heads}q:{cfg.kv_heads}kv)")
    loss_fn = llama_loss_fn(cfg)
    specs = llama_param_specs(cfg) if args.mode == "tp" else None
    engine, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                               param_specs=specs, config=config)

    rng = np.random.RandomState(0)
    ga = config.get("gradient_accumulation_steps", 1)
    bs = engine.train_batch_size() // ga

    def micro_batches():
        while True:
            yield {"input_ids": rng.randint(
                0, cfg.vocab_size, (bs, seq + 1)).astype(np.int32)}

    it = micro_batches()
    for step in range(args.steps):
        loss = engine.train_batch(it)
        if step == 0 or (step + 1) % 5 == 0:
            print(f"step {step + 1}: loss {float(np.asarray(loss)):.4f}")
    print(f"final loss: {float(np.asarray(loss)):.4f}")

    if args.generate > 0:
        prompt = rng.randint(0, cfg.vocab_size, (1, 8)).astype(np.int32)
        out = llama_generate(engine.module_params, cfg,
                             jax.numpy.asarray(prompt), args.generate,
                             rng=jax.random.PRNGKey(7), temperature=0.8,
                             top_k=40)
        print("generated:", np.asarray(out)[0, 8:].tolist())


if __name__ == "__main__":
    main()
