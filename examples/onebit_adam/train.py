"""BERT fine-tune with 1-bit Adam (BASELINE.md ladder item 5): the
communication-compressed optimizer switches from dense warmup to 1-bit
compressed momentum exchange at freeze_step, cutting data-parallel traffic
~32x per phase-1 leg (recreates the reference's
DeepSpeedExamples/onebit_adam BingBertSQuAD workload shape).

    python examples/onebit_adam/train.py
"""

import argparse
import json
import os

import jax

from deepspeed_tpu.utils.platform import apply_platform_env

apply_platform_env()  # honor DSTPU_PLATFORM/DSTPU_HOST_DEVICES (CLI tests)
import numpy as np

import deepspeed_tpu as ds
from deepspeed_tpu.models.bert import (BertConfig, bert_mlm_loss_fn,
                                       init_bert_params)


def main():
    parser = argparse.ArgumentParser()
    ds.add_config_arguments(parser)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--steps", type=int, default=16)
    args = parser.parse_args()

    config = args.deepspeed_config or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ds_config.json")
    with open(config) as f:
        config = json.load(f)

    cfg = BertConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                     num_heads=4, intermediate_size=1024,
                     max_position_embeddings=512)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    loss_fn = bert_mlm_loss_fn(cfg, deterministic=True)
    engine, opt, _, _ = ds.initialize(model=loss_fn,
                                      model_parameters=params,
                                      config=config)
    print(f"1-bit Adam: freeze_step={opt.freeze_step} "
          f"distributed={engine._onebit_dist}")

    rng = np.random.RandomState(0)
    bs = engine.train_batch_size()
    for step in range(args.steps):
        ids = rng.randint(0, cfg.vocab_size, (bs, args.seq))
        labels = np.full_like(ids, -100)
        m = rng.rand(*ids.shape) < 0.15
        labels[m] = ids[m]
        batch = {"input_ids": ids.astype(np.int32),
                 "labels": labels.astype(np.int32)}
        loss = engine.train_batch(iter([batch]))
        phase = "compressed" if engine._onebit_compression else "warmup"
        print(f"step {step} [{phase}]: loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
