"""CIFAR-style tiny-CNN smoke workload (BASELINE.md workload ladder item 1;
recreates the absent DeepSpeedExamples/cifar tutorial for this framework).

Runs on anything — CPU mesh, one TPU chip, or a pod — in seconds. Uses a
synthetic CIFAR-shaped dataset so no download is needed; swap in real data
by passing any iterable of {"x": (B,32,32,3), "y": (B,)} batches.

    python examples/cifar/train.py [--deepspeed_config ds_config.json]
"""

import argparse
import json
import os

import jax

from deepspeed_tpu.utils.platform import apply_platform_env

apply_platform_env()  # honor DSTPU_PLATFORM/DSTPU_HOST_DEVICES (CLI tests)
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds


def init_params(key):
    k = jax.random.split(key, 4)
    glorot = jax.nn.initializers.glorot_normal()
    return {
        "conv1": {"w": glorot(k[0], (3, 3, 3, 32)),
                  "b": jnp.zeros((32,))},
        "conv2": {"w": glorot(k[1], (3, 3, 32, 64)),
                  "b": jnp.zeros((64,))},
        "fc1": {"w": glorot(k[2], (64 * 8 * 8, 256)),
                "b": jnp.zeros((256,))},
        "fc2": {"w": glorot(k[3], (256, 10)), "b": jnp.zeros((10,))},
    }


def _conv_block(p, x):
    x = jax.lax.conv_general_dilated(
        x, p["w"], (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["b"]
    x = jax.nn.relu(x)
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")


def loss_fn(params, batch, rng):
    x = batch["x"].astype(jnp.float32)
    x = _conv_block(params["conv1"], x)
    x = _conv_block(params["conv2"], x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
    logits = x @ params["fc2"]["w"] + params["fc2"]["b"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, batch["y"][:, None], 1))


def synthetic_batches(n, batch_size, seed=0):
    rng = np.random.RandomState(seed)
    protos = rng.randn(10, 32, 32, 3).astype(np.float32)
    for _ in range(n):
        y = rng.randint(0, 10, batch_size)
        x = protos[y] + 0.3 * rng.randn(batch_size, 32, 32, 3)
        yield {"x": x.astype(np.float32), "y": y.astype(np.int32)}


def main():
    parser = argparse.ArgumentParser()
    ds.add_config_arguments(parser)
    parser.add_argument("--steps", type=int, default=30)
    args = parser.parse_args()

    config = args.deepspeed_config or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "ds_config.json")
    with open(config) as f:
        config = json.load(f)

    params = init_params(jax.random.PRNGKey(0))
    engine, _, _, _ = ds.initialize(model=loss_fn, model_parameters=params,
                                    config=config)
    bs = engine.train_batch_size()
    for step, batch in enumerate(synthetic_batches(args.steps, bs)):
        loss = engine.train_batch(iter([batch]))
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
