#!/bin/bash
cd /root/repo
for i in $(seq 1 40); do
  if timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
np.asarray(jax.jit(lambda: jnp.ones(1))())
print('TPU_UP')
" 2>/dev/null | grep -q TPU_UP; then
    echo "TPU back at attempt $i: $(date)"
    timeout 2400 python _profile_attn.py > /tmp/profile_attn.log 2>&1
    echo "profile done rc=$?"
    timeout 2400 python bench.py > /tmp/bench3.log 2>&1
    echo "bench done rc=$?"
    exit 0
  fi
  sleep 240
done
echo "TPU never returned"
exit 1
