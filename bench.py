"""Benchmark ladder: JSON rows on stdout, headline LAST.

Rows are streamed the moment they complete AND re-emitted at the end in
canonical order (headline last), so a metric may appear twice —
consumers key on metric name and take the LAST occurrence. The final
line is always the headline (value row or explicit error row).

Metrics (BASELINE.md rows):
- comm_wire_bytes_per_step : HARDWARE-FREE — per-rank wire bytes of the
  qgZ two-hop quantized gradient allreduce at W=8 for a 1M-element
  gradient, counted from the partitioned HLO on a forced 8-device CPU
  mesh (same accounting as tests/unit/test_hlo_quantized_comm.py);
  vs_baseline = quantized / dense-bf16-ring ratio (acceptance: <= 0.6)
- comm_overlap_structure : HARDWARE-FREE — structural compute/comm
  overlap of the comm_autotune fused step: fraction of grad-exchange
  collectives in the scan body whose operand cone is dot-general-free
  (data-independent of the iteration's compute -> schedulable under it;
  serial = 0, overlapped = 1), counted from the partitioned HLO on the
  forced 8-device CPU mesh; vs_baseline = modeled overlapped/serial
  step time from the comm_autotune cost model
- mfu_cost_model : HARDWARE-FREE — XLA cost-analysis FLOPs/token of the
  compiled GPT-2 micro-step (the same record the observability layer's
  flops profiler writes per run), on the forced 8-device CPU mesh;
  vs_baseline = cost-model / analytic (6N + 12LSH) FLOPs ratio — a
  drift guard on the MFU accounting both bench rows and per-run MFU
  telemetry rely on
- host_dispatch_overhead : HARDWARE-FREE — compiled-program dispatches
  and forced host syncs per train_batch at gas=4, counted by the
  observability CompileTracker on the forced 8-device CPU mesh — pins
  the async-pipeline contract (1 fused dispatch/step, 0 steady-state
  syncs); vs_baseline = fused dispatches / the per-micro loop's gas
- decode_throughput : HARDWARE-FREE — serving tokens/s of the inference
  engine's bucketed KV-cache decode on a tiny GPT-2 (CPU), after bucket
  warmup; pins the serving contract of 0 steady-state recompiles (the
  CompileTracker count is in detail and must be 0); vs_baseline =
  cached decode tokens/s / a no-cache full-forward-per-token loop at
  the same batch size (isolates the KV-cache payoff from batching)
- paged_kv_occupancy : HARDWARE-FREE — serving-capacity payoff of the
  paged KV cache on a mixed-length workload at EQUAL cache HBM budget:
  value = peak live tokens in flight per cache KiB for the paged
  engine, vs_baseline = that density / the dense slot x max_len
  engine's (acceptance: >= 2x); detail carries both engines' decode
  tokens/s, peak concurrency, prefix hit rate, and the paged engine's
  0-steady-state-recompile pin under the mixed-length churn
- paged_decode_bytes : HARDWARE-FREE — serving-BANDWIDTH payoff of the
  fused Pallas paged-decode kernel (ops/attention/paged.py): the
  compiled pallas decode program is audited gather-free (no
  max_len-sized stripe materialization; the gather fallback's program
  shows the per-layer stripe gather as the contrast), and a bytes-read
  cost model (live pages streamed vs the full table-width stripe, the
  mfu_cost_model pattern) prices the mixed-length reference workload:
  value = modeled pallas KiB/decode-step, vs_baseline = stripe bytes /
  pallas bytes (ISSUE 8 acceptance: >= 2x reduction)
- masked_flash_flops_bytes : HARDWARE-FREE — mask-proportional work of
  the ONE unified flash kernel (ops/attention/masked_flash.py): cost-
  model FLOPs and K/V stream bytes for a dense BlockMask vs the BigBird
  reference layout at S=8192 (H=16, D=64, fine block 128), structurally
  pinned against the CSR metadata the kernel walks and a small
  interpret-mode oracle run; value = modeled BigBird K/V KiB/fwd,
  vs_baseline = dense/BigBird K/V bytes (ISSUE 11 acceptance: >= 2.5x,
  BigBird <= 40% of dense bytes in detail)
- sparse_attn_speedup_v2 : TPU — the r01 1.066x sparse config
  (BSLongformer block=128 win=3 @ S=8192) re-measured through the
  UNIFIED masked kernel (banded structure walks coarse MXU tiles,
  fine bits in register predicates); sparse_attention_speedup_s8k now
  pins the LEGACY dispatch at the same geometry, so the pair A/Bs the
  kernels on hardware (next window)
- serve_trace_overhead : HARDWARE-FREE — cost of the request-granular
  serving observability plane (inference/tracing.py): the identical
  mixed-length continuous-batching workload runs with tracing OFF and
  with tracing ON at the DEFAULT config (full lifecycle trail into
  events.jsonl, per-token TBT sampling, decode-window rows at the
  default 1/16 stride), both engines carrying the baseline event log;
  the compiled program set and per-step dispatch counts must be
  IDENTICAL (tracing is host-side by construction, so with equal
  dispatches any wall delta IS host gap), steady-state recompiles 0
  for both, greedy outputs bitwise equal; value = wall-clock overhead
  percent (min-of-5 interleaved runs), acceptance <= 5%;
  vs_baseline = traced tokens/s / untraced tokens/s
- async_ckpt_stall_ms : HARDWARE-FREE — step-loop stall per global batch
  when a checkpoint save rides every step, async (snapshot-and-return,
  background writer commits) vs blocking, at EQUAL checkpoint size on
  the forced 8-device CPU mesh: value = async stall ms/step (loop wall
  minus a no-save baseline), vs_baseline = async stall / blocking stall
  (ISSUE 10 acceptance: <= 0.20); detail pins dispatches/train_batch
  unchanged at 1.0 for both modes and the newest async tag
  COMMITTED+VERIFIED after the drain
- spec_decode_accepted_per_dispatch : HARDWARE-FREE — speculative
  multi-token decoding on the paged pool (ISSUE 13): a repetitive
  workload (prompts the host-side n-gram drafter can actually predict)
  runs spec OFF vs spec ON at the same config/seed; value = verified-
  and-kept tokens emitted per decode-phase dispatch with speculation
  (acceptance >= 2.0), vs_baseline = spec dispatches / baseline
  dispatches (< 1.0 — fewer device round-trips for the same tokens);
  pins greedy outputs bitwise equal and 0 steady-state recompiles for
  both engines
- disagg_dispatch_structure : HARDWARE-FREE — the disaggregated
  prefill/decode step discipline as pure dispatch ordering: a workload
  submitted in waves (so prefill and decode phases mix within single
  steps) must show every decode/verify dispatch preceding every
  prefill dispatch of its step; value = decode_first_fraction
  (acceptance == 1.0), pins greedy parity vs the interleaved engine,
  0 recompiles, and TTFT queue/prefill/handoff decomposition in the
  trail
- quant_serving_bytes : HARDWARE-FREE — serving-HBM payoff of int8
  quantization on BOTH byte levers (ISSUE 17), pure accounting vs bf16
  at the same geometry: value = bf16/int8 KV pool byte ratio
  (per-token-row fp32 scales included), vs_baseline = bf16/int8
  resident weight byte ratio (qwZ block 256, 1-D leaves dense);
  detail cross-checks the pool ratio against the decode_read_bytes
  cost model on the mixed-length workload (acceptance: both >= 1.8x)
- quant_kv_occupancy : HARDWARE-FREE — serving-capacity payoff of the
  int8 KV pool: the paged_kv_occupancy experiment with pool dtype as
  the only variable; value = int8 engine's peak live tokens in flight
  per cache KiB, vs_baseline = that density / the bf16 pool engine's;
  pins 0 steady-state recompiles for both and carries greedy
  agreement + decode tokens/s
- paged_decode_tokens_per_s : TPU — wall-clock decode tokens/s of the
  serving engine with the compiled Pallas paged-decode kernel at a
  TPU-legal geometry (head_dim 128), vs_baseline = pallas tokens/s /
  the gather-fallback engine's at identical config; pins
  0 steady-state recompiles for both (next hardware window)
- quant_decode_tokens_per_s : TPU — wall-clock decode tokens/s of the
  FULLY quantized engine (int8-resident weights + int8 KV pool,
  dequant in-program/in-kernel) vs the unquantized engine at identical
  config; decode is KV-bandwidth-bound so the halved pool bytes should
  price into tokens/s on hardware; functional pin off-TPU (next
  hardware window)
- disagg_ttft_p95 : TPU — p95 TTFT of the disaggregated engine
  (decode-first step order, handoff queue between the phases) vs the
  interleaved engine under the same open-loop load; on a non-TPU
  backend it is a functional pin, not a perf number (next hardware
  window)
- bert_large_samples_per_s : BERT-large fused-layer training @ seq 128
  (reference: 272 samples/s on 1x V100, fastest-bert post :38-40)
- bert_onebit_samples_per_s : BERT + 1-bit Adam in the compression
  phase vs plain Adam at the same geometry (BASELINE.md ladder item 5;
  vs_baseline = onebit/adam throughput, the single-chip compression
  tax — the wire saving is pinned by the HLO audit)
- sparse_attention_speedup_s8k : block-sparse vs dense O(S^2) softmax
  attention fwd+bwd wall time @ S=8192 — the baseline the reference's
  6.3x claim uses (sparse-attention post :28-33); the unit string names
  the baseline actually measured (vanilla, or flash if the O(S^2)
  buffers don't fit), and detail.vs_flash carries the tougher
  sparse-vs-our-own-flash ratio
- gpt2_train_mfu_dropout : the 345M step with the realistic pretraining
  config (attn/resid/embd dropout 0.1 — exercises the in-kernel Pallas
  dropout path)
- gpt2_train_mfu : the headline — Megatron-GPT2 345M + ZeRO-2, bf16,
  printed last (reference hardware-efficiency headline: 52% of peak)

Architecture (tunnel-hardened): the parent process NEVER touches the
device. Each metric runs in its own child subprocess
(`bench.py --metric NAME`) with a wall-clock timeout; a dead tunnel
hangs (and then kills) one child, not the whole ladder. Completed rows
are checkpointed to a commit-keyed partial file so a re-run resumes
instead of repeating, and each failed metric is retried after a tunnel
liveness probe. A flaky tunnel therefore yields N good rows + an error
row for the metric that died — never a single error line.

Timing protocol (inside each child): value-fetch completion barrier +
RTT subtraction, because block_until_ready acks early across the device
tunnel (see .claude/skills/verify/SKILL.md).

MFU accounting: model flops/token = 6*N + 12*L*S*H (PaLM appendix formula);
peak = 197 TFLOP/s bf16 (TPU v5e).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

_EMIT_LOCK = threading.Lock()

# Canonical ladder order; headline last (the driver reads the final line).
# comm_wire_bytes_per_step is HARDWARE-FREE (compiled-HLO accounting on a
# virtual CPU mesh) and runs first: it lands even when the tunnel is dead.
METRICS = [
    "comm_wire_bytes_per_step",
    "comm_overlap_structure",
    "mfu_cost_model",
    "host_dispatch_overhead",
    "decode_throughput",
    "paged_kv_occupancy",
    "paged_decode_bytes",
    "masked_flash_flops_bytes",
    "serve_trace_overhead",
    "health_overhead",
    "async_ckpt_stall_ms",
    "spec_decode_accepted_per_dispatch",
    "disagg_dispatch_structure",
    "chunked_prefill_tbt",
    "fleet_drain_goodput",
    "fleet_migration_goodput",
    "fleet_trace_overhead",
    "quant_serving_bytes",
    "quant_kv_occupancy",
    "paged_decode_tokens_per_s",
    "quant_decode_tokens_per_s",
    "disagg_ttft_p95",
    "long_prompt_prefill_tokens_per_s",
    "bert_large_samples_per_s",
    "bert_onebit_samples_per_s",
    "sparse_attention_speedup_s8k",
    "sparse_attn_speedup_v2",
    "gpt2_train_mfu_dropout",
    "gpt2_train_mfu",
]
HEADLINE = "gpt2_train_mfu"
# metrics that never touch the device tunnel: forced onto a virtual
# 8-device CPU mesh in their child, runnable with the tunnel down
HW_FREE = {"comm_wire_bytes_per_step", "comm_overlap_structure",
           "mfu_cost_model", "host_dispatch_overhead",
           "decode_throughput", "paged_kv_occupancy",
           "paged_decode_bytes", "masked_flash_flops_bytes",
           "serve_trace_overhead", "health_overhead",
           "async_ckpt_stall_ms",
           "spec_decode_accepted_per_dispatch",
           "disagg_dispatch_structure", "chunked_prefill_tbt",
           "fleet_drain_goodput",
           "fleet_migration_goodput", "fleet_trace_overhead",
           "quant_serving_bytes", "quant_kv_occupancy"}

PARTIAL_PATH = os.environ.get(
    "BENCH_PARTIAL", "/tmp/dstpu_bench_partial.jsonl")
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") and \
        "BENCH_PARTIAL" not in os.environ:
    # forced-CPU smoke runs must not clobber the TPU ladder's checkpoint
    # (a CPU parent run once overwrote the hardware rows the stale-
    # pointer audit trail depends on)
    PARTIAL_PATH += ".cpu"
# First metric in a cold child pays remote compile time; give headroom.
METRIC_TIMEOUT = int(os.environ.get("BENCH_METRIC_TIMEOUT", "1500"))
METRIC_RETRIES = int(os.environ.get("BENCH_METRIC_RETRIES", "1"))
# Hardware-free rows compile tiny programs on the CPU backend — a much
# tighter per-row budget than the tunnel rows, so the rows that CAN
# always land do so early (the BENCH_r05 rc=124 empty-tail fix: two
# hw-free children at the full 1500s each could eat the driver's whole
# window before a single row printed).
HW_FREE_TIMEOUT = int(os.environ.get("BENCH_HW_FREE_TIMEOUT", "300"))
# Overall ladder wall-clock budget: when it runs out, remaining metrics
# become explicit error rows IMMEDIATELY and the ladder finishes with
# the headline line — completed rows are never lost to an outer
# timeout's SIGKILL. 0 disables the budget.
TIME_BUDGET = int(os.environ.get("BENCH_TIME_BUDGET", "840"))
_T_START = time.monotonic()


def _remaining_budget():
    """Seconds left in the ladder budget, or None when unbudgeted."""
    if TIME_BUDGET <= 0:
        return None
    return TIME_BUDGET - (time.monotonic() - _T_START)


def _budget_exhausted(floor=45):
    rem = _remaining_budget()
    return rem is not None and rem < floor
# Child stall watchdog: a fresh remote model compile through the tunnel
# can exceed 15 min with no heartbeat (the first train_batch call IS the
# compile), so the stall budget tracks the per-metric budget rather than
# racing it.  Control knob: excluded from the source digest (see
# _git_head's control set).
STALL_TIMEOUT = int(os.environ.get(
    "BENCH_STALL_TIMEOUT", str(max(900, METRIC_TIMEOUT - 120))))


def _apply_platform_override(jax):
    """Honor JAX_PLATFORMS even though sitecustomize preloads jax (and the
    axon TPU plugin) before env vars are read — same workaround as
    tests/conftest.py. Without this, JAX_PLATFORMS=cpu still initializes
    the tunnel backend, which HANGS when the tunnel is down."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)


# Stall-watchdog heartbeat, shared with the child watchdog in run_child:
# long remote compiles inside the scan-timing protocol beat this so a
# slow-but-alive tunnel is not mistaken for a dead one.
_BEAT = [time.monotonic()]
# health-plane black box (utils/health.py FlightRecorder), armed by
# run_child: every _beat() lands a ring row, and the child watchdog
# dumps the ring + all-thread stacks to _flight_path() on a stall so
# the parent can salvage a postmortem instead of an empty tail
_FLIGHT = [None]


def _beat():
    _BEAT[0] = time.monotonic()
    if _FLIGHT[0] is not None:
        _FLIGHT[0].record({"event": "bench_beat",
                           "t_mono": round(time.monotonic(), 3)})


def _flight_path(metric):
    """Where the child's black box lands — deterministic per metric so
    the parent knows where to look after a kill. Control knob: excluded
    from the source digest (see _git_head's control set)."""
    return os.environ.get("BENCH_FLIGHT_PATH",
                          f"/tmp/dstpu_bench_flight_{metric}.json")


def _rtt():
    from deepspeed_tpu.utils.benchtime import measure_rtt
    return measure_rtt()


def _emit_row(row):
    with _EMIT_LOCK:
        print(json.dumps(row), flush=True)


def _emit(metric, value, unit, vs_baseline, detail):
    row = {"metric": metric, "value": value, "unit": unit,
           "vs_baseline": vs_baseline, "detail": detail}
    _emit_row(row)
    return row


def _hbm_peak_mb():
    """Child-process-wide device peak memory, recorded by each metric
    function AFTER its measurements (the device is known alive there —
    _emit itself must stay device-free: it also serves the dead-tunnel
    error paths, where a memory_stats() call would hang in C++ past
    every watchdog). Each metric runs in its own subprocess, so this is
    the peak across everything that row measured (for the sparse row:
    incl. its vanilla/flash baselines and the S=16k detail)."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**20, 1) if peak else None
    except Exception:
        return None


# ---------------------------------------------------------------- metrics


def bench_bert_large(on_tpu, rtt):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.bert import (BERT_LARGE, BertConfig,
                                           bert_mlm_loss_fn,
                                           init_bert_params)

    if on_tpu:
        cfg, batch, seq, steps = BERT_LARGE, 32, 128, 10
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128,
                         max_position_embeddings=128)
        batch, seq, steps = 4, 32, 2
    # BENCH_SCAN_LAYERS=1: stacked-layer scan trunk — ~num_layers x less
    # to compile (A/B knob for flaky-tunnel windows; throughput parity
    # should be confirmed on hardware before making it the default)
    if os.environ.get("BENCH_SCAN_LAYERS", "0") == "1":
        cfg = cfg._replace(scan_layers=True)

    n_dev = jax.device_count()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    # realistic pretraining config: dropout ON (cfg defaults 0.1)
    loss_fn = bert_mlm_loss_fn(cfg, deterministic=False)
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": max(batch // n_dev, 1),
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "steps_per_print": 10**9,
            "zero_optimization": {"stage": 2 if n_dev > 1 else 0},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        })

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.where(rng.rand(batch, seq) < 0.15, ids, -100).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec
    shd = NamedSharding(engine.mesh,
                        PartitionSpec("data" if n_dev > 1 else None))
    b = {"input_ids": jax.device_put(ids, shd),
         "labels": jax.device_put(labels, shd)}

    loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9)
    sps = batch * steps / dt
    return _emit("bert_large_samples_per_s", round(sps / max(n_dev, 1), 2),
                 "samples_per_s_per_chip", round(sps / max(n_dev, 1) / 272.0, 4),
                 {"seq": seq, "batch": batch, "dropout": 0.1,
                  "step_ms": round(dt / steps * 1000, 2), "loss": float(loss),
                  "hbm_peak_mb_child": _hbm_peak_mb()})


def bench_bert_onebit(on_tpu, rtt):
    """BERT + 1-bit Adam, compression phase (BASELINE.md ladder item 5;
    reference claim: <=5x comm reduction, 3.5x e2e on 40GbE clusters —
    onebit-adam-blog-post.md:85,135). A single chip cannot show the
    cluster speedup, so this row measures the COMPRESSION TAX: 1-bit
    samples/s vs plain-Adam samples/s at the same geometry
    (vs_baseline = onebit/adam; 1.0 = compression is free). The wire
    saving itself is pinned backend-invariantly by
    test_hlo_collectives.py::test_onebit_adam_compressed_wire_traffic
    (compressed exchange <= 1/5 of the dense exchange in elements,
    1/32 in payload bytes)."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.bert import (BERT_LARGE, BertConfig,
                                           bert_mlm_loss_fn,
                                           init_bert_params)

    if on_tpu:
        cfg, batch, seq, steps = BERT_LARGE, 32, 128, 10
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=2, intermediate_size=128,
                         max_position_embeddings=128)
        batch, seq, steps = 4, 32, 2
    if os.environ.get("BENCH_SCAN_LAYERS", "0") == "1":
        cfg = cfg._replace(scan_layers=True)
    n_dev = jax.device_count()
    warm = 2  # freeze_step: warmup optimizer steps before compression

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.where(rng.rand(batch, seq) < 0.15, ids, -100).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec
    shd_spec = PartitionSpec("data" if n_dev > 1 else None)

    def make_engine(opt):
        params = init_bert_params(cfg, jax.random.PRNGKey(0))
        loss_fn = bert_mlm_loss_fn(cfg, deterministic=False)
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=params,
            config={
                "train_micro_batch_size_per_gpu": max(batch // n_dev, 1),
                "gradient_accumulation_steps": 1,
                "bf16": {"enabled": True},
                "steps_per_print": 10**9,
                # OnebitAdam requires ZeRO stage 0 (reference
                # is_zero_supported_optimizer); keep Adam comparable
                "zero_optimization": {"stage": 0},
                "optimizer": opt,
            })
        shd = NamedSharding(engine.mesh, shd_spec)
        b = {"input_ids": jax.device_put(ids, shd),
             "labels": jax.device_put(labels, shd)}
        return engine, b

    def timed_sps(engine, b, n):
        loss = engine.train_batch(iter([b]))
        np.asarray(loss)                       # compile + settle
        t0 = time.perf_counter()
        for _ in range(n):
            loss = engine.train_batch(iter([b]))
        np.asarray(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        return batch * n / dt, float(loss)

    # -- 1-bit engine: run past freeze_step so the timed window is the
    # compression phase (the phase switch recompiles once)
    engine1, b1 = make_engine(
        {"type": "OneBitAdam",
         "params": {"lr": 1e-4, "freeze_step": warm}})
    for _ in range(warm + 1):                  # cross the phase boundary
        engine1.train_batch(iter([b1]))
    assert engine1._onebit_compression, "compression phase not reached"
    sps1, loss1 = timed_sps(engine1, b1, steps)
    distributed = bool(engine1._onebit_dist)
    # free the 1-bit engine's full state (params + master + moments +
    # error feedback) before the Adam engine allocates its own — the
    # row must not need 2x one configuration's HBM
    del engine1, b1
    _beat()

    # -- plain-Adam reference at the same geometry
    engine0, b0 = make_engine(
        {"type": "Adam", "params": {"lr": 1e-4}})
    sps0, _loss0 = timed_sps(engine0, b0, steps)

    return _emit("bert_onebit_samples_per_s",
                 round(sps1 / max(n_dev, 1), 2), "samples_per_s_per_chip",
                 round(sps1 / sps0, 4),
                 {"seq": seq, "batch": batch, "freeze_step": warm,
                  "phase": "compression",
                  "distributed": distributed,
                  "adam_samples_per_s_per_chip":
                      round(sps0 / max(n_dev, 1), 2),
                  "compression_tax": round(1.0 - sps1 / sps0, 4),
                  "loss": loss1,
                  "hbm_peak_mb_child": _hbm_peak_mb()})


def _sparse_row_geometry(on_tpu):
    """Shared r01 geometry + scan length for the TWO sparse ladder rows
    (sparse_attention_speedup_s8k = legacy dispatch,
    sparse_attn_speedup_v2 = unified kernel): the pair A/Bs the kernels
    directly, so config and timing protocol MUST stay identical — one
    definition, consumed by both."""
    if on_tpu:
        return 1, 16, 8192, 64, 32, 128, 3    # B, H, S, D, iters, block, win
    return 1, 2, 256, 16, 2, 16, 3


def _sparse_vanilla_loss(S):
    """The reference-methodology dense baseline (materialized O(S^2)
    causal softmax, bf16) both sparse rows measure against."""
    import jax
    import jax.numpy as jnp

    def vanilla_loss(q, k, v):
        sm = q.shape[-1] ** -0.5
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
        idx = jnp.arange(S)
        s_ = jnp.where(idx[:, None] >= idx[None, :], s_, -1e30)
        p = jax.nn.softmax(s_, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        return jnp.sum(o.astype(jnp.float32))
    return vanilla_loss


def _sparse_scan_timed(fn, args, rtt, iters):
    """Shared scan-amortized fwd+bwd timing (utils/benchtime.py) for
    the sparse ladder rows — chained grad evals in ONE dispatch."""
    import jax
    from deepspeed_tpu.utils.benchtime import scan_grad_seconds
    sec, _n = scan_grad_seconds(jax.grad(fn, argnums=(0, 1, 2)), args,
                                rtt, start_len=iters, beat=_beat)
    return sec


def bench_sparse_attention(on_tpu, rtt):
    # Pin the LEGACY dispatch (pre-PR-11 flash + banded/hybrid/v2
    # kernels) so this row stays comparable with the r01..r05 ladder
    # history; the unified masked kernel measures through its own row
    # (sparse_attn_speedup_v2) at the identical geometry.
    from deepspeed_tpu.ops.attention import flash as _Fo
    from deepspeed_tpu.ops.sparse_attention import blocksparse as _bso
    old_masked = _bso.USE_MASKED_FLASH
    # an explicit BENCH_REF_ATTN=1 "reference" request must survive the
    # pin (ADVICE r3 #2: never misattribute the dense baseline) — only
    # the masked default is re-routed to the legacy kernels
    pin = ("flash" if _Fo.get_attention_options().kernel == "masked"
           else _Fo.get_attention_options().kernel)
    old_opts = _Fo.set_attention_options(kernel=pin)
    _bso.USE_MASKED_FLASH = False
    _bso._FN_CACHE.clear()
    try:
        return _bench_sparse_attention_legacy(on_tpu, rtt)
    finally:
        _bso.USE_MASKED_FLASH = old_masked
        _Fo._OPTIONS = old_opts
        _bso._FN_CACHE.clear()


def _bench_sparse_attention_legacy(on_tpu, rtt):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention.flash import flash_attention
    from deepspeed_tpu.ops.sparse_attention import (
        SparseSelfAttention, BSLongformerSparsityConfig)

    # S=8192 with both kernels DMA-streaming; the O(S) Longformer
    # layout is where block-sparse pulls ahead, and the gap widens
    # at S=16k/32k where dense pays the full O(S^2) compute (the
    # reference's 10x-longer-sequences claim). win=3 is the
    # BSLongformer class default on both sides (reference
    # sparsity_config.py:556) — 384-token window, 4.7% density at
    # S=8192; the reference's 6.3x was measured at comparable or
    # lower density (its default block=16 window is 48 tokens).
    B, H, S, D, iters, block, win = _sparse_row_geometry(on_tpu)

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                 jnp.bfloat16) for i in range(3))
    sp = SparseSelfAttention(BSLongformerSparsityConfig(
        num_heads=H, block=block, num_sliding_window_blocks=win))

    def dense_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    def sparse_loss(q, k, v):
        return jnp.sum(sp(q, k, v).astype(jnp.float32))

    def timed(fn, arrays=None, start_len=None):
        # Scan-amortized timing (_sparse_scan_timed): chained grad
        # evals in ONE dispatch, scalar result.  A per-call loop pays
        # the tunnel's per-dispatch latency AND eagerly transfers 48MB
        # of gradients per call — at S=8192 that measured ~870ms/call
        # for a kernel whose device time is ~10ms.
        return _sparse_scan_timed(
            fn, (q, k, v) if arrays is None else arrays, rtt,
            iters if start_len is None else start_len)

    from deepspeed_tpu.utils.benchtime import NoiseFloorError
    t_dense = timed(dense_loss)
    try:
        t_sparse = timed(sparse_loss)
        from deepspeed_tpu.ops.sparse_attention import blocksparse as _bsk
        kernel = _bsk.planned_kernel(sp.get_layout(S), block)
    except NoiseFloorError:
        raise   # measurement failure, not a kernel failure: error row
    except Exception:
        # fall back to the per-triple v1 kernels rather than losing the
        # row (banded must drop too or the retry re-dispatches the very
        # kernel that failed; hybrid rides USE_SPLASH_V2).  Restore the
        # flags afterwards — a later metric in the same process must
        # not silently measure v1 (ADVICE r4).
        from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
        old_v2, old_banded = bs.USE_SPLASH_V2, bs.USE_BANDED
        bs.USE_SPLASH_V2 = False
        bs.USE_BANDED = False
        bs._FN_CACHE.clear()
        try:
            t_sparse = timed(sparse_loss)
        finally:
            bs.USE_SPLASH_V2, bs.USE_BANDED = old_v2, old_banded
            bs._FN_CACHE.clear()
        kernel = "v1-fallback"
    # the reference's 6.3x headline compares sparse vs its dense O(S^2)
    # softmax attention (sparse-attention post :28-33) — mirror that
    # methodology with a bf16 materialized-scores path (the reference's
    # dense kernels are fp16; bf16 keeps the S^2 buffers inside HBM at
    # S=8192), and report sparse-vs-our-own-flash alongside in detail
    vanilla_loss = _sparse_vanilla_loss(S)

    try:
        t_vanilla = timed(vanilla_loss)
    except NoiseFloorError:
        raise   # measurement failure: error row, not a baseline switch
    except Exception:
        t_vanilla = None               # O(S^2) buffers may not fit
    # Long-context detail (reference claim: 10x longer sequences,
    # sparse-attention post :28): at 2x the row's sequence the dense
    # kernel pays O(S^2) while the Longformer walk stays O(S) — measure
    # sparse-vs-flash at S=16k as evidence the gap widens.  Best-effort:
    # a failure (VMEM, tunnel) never costs the row.
    s16k = {}
    if on_tpu:
        try:
            S2 = 2 * S
            q2, k2, v2 = (jax.random.normal(jax.random.fold_in(key, 9 + i),
                                            (B, H, S2, D), jnp.bfloat16)
                          for i in range(3))
            # sp resolves its layout per sequence length at call time
            args2 = (q2, k2, v2)
            n2 = max(iters // 2, 1)
            t_d2 = timed(dense_loss, arrays=args2, start_len=n2)
            t_s2 = timed(sparse_loss, arrays=args2, start_len=n2)
            s16k = {"s16k_flash_ms": round(t_d2 * 1000, 2),
                    "s16k_sparse_ms": round(t_s2 * 1000, 2),
                    "s16k_vs_flash": round(t_d2 / t_s2, 3)}
        except Exception as e:
            s16k = {"s16k_error": f"{type(e).__name__}: {e}"[:120]}
    # Best-effort auxiliary layout details (shared shape with s16k: a
    # failure never costs the row). Each times the dispatcher on one
    # more layout family at this row's geometry:
    # - refdensity: the reference's OWN 6.3x-headline geometry — block
    #   16, 48-token window, ~1% density (this row's canonical config
    #   is the denser class-default 384-token window). FLOP bound ~51x
    #   vs causal-dense; static waste 8x at (128,128) walk tiles ->
    #   ~6x-vs-flash potential.
    # - bigbird: random blocks ride the hybrid banded+residual
    #   lse-merge path (hybrid.py; reference sparsity_config.py:421).
    def aux_layout_detail(prefix, sp_cfg, fb):
        if not on_tpu:
            return {}
        try:
            from deepspeed_tpu.ops.sparse_attention import (
                SparseSelfAttention as _SSA)
            from deepspeed_tpu.ops.sparse_attention import (
                blocksparse as _bsx)
            sp_x = _SSA(sp_cfg)

            def aux_loss(q, k, v):
                return jnp.sum(sp_x(q, k, v).astype(jnp.float32))

            t_x = timed(aux_loss, start_len=max(iters // 2, 1))
            out = {f"{prefix}_sparse_ms": round(t_x * 1000, 2),
                   f"{prefix}_vs_flash": round(t_dense / t_x, 3),
                   f"{prefix}_kernel": _bsx.planned_kernel(
                       sp_x.get_layout(S), fb)}
            if t_vanilla:
                out[f"{prefix}_vs_vanilla"] = round(t_vanilla / t_x, 3)
            return out
        except Exception as e:
            return {f"{prefix}_error": f"{type(e).__name__}: {e}"[:120]}

    refdensity = aux_layout_detail(
        "refdensity", BSLongformerSparsityConfig(
            num_heads=H, block=16, num_sliding_window_blocks=win), 16)
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    bigbird = aux_layout_detail(
        "bigbird", BigBirdSparsityConfig(
            num_heads=H, block=block, num_random_blocks=1,
            num_sliding_window_blocks=win, num_global_blocks=1), block)

    # which walk the cost model actually picked for this layout
    try:
        from deepspeed_tpu.ops.sparse_attention import blocksparse as _bs
        coarse_pick = _bs._pick_coarse_block(
            np.asarray(sp.sparsity_config.make_layout(S)), block,
            has_am=False)
    except Exception:
        coarse_pick = "unknown"

    speedup = (t_vanilla / t_sparse) if t_vanilla else t_dense / t_sparse
    unit = ("vanilla_time_over_sparse_time" if t_vanilla
            else "flash_time_over_sparse_time")
    # record the A/B knob state: with BENCH_REF_ATTN=1 the 'flash'
    # baseline is the XLA reference path below the streaming threshold
    # (ADVICE r3 #2 — never leave that attribution implicit)
    from deepspeed_tpu.ops.attention import flash as _F
    # the 6.3x reference target is vanilla-relative: a flash-relative
    # fallback ratio is not comparable to it, so report no vs_baseline
    return _emit("sparse_attention_speedup_s8k", round(speedup, 3),
                 unit, round(speedup / 6.3, 4) if t_vanilla else None,
                 {"seq": S, "heads": H, "block": block, "window_blocks": win,
                  "kernel": kernel, "coarse_block": coarse_pick,
                  # EFFECTIVE state at this row's S: above the streaming
                  # threshold flash_attention ignores the force knob
                  "ref_attn_forced": bool(
                      _F.get_attention_options().kernel == "reference"
                      and S < _F.STREAM_THRESHOLD),
                  "baseline": "vanilla" if t_vanilla else "flash",
                  "vanilla_ms": round(t_vanilla * 1000, 2) if t_vanilla else None,
                  "flash_ms": round(t_dense * 1000, 2),
                  "vs_flash": round(t_dense / t_sparse, 3),
                  "sparse_ms": round(t_sparse * 1000, 2), **s16k,
                  **refdensity, **bigbird,
                  "hbm_peak_mb_child": _hbm_peak_mb()})


def bench_masked_flash_flops_bytes(on_tpu, rtt):
    """Hardware-free row: the unified mask-parameterized flash kernel's
    work is PROPORTIONAL TO NONZERO BLOCKS (ISSUE 11 acceptance),
    pinned two independent ways (the mfu_cost_model pattern).

    (1) Cost model (masked_flash_cost): modeled MXU FLOPs and K/V
    stream bytes for a dense BlockMask vs the BigBird reference layout
    at the S=8192 ladder geometry (H=16, D=64, fine block 128, win=3,
    1 random + 1 global — the bench_sparse_attention aux config). The
    mask-proportional K/V stream is the priced quantity (q/o/lse
    traffic is S*D regardless of mask and reported separately):
    value = modeled BigBird K/V KiB per forward,
    vs_baseline = dense/BigBird K/V bytes (acceptance >= 2.5x; the
    FLOPs ratio and the BigBird<=40%-of-dense fraction ride in detail).

    (2) Structural pin: the CSR metadata the kernel actually walks has
    exactly nnz items (cost model and kernel count the same work), and
    a small interpret-mode run of the identical kernel matches the
    block-sparse oracle — the cost model prices the kernel that runs,
    not a hypothetical.
    """
    del on_tpu, rtt       # pure accounting + a tiny interpret run
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention.flash import pick_masked_block
    from deepspeed_tpu.ops.attention.masked_flash import (
        BlockMask, masked_flash_attention, masked_flash_cost)
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig, BSLongformerSparsityConfig,
        block_sparse_attention_reference)

    S, H, D, fb, win = 8192, 16, 64, 128, 3
    dense = BlockMask.dense(S, S, pick_masked_block(S, S, D))
    bird = BlockMask.from_layout(BigBirdSparsityConfig(
        num_heads=H, block=fb, num_random_blocks=1,
        num_sliding_window_blocks=win,
        num_global_blocks=1).make_layout(S), fb)
    lonf = BlockMask.from_layout(BSLongformerSparsityConfig(
        num_heads=H, block=fb,
        num_sliding_window_blocks=win).make_layout(S), fb)
    cd = masked_flash_cost(dense, 1, H, D)
    cb = masked_flash_cost(bird, 1, H, D)
    cl = masked_flash_cost(lonf, 1, H, D)
    _beat()

    # structural pin: the CSR walk counts the same work the model prices
    offs, cnts, cols, kinds = bird.csr()
    csr_ok = int(cnts.sum()) == bird.nnz == len(cols)

    # tiny interpret-mode parity spot check — same kernel, same masks
    Sp, Hp, Dp, fbp = 256, 2, 16, 16
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, Hp, Sp, Dp), jnp.float32) * 0.3
               for _ in range(3))
    layout_p = BigBirdSparsityConfig(
        num_heads=Hp, block=fbp, num_random_blocks=1,
        num_sliding_window_blocks=win,
        num_global_blocks=1).make_layout(Sp)
    o = masked_flash_attention(q, k, v,
                               BlockMask.from_layout(layout_p, fbp),
                               sm_scale=Dp ** -0.5, interpret=True)
    ref = block_sparse_attention_reference(q, k, v, layout_p,
                                           sm_scale=Dp ** -0.5)
    parity = float(np.abs(np.asarray(o) - np.asarray(ref)).max())
    _beat()

    kv_ratio = cd["kv_bytes"] / cb["kv_bytes"]
    return _emit(
        "masked_flash_flops_bytes", round(cb["kv_bytes"] / 1024, 2),
        "modeled_kv_kib_per_fwd", round(kv_ratio, 3),
        {"flops_ratio_dense_over_bigbird": round(
            cd["flops"] / cb["flops"], 3),
         "bigbird_frac_of_dense_kv_bytes": round(
             cb["kv_bytes"] / cd["kv_bytes"], 4),
         "bigbird_frac_of_dense_total_bytes": round(
             cb["bytes"] / cd["bytes"], 4),
         "longformer_frac_of_dense_kv_bytes": round(
             cl["kv_bytes"] / cd["kv_bytes"], 4),
         "walk_blocks": {"dense": cd["block"], "bigbird": cb["block"],
                         "longformer": cl["block"]},
         "items": {"dense": cd["items"], "bigbird": cb["items"],
                   "longformer": cl["items"]},
         "longformer_coarsened": bool(lonf.block > fb),
         "csr_items_match_nnz": bool(csr_ok),
         "interpret_parity_max_abs": round(parity, 8),
         "geometry": {"seq": S, "heads": H, "d": D, "fine_block": fb,
                      "window_blocks": win},
         "backend": jax.default_backend(),
         "source": "masked_flash_cost model + CSR structural pin + "
                   "interpret parity (hardware-free)"})


def bench_sparse_attn_speedup_v2(on_tpu, rtt):
    """TPU ladder row (next hardware window): the r01 1.066x config —
    BSLongformer block=128 win=3 at B=1 H=16 S=8192 D=64, fwd+bwd —
    re-measured through the UNIFIED masked kernel (ISSUE 11): banded
    structure walks coarsened MXU tiles with the fine bits in register
    predicates, zero mask bytes from HBM. Same protocol and baselines
    as sparse_attention_speedup_s8k (which now pins the LEGACY
    dispatch), so the two rows A/B the kernels directly. On a non-TPU
    backend this is a small functional pin (backend in detail)."""
    from deepspeed_tpu.ops.attention import flash as _F
    from deepspeed_tpu.ops.sparse_attention import blocksparse as _bs

    # this row's identity IS the unified kernel: pin it for the row's
    # duration even when a global A/B knob (BENCH_REF_ATTN /
    # BENCH_LEGACY_ATTN) re-routed the process default
    old_masked = _bs.USE_MASKED_FLASH
    old_opts = _F.set_attention_options(kernel="masked")
    _bs.USE_MASKED_FLASH = True
    _bs._FN_CACHE.clear()
    try:
        return _bench_sparse_attn_speedup_v2(on_tpu, rtt)
    finally:
        _bs.USE_MASKED_FLASH = old_masked
        _F._OPTIONS = old_opts
        _bs._FN_CACHE.clear()


def _bench_sparse_attn_speedup_v2(on_tpu, rtt):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention.flash import flash_attention
    from deepspeed_tpu.ops.sparse_attention import (
        SparseSelfAttention, BSLongformerSparsityConfig)
    from deepspeed_tpu.ops.sparse_attention import blocksparse as _bs

    B, H, S, D, iters, block, win = _sparse_row_geometry(on_tpu)

    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                 jnp.bfloat16) for i in range(3))
    sp = SparseSelfAttention(BSLongformerSparsityConfig(
        num_heads=H, block=block, num_sliding_window_blocks=win))
    planned = _bs.planned_kernel(sp.get_layout(S), block)

    def dense_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    def sparse_loss(q, k, v):
        return jnp.sum(sp(q, k, v).astype(jnp.float32))

    vanilla_loss = _sparse_vanilla_loss(S)

    def timed(fn):
        return _sparse_scan_timed(fn, (q, k, v), rtt, iters)

    t_dense = timed(dense_loss)
    t_sparse = timed(sparse_loss)
    try:
        t_vanilla = timed(vanilla_loss)
    except Exception:
        t_vanilla = None               # O(S^2) buffers may not fit
    speedup = (t_vanilla / t_sparse) if t_vanilla else t_dense / t_sparse
    unit = ("vanilla_time_over_sparse_time" if t_vanilla
            else "flash_time_over_sparse_time")
    return _emit(
        "sparse_attn_speedup_v2", round(speedup, 3), unit,
        round(speedup / 6.3, 4) if t_vanilla else None,
        {"seq": S, "heads": H, "block": block, "window_blocks": win,
         "kernel": planned, "r01_legacy_anchor": 1.066,
         "baseline": "vanilla" if t_vanilla else "flash",
         "vanilla_ms": round(t_vanilla * 1000, 2) if t_vanilla else None,
         "flash_ms": round(t_dense * 1000, 2),
         "vs_flash": round(t_dense / t_sparse, 3),
         "sparse_ms": round(t_sparse * 1000, 2),
         "backend": jax.default_backend(),
         "hbm_peak_mb_child": _hbm_peak_mb(),
         "source": "unified masked kernel, scan-amortized fwd+bwd "
                   "wall clock"})


def gpt2_analytic_flops_per_token(n_params, num_layers, seq, hidden):
    """PaLM-appendix model FLOPs/token: 6N + 12*L*S*H (fwd+bwd; shared
    by the hardware MFU rows and the mfu_cost_model drift guard — keep
    ONE instance so a correction can't silently diverge them)."""
    return 6 * n_params + 12 * num_layers * seq * hidden


def bench_gpt2(on_tpu, rtt, dropout: float, metric: str):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, count_params, gpt2_loss_fn, init_gpt2_params)

    if on_tpu:
        # GPT-2 345M: the reference baseline's stated config
        # (BASELINE.md north star: Megatron-GPT2 345M + ZeRO-2 >=45% MFU)
        cfg = GPT2Config(vocab_size=50304,  # 128-aligned vocab
                         max_position_embeddings=1024,
                         hidden_size=1024, num_layers=24, num_heads=16,
                         embd_dropout=dropout, attn_dropout=dropout,
                         resid_dropout=dropout)
        batch, seq, steps = 8, 1024, 15 if dropout == 0.0 else 10
    else:  # CPU smoke fallback
        cfg = GPT2Config(vocab_size=512, max_position_embeddings=128,
                         hidden_size=64, num_layers=2, num_heads=2,
                         embd_dropout=dropout, attn_dropout=dropout,
                         resid_dropout=dropout)
        batch, seq, steps = 4, 64, 2
    if os.environ.get("BENCH_SCAN_LAYERS", "0") == "1":
        cfg = cfg._replace(scan_layers=True)   # see bench_bert_large

    n_dev = jax.device_count()
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    loss_fn = gpt2_loss_fn(cfg, dtype=jnp.bfloat16,
                           deterministic=(dropout == 0.0))

    bf16_cfg = {"enabled": True}
    if os.environ.get("BENCH_MASTER_FREE", "0") == "1":
        # master-weight-free bf16 + stochastic rounding (docs/config.md):
        # A/B the fp32-master-less update (no fp32 param copy to stream
        # through HBM at the optimizer boundary; same compute path)
        bf16_cfg.update(master_weights=False, stochastic_rounding=True)
    # BENCH_ADAM8BIT=1: quantized moments — ~4x less optimizer-state
    # HBM traffic at the update boundary (A/B knob)
    opt_type = ("Adam8bit"
                if os.environ.get("BENCH_ADAM8BIT", "0") == "1"
                else "Adam")
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": max(batch // n_dev, 1),
            "gradient_accumulation_steps": 1,
            "bf16": bf16_cfg,
            "steps_per_print": 10**9,
            "zero_optimization": {"stage": 2 if n_dev > 1 else 0},
            "optimizer": {"type": opt_type, "params": {"lr": 1e-4}},
        })

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec
    b = {"input_ids": jax.device_put(
        ids, NamedSharding(engine.mesh,
                           PartitionSpec("data" if n_dev > 1 else None)))}

    loss = engine.train_batch(iter([b]))
    np.asarray(loss)  # compile + settle

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9)

    tokens_per_s = batch * seq * steps / dt
    flops_per_token = gpt2_analytic_flops_per_token(
        n_params, cfg.num_layers, seq, cfg.hidden_size)
    tflops = tokens_per_s * flops_per_token / 1e12
    peak = 197.0 if on_tpu else 1e9
    mfu = tflops / peak / max(n_dev, 1)
    return _emit(metric, round(mfu, 4), "fraction_of_peak_bf16",
                 round(mfu / 0.52, 4),
                 {"model": f"gpt2-{n_params/1e6:.0f}M", "dropout": dropout,
                  "tokens_per_s_per_chip": round(tokens_per_s / max(n_dev, 1), 1),
                  "tflops_per_chip": round(tflops / max(n_dev, 1), 2),
                  "step_ms": round(dt / steps * 1000, 2), "loss": float(loss),
                  "hbm_peak_mb_child": _hbm_peak_mb()})


def bench_comm_wire_bytes(on_tpu, rtt):
    """Hardware-free row: per-rank DP gradient-exchange wire bytes of the
    qgZ two-hop quantized allreduce, measured from the PARTITIONED HLO
    of a >= 1M-element gradient at W=8 (the same accounting the tier-1
    audits pin, tests/unit/test_hlo_quantized_comm.py) — so the ladder
    tracks the compression ratio without a hardware window.

    value = per-rank wire bytes per step; vs_baseline = quantized /
    dense-bf16-ring ratio (< 0.6 is the ISSUE-2 acceptance bar; the
    legacy all_gather exchange scores > 2 here at W=8).
    """
    del on_tpu, rtt           # compiled-HLO accounting; no device timing
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    import deepspeed_tpu  # noqa: F401  (installs the shard_map shim)
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime.quantized_collectives import (
        ALGO_ALLGATHER, ALGO_TWOHOP, quantized_allreduce_mean)
    from deepspeed_tpu.utils.hlo_audit import (
        collect_collectives_full, dense_allreduce_ring_bytes,
        wire_bytes_of)

    n = 1 << 20
    W = 8
    assert jax.device_count() >= W, \
        f"comm audit needs {W} devices (forced-cpu child env), " \
        f"got {jax.device_count()}"
    mesh = build_mesh({"data": W})

    def hlo_bytes(algo):
        def inner(x):
            return quantized_allreduce_mean(x[0], "data", algo=algo,
                                            world_size=W)
        g = jax.ShapeDtypeStruct((W, n), jnp.float32)
        txt = jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_vma=False)).lower(g).compile().as_text()
        return wire_bytes_of(collect_collectives_full(txt))

    twohop = hlo_bytes(ALGO_TWOHOP)
    _beat()
    legacy = hlo_bytes(ALGO_ALLGATHER)
    dense = dense_allreduce_ring_bytes(n, W, dtype_bytes=2)
    return _emit("comm_wire_bytes_per_step", twohop,
                 "bytes_per_rank_per_step", round(twohop / dense, 4),
                 {"elements": n, "world": W, "algo": "twohop",
                  "dense_bf16_ring_bytes": dense,
                  "legacy_allgather_bytes": legacy,
                  "legacy_vs_dense": round(legacy / dense, 3),
                  "backend": jax.default_backend(),
                  "source": "partitioned-HLO audit (hardware-free)"})


def bench_comm_overlap_structure(on_tpu, rtt):
    """Hardware-free row: structural compute/comm overlap of the
    comm_autotune fused step (ISSUE 6), from the partitioned HLO of a
    tiny quantized-comm engine on the virtual 8-device CPU mesh.

    value = fraction of grad-exchange collectives inside the scan body
    whose operand cone contains NO dot-general — i.e. they consume only
    the double-buffered carry, so the scheduler can run them under the
    iteration's compute (serial exchange scores 0, overlapped 1; the
    same dependence audit tier-1 pins in test_hlo_quantized_comm.py).
    vs_baseline = modeled overlapped/serial step time from the
    comm_autotune cost model + the program's cost-analysis FLOPs at the
    45%-MFU v5e bar (< 1.0 = overlap pays). detail carries the serial
    program's fractions (sanity: ~0), the post-scan flush count, and
    the positional interleave view (printed HLO order — NOT schedule
    order on CPU, reported for reference only).
    """
    del on_tpu, rtt           # compiled-HLO accounting; no device timing
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from jax.sharding import NamedSharding, PartitionSpec
    from deepspeed_tpu.profiling.flops import profile_jit_fn
    from deepspeed_tpu.runtime.comm_autotune import (LinkModel,
                                                     exchange_time_us)
    from deepspeed_tpu.utils.hlo_audit import overlap_structure

    gas, d_in, d_h = 3, 64, 256
    n_dev = jax.device_count()

    def loss_fn(params, batch, rngs=None):
        h = jnp.tanh(batch["x"] @ params["w1"])
        return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)

    key = jax.random.PRNGKey(0)
    params = {"w1": jax.random.normal(key, (d_in, d_h)) * 0.1,
              "w2": jax.random.normal(key, (d_h, d_in)) * 0.1}

    def fused_hlo(overlap):
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": gas,
                    "steps_per_print": 10**9,
                    "quantized_comm": {"enabled": True},
                    "comm_autotune": {"enabled": True, "overlap": overlap},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        rs = np.random.RandomState(0)
        shd = NamedSharding(engine.mesh,
                            PartitionSpec(engine._dp_axis_entry))
        b = {"x": jax.device_put(rs.randn(4 * n_dev, d_in)
                                 .astype(np.float32), shd),
             "y": jax.device_put(rs.randn(4 * n_dev, d_in)
                                 .astype(np.float32), shd)}
        stacked = jax.device_put(
            jax.tree_util.tree_map(
                lambda x: np.stack([np.asarray(x)] * gas), b),
            engine._stacked_batch_sharding())
        assert engine._batch_path() and engine._overlap_path() == overlap
        step = engine._get_compiled_batch_step()
        txt = step.lower(engine.state, stacked).compile().as_text()
        return engine, step, (engine.state, stacked), txt

    engine, step, args, txt_o = fused_hlo(True)
    stats_o = overlap_structure(txt_o)
    _beat()
    _eng_s, _step_s, _args_s, txt_s = fused_hlo(False)
    stats_s = overlap_structure(txt_s)
    _beat()

    # modeled step-time gain: per-micro exchange time from the cost
    # model, per-micro compute time from the program's cost-analysis
    # FLOPs at the reference 45%-MFU v5e bar; the overlapped window
    # hides gas-1 of the gas exchanges under the next micro's compute
    sizes = [p.size for p in jax.tree_util.tree_leaves(params)]
    t_ex = exchange_time_us(sizes, engine.dp_world_size,
                            block=engine._quant_block,
                            algo=engine._quant_algo, link=LinkModel())
    prof = profile_jit_fn(step, args, name="fused_step")
    t_c = prof.flops / gas / (0.45 * 197e12) * 1e6   # us per micro
    serial_us = gas * (t_c + t_ex)
    overlap_us = gas * max(t_c, t_ex) + min(t_c, t_ex)
    return _emit("comm_overlap_structure",
                 round(stats_o["overlap_fraction"], 4),
                 "fraction_exchange_collectives_dot_free",
                 round(overlap_us / serial_us, 4),
                 {"gas": gas, "world": engine.dp_world_size,
                  "serial_overlap_fraction":
                      round(stats_s["overlap_fraction"], 4),
                  "flush_outside_loop": stats_o["flush_outside_loop"],
                  "serial_flush_outside_loop":
                      stats_s["flush_outside_loop"],
                  "exchange_collectives_in_body":
                      stats_o["exchange_collectives"],
                  "positional_interleaved_fraction":
                      round(stats_o["interleaved_fraction"], 4),
                  "modeled_exchange_us_per_micro": round(t_ex, 3),
                  "modeled_compute_us_per_micro_at_45pct_v5e":
                      round(t_c, 3),
                  "modeled_serial_step_us": round(serial_us, 3),
                  "modeled_overlapped_step_us": round(overlap_us, 3),
                  "backend": jax.default_backend(),
                  "source": "partitioned-HLO dependence audit + "
                            "comm_autotune cost model (hardware-free)"})


def bench_mfu_cost_model(on_tpu, rtt):
    """Hardware-free row: cost-analysis FLOPs per token of the compiled
    GPT-2 micro-step (fwd + bwd + Adam update, ZeRO-2 over the virtual
    8-device mesh) — the exact record the observability layer's flops
    profiler writes per run (deepspeed_tpu/profiling/flops.py), pinned
    here against the analytic PaLM-appendix count so a silent change in
    what the compiled program computes (lost fusion, duplicated
    backward, an optimizer graph regression) moves a checked number.

    value = cost-model FLOPs/token; vs_baseline = cost / analytic
    (6N + 12LSH) ratio — expected O(1); detail carries a projected v5e
    step time at the reference 45% MFU bar for quick mental math.
    """
    del on_tpu, rtt           # compiled-program accounting; no device timing
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, count_params, gpt2_loss_fn, init_gpt2_params)
    from deepspeed_tpu.profiling.flops import profile_jit_fn

    cfg = GPT2Config(vocab_size=512, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=2)
    batch, seq = 8, 64
    n_dev = jax.device_count()
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    loss_fn = gpt2_loss_fn(cfg, dtype=jnp.bfloat16, deterministic=True)
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": max(batch // n_dev, 1),
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "steps_per_print": 10**9,
            "zero_optimization": {"stage": 2 if n_dev > 1 else 0},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        })
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec
    b = {"input_ids": jax.device_put(
        ids, NamedSharding(engine.mesh,
                           PartitionSpec("data" if n_dev > 1 else None)))}
    _beat()
    prof = profile_jit_fn(engine._get_compiled_micro_step(),
                          (engine.state, b), name="gpt2_micro_step")
    # cost_analysis flops are PER-DEVICE for the partitioned program
    # (FlopsProfile docstring), so divide by the per-device token share
    tokens = batch * seq
    tokens_per_dev = tokens / max(n_dev, 1)
    flops_per_token = prof.flops / tokens_per_dev
    analytic = gpt2_analytic_flops_per_token(
        n_params, cfg.num_layers, seq, cfg.hidden_size)
    # projected v5e step time at the reference's 45% MFU bar
    # (per-device program against the per-device peak)
    v5e_peak = 197e12
    proj_step_ms = prof.flops / (0.45 * v5e_peak) * 1e3
    return _emit("mfu_cost_model", round(flops_per_token, 1),
                 "flops_per_token_cost_model",
                 round(flops_per_token / analytic, 4),
                 {"model": f"gpt2-{n_params/1e6:.1f}M", "tokens": tokens,
                  "flops_per_step_per_device": prof.flops,
                  "bytes_accessed_per_device": prof.bytes_accessed,
                  "arithmetic_intensity": round(
                      prof.arithmetic_intensity, 3),
                  "analytic_flops_per_token": analytic,
                  "projected_v5e_step_ms_at_45pct_mfu": round(
                      proj_step_ms, 4),
                  "world": n_dev, "backend": jax.default_backend(),
                  "source": "compiled-program cost analysis "
                            "(hardware-free)"})


def bench_host_dispatch_overhead(on_tpu, rtt):
    """Hardware-free row: host-dispatch accounting of the async step
    pipeline on the virtual 8-device CPU mesh — compiled-program
    executions and forced host syncs per ``train_batch`` at gas=4,
    counted exactly by the observability CompileTracker (the same
    counters ``tools/obs_report.py`` surfaces).

    value = dispatches per train_batch on the default (fused) path —
    the async-pipeline contract is exactly 1.0; vs_baseline = fused
    dispatches / the per-micro loop's gas dispatches (0.25 at gas=4).
    detail carries the steady-state forced-sync count (contract: 0)
    and the measured host-gap time.
    """
    del on_tpu, rtt       # CompileTracker accounting; no device timing
    import tempfile
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu

    gas, steps, hidden = 4, 5, 64
    n_dev = jax.device_count()

    def init_params(key):
        k1, k2 = jax.random.split(key)
        scale = 1.0 / np.sqrt(hidden)
        return {"w1": jax.random.normal(k1, (hidden, hidden),
                                        jnp.float32) * scale,
                "w2": jax.random.normal(k2, (hidden, hidden),
                                        jnp.float32) * scale}

    def loss_fn(p, batch):
        h = jnp.maximum(batch["x"] @ p["w1"], 0.0)
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    obs_dir = tempfile.mkdtemp(prefix="dstpu_bench_obs_")
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=init_params(jax.random.PRNGKey(0)),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": gas,
            "steps_per_print": 10**9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "observability": {"enabled": True, "events_dir": obs_dir,
                              "flops_profiler": False,
                              "memory_watermarks": False},
        })
    bs = 2 * n_dev
    rng = np.random.RandomState(0)

    def window():
        return iter([{"x": rng.randn(bs, hidden).astype(np.float32),
                      "y": rng.randn(bs, hidden).astype(np.float32)}
                     for _ in range(gas)])

    engine.train_batch(window())          # compile + settle
    _beat()
    tracker = engine.observability.compile_tracker
    d0, s0 = tracker.total_dispatches, engine._host_sync_count
    gaps = []
    for _ in range(steps):
        engine.train_batch(window())
        gaps.append(engine._host_gap_ms or 0.0)
    d_per_step = (tracker.total_dispatches - d0) / steps
    syncs_per_step = (engine._host_sync_count - s0) / steps
    fused = bool(engine._use_fused_batch)
    return _emit("host_dispatch_overhead", round(d_per_step, 3),
                 "dispatches_per_train_batch", round(d_per_step / gas, 4),
                 {"gas": gas, "path": "fused" if fused else "per-micro",
                  "steady_state_syncs_per_step": syncs_per_step,
                  "host_gap_ms_mean": round(sum(gaps) / len(gaps), 3),
                  "last_step_ms": round(engine._last_step_time_ms or 0.0,
                                        3),
                  "compiles": dict(tracker.counts),
                  "world": n_dev, "backend": jax.default_backend(),
                  "source": "CompileTracker dispatch accounting "
                            "(hardware-free)"})


def bench_decode_throughput(on_tpu, rtt):
    """Hardware-free row: serving decode throughput of the inference
    engine (bucketed prefill/decode + continuous batching + donated KV
    cache) on a tiny GPT-2, CPU backend.

    value = generated tokens/s across a mixed-length request burst
    after bucket warmup; vs_baseline = that rate / a no-cache
    full-forward-per-token greedy loop on the same model AT THE SAME
    BATCH as the decode slots — the ratio isolates the KV-cache
    payoff, not batching. detail pins the serving latency contract:
    ``steady_state_recompiles`` MUST be 0 — every steady-state shape
    was compiled during warmup.
    """
    del on_tpu, rtt        # CPU-only accounting + wall-clock on tiny model
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_forward,
                                           init_gpt2_params)
    from deepspeed_tpu.inference import InferenceEngine

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 24
    engine = InferenceEngine(cfg, params, {
        "max_batch_size": 4, "prompt_buckets": [8, 16],
        "batch_buckets": [1, 4], "max_seq_len": 128,
        "max_new_tokens": new_tokens}, dtype=jnp.float32)
    warm_programs = engine.warmup()
    _beat()

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 8, 13, 3, 16, 7, 11, 4)]
    t0 = time.perf_counter()
    outs = engine.generate(prompts, max_new_tokens=new_tokens,
                           temperature=0.0)
    wall = time.perf_counter() - t0
    gen_tokens = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    tps = gen_tokens / wall
    recompiles = engine.steady_state_recompiles
    _beat()

    # baseline: no-cache greedy loop — a full forward over a
    # fixed-length padded buffer for EVERY generated token (what
    # serving without a KV cache costs; fixed shape so the baseline
    # pays compile once, not per token). Runs at the SAME batch as the
    # engine's decode slots so the ratio isolates the KV-cache payoff,
    # not a batching difference.
    fwd = jax.jit(lambda p, ids: gpt2_forward(p, cfg, ids,
                                              dtype=jnp.float32))
    Lfix, nb = 64, 4                   # nb == engine max_batch_size
    buf = np.zeros((nb, Lfix), np.int32)
    for r, prompt in enumerate(prompts[:nb]):
        buf[r, :8] = (prompt + [1] * 8)[:8]    # uniform 8-token prompts
    buf = jnp.asarray(buf)
    cur = 7
    jax.block_until_ready(fwd(params, buf))      # compile outside timing
    t0 = time.perf_counter()
    n_base = 8
    for i in range(n_base):
        logits = fwd(params, buf)
        nxt = jnp.argmax(logits[:, cur + i], axis=-1).astype(jnp.int32)
        buf = buf.at[:, cur + i + 1].set(nxt)
    jax.block_until_ready(buf)
    base_tps = n_base * nb / (time.perf_counter() - t0)
    return _emit("decode_throughput", round(tps, 2), "tokens_per_s",
                 round(tps / base_tps, 3) if base_tps > 0 else 0.0,
                 {"requests": len(prompts), "new_tokens": new_tokens,
                  "warmup_programs": warm_programs,
                  "steady_state_recompiles": recompiles,
                  "baseline_tokens_per_s": round(base_tps, 2),
                  "slots": 4, "backend": jax.default_backend(),
                  "source": "inference engine wall clock + "
                            "CompileTracker (hardware-free)"})


def bench_paged_kv_occupancy(on_tpu, rtt):
    """Hardware-free row: paged vs dense KV cache serving capacity at
    EQUAL cache HBM budget on a mixed-length workload (tiny GPT-2,
    CPU).

    Both engines get the same cache byte budget (dense: 4 slots x
    max_len 128 + scratch; paged: the same token capacity as a page
    pool). The paged engine runs 16 decode slots over it — dense can't,
    its geometry charges every slot max_len up front. value = the paged
    engine's peak live tokens in flight per cache KiB; vs_baseline =
    that density / the dense engine's (ISSUE 7 acceptance: >= 2x on the
    mixed-length workload). detail pins `steady_state_recompiles == 0`
    for the paged engine under the mixed-length churn, and carries both
    engines' decode tokens/s so the capacity win is visibly not bought
    with throughput.
    """
    del on_tpu, rtt        # CPU-only accounting + wall clock, tiny model
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine, kv_cache_bytes, \
        paged_kv_bytes
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    max_len, new_tokens, ps = 128, 16, 16
    dense_slots = 4
    # equal budget: dense (slots+1) rows x max_len tokens == page pool
    num_pages = (dense_slots + 1) * (max_len // ps)        # 40 pages
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 9, 14, 3, 16, 7, 12, 4, 10, 6,
                         15, 8, 5, 11, 3, 13)]

    def serve(engine):
        engine.warmup()
        _beat()
        t0 = time.perf_counter()
        outs = engine.generate(prompts, max_new_tokens=new_tokens,
                               temperature=0.0)
        wall = time.perf_counter() - t0
        gen = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        return outs, gen / wall

    paged = InferenceEngine(cfg, params, {
        "max_batch_size": 16, "prompt_buckets": [8, 16],
        "batch_buckets": [1, 4, 16], "max_seq_len": max_len,
        "max_new_tokens": new_tokens,
        "paged_kv": {"page_size": ps, "num_pages": num_pages}},
        dtype=jnp.float32)
    paged_bytes = paged_kv_bytes(paged.paged_spec)
    paged_outs, paged_tps = serve(paged)
    paged_recompiles = paged.steady_state_recompiles
    paged_peak = paged.scheduler.peak_tokens_in_flight
    alloc = paged.scheduler.allocator
    seen = alloc.prefix_hit_tokens + alloc.prefix_miss_tokens
    _beat()

    dense = InferenceEngine(cfg, params, {
        "max_batch_size": dense_slots, "prompt_buckets": [8, 16],
        "batch_buckets": [1, 4], "max_seq_len": max_len,
        "max_new_tokens": new_tokens,
        "paged_kv": {"enabled": False}}, dtype=jnp.float32)
    dense_bytes = kv_cache_bytes(dense.cache_spec)
    dense_outs, dense_tps = serve(dense)
    dense_peak = dense.scheduler.peak_tokens_in_flight
    _beat()

    parity = paged_outs == dense_outs
    paged_density = paged_peak / (paged_bytes / 1024)
    dense_density = dense_peak / (dense_bytes / 1024)
    return _emit("paged_kv_occupancy", round(paged_density, 4),
                 "tokens_in_flight_per_cache_kib",
                 round(paged_density / dense_density, 3)
                 if dense_density > 0 else 0.0,
                 {"requests": len(prompts), "new_tokens": new_tokens,
                  "page_size": ps, "num_pages": num_pages,
                  "cache_bytes": {"paged": paged_bytes,
                                  "dense": dense_bytes},
                  "peak_tokens_in_flight": {"paged": paged_peak,
                                            "dense": dense_peak},
                  "decode_tokens_per_s": {"paged": round(paged_tps, 2),
                                          "dense": round(dense_tps, 2)},
                  "greedy_outputs_match_dense": bool(parity),
                  "steady_state_recompiles": paged_recompiles,
                  "prefix_hit_rate": round(
                      alloc.prefix_hit_tokens / seen, 4) if seen else 0.0,
                  "backend": jax.default_backend(),
                  "source": "inference engine scheduler accounting "
                            "(hardware-free)"})


def bench_paged_decode_bytes(on_tpu, rtt):
    """Hardware-free row: decode-BANDWIDTH payoff of the fused Pallas
    paged-attention kernel, pinned two independent ways (the
    mfu_cost_model pattern: a structural compiled-program audit plus an
    analytic cost model it cross-checks).

    (1) HLO audit: compile the serving engine's paged decode program
    with ``attn_kernel: "pallas"`` and with ``"gather"`` (CPU,
    interpret-mode kernel — the same jaxpr structure the TPU program
    partitions from) and walk both for ``gather`` instructions. The
    gather program materializes each layer's
    (rows, pages_per_seq, kv_heads, page_size, hd) stripe — a
    max_len-bounded tensor; the pallas program must contain NO gather
    that large (its pool reads are per-page dynamic slices).

    (2) Bytes-read cost model: on the mixed-length reference workload
    (the paged_kv_occupancy prompt mix mid-decode), model the K+V bytes
    one decode step reads — live pages streamed (pallas) vs the full
    table-width stripe (gather). value = modeled pallas KiB/step,
    vs_baseline = stripe/pallas (acceptance >= 2x).
    """
    del on_tpu, rtt        # CPU-only compile + accounting, tiny model
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    from deepspeed_tpu.ops.attention.paged import decode_read_bytes
    from deepspeed_tpu.utils.hlo_audit import max_gather_elems

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    max_len, ps, slots = 128, 16, 16
    spec_cfg = {"max_batch_size": slots, "prompt_buckets": [8, 16],
                "batch_buckets": [1, 4, 16], "max_seq_len": max_len,
                "max_new_tokens": 16}

    def decode_hlo(attn_kernel):
        eng = InferenceEngine(cfg, params, dict(
            spec_cfg, paged_kv={"page_size": ps,
                                "attn_kernel": attn_kernel}),
            dtype=jnp.float32)
        rows = eng.num_slots + 1
        pps = eng.paged_spec.pages_per_seq
        args = (eng.params, eng._cache,
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows, pps), jnp.int32),
                jnp.zeros((rows, 2), jnp.uint32),
                jnp.zeros((rows,), jnp.float32))
        spec = eng.paged_spec
        hlo = jax.jit(eng._decode_paged_impl).lower(
            *args).compile().as_text()
        return hlo, spec
    hlo_pallas, spec = decode_hlo("pallas")
    _beat()
    hlo_gather, _ = decode_hlo("gather")
    _beat()

    # one layer's stripe: every table entry's page for every row
    stripe_elems = ((slots + 1) * spec.pages_per_seq * spec.kv_heads
                    * spec.page_size * spec.head_dim)
    pallas_max = max_gather_elems(hlo_pallas)
    gather_max = max_gather_elems(hlo_gather)
    pallas_gather_free = pallas_max < stripe_elems
    gather_shows_stripe = gather_max >= stripe_elems

    # mixed-length reference workload: the paged_kv_occupancy prompt mix
    # mid-decode (each request 8 tokens into its generation)
    lens = (5, 9, 14, 3, 16, 7, 12, 4, 10, 6, 15, 8, 5, 11, 3, 13)
    positions = [l + 8 for l in lens]
    pallas_bytes, gather_bytes = decode_read_bytes(
        positions, ps, spec.pages_per_seq, spec.kv_heads,
        spec.head_dim, dtype_bytes=2)          # priced at bf16 serving
    pallas_bytes *= spec.num_layers
    gather_bytes *= spec.num_layers
    reduction = gather_bytes / pallas_bytes if pallas_bytes else 0.0
    return _emit(
        "paged_decode_bytes", round(pallas_bytes / 1024, 2),
        "modeled_kib_per_decode_step",
        round(reduction, 3),
        {"pallas_gather_free": bool(pallas_gather_free),
         "gather_shows_stripe": bool(gather_shows_stripe),
         "max_gather_elems": {"pallas": int(pallas_max),
                              "gather": int(gather_max)},
         "stripe_elems_per_layer": int(stripe_elems),
         "modeled_bytes_per_step": {"pallas": int(pallas_bytes),
                                    "gather_stripe": int(gather_bytes)},
         "workload_positions": positions, "page_size": ps,
         "pages_per_seq": spec.pages_per_seq,
         "backend": jax.default_backend(),
         "source": "compiled-HLO gather audit + live-page bytes cost "
                   "model (hardware-free)"})


def bench_serve_trace_overhead(on_tpu, rtt):
    """Hardware-free row: the request-granular serving observability
    plane must be free at the dispatch level. The same mixed-length
    continuous-batching workload runs on two engines, BOTH with the
    crash-safe events.jsonl wired (the PR-5 aggregate telemetry is the
    shared baseline — its line-buffered IO is the dominant telemetry
    cost on a toy model and is not what this row prices): tracing OFF
    (``observability.serve.enabled: false``) vs tracing ON at the
    default config (full lifecycle trail, per-token TBT sampling,
    ``serve_decode_window`` rows at the default 1/16 stride,
    SLO/goodput scalars).

    Pins (ISSUE 9 acceptance): the warmup program set and per-run
    dispatch counts are IDENTICAL (tracing is host-side pure-Python by
    construction — with equal dispatches, any wall-clock delta IS host
    gap), ``steady_state_recompiles == 0`` for both, greedy outputs
    bitwise equal. value = wall overhead percent of the traced engine
    (min-of-5 interleaved runs — min, not mean, because tiny-model CPU
    wall clocks are noise-dominated); acceptance <= 5%.
    """
    del on_tpu, rtt       # host-side accounting on the CPU backend
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 24
    icfg = {"max_batch_size": 4, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 4], "max_seq_len": 128,
            "max_new_tokens": new_tokens}
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (length,)).tolist()
               for length in (5, 8, 13, 3, 16, 7, 11, 4)]
    tmp = tempfile.mkdtemp(prefix="dstpu_serve_trace_")

    def build(traced):
        ic = dict(icfg, events_dir=os.path.join(
            tmp, "on" if traced else "off"))
        eng = InferenceEngine(
            cfg, params, ic, dtype=jnp.float32,
            observability_config={"serve": {"enabled": traced}})
        eng.warmup()
        return eng

    eng_off = build(False)
    eng_on = build(True)
    warm_off = eng_off.compile_tracker.total_compiles
    warm_on = eng_on.compile_tracker.total_compiles
    _beat()

    def one_run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        return time.perf_counter() - t0, outs

    walls_off, walls_on = [], []
    outs_off = outs_on = None
    disp0_off = eng_off.compile_tracker.total_dispatches
    disp0_on = eng_on.compile_tracker.total_dispatches
    for _ in range(5):
        w, outs_off = one_run(eng_off)
        walls_off.append(w)
        w, outs_on = one_run(eng_on)
        walls_on.append(w)
        _beat()
    disp_off = eng_off.compile_tracker.total_dispatches - disp0_off
    disp_on = eng_on.compile_tracker.total_dispatches - disp0_on
    gen_tokens = sum(len(o) - len(p) for o, p in zip(outs_off, prompts))
    tps_off = gen_tokens / min(walls_off)
    tps_on = gen_tokens / min(walls_on)
    overhead_pct = (min(walls_on) - min(walls_off)) / min(walls_off) * 100
    state = eng_on.debug_state()
    eng_on.close()
    events_path = os.path.join(tmp, "on", "events.jsonl")
    trail_rows = sum(1 for _ in open(events_path)) \
        if os.path.exists(events_path) else 0
    row = _emit(
        "serve_trace_overhead", round(overhead_pct, 2),
        "pct_wall_overhead",
        round(tps_on / tps_off, 3) if tps_off > 0 else 0.0,
        {"accept_overhead_pct": 5.0,
         "tokens_per_s_off": round(tps_off, 2),
         "tokens_per_s_on": round(tps_on, 2),
         "dispatches_off": disp_off, "dispatches_on": disp_on,
         "dispatch_delta": disp_on - disp_off,
         "warmup_programs_off": warm_off,
         "warmup_programs_on": warm_on,
         "steady_state_recompiles_off": eng_off.steady_state_recompiles,
         "steady_state_recompiles_on": eng_on.steady_state_recompiles,
         "greedy_parity": outs_on == outs_off,
         "trail_rows": trail_rows,
         "slo_attainment": state["slo"]["attainment"],
         "requests_per_run": len(prompts), "new_tokens": new_tokens,
         "backend": jax.default_backend(),
         "source": "interleaved wall clock + CompileTracker dispatch "
                   "accounting (hardware-free)"})
    shutil.rmtree(tmp, ignore_errors=True)
    return row


def bench_health_overhead(on_tpu, rtt):
    """Hardware-free row: the health plane (flight-recorder mirror tap,
    live stall watchdog, numeric detectors) must be free at the
    dispatch level. The same mixed-length continuous-batching workload
    runs on two engines: health fully OFF vs fully ON (ring tap +
    armed watchdog at a timeout the run never hits + all detectors at
    defaults).

    Pins (ISSUE 15 acceptance): per-run dispatch counts IDENTICAL
    (the plane is host-side pure-Python by construction — with equal
    dispatches, any wall delta IS host gap), ``steady_state_recompiles
    == 0`` for both, greedy outputs bitwise equal, zero health alerts
    on the healthy run. value = wall overhead percent of the enabled
    engine (min-of-5 interleaved runs); acceptance <= 2%.
    """
    del on_tpu, rtt       # host-side accounting on the CPU backend
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 24
    icfg = {"max_batch_size": 4, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 4], "max_seq_len": 128,
            "max_new_tokens": new_tokens}
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (length,)).tolist()
               for length in (5, 8, 13, 3, 16, 7, 11, 4)]
    tmp = tempfile.mkdtemp(prefix="dstpu_health_ovh_")

    def build(on):
        ic = dict(icfg, events_dir=os.path.join(
            tmp, "on" if on else "off"))
        # watchdog armed at a timeout the healthy run never trips, so
        # the beat path itself is part of what this row prices
        health = {"enabled": on, "stall_timeout_s": 120.0,
                  "on_stall": "warn"}
        eng = InferenceEngine(
            cfg, params, ic, dtype=jnp.float32,
            observability_config={"health": health})
        eng.warmup()
        return eng

    eng_off = build(False)
    eng_on = build(True)
    _beat()

    def one_run(eng):
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        return time.perf_counter() - t0, outs

    walls_off, walls_on = [], []
    outs_off = outs_on = None
    disp0_off = eng_off.compile_tracker.total_dispatches
    disp0_on = eng_on.compile_tracker.total_dispatches
    for _ in range(5):
        w, outs_off = one_run(eng_off)
        walls_off.append(w)
        w, outs_on = one_run(eng_on)
        walls_on.append(w)
        _beat()
    disp_off = eng_off.compile_tracker.total_dispatches - disp0_off
    disp_on = eng_on.compile_tracker.total_dispatches - disp0_on
    gen_tokens = sum(len(o) - len(p) for o, p in zip(outs_off, prompts))
    tps_off = gen_tokens / min(walls_off)
    tps_on = gen_tokens / min(walls_on)
    overhead_pct = (min(walls_on) - min(walls_off)) / min(walls_off) * 100
    alerts_on = eng_on.health.alerts_total
    eng_on.close()
    eng_off.close()
    row = _emit(
        "health_overhead", round(overhead_pct, 2),
        "pct_wall_overhead",
        round(tps_on / tps_off, 3) if tps_off > 0 else 0.0,
        {"accept_overhead_pct": 2.0,
         "tokens_per_s_off": round(tps_off, 2),
         "tokens_per_s_on": round(tps_on, 2),
         "dispatches_off": disp_off, "dispatches_on": disp_on,
         "dispatch_delta": disp_on - disp_off,
         "steady_state_recompiles_off": eng_off.steady_state_recompiles,
         "steady_state_recompiles_on": eng_on.steady_state_recompiles,
         "greedy_parity": outs_on == outs_off,
         "health_alerts_on": alerts_on,
         "requests_per_run": len(prompts), "new_tokens": new_tokens,
         "backend": jax.default_backend(),
         "source": "interleaved wall clock + CompileTracker dispatch "
                   "accounting (hardware-free)"})
    shutil.rmtree(tmp, ignore_errors=True)
    return row


def bench_async_ckpt_stall(on_tpu, rtt):
    """Hardware-free row: the step-loop stall a checkpoint save costs
    per global batch, async vs blocking, at EQUAL checkpoint size
    (ISSUE 10). Three interleave-measured loops on the same
    model/config/seed: no-save baseline, save-every-step blocking, and
    save-every-step async (snapshot-and-return; the stage/commit
    protocol runs on the background writer while the loop keeps
    dispatching — the loop pays only the device->host snapshot).

    The stall is the wall time the step loop spends BLOCKED inside
    ``save_checkpoint`` (async: the snapshot; blocking: the whole
    stage/commit protocol) — on TPU hardware that call is the only
    part the device ever waits on. The CPU harness adds a second,
    harness-only effect the row reports separately in detail: the
    background writer's npz/CRC work shares the host cores with XLA
    compute, so the loop-wall delta (``loop_overhead_ms``) overstates
    what a device-bound run would see.

    value = async stall ms per train_batch (mean save-call wall);
    vs_baseline = async stall / blocking stall — acceptance <= 0.20.
    detail pins the async-save contract: dispatches per train_batch
    identical (1.0) in all three loops, and after the drain the newest
    async tag verifies COMMITTED (sizes + CRC32).
    """
    del on_tpu, rtt      # host wall-clock accounting; no device timing
    import shutil
    import tempfile
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.runtime import checkpoint as _ckpt

    hidden, layers, gas, steps = 512, 4, 2, 6
    n_dev = jax.device_count()

    def init_params(key):
        p = {}
        scale = 1.0 / np.sqrt(hidden)
        for i in range(layers):
            key, k = jax.random.split(key)
            p[f"w{i}"] = jax.random.normal(
                k, (hidden, hidden), jnp.float32) * scale
        return p

    def loss_fn(p, batch):
        h = batch["x"]
        for i in range(layers):
            h = jnp.maximum(h @ p[f"w{i}"], 0.0)
        return jnp.mean((h - batch["y"]) ** 2)

    bs = 2 * n_dev
    rng = np.random.RandomState(0)
    window_data = [[{"x": rng.randn(bs, hidden).astype(np.float32),
                     "y": rng.randn(bs, hidden).astype(np.float32)}
                    for _ in range(gas)] for _ in range(steps + 1)]

    def make_engine(obs_dir):
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn,
            model_parameters=init_params(jax.random.PRNGKey(0)),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": gas,
                "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "observability": {"enabled": True, "events_dir": obs_dir,
                                  "flops_profiler": False,
                                  "memory_watermarks": False},
            })
        return engine

    tmp = tempfile.mkdtemp(prefix="dstpu_bench_ackpt_")

    def run_loop(mode):
        """One measured loop; returns (loop_wall_s, mean save-call
        stall ms, dispatches_per_step, engine, save_dir)."""
        obs_dir = os.path.join(tmp, f"obs_{mode}")
        save_dir = os.path.join(tmp, f"ckpt_{mode}")
        engine = make_engine(obs_dir)
        engine.train_batch(iter(window_data[0]))   # compile + settle
        _beat()
        tracker = engine.observability.compile_tracker
        d0 = tracker.total_dispatches
        stalls = []
        t0 = time.perf_counter()
        for s in range(steps):
            engine.train_batch(iter(window_data[s + 1]))
            if mode != "none":
                t_save = time.perf_counter()
                engine.save_checkpoint(save_dir,
                                       async_=(mode == "async"))
                stalls.append(time.perf_counter() - t_save)
        wall = time.perf_counter() - t0
        disp = (tracker.total_dispatches - d0) / steps
        stall_ms = (sum(stalls) / len(stalls) * 1e3) if stalls else 0.0
        return wall, stall_ms, disp, engine, save_dir

    wall_base, _, disp_base, eng_base, _ = run_loop("none")
    eng_base.close()
    wall_block, stall_block, disp_block, eng_block, _ = run_loop("blocking")
    eng_block.close()
    wall_async, stall_async, disp_async, eng_async, async_dir = \
        run_loop("async")
    # drain OUTSIDE the timed loop: background work must still complete
    # and commit, it just must not stall the step loop
    t_drain = time.perf_counter()
    eng_async.wait_pending_saves()
    drain_ms = (time.perf_counter() - t_drain) * 1e3
    superseded = (eng_async._ckpt_writer.superseded
                  if eng_async._ckpt_writer else 0)
    eng_async.close()

    newest = _ckpt.candidate_tags(async_dir)
    tag_ok, problems = (
        _ckpt.verify_checkpoint_dir(os.path.join(async_dir, newest[0]))
        if newest else (False, ["no committed tag"]))
    ratio = stall_async / stall_block if stall_block > 0 else 0.0
    row = _emit(
        "async_ckpt_stall_ms", round(stall_async, 3), "ms_per_step",
        round(ratio, 4),
        {"accept_ratio": 0.20,
         "stall_blocking_ms": round(stall_block, 3),
         "step_ms_baseline": round(wall_base / steps * 1e3, 3),
         # harness-only CPU contention view: loop wall minus baseline
         # (the background writer shares the host cores with XLA here;
         # on a device backend the step compute doesn't)
         "loop_overhead_ms": {
             "blocking": round(
                 max((wall_block - wall_base) / steps * 1e3, 0.0), 3),
             "async": round(
                 max((wall_async - wall_base) / steps * 1e3, 0.0), 3)},
         "dispatches_per_step": {"baseline": disp_base,
                                 "blocking": disp_block,
                                 "async": disp_async},
         "dispatch_invariant": disp_base == disp_block == disp_async,
         "drain_ms": round(drain_ms, 3),
         "saves_superseded": superseded,
         "newest_async_tag": newest[0] if newest else None,
         "newest_tag_verified": bool(tag_ok),
         "verify_problems": problems if not tag_ok else [],
         "params_mb": round(layers * hidden * hidden * 4 / 2**20, 2),
         "gas": gas, "steps": steps, "world": n_dev,
         "backend": jax.default_backend(),
         "source": "save-call wall clock (the loop's blocked time) + "
                   "no-save loop baseline + CompileTracker dispatch "
                   "accounting (hardware-free)"})
    shutil.rmtree(tmp, ignore_errors=True)
    return row


def bench_paged_decode_tokens_per_s(on_tpu, rtt):
    """TPU ladder row (next hardware window): wall-clock decode
    tokens/s of the serving engine running the COMPILED Pallas
    paged-decode kernel, vs the gather-fallback engine at identical
    config. Geometry is TPU-legal for the kernel (head_dim 128,
    page_size 16); both engines must hold 0 steady-state recompiles.
    On a non-TPU backend the kernel runs interpret mode — the row is
    then a functional pin, not a perf number (backend in detail).
    """
    del rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=512,
                     hidden_size=512, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)          # head_dim 128
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 64
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 8, 13, 3, 16, 7, 11, 4)]

    def serve(attn_kernel):
        eng = InferenceEngine(cfg, params, {
            "max_batch_size": 8, "prompt_buckets": [16],
            "batch_buckets": [8], "max_seq_len": 256,
            "max_new_tokens": new_tokens,
            "paged_kv": {"page_size": 16,
                         "attn_kernel": attn_kernel}}, dtype=dtype)
        path = eng._decode_attn_path
        eng.warmup()
        _beat()
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        wall = time.perf_counter() - t0
        gen = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        return gen / wall, path, eng.steady_state_recompiles, outs
    pallas_tps, pallas_path, pallas_rc, pallas_outs = serve("pallas")
    gather_tps, _, gather_rc, gather_outs = serve("gather")
    _beat()
    return _emit(
        "paged_decode_tokens_per_s", round(pallas_tps, 2),
        "tokens_per_s",
        round(pallas_tps / gather_tps, 3) if gather_tps > 0 else 0.0,
        {"gather_tokens_per_s": round(gather_tps, 2),
         "decode_attn_path": pallas_path,
         "steady_state_recompiles": {"pallas": pallas_rc,
                                     "gather": gather_rc},
         "greedy_outputs_match_gather": bool(pallas_outs == gather_outs),
         "new_tokens": new_tokens, "requests": len(prompts),
         "hbm_peak_mb": _hbm_peak_mb(),
         "backend": jax.default_backend(),
         "source": "inference engine wall clock, pallas vs gather "
                   "decode"})


def bench_spec_decode_accepted_per_dispatch(on_tpu, rtt):
    """Hardware-free row: speculative multi-token decoding on the paged
    pool (ISSUE 13). The host-side n-gram drafter proposes k tokens per
    in-flight request; ONE seq-(k+1) verify dispatch through the paged
    path scores them all, and only verified-greedy-matching tokens are
    kept. On a repetitive workload (greedy decode of a tiny model falls
    into a cycle, which prompt-lookup drafting then predicts) the value
    is verified-and-kept tokens emitted per decode-phase device
    dispatch — the device round-trips actually saved.

    Pins (ISSUE 13 acceptance): value >= 2.0; greedy outputs bitwise
    equal to the non-speculative engine at the same config/seed;
    ``steady_state_recompiles == 0`` for BOTH engines (the verify
    program set is fixed at warmup); vs_baseline = spec decode-phase
    dispatches / baseline decode dispatches (< 1.0 — same tokens, fewer
    dispatches).
    """
    del on_tpu, rtt      # host accounting + CPU backend by design
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=61, max_position_embeddings=128,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(3))
    new_tokens = 24
    icfg = {"max_batch_size": 4, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 4], "max_seq_len": 128,
            "max_new_tokens": new_tokens}
    # period-3 / period-4 repeated patterns: the n-gram drafter's bread
    # and butter, and short enough that greedy decode cycles quickly
    prompts = [[5, 6, 7] * 4, [9, 10, 11, 12] * 3, [1, 2] * 5,
               [20, 21, 22] * 4]

    def serve(spec_on):
        ic = dict(icfg)
        if spec_on:
            ic["spec_decode"] = {"enabled": True, "k": 4}
        eng = InferenceEngine(cfg, params, ic, dtype=jnp.float32)
        eng.warmup()
        _beat()
        d0 = dict(eng.compile_tracker.dispatch_counts)
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        disp = {n: c - d0.get(n, 0)
                for n, c in eng.compile_tracker.dispatch_counts.items()}
        state = eng.debug_state()
        rc = eng.steady_state_recompiles
        eng.close()
        gen = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        return outs, gen, disp, state, rc

    outs_off, gen_off, disp_off, _, rc_off = serve(False)
    outs_on, gen_on, disp_on, state_on, rc_on = serve(True)
    _beat()
    phase_off = disp_off.get("decode", 0)
    phase_on = disp_on.get("verify", 0) + disp_on.get("decode", 0)
    per_dispatch = gen_on / phase_on if phase_on else 0.0
    spec = state_on["slo"]["spec"]
    return _emit(
        "spec_decode_accepted_per_dispatch", round(per_dispatch, 3),
        "kept_tokens_per_dispatch",
        round(phase_on / phase_off, 3) if phase_off else 0.0,
        {"accept_min": 2.0,
         "greedy_parity": bool(outs_on == outs_off),
         "steady_state_recompiles": {"off": rc_off, "on": rc_on},
         "decode_dispatches_off": phase_off,
         "verify_dispatches_on": disp_on.get("verify", 0),
         "fallback_decode_dispatches_on": disp_on.get("decode", 0),
         "drafted": spec["proposed"], "accepted": spec["accepted"],
         "accept_rate": spec["accept_rate"],
         "generated_tokens": gen_on,
         "baseline_tokens": gen_off,
         "backend": jax.default_backend(),
         "source": "CompileTracker dispatch accounting, spec on/off "
                   "(hardware-free)"})


def bench_disagg_dispatch_structure(on_tpu, rtt):
    """Hardware-free row: the disaggregated serving step discipline as
    pure dispatch ordering. Requests are submitted in waves while
    earlier ones still decode, so single engine steps mix the decode
    phase (handoff claims + decode/verify dispatch) with the prefill
    phase. The structural guarantee — no decode dispatch ever waits
    behind a prefill dispatch — is then checkable without a clock:
    within every step of the dispatch trace, all decode-phase ordinals
    precede all prefill ordinals.

    Pins (ISSUE 13 acceptance): value = decode_first_fraction over
    steps that mixed both phases, acceptance == 1.0, and the trace must
    actually contain mixed steps; greedy outputs bitwise equal to the
    interleaved (non-disagg) engine; 0 steady-state recompiles; every
    handoff claimed (queue drains).
    """
    del on_tpu, rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine, Request
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=61, max_position_embeddings=128,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(3))
    new_tokens = 12
    icfg = {"max_batch_size": 3, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 2], "max_seq_len": 64,
            "max_new_tokens": new_tokens}
    rng = np.random.RandomState(7)
    waves = [[rng.randint(1, 61, (l,)).tolist() for l in lens]
             for lens in ((5, 9, 3), (12, 4), (7, 15, 6))]

    def serve(disagg_on):
        ic = dict(icfg)
        if disagg_on:
            ic["disagg"] = {"enabled": True}
        eng = InferenceEngine(cfg, params, ic, dtype=jnp.float32)
        eng.warmup()
        _beat()
        done = {}
        pending = list(waves)
        uid2prompt = {}
        while pending or not eng.scheduler.idle():
            if pending:
                # next wave lands while the previous one still decodes:
                # the admitting step runs prefill AND decode phases
                for p in pending.pop(0):
                    uid = eng.submit(Request(
                        prompt=p, max_new_tokens=new_tokens,
                        temperature=0.0, seed=0))
                    uid2prompt[uid] = tuple(p)
            for f in eng.step():
                done[uid2prompt[f.uid]] = f.tokens
        state = eng.debug_state()
        rc = eng.steady_state_recompiles
        eng.close()
        return done, state, rc

    base_done, _, base_rc = serve(False)
    dis_done, dis_state, dis_rc = serve(True)
    _beat()
    dg = dis_state["disagg"]
    frac = dg["decode_first_fraction"]
    return _emit(
        "disagg_dispatch_structure",
        round(frac, 4) if frac is not None else -1.0,
        "decode_first_fraction", 1.0 if dis_done == base_done else 0.0,
        {"accept_fraction": 1.0,
         "mixed_steps_traced": frac is not None,
         "greedy_parity": bool(dis_done == base_done),
         "steady_state_recompiles": {"interleaved": base_rc,
                                     "disagg": dis_rc},
         "handoffs": dg["queue"]["handoffs"],
         "handoff_queue_drained": dg["queue"]["depth"] == 0,
         "requeues": dg["queue"]["requeues"],
         "requests": sum(len(w) for w in waves),
         "backend": jax.default_backend(),
         "source": "DispatchTrace step ordering, disagg vs interleaved "
                   "(hardware-free)"})


def bench_fleet_drain_goodput(on_tpu, rtt):
    """Hardware-free row: serve THROUGH a replica preemption. The same
    mixed-length workload runs twice over a 3-replica FleetRouter —
    once undisturbed, once with replica 0 drained mid-run (its queued
    requests redistribute to survivors, in-flight requests finish where
    they are). Pins (ISSUE 14 acceptance): zero dropped responses
    (every submitted uid answers in both runs), greedy outputs bitwise
    identical with and without the drain, zero steady-state recompiles
    on every replica, and goodput (tokens/s over the serve window)
    degrades boundedly rather than collapsing — value is the
    drained/undrained goodput ratio.
    """
    del on_tpu, rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import (FleetRouter, InferenceEngine,
                                         Request)
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=61, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(3))
    new_tokens = 8
    icfg = {"max_batch_size": 2, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 2], "max_seq_len": 48,
            "max_new_tokens": new_tokens}
    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, 61, (l,)).tolist()
               for l in (5, 9, 3, 12, 4, 7, 15, 6, 8, 10, 5, 13)]

    def serve(do_drain):
        engines = []
        for _ in range(3):
            eng = InferenceEngine(cfg, params, dict(icfg),
                                  dtype=jnp.float32)
            eng.warmup()
            _beat()
            engines.append(eng)
        router = FleetRouter(engines)
        uids = [router.submit(Request(prompt=p,
                                      max_new_tokens=new_tokens,
                                      temperature=0.0, seed=0))
                for p in prompts]
        t0 = time.perf_counter()
        fins = router.step()
        if do_drain:
            router.drain(0, reason="bench")
        fins.extend(router.run())
        wall = time.perf_counter() - t0
        tokens = sum(len(f.tokens) for f in fins)
        by_uid = {f.uid: f.tokens for f in fins}
        # ordered by submission, so the two runs compare positionally
        # (uids are process-global and differ between runs)
        outs = [by_uid.get(u) for u in uids]
        rc = [e.steady_state_recompiles for e in engines]
        redistributed = router.total_redistributed
        router.close()
        return (outs, tokens / wall if wall > 0 else 0.0,
                rc, redistributed)

    base_out, base_gp, base_rc, _ = serve(False)
    drain_out, drain_gp, drain_rc, redistributed = serve(True)
    _beat()
    dropped = base_out.count(None) + drain_out.count(None)
    parity = base_out == drain_out
    ratio = drain_gp / base_gp if base_gp > 0 else 0.0
    # bounded degradation: a drain costs re-prefill of the redistributed
    # queue, never an order of magnitude (the loose floor keeps the pin
    # meaningful without making a CPU-timing row flaky)
    ok = parity and dropped == 0 and all(r == 0 for r in base_rc + drain_rc) \
        and ratio >= 0.1
    return _emit(
        "fleet_drain_goodput", round(ratio, 4),
        "drained/undrained_goodput_ratio", 1.0 if ok else 0.0,
        {"undrained_tokens_per_s": round(base_gp, 2),
         "drained_tokens_per_s": round(drain_gp, 2),
         "dropped_responses": dropped,
         "greedy_parity": parity,
         "redistributed": redistributed,
         "steady_state_recompiles": {"undrained": base_rc,
                                     "drained": drain_rc},
         "requests": len(prompts), "replicas": 3,
         "backend": jax.default_backend(),
         "source": "FleetRouter 3 replicas, drain replica 0 mid-run "
                   "vs undisturbed (hardware-free)"})


def bench_fleet_migration_goodput(on_tpu, rtt):
    """Hardware-free row: serve through a replica KILL with live
    KV-page migration (ISSUE 16). The same mixed-length greedy
    workload runs twice over a 3-replica FleetRouter of
    migration-warmed engines — once undisturbed, once with replica 0
    yanked mid-run, its in-flight requests' live pages exported and
    imported into survivors (decode resumes at the same
    cache_position, no re-prefill). Pins: zero dropped responses,
    greedy outputs bitwise identical with and without the kill, at
    least one live migration actually happened, zero steady-state
    recompiles on the survivors (import runs through the
    warmup-compiled programs), and goodput holds >= 0.90 of the
    undisturbed run — migration moves pages, not re-decodes tokens.
    """
    del on_tpu, rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import (FleetRouter, InferenceEngine,
                                         Request)
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=61, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(3))
    new_tokens = 16
    icfg = {"max_batch_size": 2, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 2], "max_seq_len": 48,
            "max_new_tokens": new_tokens}
    rng = np.random.RandomState(13)
    # 4 requests over 3 replicas x 2 slots: the survivors hold free
    # decode slots at kill time — an import needs one (a full target
    # falls back to redistribute-and-re-decode, which is the OTHER
    # row's regime)
    prompts = [rng.randint(1, 61, (l,)).tolist()
               for l in (5, 9, 3, 12)]

    def serve(do_kill):
        engines = []
        for _ in range(3):
            eng = InferenceEngine(cfg, params, dict(icfg),
                                  dtype=jnp.float32)
            eng.warmup()
            eng.warm_migration()
            _beat()
            engines.append(eng)
        router = FleetRouter(engines)
        uids = [router.submit(Request(prompt=p,
                                      max_new_tokens=new_tokens,
                                      temperature=0.0, seed=0))
                for p in prompts]
        t0 = time.perf_counter()
        fins = router.step()
        fins.extend(router.step())   # decode underway fleet-wide
        if do_kill:
            router.drain(0, reason="kill")
        fins.extend(router.run())
        wall = time.perf_counter() - t0
        tokens = sum(len(f.tokens) for f in fins)
        by_uid = {f.uid: f.tokens for f in fins}
        outs = [by_uid.get(u) for u in uids]
        # survivors only: the killed replica's programs are gone with it
        rc = [e.steady_state_recompiles for e in engines[1:]]
        migrated = router.total_migrated
        mig_bytes = router.migration_bytes
        router.close()
        return (outs, tokens / wall if wall > 0 else 0.0,
                rc, migrated, mig_bytes)

    base_out, base_gp, base_rc, _, _ = serve(False)
    kill_out, kill_gp, kill_rc, migrated, mig_bytes = serve(True)
    _beat()
    dropped = base_out.count(None) + kill_out.count(None)
    parity = base_out == kill_out
    ratio = kill_gp / base_gp if base_gp > 0 else 0.0
    ok = parity and dropped == 0 and migrated >= 1 \
        and all(r == 0 for r in base_rc + kill_rc) and ratio >= 0.90
    return _emit(
        "fleet_migration_goodput", round(ratio, 4),
        "killed/undisturbed_goodput_ratio", 1.0 if ok else 0.0,
        {"undisturbed_tokens_per_s": round(base_gp, 2),
         "killed_tokens_per_s": round(kill_gp, 2),
         "dropped_responses": dropped,
         "greedy_parity": parity,
         "live_migrations": migrated,
         "migration_bytes": mig_bytes,
         "steady_state_recompiles": {"undisturbed": base_rc,
                                     "killed": kill_rc},
         "requests": len(prompts), "replicas": 3,
         "backend": jax.default_backend(),
         "source": "FleetRouter 3 migration-warmed replicas, kill "
                   "replica 0 mid-decode, live KV pages migrate to "
                   "survivors vs undisturbed (hardware-free)"})


def bench_fleet_trace_overhead(on_tpu, rtt):
    """Hardware-free row: the cross-process tracing plane (ISSUE 18)
    must be free at the dispatch level. The same mixed greedy/seeded
    workload runs over two 2-replica PROCESS fleets — tracing fully
    OFF (serve tracer disabled in every child, no router event log)
    vs fully ON (router trace-id stamping + ``fleet_dispatch`` rows,
    per-child serve trails into per-replica ``events.jsonl``,
    ``clock_sync`` ping rows). The children report their
    CompileTracker dispatch counts through the RPC state piggyback,
    so the pin crosses the process boundary: per-run dispatch counts
    IDENTICAL (``dispatch_delta == 0`` — tracing is host-side pure
    Python on both sides of the wire), steady-state recompiles 0 on
    every replica, outputs bitwise equal between the two fleets.
    value = wall overhead percent of the traced fleet (min-of-3
    interleaved runs); acceptance <= 5%.
    """
    del on_tpu, rtt
    import shutil
    import tempfile

    from deepspeed_tpu.inference import Request
    from deepspeed_tpu.inference.fleet import (FleetRouter,
                                               launch_replica_processes)
    from deepspeed_tpu.utils.monitor import _JsonlWriter

    mcfg = {"vocab_size": 61, "max_position_embeddings": 64,
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "embd_dropout": 0.0, "attn_dropout": 0.0,
            "resid_dropout": 0.0}
    new_tokens = 8
    icfg = {"max_batch_size": 2, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 2], "max_seq_len": 48,
            "max_new_tokens": new_tokens}
    tmp = tempfile.mkdtemp(prefix="dstpu_fleet_trace_")
    env = {"JAX_PLATFORMS": "cpu", "JAX_THREEFRY_PARTITIONABLE": "1"}
    spec = {"family": "gpt2", "model_config": mcfg, "init_seed": 3,
            "dtype": "float32", "inference": icfg}

    def build(traced):
        tag = "on" if traced else "off"
        spec_by = {}
        for i in range(2):
            if traced:
                spec_by[i] = {
                    "inference": dict(icfg, events_dir=os.path.join(
                        tmp, f"{tag}_r{i}")),
                    "observability": {"enabled": True,
                                      "serve": {"enabled": True}}}
            else:
                spec_by[i] = {"observability": {
                    "enabled": True, "serve": {"enabled": False}}}
        reps = launch_replica_processes(
            spec, 2, env_by_replica={i: dict(env) for i in range(2)},
            spec_by_replica=spec_by)
        writer = _JsonlWriter(os.path.join(tmp, f"{tag}_router")) \
            if traced else None
        router = FleetRouter(
            reps, {"process_mode": {"enabled": True}}, writer=writer)
        return router, reps, writer

    def requests(round_no):
        return [Request(prompt=[1 + u % 7, 2, 3, 4, (5 + u) % 61],
                        max_new_tokens=new_tokens,
                        temperature=0.0 if u % 2 == 0 else 0.7,
                        seed=100 + u, uid=round_no * 100 + u)
                for u in range(6)]

    def one_run(router, round_no):
        t0 = time.perf_counter()
        for r in requests(round_no):
            router.submit(r)
        fins = router.run()
        # uid mod 100 folds the per-round uid namespace back so runs
        # compare like-for-like
        return (time.perf_counter() - t0,
                {f.uid % 100: tuple(f.tokens) for f in fins})

    router_off, reps_off, _w_off = build(False)
    _beat()
    router_on, reps_on, w_on = build(True)
    _beat()
    # warm round (not timed) — also primes the dispatch-count baseline
    # via the state piggyback on each RPC reply
    one_run(router_off, 0)
    one_run(router_on, 0)
    disp0_off = sum(r.total_dispatches or 0 for r in reps_off)
    disp0_on = sum(r.total_dispatches or 0 for r in reps_on)
    walls_off, walls_on = [], []
    parity = True
    tokens = 0
    for k in range(1, 4):
        w, o_off = one_run(router_off, k)
        walls_off.append(w)
        w, o_on = one_run(router_on, k)
        walls_on.append(w)
        parity = parity and (o_on == o_off)
        tokens = sum(len(t) for t in o_off.values())
        _beat()
    disp_off = sum(r.total_dispatches or 0
                   for r in reps_off) - disp0_off
    disp_on = sum(r.total_dispatches or 0 for r in reps_on) - disp0_on
    rc = [r.steady_state_recompiles for r in reps_off + reps_on]
    overhead_pct = (min(walls_on) - min(walls_off)) \
        / min(walls_off) * 100
    router_off.close()
    router_on.close()
    if w_on is not None:
        w_on.close()
    trail_rows = 0
    for i in range(2):
        p = os.path.join(tmp, f"on_r{i}", "events.jsonl")
        if os.path.exists(p):
            trail_rows += sum(1 for _ in open(p))
    row = _emit(
        "fleet_trace_overhead", round(overhead_pct, 2),
        "pct_wall_overhead",
        round(min(walls_off) / min(walls_on), 3)
        if min(walls_on) > 0 else 0.0,
        {"accept_overhead_pct": 5.0,
         "wall_off_s": round(min(walls_off), 4),
         "wall_on_s": round(min(walls_on), 4),
         "tokens_per_run": tokens,
         "dispatches_off": disp_off, "dispatches_on": disp_on,
         "dispatch_delta": disp_on - disp_off,
         "steady_state_recompiles": rc,
         "greedy_parity": parity,
         "replica_trail_rows": trail_rows,
         "requests_per_run": 6, "new_tokens": new_tokens,
         "replicas_per_fleet": 2,
         "source": "two 2-replica process fleets, interleaved "
                   "min-of-3 wall + RPC-piggybacked CompileTracker "
                   "dispatch accounting (hardware-free)"})
    shutil.rmtree(tmp, ignore_errors=True)
    return row


def bench_quant_serving_bytes(on_tpu, rtt):
    """Hardware-free row: serving-HBM payoff of int8 quantization on
    BOTH byte levers (ISSUE 17), priced against bf16 serving at the
    same geometry — pure accounting, no wall clock.

    Weight lever: a head_dim-128 GPT-2 param tree in bf16 is qwZ
    block-quantized (block 256) and `quantized_tree_bytes` prices the
    resident int8+fp32-scale footprint against the dense bf16 bytes
    (1-D leaves stay dense by design, so the ratio honestly includes
    them). KV lever: `paged_kv_bytes` of the int8+per-row-scale pool
    vs the bf16 pool at identical page geometry, cross-checked by the
    `decode_read_bytes` cost model on the mixed-length reference
    workload (whole pages stream, so bytes/step shrinks by the same
    ratio — the decode-bandwidth payoff rides the pool dtype).
    value = the KV byte ratio; vs_baseline = the weight byte ratio
    (ISSUE 17 acceptance: BOTH >= 1.8x on top of paged).
    """
    del on_tpu, rtt        # CPU-only byte accounting, tiny model
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.kv_cache import (paged_kv_bytes,
                                                  paged_spec_for)
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    from deepspeed_tpu.ops.attention.paged import decode_read_bytes
    from deepspeed_tpu.runtime.quantized_params import (
        quantize_param_tree, quantized_tree_bytes)

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=512,
                     hidden_size=512, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)          # head_dim 128
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), params)
    qtree = quantize_param_tree(params, 256)
    wq, wd = quantized_tree_bytes(qtree)
    weight_ratio = wd / wq
    _beat()

    num_pages, ps = 144, 16                   # 9 slots x 256 tokens
    spec_bf16 = paged_spec_for(cfg, num_pages, ps, 256,
                               dtype=jnp.bfloat16)
    spec_int8 = paged_spec_for(cfg, num_pages, ps, 256,
                               dtype=jnp.int8, kv_quant_block=0)
    bf16_pool = paged_kv_bytes(spec_bf16)
    int8_pool = paged_kv_bytes(spec_int8)
    kv_ratio = bf16_pool / int8_pool

    # decode-bytes cross-check on the reference mixed-length workload
    lens = (5, 9, 14, 3, 16, 7, 12, 4, 10, 6, 15, 8, 5, 11, 3, 13)
    positions = [l + 8 for l in lens]
    bf16_step, _ = decode_read_bytes(
        positions, ps, spec_bf16.pages_per_seq, spec_bf16.kv_heads,
        spec_bf16.head_dim, dtype_bytes=2)
    int8_step, _ = decode_read_bytes(
        positions, ps, spec_int8.pages_per_seq, spec_int8.kv_heads,
        spec_int8.head_dim, dtype_bytes=1,
        scale_blocks=spec_int8.scale_blocks)
    step_ratio = bf16_step / int8_step if int8_step else 0.0
    ok = weight_ratio >= 1.8 and kv_ratio >= 1.8 and step_ratio >= 1.8
    return _emit(
        "quant_serving_bytes", round(kv_ratio, 4),
        "bf16/int8_kv_pool_bytes_ratio", round(weight_ratio, 3),
        {"weight_bytes": {"int8_resident": wq, "bf16_dense": wd},
         "weight_ratio": round(weight_ratio, 4),
         "kv_pool_bytes": {"int8": int8_pool, "bf16": bf16_pool},
         "kv_ratio": round(kv_ratio, 4),
         "decode_bytes_per_step": {"int8": int(int8_step * 2),
                                   "bf16": int(bf16_step * 2)},
         "decode_bytes_ratio": round(step_ratio, 4),
         "both_levers_ge_1p8x": bool(ok),
         "quant_block": 256, "kv_quant_block": "head_dim",
         "page_size": ps, "num_pages": num_pages,
         "backend": jax.default_backend(),
         "source": "quantized_tree_bytes + paged_kv_bytes + "
                   "decode_read_bytes accounting (hardware-free)"})


def bench_quant_kv_occupancy(on_tpu, rtt):
    """Hardware-free row: serving-capacity payoff of the int8 KV pool
    — the paged_kv_occupancy experiment re-run with the pool dtype as
    the ONLY variable (ISSUE 17). The same mixed-length workload runs
    on a bf16 pool and on an int8+per-row-scale pool at identical page
    geometry; value = the int8 engine's peak live tokens in flight per
    cache KiB, vs_baseline = that density / the bf16 engine's (the
    byte ratio, since both engines pack the same peak concurrency).
    Pins 0 steady-state recompiles for BOTH engines and carries the
    greedy-vs-bf16 agreement plus decode tokens/s so the density win
    is visibly not bought with accuracy or throughput collapse.
    """
    del on_tpu, rtt        # CPU-only accounting + wall clock, tiny model
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine, paged_kv_bytes
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=128,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    max_len, new_tokens, ps = 128, 16, 16
    num_pages = 40
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 9, 14, 3, 16, 7, 12, 4, 10, 6,
                         15, 8, 5, 11, 3, 13)]

    def serve(kv_dtype):
        eng = InferenceEngine(cfg, params, {
            "max_batch_size": 16, "prompt_buckets": [8, 16],
            "batch_buckets": [1, 4, 16], "max_seq_len": max_len,
            "max_new_tokens": new_tokens,
            "paged_kv": {"page_size": ps, "num_pages": num_pages,
                         "kv_dtype": kv_dtype}}, dtype=jnp.float32)
        eng.warmup()
        _beat()
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        wall = time.perf_counter() - t0
        gen = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        return (outs, gen / wall, paged_kv_bytes(eng.paged_spec),
                eng.scheduler.peak_tokens_in_flight,
                eng.steady_state_recompiles)

    bf_outs, bf_tps, bf_bytes, bf_peak, bf_rc = serve("bf16")
    q_outs, q_tps, q_bytes, q_peak, q_rc = serve("int8")
    _beat()
    q_density = q_peak / (q_bytes / 1024)
    bf_density = bf_peak / (bf_bytes / 1024)
    agree = sum(a == b for a, b in zip(q_outs, bf_outs))
    return _emit(
        "quant_kv_occupancy", round(q_density, 4),
        "tokens_in_flight_per_cache_kib",
        round(q_density / bf_density, 3) if bf_density > 0 else 0.0,
        {"requests": len(prompts), "new_tokens": new_tokens,
         "page_size": ps, "num_pages": num_pages,
         "cache_bytes": {"int8": q_bytes, "bf16": bf_bytes},
         "peak_tokens_in_flight": {"int8": q_peak, "bf16": bf_peak},
         "decode_tokens_per_s": {"int8": round(q_tps, 2),
                                 "bf16": round(bf_tps, 2)},
         "greedy_agree_with_bf16": f"{agree}/{len(prompts)}",
         "steady_state_recompiles": {"int8": q_rc, "bf16": bf_rc},
         "backend": jax.default_backend(),
         "source": "inference engine scheduler accounting, int8 vs "
                   "bf16 KV pool at equal page geometry "
                   "(hardware-free)"})


def bench_quant_decode_tokens_per_s(on_tpu, rtt):
    """TPU ladder row (next hardware window): wall-clock decode
    tokens/s of the FULLY quantized serving engine — int8-resident
    weights (in-program dequant at the matmuls) + int8 KV pool
    (in-kernel dequant in the Pallas paged-decode kernel) — vs the
    unquantized engine at identical config. The decode step is
    KV-bandwidth-bound, so halving pool bytes should show up as
    tokens/s on hardware; on a non-TPU backend the kernel runs
    interpret mode and the row is a functional pin (zero steady-state
    recompiles for both engines, greedy agreement count in detail),
    not a perf number.
    """
    del rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=512,
                     hidden_size=512, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)          # head_dim 128
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 64
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 8, 13, 3, 16, 7, 11, 4)]

    def serve(quantized):
        icfg = {"max_batch_size": 8, "prompt_buckets": [16],
                "batch_buckets": [8], "max_seq_len": 256,
                "max_new_tokens": new_tokens,
                "paged_kv": {"page_size": 16, "attn_kernel": "pallas"}}
        if quantized:
            icfg["quantize_weights"] = "int8"
            icfg["paged_kv"]["kv_dtype"] = "int8"
        eng = InferenceEngine(cfg, params, icfg, dtype=dtype)
        eng.warmup()
        _beat()
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        wall = time.perf_counter() - t0
        gen = sum(len(o) - len(p) for o, p in zip(outs, prompts))
        return gen / wall, eng.steady_state_recompiles, outs
    q_tps, q_rc, q_outs = serve(True)
    fp_tps, fp_rc, fp_outs = serve(False)
    _beat()
    agree = sum(a == b for a, b in zip(q_outs, fp_outs))
    return _emit(
        "quant_decode_tokens_per_s", round(q_tps, 2),
        "tokens_per_s",
        round(q_tps / fp_tps, 3) if fp_tps > 0 else 0.0,
        {"unquantized_tokens_per_s": round(fp_tps, 2),
         "steady_state_recompiles": {"quantized": q_rc,
                                     "unquantized": fp_rc},
         "greedy_agree_with_fp": f"{agree}/{len(prompts)}",
         "new_tokens": new_tokens, "requests": len(prompts),
         "hbm_peak_mb": _hbm_peak_mb(),
         "backend": jax.default_backend(),
         "source": "inference engine wall clock, int8 weights + int8 "
                   "KV pool vs unquantized at identical config"})


def bench_disagg_ttft_p95(on_tpu, rtt):
    """TPU ladder row (next hardware window): p95 TTFT of the
    disaggregated engine — decode-first step order with the handoff
    queue between the phases — vs the interleaved engine under the same
    load. On hardware the interleaved engine stalls every in-flight
    request's next token behind each prefill dispatch; disaggregation
    converts that stall into bounded handoff queue time. On a non-TPU
    backend the row is a functional pin (parity + decomposition), not a
    perf number.
    """
    del rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=256, max_position_embeddings=512,
                     hidden_size=512 if on_tpu else 64,
                     num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    new_tokens = 32
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 256, (l,)).tolist()
               for l in (5, 8, 13, 3, 16, 7, 11, 4, 9, 14, 6, 12)]
    icfg = {"max_batch_size": 4, "prompt_buckets": [16],
            "batch_buckets": [4], "max_seq_len": 256,
            "max_new_tokens": new_tokens}

    def serve(disagg_on):
        ic = dict(icfg)
        if disagg_on:
            ic["disagg"] = {"enabled": True}
        eng = InferenceEngine(cfg, params, ic, dtype=dtype)
        eng.warmup()
        _beat()
        outs = eng.generate(prompts, max_new_tokens=new_tokens,
                            temperature=0.0)
        p95 = eng._tracer.hist["ttft_ms"].percentile(0.95)
        rc = eng.steady_state_recompiles
        eng.close()
        return outs, p95 or 0.0, rc

    outs_i, p95_i, rc_i = serve(False)
    outs_d, p95_d, rc_d = serve(True)
    _beat()
    return _emit(
        "disagg_ttft_p95", round(p95_d, 3), "ms",
        round(p95_i / p95_d, 3) if p95_d > 0 else 0.0,
        {"interleaved_p95_ms": round(p95_i, 3),
         "greedy_parity": bool(outs_d == outs_i),
         "steady_state_recompiles": {"interleaved": rc_i, "disagg": rc_d},
         "requests": len(prompts), "new_tokens": new_tokens,
         "backend": jax.default_backend(),
         "functional_pin_only": jax.default_backend() != "tpu",
         "source": "tracer TTFT histogram, disagg vs interleaved"})


def bench_chunked_prefill_tbt(on_tpu, rtt):
    """Hardware-free row: TBT-max under a mixed one-long-many-short
    workload, chunked prefill vs whole-prompt prefill (ISSUE 19). The
    whole-prompt engine prefills the long prompt in one dispatch, so
    every in-flight short request's next token waits behind the full
    prompt — the TBT spike. The chunked engine runs decode FIRST each
    step and slips at most ONE chunk_tokens-wide chunk dispatch after
    it, so the worst inter-token gap is bounded by one decode + one
    chunk regardless of prompt length.

    Value = chunked TBT-max (ms); vs_baseline = whole-prompt TBT-max /
    chunked TBT-max (>1 means the spike was flattened). Wall clocks on
    CPU are noisy, so the ACCEPTANCE pins are structural: the bound
    itself is checked as pure dispatch ordering (at most one chunk
    dispatch per step, every decode of the step before it), greedy
    outputs bitwise equal to the whole-prompt engine, zero steady-state
    recompiles for both, and the warmup program-count reduction from
    collapsing the prompt-bucket ladder is reported in detail.
    """
    del on_tpu, rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine, Request
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    cfg = GPT2Config(vocab_size=61, max_position_embeddings=256,
                     hidden_size=64, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(5))
    new_tokens = 16
    rng = np.random.RandomState(11)
    shorts = [rng.randint(1, 61, (l,)).tolist() for l in (5, 7, 3, 6)]
    long_prompt = rng.randint(1, 61, (80,)).tolist()

    def serve(chunked):
        icfg = {"max_batch_size": 5, "batch_buckets": [1, 4],
                "max_seq_len": 128, "max_new_tokens": new_tokens,
                "paged_kv": {"page_size": 8, "num_pages": 96}}
        if chunked:
            # the ladder collapse: ONE short bucket; the long prompt is
            # chunk dispatches, not a 96-wide compile
            icfg["prompt_buckets"] = [8]
            icfg["chunked_prefill"] = {"enabled": True,
                                       "chunk_tokens": 16}
        else:
            # the ladder the chunked engine collapses: one bucket per
            # prompt-length regime, each a compiled program per batch
            # bucket
            icfg["prompt_buckets"] = [8, 32, 96]
        eng = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        warm = eng.warmup()
        _beat()
        done, uids = {}, {}
        for p in shorts:
            uids[eng.submit(Request(prompt=p, max_new_tokens=new_tokens,
                                    temperature=0.0, seed=0))] = tuple(p)
        # get the shorts decoding before the long prompt lands: the
        # landing step then mixes decode with (chunked) prefill
        for _ in range(3):
            for f in eng.step():
                done[uids[f.uid]] = f.tokens
        uids[eng.submit(Request(prompt=long_prompt,
                                max_new_tokens=new_tokens,
                                temperature=0.0, seed=0))] = \
            tuple(long_prompt)
        while not eng.scheduler.idle():
            for f in eng.step():
                done[uids[f.uid]] = f.tokens
        tbt_max = eng._tracer.hist["tbt_ms"].max or 0.0
        trace = eng._dispatch_trace.rows() \
            if eng._dispatch_trace is not None else []
        rc = eng.steady_state_recompiles
        eng.close()
        return done, tbt_max, warm, rc, trace

    ck_done, ck_tbt, ck_warm, ck_rc, ck_trace = serve(True)
    wp_done, wp_tbt, wp_warm, wp_rc, _ = serve(False)
    _beat()
    # the TBT bound as pure ordering: within every traced step, at most
    # one chunk dispatch, and every decode-phase dispatch precedes it
    by_step = {}
    for step, kind in ck_trace:
        by_step.setdefault(step, []).append(kind)
    chunk_steps = {s: k for s, k in by_step.items() if "chunk" in k}
    at_most_one = all(k.count("chunk") <= 1 for k in chunk_steps.values())
    decode_first = all(
        max((i for i, x in enumerate(k) if x == "decode"), default=-1)
        < k.index("chunk") for k in chunk_steps.values())
    return _emit(
        "chunked_prefill_tbt", round(ck_tbt, 3), "ms",
        round(wp_tbt / ck_tbt, 3) if ck_tbt > 0 else 0.0,
        {"whole_prompt_tbt_max_ms": round(wp_tbt, 3),
         "tbt_bound_structural": {
             "chunk_steps_traced": len(chunk_steps),
             "at_most_one_chunk_per_step": at_most_one,
             "decode_before_chunk": decode_first},
         "greedy_parity": bool(ck_done == wp_done),
         "steady_state_recompiles": {"chunked": ck_rc,
                                     "whole_prompt": wp_rc},
         "warmup_programs": {"chunked": ck_warm,
                             "whole_prompt": wp_warm},
         "long_prompt_tokens": len(long_prompt), "chunk_tokens": 16,
         "requests": len(shorts) + 1,
         "backend": jax.default_backend(),
         "source": "tracer TBT histogram + DispatchTrace ordering, "
                   "chunked vs whole-prompt prefill (hardware-free)"})


def bench_long_prompt_prefill_tokens_per_s(on_tpu, rtt):
    """TPU ladder row (next hardware window): prefill throughput on a
    long prompt, context-parallel chunked prefill (ring K/V rotation
    over the serving mesh) vs single-shard chunked prefill at identical
    config (ISSUE 19). On hardware the CP path divides each chunk's
    attention and MLP work over the mesh's model axis, so long-prompt
    TTFT drops roughly by the shard count; on a non-TPU backend the row
    is a functional pin (bitwise greedy parity CP vs single-shard, zero
    steady-state recompiles, cp_shards actually engaged), not a perf
    number.
    """
    del rtt
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params

    n_dev = len(jax.devices())
    shards = 2 if n_dev >= 2 else 1
    cfg = GPT2Config(vocab_size=256, max_position_embeddings=2048,
                     hidden_size=512 if on_tpu else 64,
                     num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.RandomState(0)
    plen = 1024 if on_tpu else 192
    prompt = rng.randint(1, 256, (plen,)).tolist()
    new_tokens = 8

    def serve(cp_on):
        icfg = {"max_batch_size": 1, "prompt_buckets": [16],
                "batch_buckets": [1],
                "max_seq_len": plen + new_tokens + 16,
                "max_new_tokens": new_tokens,
                "paged_kv": {"page_size": 16},
                "chunked_prefill": {"enabled": True, "chunk_tokens": 64,
                                    "cp_threshold_tokens":
                                        64 if cp_on else 0}}
        if cp_on and shards > 1:
            icfg["mesh"] = {"axes": {"model": shards}}
        eng = InferenceEngine(cfg, params, icfg, dtype=dtype)
        eng.warmup()
        _beat()
        t0 = time.perf_counter()
        outs = eng.generate([prompt], max_new_tokens=new_tokens,
                            temperature=0.0)
        wall = time.perf_counter() - t0
        ttft = eng._tracer.hist["ttft_ms"].max or 0.0
        state = eng.debug_state()
        rc = eng.steady_state_recompiles
        eng.close()
        return outs, plen / wall, ttft, rc, state

    cp_outs, cp_tps, cp_ttft, cp_rc, cp_state = serve(True)
    ss_outs, ss_tps, ss_ttft, ss_rc, _ = serve(False)
    _beat()
    ck = cp_state.get("chunked_prefill", {})
    return _emit(
        "long_prompt_prefill_tokens_per_s", round(cp_tps, 2),
        "prompt_tokens_per_s",
        round(cp_tps / ss_tps, 3) if ss_tps > 0 else 0.0,
        {"single_shard_tokens_per_s": round(ss_tps, 2),
         "ttft_ms": {"cp": round(cp_ttft, 3),
                     "single_shard": round(ss_ttft, 3)},
         "greedy_parity": bool(cp_outs == ss_outs),
         "steady_state_recompiles": {"cp": cp_rc, "single_shard": ss_rc},
         "cp_shards": ck.get("cp_shards"),
         "cp_reason": ck.get("cp_reason"),
         "prompt_tokens": plen, "chunk_tokens": 64,
         "hbm_peak_mb": _hbm_peak_mb(),
         "backend": jax.default_backend(),
         "functional_pin_only": jax.default_backend() != "tpu",
         "source": "engine wall clock over one long prompt, "
                   "context-parallel vs single-shard chunked prefill"})


# ------------------------------------------------------------- child mode


def run_child(metric):
    """Run one metric in this process; print exactly one JSON row.

    A stall watchdog still guards the child: a blocked device fetch hangs
    inside the C++ runtime where Python signal handlers never run, so a
    watchdog THREAD with os._exit is the only reliable escape (the parent's
    subprocess timeout is the backstop if even this thread is starved).
    """
    _beat()
    flight = _flight_path(metric)

    def _watchdog():
        while True:
            time.sleep(30)
            if time.monotonic() - _BEAT[0] > STALL_TIMEOUT:
                rec = _FLIGHT[0]
                if rec is not None:   # black box first, then the row
                    rec.dump("bench_stall", extra={"stall": {
                        "metric": metric, "phase": "bench_metric",
                        "timeout_s": STALL_TIMEOUT}}, stacks=True)
                _emit(metric, 0.0, "error", 0.0,
                      {"error": "device_unreachable: no benchmark "
                                f"progress for {STALL_TIMEOUT}s "
                                "(tunnel down?)", "skipped": True,
                       "stall_detected": {"phase": "bench_metric",
                                          "flight": flight}})
                os._exit(2)

    threading.Thread(target=_watchdog, daemon=True).start()
    import jax
    _apply_platform_override(jax)
    # arm the flight recorder AFTER the watchdog thread exists (the
    # package import below is itself inside the protected window — a
    # dead tunnel can wedge any first device touch)
    from deepspeed_tpu.utils.health import FlightRecorder
    _FLIGHT[0] = FlightRecorder(flight, ring_events=128)
    _FLIGHT[0].record({"event": "bench_start", "metric": metric})
    # persistent compile cache: children share compiled executables, so a
    # retried/resumed ladder only pays each remote compile once
    from deepspeed_tpu.utils.platform import enable_compile_cache
    enable_compile_cache(None)   # shared per-user default dir
    on_tpu = jax.default_backend() == "tpu"
    if os.environ.get("BENCH_REF_ATTN", "0") == "1":
        # A/B knob: route attention through the XLA-fused reference path
        # (bf16 MXU operands) instead of the Pallas flash kernels
        from deepspeed_tpu.ops.attention import flash as _F
        _F.set_attention_options(kernel="reference")
    if os.environ.get("BENCH_LEGACY_ATTN", "0") == "1":
        # A/B knob: the pre-PR-11 per-path Pallas kernels (flash.py
        # dense/causal + banded/hybrid/v2 sparse dispatch) instead of
        # the unified masked kernel
        from deepspeed_tpu.ops.attention import flash as _F
        from deepspeed_tpu.ops.sparse_attention import blocksparse as _bs
        _F.set_attention_options(kernel="flash")
        _bs.USE_MASKED_FLASH = False
    if os.environ.get("BENCH_DROPOUT_HASH1", "0") == "1":
        # A/B knob: single-round dropout-hash finalizer (same keep
        # statistics, ~half the tile-wide VPU hash work)
        from deepspeed_tpu.ops.attention import flash as _F
        _F._HASH_FINAL_ROUNDS = 1
    rtt = _rtt()
    _beat()

    if metric == "comm_wire_bytes_per_step":
        bench_comm_wire_bytes(on_tpu, rtt)
    elif metric == "comm_overlap_structure":
        bench_comm_overlap_structure(on_tpu, rtt)
    elif metric == "mfu_cost_model":
        bench_mfu_cost_model(on_tpu, rtt)
    elif metric == "host_dispatch_overhead":
        bench_host_dispatch_overhead(on_tpu, rtt)
    elif metric == "decode_throughput":
        bench_decode_throughput(on_tpu, rtt)
    elif metric == "paged_kv_occupancy":
        bench_paged_kv_occupancy(on_tpu, rtt)
    elif metric == "paged_decode_bytes":
        bench_paged_decode_bytes(on_tpu, rtt)
    elif metric == "masked_flash_flops_bytes":
        bench_masked_flash_flops_bytes(on_tpu, rtt)
    elif metric == "serve_trace_overhead":
        bench_serve_trace_overhead(on_tpu, rtt)
    elif metric == "health_overhead":
        bench_health_overhead(on_tpu, rtt)
    elif metric == "async_ckpt_stall_ms":
        bench_async_ckpt_stall(on_tpu, rtt)
    elif metric == "spec_decode_accepted_per_dispatch":
        bench_spec_decode_accepted_per_dispatch(on_tpu, rtt)
    elif metric == "disagg_dispatch_structure":
        bench_disagg_dispatch_structure(on_tpu, rtt)
    elif metric == "chunked_prefill_tbt":
        bench_chunked_prefill_tbt(on_tpu, rtt)
    elif metric == "long_prompt_prefill_tokens_per_s":
        bench_long_prompt_prefill_tokens_per_s(on_tpu, rtt)
    elif metric == "fleet_drain_goodput":
        bench_fleet_drain_goodput(on_tpu, rtt)
    elif metric == "fleet_migration_goodput":
        bench_fleet_migration_goodput(on_tpu, rtt)
    elif metric == "fleet_trace_overhead":
        bench_fleet_trace_overhead(on_tpu, rtt)
    elif metric == "quant_serving_bytes":
        bench_quant_serving_bytes(on_tpu, rtt)
    elif metric == "quant_kv_occupancy":
        bench_quant_kv_occupancy(on_tpu, rtt)
    elif metric == "quant_decode_tokens_per_s":
        bench_quant_decode_tokens_per_s(on_tpu, rtt)
    elif metric == "paged_decode_tokens_per_s":
        bench_paged_decode_tokens_per_s(on_tpu, rtt)
    elif metric == "disagg_ttft_p95":
        bench_disagg_ttft_p95(on_tpu, rtt)
    elif metric == "bert_large_samples_per_s":
        bench_bert_large(on_tpu, rtt)
    elif metric == "bert_onebit_samples_per_s":
        bench_bert_onebit(on_tpu, rtt)
    elif metric == "sparse_attention_speedup_s8k":
        bench_sparse_attention(on_tpu, rtt)
    elif metric == "sparse_attn_speedup_v2":
        bench_sparse_attn_speedup_v2(on_tpu, rtt)
    elif metric == "gpt2_train_mfu_dropout":
        bench_gpt2(on_tpu, rtt, 0.1, "gpt2_train_mfu_dropout")
    elif metric == "gpt2_train_mfu":
        bench_gpt2(on_tpu, rtt, 0.0, "gpt2_train_mfu")
    else:
        raise SystemExit(f"unknown metric {metric!r}")


# ------------------------------------------------------------ parent mode


def _git_head():
    """Resume key: a digest of the sources that determine the measured
    numbers — bench.py itself plus everything importable from the
    package (py/json/cpp/h under deepspeed_tpu/ and csrc/, setup.py).
    Edits to tests/docs/examples/notes do NOT invalidate checkpointed
    rows (they cannot change a measurement); any edit to benchmarked
    code does, whether committed or not."""
    import hashlib
    repo = os.path.dirname(os.path.abspath(__file__))
    # sources only, never build artifacts: the runtime-built .so would
    # make the key unstable (rebuilt on import), and its inputs (.cpp/.h
    # + Makefile flags) are what actually determine the measurement
    exts = (".py", ".json", ".cpp", ".cc", ".h")
    names = ("Makefile",)
    roots = ["bench.py", "setup.py", "deepspeed_tpu", "csrc"]
    try:
        h = hashlib.sha256()
        for root in roots:
            path = os.path.join(repo, root)
            if os.path.isfile(path):
                files = [path]
            else:
                files = []
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = [d for d in dirnames
                                   if d != "__pycache__"]
                    files.extend(os.path.join(dirpath, f)
                                 for f in filenames
                                 if f.endswith(exts) or f in names)
            for f in sorted(files):
                try:
                    with open(f, "rb") as fh:
                        content = fh.read()
                except OSError:
                    continue   # racing writer/deleter; skip, stay stable
                h.update(os.path.relpath(f, repo).encode())
                h.update(content)
        # measurement-config env knobs (BENCH_SCAN_LAYERS, BENCH_MASTER_FREE,
        # future ones) change what a row measures and must invalidate it;
        # control knobs (timeouts/paths/retries/resume) must not
        control = {"BENCH_PARTIAL", "BENCH_METRIC_TIMEOUT",
                   "BENCH_METRIC_RETRIES", "BENCH_NO_RESUME",
                   "BENCH_STALL_TIMEOUT", "BENCH_HW_FREE_TIMEOUT",
                   "BENCH_TIME_BUDGET", "BENCH_FLIGHT_PATH"}
        for k in sorted(os.environ):
            if k.startswith("BENCH_") and k not in control:
                h.update(f"{k}={os.environ[k]}".encode())
        # the measurement platform is part of the resume key: rows from
        # a forced-CPU run must never resume as hardware rows (the .cpu
        # partial-path suffix only protects the DEFAULT path)
        if os.environ.get("JAX_PLATFORMS"):
            h.update(f"JAX_PLATFORMS={os.environ['JAX_PLATFORMS']}"
                     .encode())
        return "src-" + h.hexdigest()[:16]
    except Exception:
        return None


def _load_partial(head):
    """Rows checkpointed by a previous run at the SAME commit, else {}."""
    if os.environ.get("BENCH_NO_RESUME") or head is None:
        return {}
    rows = {}
    try:
        with open(PARTIAL_PATH) as f:
            header = json.loads(f.readline())
            if header.get("head") != head:
                return {}
            for line in f:
                row = json.loads(line)
                if row.get("unit") != "error":
                    rows[row["metric"]] = row
    except Exception:
        return {}
    return rows


def _stale_partial(head):
    """Rows from a previous COMPLETED ladder at a DIFFERENT source
    digest. Never resumed as measurements — attached to dead-tunnel
    error rows (clearly labeled) so the audit trail points at the most
    recent hardware data instead of a bare error."""
    try:
        with open(PARTIAL_PATH) as f:
            header = json.loads(f.readline())
            if header.get("head") == head:
                return None
            rows = {}
            for line in f:
                row = json.loads(line)
                if row.get("unit") != "error":
                    rows[row["metric"]] = {
                        "value": row["value"], "unit": row["unit"],
                        "vs_baseline": row["vs_baseline"]}
            if not rows:
                return None
            return {"source_digest": header.get("head"),
                    "note": "measured by an EARLIER source revision; "
                            "NOT a current measurement — see "
                            "BENCH_NOTES.md for the full rows",
                    "rows": rows}
    except Exception:
        return None


def _append_partial(head, row, fresh):
    """Returns the next value of ``fresh``: stays True if the header
    write failed (appending under a stale different-commit header would
    let a later run resume the wrong rows)."""
    try:
        mode = "w" if fresh else "a"
        with open(PARTIAL_PATH, mode) as f:
            if fresh:
                f.write(json.dumps({"head": head}) + "\n")
            f.write(json.dumps(row) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return False
    except Exception:
        # checkpointing is best-effort; never kill the ladder for it
        return fresh


def _probe_tunnel(timeout=300):
    """True iff a tiny device matmul completes in a fresh subprocess ON
    THE TPU BACKEND. The backend assertion is the round-5 fix: a
    CPU-fallback matmul once passed this probe and burned the hardware
    window measuring nothing — the probe must prove the accelerator, not
    just a working Python. A run explicitly forced to CPU
    (JAX_PLATFORMS=cpu...) only asserts completion."""
    forced_cpu = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p: jax.config.update('jax_platforms', p)\n"
            "import numpy as np, jax.numpy as jnp\n"
            "x = jnp.ones((256,256), jnp.bfloat16)\n"
            "np.asarray(x @ x)\n"
            "assert os.environ.get('JAX_PLATFORMS','').startswith('cpu') "
            "or jax.default_backend() == 'tpu', (\n"
            "    'probe ran on %s, not tpu' % jax.default_backend())\n"
            "print('ok:' + jax.default_backend())")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        if "ok:" not in r.stdout:
            return False
        backend = r.stdout.split("ok:", 1)[1].strip().splitlines()[0]
        return backend == "tpu" or forced_cpu
    except Exception:
        return False


def _last_metric_row(stdout, metric):
    """Last JSON row for ``metric`` in a child's stdout, preferring
    VALUE rows over error rows: a child whose stall watchdog fired
    during teardown — AFTER the measurement row streamed — appends a
    ``device_unreachable`` error row last, and taking it would discard
    a completed measurement (the same teardown-hang failure the
    TimeoutExpired salvage covers, via the in-child watchdog instead of
    the parent timeout). None when no row matched."""
    row = err_row = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if cand.get("metric") == metric:
                if cand.get("unit") == "error":
                    err_row = cand
                else:
                    row = cand
    return row if row is not None else err_row


# Postmortems salvaged from stalled children, keyed by metric. A side
# table (not a third return value) because the (row, err) contract of
# _run_metric_subprocess is pinned by the ladder tests.
_STALL_POSTMORTEMS = {}


def _salvage_stall(metric, flight, err_row=None):
    """Fold a stalled child's black box into _STALL_POSTMORTEMS so the
    parent's error row carries the postmortem (which phase went silent,
    how much pre-stall telemetry survived) instead of a bare timeout."""
    post = {}
    if err_row is not None:
        sd = (err_row.get("detail") or {}).get("stall_detected")
        if sd:
            post["stall_detected"] = sd
    try:
        with open(flight) as f:
            payload = json.load(f)
        post["flight"] = {
            "path": flight,
            "trigger": payload.get("trigger"),
            "rows": len(payload.get("rows", [])),
            "stall": payload.get("stall"),
            "threads": len(payload.get("stacks", [])),
        }
    except FileNotFoundError:
        pass   # child died before the ring armed; nothing to attach
    except Exception:
        post["flight"] = {"path": flight, "error": "unreadable"}
    if post:
        _STALL_POSTMORTEMS[metric] = post


def _run_metric_subprocess(metric):
    """(row, err): parse the child's last JSON row; err string on failure.

    Per-row time budget: hardware-free rows get the tight
    HW_FREE_TIMEOUT, device rows the full METRIC_TIMEOUT, and BOTH are
    clamped to what is left of the overall ladder budget — a slow row
    can delay later rows but never erase already-streamed ones.

    Rows are streamed by the child the moment they land, so a child
    killed by the timeout may STILL have finished its measurement (a
    teardown hang — historically a dead tunnel during device shutdown):
    the captured-so-far stdout is parsed and a completed value row is
    salvaged instead of discarded (the r02–r05 "one hang zeroed the
    revision" fix)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--metric", metric]
    timeout = HW_FREE_TIMEOUT if metric in HW_FREE else METRIC_TIMEOUT
    rem = _remaining_budget()
    if rem is not None:
        timeout = max(min(timeout, int(rem) - 10), 30)
    # every child gets a deterministic flight-recorder path so a stalled
    # child's black box can be salvaged even after a hard kill; a stale
    # file from an earlier run must not masquerade as this run's dump
    flight = _flight_path(metric)
    env = dict(os.environ)
    env["BENCH_FLIGHT_PATH"] = flight
    try:
        os.remove(flight)
    except OSError:
        pass
    if metric in HW_FREE:
        # hardware-free audits run on a virtual 8-device CPU mesh in
        # their own child — deterministic, tunnel-independent
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        # the child's in-process stall watchdog must not outlive the
        # row budget (it defaults to tracking the device-row budget)
        env["BENCH_STALL_TIMEOUT"] = str(max(timeout - 30, 30))
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout, env=env)
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        row = _last_metric_row(out, metric)
        if row is not None and row.get("unit") != "error":
            row.setdefault("detail", {})["salvaged"] = (
                f"child exceeded {timeout}s after the row landed "
                "(teardown hang); measurement kept")
            return row, None
        _salvage_stall(metric, flight, err_row=row)
        return None, f"metric subprocess exceeded {timeout}s (killed)"
    row = _last_metric_row(r.stdout, metric)
    if row is None:
        _salvage_stall(metric, flight)
        tail = (r.stderr or r.stdout or "").strip().splitlines()[-3:]
        return None, f"child rc={r.returncode}, no row; tail={' | '.join(tail)}"
    if row.get("unit") == "error":
        _salvage_stall(metric, flight, err_row=row)
        return None, str(row.get("detail", {}).get("error", "child error row"))
    if r.returncode != 0:
        # value row streamed, then the child died (in-child watchdog
        # os._exit, teardown crash): the measurement is complete — keep
        # it, flagged
        row.setdefault("detail", {})["salvaged"] = (
            f"child exited rc={r.returncode} after the row landed; "
            "measurement kept")
    return row, None


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--metric":
        run_child(sys.argv[2])
        return

    head = _git_head()
    done = _load_partial(head)
    fresh = not done  # rewrite the partial file unless resuming
    if done:
        print(f"# resuming {len(done)} checkpointed row(s) from "
              f"{PARTIAL_PATH}", file=sys.stderr, flush=True)

    # Streaming guarantee (round-5 VERDICT): every completed row is
    # fsynced to the partial file (_append_partial) AND echoed to stdout
    # THE MOMENT it lands, so an rc=124 kill mid-ladder leaves the
    # finished rows on both channels instead of zero captured bytes.
    # The canonical ordered emission (headline last) repeats them at the
    # end; consumers keyed on metric name take the last occurrence.
    for metric in METRICS:
        if metric in done:
            _emit_row(done[metric])

    failed = {}

    # hardware-free metrics first (forced-CPU children): they cannot
    # hang on the tunnel and land even when the device is unreachable
    for metric in [m for m in METRICS if m in HW_FREE and m not in done]:
        if _budget_exhausted():
            failed[metric] = (f"skipped: ladder time budget "
                              f"({TIME_BUDGET}s) exhausted")
            continue
        row, err = _run_metric_subprocess(metric)
        if row is not None:
            done[metric] = row
            fresh = _append_partial(head, row, fresh)
            _emit_row(row)
        else:
            failed[metric] = err or "unknown failure"

    need_hw = [m for m in METRICS if m not in done and m not in HW_FREE]
    failed_detail = {}
    tunnel_dead = False
    if need_hw:
        # upfront liveness gate: with a dead tunnel every child would
        # burn METRIC_TIMEOUT before failing (~25 min per metric);
        # probing twice up front converts that into explicit error rows
        # in minutes. The probe asserts default_backend() == "tpu" — a
        # CPU-fallback matmul must never pass for hardware rows. Probe
        # time is clamped to the ladder budget so the gate itself can
        # never eat the window the completed rows need to be reported.
        probe_t = 300
        rem = _remaining_budget()
        if rem is not None:
            probe_t = max(min(300, int(rem / 3)), 30)
        if not _probe_tunnel(probe_t) and \
                (time.sleep(min(60, probe_t)) or not _probe_tunnel(probe_t)):
            tunnel_dead = True
            err = ("device_unreachable: probe-before-run failed twice "
                   "to complete a matmul on the tpu backend — hardware "
                   "rows skipped fast instead of hanging per-metric")
            stale = _stale_partial(head)
            detail = {"error": err, "skipped": True}
            if stale:
                detail["last_completed_ladder"] = stale
            for metric in need_hw:
                failed[metric] = err
                failed_detail[metric] = detail

    if not tunnel_dead:
        for metric in need_hw:
            if _budget_exhausted():
                failed[metric] = (f"skipped: ladder time budget "
                                  f"({TIME_BUDGET}s) exhausted; "
                                  "completed rows already streamed")
                continue
            err = None
            for attempt in range(1 + METRIC_RETRIES):
                if attempt > 0:
                    if _budget_exhausted(floor=120):
                        err = f"{err}; budget exhausted, retry skipped"
                        break
                    # only retry against a live tunnel; a second hang
                    # costs another METRIC_TIMEOUT for nothing. The
                    # probe is clamped to the remaining budget like the
                    # upfront gate — it must never be what overruns it.
                    rem = _remaining_budget()
                    probe_t = (300 if rem is None
                               else max(min(300, int(rem / 3)), 30))
                    if not _probe_tunnel(probe_t):
                        time.sleep(min(60, probe_t))
                        if not _probe_tunnel(probe_t):
                            err = f"{err}; tunnel probe dead, retry skipped"
                            break
                row, err = _run_metric_subprocess(metric)
                if row is not None:
                    done[metric] = row
                    fresh = _append_partial(head, row, fresh)
                    _emit_row(row)
                    break
            if metric not in done:
                failed[metric] = err or "unknown failure"

    # Emit everything in canonical order, headline last. Completed rows
    # are real; failed rows are explicit error rows — a flaky tunnel
    # yields N good rows + per-metric errors, never one bare error line.
    def error_row(metric):
        detail = failed_detail.get(
            metric, {"error": failed.get(metric, "unknown failure")})
        post = _STALL_POSTMORTEMS.get(metric)
        if post:
            # the salvaged black box rides the error row: which phase
            # went silent + how much pre-stall telemetry survived
            detail = dict(detail, stalled=post)
        _emit(metric, 0.0, "error", 0.0, detail)

    for metric in METRICS:
        if metric == HEADLINE:
            continue
        if metric in done:
            _emit_row(done[metric])
        else:
            error_row(metric)
    if HEADLINE in done:
        _emit_row(done[HEADLINE])
    else:
        error_row(HEADLINE)


if __name__ == "__main__":
    main()
