"""Benchmark: prints ONE JSON line with the headline metric.

Run on real TPU hardware by the driver at end of round. Currently measures
the engine's fused train-step throughput on a matmul-heavy MLP in bf16
(placeholder until the GPT-2/BERT model families land); reports achieved
TFLOP/s and MFU vs the reference's 52%-of-peak V100 BERT number
(BASELINE.md: 66 TFLOPS/GPU = 52% of V100 peak).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu

    hidden = 2048
    n_layers = 8
    batch = 256
    steps = 100

    key = jax.random.PRNGKey(0)
    params = {}
    for i in range(n_layers):
        key, k = jax.random.split(key)
        params[f"layer_{i}"] = {
            "w": jax.random.normal(k, (hidden, hidden), jnp.float32)
            / np.sqrt(hidden),
            "b": jnp.zeros((hidden,), jnp.float32),
        }

    def loss_fn(p, b):
        x = b["x"]
        for i in range(n_layers):
            layer = p[f"layer_{i}"]
            x = x @ layer["w"].astype(x.dtype) + layer["b"].astype(x.dtype)
            if i < n_layers - 1:
                x = jax.nn.relu(x)
        return jnp.mean((x - b["y"].astype(x.dtype)) ** 2)

    n_dev = jax.device_count()
    config = {
        "train_micro_batch_size_per_gpu": batch // n_dev if n_dev > 1 else batch,
        "gradient_accumulation_steps": 1,
        "bf16": {"enabled": True},
        "steps_per_print": 10**9,  # no mid-bench host fetches
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
    }
    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params, config=config)

    rng = np.random.RandomState(0)
    b = {"x": rng.randn(batch, hidden).astype(np.float32),
         "y": rng.randn(batch, hidden).astype(np.float32)}
    # device-resident batch: host->device transfer is NOT part of the
    # measured step (and the device may sit across a network tunnel)
    from jax.sharding import NamedSharding, PartitionSpec
    b = jax.device_put(b, NamedSharding(
        engine.mesh, PartitionSpec("data" if n_dev > 1 else None)))

    # warmup/compile; a value fetch (not block_until_ready) is the only
    # reliable completion barrier across the device tunnel
    loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    zf = jax.jit(lambda: jax.numpy.zeros(()))
    np.asarray(zf())  # compile
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(zf())
        rtts.append(time.perf_counter() - t0)
    rtt = min(rtts)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9)

    # fwd+bwd ≈ 3x fwd matmul flops
    flops_per_step = 3 * 2 * batch * hidden * hidden * n_layers
    tflops = flops_per_step * steps / dt / 1e12
    # v5e peak bf16 ≈ 197 TFLOP/s; v5p ≈ 459
    peak = 197.0
    mfu = tflops / peak
    # reference fused-kernel hardware efficiency: 52% of peak (BASELINE.md)
    print(json.dumps({
        "metric": "train_step_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.52, 4),
        "detail": {"tflops": round(tflops, 2), "steps_per_s": round(steps / dt, 2),
                   "loss": float(loss)},
    }))


if __name__ == "__main__":
    main()
