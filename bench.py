"""Benchmark: prints ONE JSON line with the headline metric.

Flagship workload: GPT-2 pretraining step (the reference's Megatron-GPT2 +
ZeRO-2 headline, BASELINE.md) — bf16, Pallas flash attention, fused compiled
train step, on whatever devices are live (1 real TPU chip under the driver).

Timing protocol: value-fetch completion barrier + RTT subtraction, because
block_until_ready acks early across the device tunnel (see
.claude/skills/verify/SKILL.md).

MFU accounting: model flops/token = 6*N + 12*L*S*H (PaLM appendix formula:
6N covers fwd+bwd matmuls, attention term extra); peak = 197 TFLOP/s bf16
(TPU v5e). vs_baseline compares against the reference's 52%-of-peak
hardware-efficiency headline (BASELINE.md: 66/126.6 TFLOPS on V100).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, count_params, gpt2_loss_fn, init_gpt2_params)

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # GPT-2 345M: the reference baseline's stated config
        # (BASELINE.md north star: Megatron-GPT2 345M + ZeRO-2 ≥45% MFU)
        cfg = GPT2Config(vocab_size=50304,  # 128-aligned vocab
                         max_position_embeddings=1024,
                         hidden_size=1024, num_layers=24, num_heads=16,
                         embd_dropout=0.0, attn_dropout=0.0,
                         resid_dropout=0.0)
        batch, seq, steps = 8, 1024, 15
    else:  # CPU smoke fallback
        cfg = GPT2Config(vocab_size=512, max_position_embeddings=128,
                         hidden_size=64, num_layers=2, num_heads=2,
                         embd_dropout=0.0, attn_dropout=0.0,
                         resid_dropout=0.0)
        batch, seq, steps = 4, 64, 3

    n_dev = jax.device_count()
    params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    loss_fn = gpt2_loss_fn(cfg, dtype=jnp.bfloat16, deterministic=True)

    engine, *_ = deepspeed_tpu.initialize(
        model=loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": max(batch // n_dev, 1),
            "gradient_accumulation_steps": 1,
            "bf16": {"enabled": True},
            "steps_per_print": 10**9,
            "zero_optimization": {"stage": 2 if n_dev > 1 else 0},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        })

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec
    b = {"input_ids": jax.device_put(
        ids, NamedSharding(engine.mesh,
                           PartitionSpec("data" if n_dev > 1 else None)))}

    loss = engine.train_batch(iter([b]))
    np.asarray(loss)  # compile + settle

    zf = jax.jit(lambda: jnp.zeros(()))
    np.asarray(zf())
    rtt = min(_fetch_time(zf) for _ in range(3))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(iter([b]))
    np.asarray(loss)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9)

    tokens_per_step = batch * seq
    tokens_per_s = tokens_per_step * steps / dt
    flops_per_token = (6 * n_params +
                       12 * cfg.num_layers * seq * cfg.hidden_size)
    tflops = tokens_per_s * flops_per_token / 1e12
    peak = 197.0 if on_tpu else 1e9
    mfu = tflops / peak / max(n_dev, 1)

    print(json.dumps({
        "metric": "gpt2_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.52, 4),
        "detail": {
            "model": f"gpt2-{n_params/1e6:.0f}M",
            "tokens_per_s_per_chip": round(tokens_per_s / max(n_dev, 1), 1),
            "tflops_per_chip": round(tflops / max(n_dev, 1), 2),
            "step_ms": round(dt / steps * 1000, 2),
            "loss": float(loss),
        },
    }))


def _fetch_time(zf):
    import numpy as np
    t0 = time.perf_counter()
    np.asarray(zf())
    return time.perf_counter() - t0


if __name__ == "__main__":
    main()
