"""Install sanity check (reference basic_install_test.py analog): import
the package, report versions/backend, and probe the native host kernel.

    PYTHONPATH=/root/repo python basic_install_test.py
"""

import jax

try:
    import deepspeed_tpu
    print("deepspeed_tpu successfully imported")
except ImportError as err:
    raise err

print(f"jax version: {jax.__version__}")
print(f"deepspeed_tpu install path: {deepspeed_tpu.__path__}")
print(f"deepspeed_tpu info: {deepspeed_tpu.__version__}, "
      f"{deepspeed_tpu.__git_hash__}, {deepspeed_tpu.__git_branch__}")

try:
    from deepspeed_tpu.ops.adam.cpu_adam import load_library
    lib = load_library()
    print("native host Adam successfully loaded "
          f"(simd width {lib.ds_adam_simd_width()})"
          if lib else "native host Adam NOT built (numpy fallback active)")
except Exception as e:  # the runtime has a numpy fallback either way
    print(f"native host Adam probe failed ({type(e).__name__}: {e}); "
          "numpy fallback active")

print(f"default backend: {jax.default_backend()} "
      f"(devices: {jax.local_device_count()})")
