"""DeepSpeed-TPU installation (reference setup.py, minus CUDA extensions —
the TPU compute path is JAX/XLA/Pallas; the native host pieces build as
ctypes shared libraries from csrc/ at install time, with an on-demand
rebuild fallback in the loader for source checkouts)."""

import os
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py

_HERE = os.path.dirname(os.path.abspath(__file__))


class BuildNativeThenPy(build_py):
    """Build csrc/ ctypes libraries before packaging (reference setup.py
    built its op extensions here; DS_BUILD_OPS=0 skips, like the
    reference's env toggles). Serialized through the same .buildlock the
    runtime loader uses, so a concurrent importer never dlopens a
    half-written .so."""

    def run(self):
        csrc = os.path.join(_HERE, "csrc")
        if os.environ.get("DS_BUILD_OPS", "1") != "0":
            if os.path.isdir(csrc):
                # best-effort, mirroring the runtime loader's graceful
                # numpy fallback: a non-POSIX or make-less environment
                # must still pip-install cleanly
                try:
                    lock = os.path.join(_HERE, "deepspeed_tpu", "ops",
                                        "adam",
                                        "libdstpu_adam.so.buildlock")
                    with open(lock, "w") as fh:
                        import fcntl
                        fcntl.flock(fh, fcntl.LOCK_EX)
                        subprocess.check_call(["make", "-C", csrc])
                except Exception as e:  # noqa: BLE001
                    print(f"deepspeed_tpu: native build skipped ({e!r}) "
                          "— the runtime loader falls back to the numpy "
                          "Adam path")
            else:
                print("deepspeed_tpu: csrc/ not present (sdist without "
                      "sources?) — skipping native build; the runtime "
                      "loader falls back to the numpy Adam path")
        super().run()


setup(
    cmdclass={"build_py": BuildNativeThenPy},
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native deep learning optimization library: ZeRO, "
                "pipeline/3D parallelism, fused Pallas kernels, sparse "
                "attention — DeepSpeed capabilities on JAX/XLA",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    package_data={"deepspeed_tpu.ops.adam": ["*.so"],
                  "deepspeed_tpu.ops.attention": ["block_table.json"]},
    scripts=["bin/dstpu", "bin/ds", "bin/dstpu_ssh"],
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
)
