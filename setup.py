"""DeepSpeed-TPU installation (reference setup.py, minus CUDA extensions —
native components are prebuilt ctypes shared libraries under csrc/)."""

from setuptools import find_packages, setup

setup(
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native deep learning optimization library: ZeRO, "
                "pipeline/3D parallelism, fused Pallas kernels, sparse "
                "attention — DeepSpeed capabilities on JAX/XLA",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    scripts=["bin/dstpu", "bin/ds", "bin/dstpu_ssh"],
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
)
