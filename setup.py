"""DeepSpeed-TPU installation (reference setup.py, minus CUDA extensions —
the TPU compute path is JAX/XLA/Pallas; the native host pieces build as
ctypes shared libraries from csrc/ at install time, with an on-demand
rebuild fallback in the loader for source checkouts)."""

import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildNativeThenPy(build_py):
    """Build csrc/ ctypes libraries before packaging (reference setup.py
    built its op extensions here; DS_BUILD_OPS=0 skips, like the
    reference's env toggles)."""

    def run(self):
        import os
        if os.environ.get("DS_BUILD_OPS", "1") != "0":
            subprocess.check_call(["make", "-C", "csrc"])
        super().run()


setup(
    cmdclass={"build_py": BuildNativeThenPy},
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native deep learning optimization library: ZeRO, "
                "pipeline/3D parallelism, fused Pallas kernels, sparse "
                "attention — DeepSpeed capabilities on JAX/XLA",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    scripts=["bin/dstpu", "bin/ds", "bin/dstpu_ssh"],
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
)
