#!/bin/bash
# One-shot hardware round: run when the TPU tunnel is back.
#   PYTHONPATH=/root/repo:/root/.axon_site bash tools/on_tpu_up.sh
# (keep the axon site dir on PYTHONPATH — it registers the TPU plugin)
# Ordered by value per minute of tunnel time (windows have been
# 20-45 min): 1. probe; 2. on-chip kernel parity sweep (~5 min — the
# go/no-go that the kernels the ladder times are CORRECT on hardware);
# 3. autotune sweep — BEFORE the ladder because it writes
#    block_table.json, which is bench-visible source: the ladder must
#    measure the final table. Idempotent (covered shapes skip), so once
#    the table has this round's entries the digest stays stable and
#    later windows resume the ladder's partial rows untouched;
# 4. bench ladder (the driver-protocol artifact; resumable);
# 5. sparse kernel A/B matrix (banded/v2/flash/vanilla + fwd/bwd split);
# 6. headline variant A/Bs (master-free, scan_layers, ref-attn,
#    adam8bit, dropout-hash1).
# Outputs land in /tmp/tpu_round/.
set -u -o pipefail   # tee must not mask the bench exit code
OUT=/tmp/tpu_round
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

# Single-core host: local CPU load inflates scan-amortized timings
# (a stale watch loop once doubled measured times). The hardware
# window outranks any local test run — clear it first. Anchored
# patterns: a bare "pytest" would match any argv mentioning the word
# (the watcher's own tail, an editor on a log).
pkill -f "python[^ ]* -m pytest" 2>/dev/null || true
pkill -f "hw_kernel_checks.py --allow-cpu" 2>/dev/null || true
sleep 5   # let the killed processes actually release the core

echo "== probe"
if ! timeout 300 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((256,256), jnp.bfloat16); np.asarray(x @ x)
try:
    kind = jax.devices()[0].device_kind
except Exception as e:   # never abort the window over metadata
    kind = f'unknown ({type(e).__name__})'
print('alive:', kind)
"; then
  echo "chip unreachable; aborting" >&2
  exit 1
fi

echo "== on-chip kernel parity sweep"
timeout 1800 python tools/hw_kernel_checks.py 2>&1 | tee "$OUT/kernel_checks.log"
kc_rc=$?
if [ "$kc_rc" -ne 0 ]; then
  # go/no-go: do not spend the window benchmarking kernels just proven
  # wrong (or a tunnel that died mid-sweep); the watcher re-arms
  echo "kernel parity sweep failed (rc=$kc_rc); aborting round" >&2
  exit "$kc_rc"
fi

echo "== autotune block table (idempotent; writes deepspeed_tpu/ops/attention/block_table.json)"
timeout 5400 python tools/autotune_blocks.py 2>&1 | tee "$OUT/autotune.log"
at_rc=$?

echo "== bench ladder"
# Remote compiles through the tunnel can be slow: give each metric child
# 40 min (first child pays the model compile) and the ladder 4 h — the
# upfront liveness gate + probe-gated retries bound the all-dead case.
BENCH_METRIC_TIMEOUT=${BENCH_METRIC_TIMEOUT:-2400} \
  timeout 14400 python bench.py 2> "$OUT/bench.err" | tee "$OUT/bench.jsonl"
rc=$?
# children of the --metric A/B runs below inherit these: a fresh variant
# compile (master-free / scan_layers changes the HLO) can exceed the
# default 900s child stall watchdog with the tunnel alive
export BENCH_METRIC_TIMEOUT=${BENCH_METRIC_TIMEOUT:-2400}
export BENCH_STALL_TIMEOUT=${BENCH_STALL_TIMEOUT:-2280}

echo "== sparse kernel A/B matrix (+ BigBird hybrid + one traced dispatch)"
# 5400s: the round-5 BigBird pair adds two grad-timed variants, each
# paying fresh Pallas compiles through the tunnel
AB_TRACE=1 timeout 5400 python tools/ab_coarse_sparse.py 2>&1 | tee "$OUT/sparse_ab.log"
ab_rc=$?

echo "== interleave V=2 vs V=4 tick-granularity timing"
timeout 1800 python tools/ab_interleave.py 2>&1 | tee "$OUT/interleave_ab.log" || true

echo "== headline variant A/Bs (log-only; the ladder rows above are canonical)"
BENCH_MASTER_FREE=1 timeout 2400 python bench.py --metric gpt2_train_mfu \
  2>&1 | tee "$OUT/headline_master_free.log"
BENCH_SCAN_LAYERS=1 timeout 2400 python bench.py --metric gpt2_train_mfu \
  2>&1 | tee "$OUT/headline_scan_layers.log"
# single-round dropout-hash finalizer vs default on the dropout row
BENCH_DROPOUT_HASH1=1 timeout 2400 python bench.py \
  --metric gpt2_train_mfu_dropout 2>&1 | tee "$OUT/dropout_hash1.log"
# XLA-fused attention vs Pallas flash at short seq (BERT s128) and s1024
BENCH_REF_ATTN=1 timeout 2400 python bench.py \
  --metric bert_large_samples_per_s 2>&1 | tee "$OUT/bert_ref_attn.log"
BENCH_REF_ATTN=1 timeout 2400 python bench.py --metric gpt2_train_mfu \
  2>&1 | tee "$OUT/headline_ref_attn.log"
# 8-bit optimizer states: ~4x less optimizer-state HBM at the update
BENCH_ADAM8BIT=1 timeout 2400 python bench.py --metric gpt2_train_mfu \
  2>&1 | tee "$OUT/headline_adam8bit.log"

echo "== done (kernel checks rc=$kc_rc, autotune rc=$at_rc, bench rc=$rc, sparse A/B rc=$ab_rc); review $OUT and commit block_table.json + BENCH_NOTES update"
# an autotune or A/B failure must not read as a complete round either
# (the watcher re-arms; bench rows resume from the partial file on retry)
[ "$rc" -eq 0 ] && rc=$at_rc
[ "$rc" -eq 0 ] && rc=$ab_rc
[ "$rc" -eq 0 ] && rc=$kc_rc
exit $rc
