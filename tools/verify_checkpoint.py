#!/usr/bin/env python
"""Offline checkpoint integrity checker.

Verifies a checkpoint directory without constructing an engine: COMMITTED
marker presence, per-file sizes + CRC32 checksums, and a per-leaf chunk
coverage report (every element of every leaf's global shape accounted for
by exactly the saved fragments — the invariant the elastic loader
depends on, runtime/checkpoint.py load_tree_sharded).

Usage::

    python tools/verify_checkpoint.py <save_dir>            # resolve latest
    python tools/verify_checkpoint.py <save_dir> --tag TAG  # one tag
    python tools/verify_checkpoint.py <save_dir>/<tag>      # tag dir direct
    ... [--no-crc] [--all] [--expect-step N] [--serve-ready]

Exit status 0 iff everything checked is committed, verified, and fully
covered — and, with ``--expect-step N``, the newest committed
step-suffixed tag is at least step N (the supervisor's resume sanity
check: a relaunch that would silently lose more progress than the
preemption took exits nonzero here first). Preemption-tagged
checkpoints (``meta.preempted`` — committed by the graceful drain) are
reported distinctly.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.runtime import checkpoint as ckpt  # noqa: E402


def _leaf_coverage(ckpt_dir, name):
    """[(leaf, covered_elements, total_elements, n_chunks)] for one
    sharded pytree; chunk volumes are summed (fragments never overlap)."""
    rows = []
    merged = ckpt._merged_manifest(ckpt_dir, name)
    for key, (gshape, _dtype, chunks) in sorted(merged.items()):
        total = 1
        for d in gshape:
            total *= int(d)
        covered = 0
        for _npz, _entry, cs, ce in chunks:
            vol = 1
            for b, e in zip(cs, ce):
                vol *= max(0, int(e) - int(b))
            covered += vol if gshape else 1
        if not gshape:
            total = 1
        rows.append((key, covered, total, len(chunks)))
    return rows


def verify_tag_dir(ckpt_dir, check_crc=True, require_optim=True):
    """Print a report for one tag dir; return True iff healthy.

    ``require_optim=False`` (the ``--serve-ready`` preflight) accepts
    params-only tags: a weight push loads model_states and nothing
    else, so a missing optimizer group is by design there, not a gap.
    """
    print(f"== {ckpt_dir}")
    healthy = True
    marker = ckpt.read_commit_marker(ckpt_dir)
    if marker is None:
        print("  COMMITTED: absent (legacy/pre-durability or torn save)")
    else:
        print(f"  COMMITTED: format_version={marker.get('format_version')} "
              f"process_count={marker.get('process_count')} "
              f"files={len(marker['files'])}")
    ok, problems = ckpt.verify_checkpoint_dir(ckpt_dir, check_crc=check_crc)
    for p in problems:
        print(f"  PROBLEM: {p}")
        healthy = False
    if ok:
        print(f"  file integrity: OK "
              f"({'sizes+crc32' if check_crc and marker else 'sizes' if marker else 'legacy best-effort'})")
    # which state groups this tag carries — a params-only consumer
    # (InferenceEngine.from_checkpoint) needs model_states and nothing
    # else; a training resume needs optim_states (+ cpu_optim_states
    # under ZeRO-Offload) too
    groups = ckpt.state_groups(ckpt_dir)
    parts = []
    for name in ("model_states", "optim_states"):
        fmt = groups[name]
        parts.append(f"{name}({fmt})" if fmt else f"{name}(MISSING)")
    if groups["cpu_optim_states"]:
        parts.append("cpu_optim_states")
    if groups["meta"]:
        parts.append("meta")
    if groups["extras"]:
        parts.append(f"extras={groups['extras']}")
    print(f"  state groups: {', '.join(parts)}")
    if groups["model_states"] and not groups["optim_states"]:
        print("  note: params-only checkpoint (serving-loadable; not a "
              "training resume point)")
    for name in ("model_states", "optim_states"):
        try:
            rows = _leaf_coverage(ckpt_dir, name)
        except FileNotFoundError:
            if os.path.isfile(os.path.join(ckpt_dir, f"{name}.npz")):
                print(f"  {name}: legacy single-file format")
            else:
                print(f"  {name}: MISSING")
                if name == "model_states" or require_optim:
                    healthy = False
            continue
        except (json.JSONDecodeError, KeyError, ValueError, OSError) as e:
            # a torn/corrupt manifest is exactly what this tool exists to
            # catch — report it, don't traceback past the other tags
            print(f"  {name}: CORRUPT manifest ({e})")
            healthy = False
            continue
        bad = [(k, c, t) for k, c, t, _ in rows if c != t]
        print(f"  {name}: {len(rows)} leaves, "
              f"{sum(n for _, _, _, n in rows)} chunks")
        for k, c, t, n in rows:
            mark = "OK " if c == t else "GAP"
            print(f"    [{mark}] {k}: {c}/{t} elements in {n} chunk(s)")
        if bad:
            healthy = False
    meta_path = os.path.join(ckpt_dir, "meta.json")
    preempted = False
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        preempted = bool(meta.get("preempted"))
        print(f"  meta: global_step={meta.get('global_step')} "
              f"dp_world_size={meta.get('dp_world_size')} "
              f"zero_stage={meta.get('zero_stage')}")
        if preempted:
            print("  PREEMPTION checkpoint: committed by the graceful "
                  "drain (runtime/elastic.py) — protected from retention "
                  "GC while newer than 'latest'")
    else:
        print("  meta.json: MISSING")
        healthy = False
    verdict = ('COMMITTED+VERIFIED' if healthy and marker
               else 'OK (legacy)' if healthy else 'CORRUPT/INCOMPLETE')
    if preempted and healthy:
        verdict += " (preemption)"
    print(f"  verdict: {verdict}")
    return healthy


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="save_dir or a single <save_dir>/<tag>")
    ap.add_argument("--tag", default=None, help="verify one tag of save_dir")
    ap.add_argument("--all", action="store_true",
                    help="verify every tag in save_dir")
    ap.add_argument("--no-crc", action="store_true",
                    help="skip checksum verification (sizes only)")
    ap.add_argument("--expect-step", type=int, default=None, metavar="N",
                    help="exit nonzero unless the newest committed "
                         "step-suffixed tag is at least step N (the "
                         "supervisor's resume sanity check)")
    ap.add_argument("--serve-ready", action="store_true",
                    help="exit nonzero unless every verified tag also "
                         "carries a model_states group — the fleet "
                         "swap-weights preflight (engine.swap_params / "
                         "FleetRouter.swap_weights load params-only). "
                         "Checkpoints always hold full-precision "
                         "weights; an int8-resident replica "
                         "re-quantizes them on swap, so the same "
                         "preflight covers quantized engines")
    args = ap.parse_args(argv)
    check_crc = not args.no_crc

    path = args.path.rstrip("/")
    if not os.path.isdir(path):
        print(f"error: {path} is not a directory", file=sys.stderr)
        return 2

    def check_serve_ready(tag_dir):
        """--serve-ready: a swap target must carry model_states (the
        only group the params-only serving loader reads)."""
        if ckpt.state_groups(tag_dir)["model_states"]:
            print(f"  serve-ready OK: {tag_dir} carries model_states")
            return True
        print(f"SERVE-READY FAILED: {tag_dir} has no model_states "
              "group — swap_params would find nothing to load",
              file=sys.stderr)
        return False

    # a tag dir directly (has a marker/meta and no nested tags)
    if args.tag is None and not args.all and (
            os.path.isfile(os.path.join(path, ckpt.COMMIT_MARKER))
            or os.path.isfile(os.path.join(path, "meta.json"))):
        ok = verify_tag_dir(path, check_crc,
                            require_optim=not args.serve_ready)
        if ok and args.serve_ready:
            ok = check_serve_ready(path)
        if ok and args.expect_step is not None:
            # meta is authoritative (custom-named tags like 'best' carry
            # no step in their name); the name is only a fallback
            step = ckpt.tag_step(os.path.basename(path))
            meta_path = os.path.join(path, "meta.json")
            if os.path.isfile(meta_path):
                with open(meta_path) as f:
                    step = int(json.load(f).get("global_step", step))
            if step < args.expect_step:
                print(f"EXPECT-STEP FAILED: tag step {step} < expected "
                      f"{args.expect_step}", file=sys.stderr)
                return 1
        return 0 if ok else 1

    tags = ckpt.list_tags(path)
    latest = ckpt.read_latest(path)
    print(f"save_dir {path}: {len(tags)} tag(s), latest={latest!r}")
    if args.tag is not None:
        targets = [args.tag]
    elif args.all:
        targets = tags
    else:
        if latest is None and not tags:
            print("no tags found", file=sys.stderr)
            return 2
        targets = [latest or tags[0]]
        if latest is not None and latest not in tags:
            print(f"  WARNING: latest names {latest!r} which is not a "
                  "loadable tag")
    rc = 0
    for t in targets:
        d = os.path.join(path, t)
        if not verify_tag_dir(d, check_crc,
                              require_optim=not args.serve_ready):
            rc = 1
        elif args.serve_ready and not check_serve_ready(d):
            rc = 1
    if args.expect_step is not None:
        newest = ckpt.newest_committed_step(path)
        if newest < args.expect_step:
            print(f"EXPECT-STEP FAILED: newest committed tag is step "
                  f"{newest} < expected {args.expect_step}",
                  file=sys.stderr)
            rc = rc or 1
        else:
            print(f"expect-step OK: newest committed tag is step {newest} "
                  f">= {args.expect_step}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
