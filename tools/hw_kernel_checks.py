"""On-chip kernel parity sweep: run each Pallas kernel path on the REAL
TPU against its jnp oracle and print PASS/FAIL per check (the unit suite
runs these in interpret mode on CPU; this is the hardware evidence).

Run on hardware:  PYTHONPATH=/root/repo python tools/hw_kernel_checks.py
(~5 min; each check pays at most one compile, shared via the persistent
compile cache). Exits nonzero if any check fails.
"""

import sys
import traceback

import numpy as np


CHECKS = []


def check(name):
    def deco(fn):
        CHECKS.append((name, fn))
        return fn
    return deco


def _qkv(B, H, S, D, kv_heads=None, seed=0):
    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(seed)
    kvh = kv_heads or H
    q = jax.random.normal(jax.random.fold_in(key, 0), (B, H, S, D),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, kvh, S, D),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, kvh, S, D),
                          jnp.bfloat16)
    return q, k, v


def _close(a, b, atol=2e-2, rtol=2e-2, msg=""):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               atol=atol, rtol=rtol, err_msg=msg)


def _grad_pair(fn_a, fn_b, args):
    import jax
    import jax.numpy as jnp
    la = jax.jit(jax.grad(lambda *xs: jnp.sum(fn_a(*xs)
                                              .astype(jnp.float32)),
                          argnums=tuple(range(len(args)))))
    lb = jax.jit(jax.grad(lambda *xs: jnp.sum(fn_b(*xs)
                                              .astype(jnp.float32)),
                          argnums=tuple(range(len(args)))))
    return la(*args), lb(*args)


@check("flash causal fwd+grad vs oracle (S=512)")
def _flash_causal():
    import functools
    from deepspeed_tpu.ops.attention import flash as F
    q, k, v = _qkv(2, 4, 512, 64)
    kern = functools.partial(F.flash_attention, causal=True)
    orac = functools.partial(F.flash_attention, causal=True,
                             force_reference=True)
    _close(kern(q, k, v), orac(q, k, v), msg="fwd")
    ga, gb = _grad_pair(kern, orac, (q, k, v))
    for a, b, n in zip(ga, gb, "qkv"):
        _close(a, b, msg=f"d{n}")


@check("flash GQA kv_heads=2 vs oracle (S=512)")
def _flash_gqa():
    import functools
    from deepspeed_tpu.ops.attention import flash as F
    q, k, v = _qkv(1, 8, 512, 64, kv_heads=2)
    kern = functools.partial(F.flash_attention, causal=True)
    orac = functools.partial(F.flash_attention, causal=True,
                             force_reference=True)
    _close(kern(q, k, v), orac(q, k, v), msg="fwd")
    ga, gb = _grad_pair(kern, orac, (q, k, v))
    for a, b, n in zip(ga, gb, "qkv"):
        _close(a, b, msg=f"d{n}")


@check("flash in-kernel dropout fwd/bwd consistency (S=512)")
def _flash_dropout():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention import flash as F
    q, k, v = _qkv(1, 4, 512, 64)
    rng = jax.random.PRNGKey(7)

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=True,
                                         dropout_rate=0.1, dropout_rng=rng)
                       .astype(jnp.float32))
    # same seed twice -> identical loss and grads (mask regenerated
    # identically in fwd + both bwd kernels)
    l1 = jax.jit(loss)(q, k, v)
    l2 = jax.jit(loss)(q, k, v)
    assert float(l1) == float(l2), (float(l1), float(l2))
    g1 = jax.jit(jax.grad(loss, argnums=(0,)))(q, k, v)[0]
    g2 = jax.jit(jax.grad(loss, argnums=(0,)))(q, k, v)[0]
    assert np.array_equal(np.asarray(g1, np.float32),
                          np.asarray(g2, np.float32))


@check("streamed flash (S=8192) vs oracle")
def _flash_streamed():
    import functools
    from deepspeed_tpu.ops.attention import flash as F
    assert F._use_stream(8192, 8192), "streaming not engaged at S=8192"
    q, k, v = _qkv(1, 2, 8192, 64)
    kern = functools.partial(F.flash_attention, causal=True)
    orac = functools.partial(F.flash_attention, causal=True,
                             force_reference=True)
    _close(kern(q, k, v), orac(q, k, v), msg="fwd")


def _sparse_vs_oracle(layout, seed, expect_kernel=None):
    """Shared body of the sparse-kernel parity checks: dispatcher vs
    the dense-masked oracle, fwd + all three grads, with an optional
    planned-kernel pin so a dispatch regression cannot silently pass
    as a different (correct) kernel family."""
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import block_sparse_attention
    from deepspeed_tpu.ops.sparse_attention.blocksparse import (
        layout_additive_mask, planned_kernel)
    from deepspeed_tpu.ops.attention.flash import attention_reference
    H = layout.shape[0]
    S = layout.shape[1] * 128
    if expect_kernel is not None:
        got = planned_kernel(layout, 128)
        assert got == expect_kernel, \
            f"layout no longer dispatches to {expect_kernel} (got {got})"
    q, k, v = _qkv(1, H, S, 64, seed=seed)
    am = jnp.asarray(layout_additive_mask(layout, 128))[None]

    def kern(q, k, v):
        return block_sparse_attention(q, k, v, layout)

    def orac(q, k, v):
        return attention_reference(q, k, v, mask=am)

    _close(kern(q, k, v), orac(q, k, v), msg="fwd")
    ga, gb = _grad_pair(kern, orac, (q, k, v))
    for a, b, n in zip(ga, gb, "qkv"):
        _close(a, b, msg=f"d{n}")


@check("banded Longformer w=3 fwd+grad vs dense-masked oracle (S=2048)")
def _splash_banded():
    from deepspeed_tpu.ops.sparse_attention import (
        BSLongformerSparsityConfig)
    cfg = BSLongformerSparsityConfig(num_heads=4, block=128,
                                     num_sliding_window_blocks=3)
    _sparse_vs_oracle(cfg.make_layout(2048), seed=3,
                      expect_kernel="banded")


@check("hybrid BigBird fwd+grad vs dense-masked oracle (S=2048)")
def _hybrid_bigbird():
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    cfg = BigBirdSparsityConfig(num_heads=4, block=128,
                                num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    _sparse_vs_oracle(cfg.make_layout(2048), seed=7,
                      expect_kernel="hybrid")


@check("splash v2 (banded forced off) Longformer vs oracle (S=2048)")
def _splash_v2():
    from deepspeed_tpu.ops.sparse_attention import (
        BSLongformerSparsityConfig)
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    cfg = BSLongformerSparsityConfig(num_heads=4, block=128,
                                     num_sliding_window_blocks=3)
    old = bs.USE_BANDED
    bs.USE_BANDED = False
    try:
        _sparse_vs_oracle(cfg.make_layout(2048), seed=3)
    finally:
        bs.USE_BANDED = old


@check("coarse walk (forced 512) == fine walk, grads (S=2048)")
def _coarse_parity():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        BSLongformerSparsityConfig, block_sparse_attention)
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    H, S = 4, 2048
    cfg = BSLongformerSparsityConfig(num_heads=H, block=128,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(S)
    q, k, v = _qkv(1, H, S, 64, seed=5)

    old = bs.USE_BANDED
    bs.USE_BANDED = False          # the coarse/fine walk is the v2 path
    try:
        def run(force):
            # _FN_CACHE keys on _FORCE_COARSE_BLOCK: no clear() needed
            bs._FORCE_COARSE_BLOCK = force
            try:
                g = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(
                        block_sparse_attention(q, k, v, layout)
                        .astype(jnp.float32)), argnums=(0, 1, 2)))
                return jax.tree_util.tree_map(np.asarray, g(q, k, v))
            finally:
                bs._FORCE_COARSE_BLOCK = None
        fine, coarse = run(0), run(512)
        for a, b, n in zip(fine, coarse, "qkv"):
            _close(a, b, msg=f"d{n}")
    finally:
        bs.USE_BANDED = old


@check("fine block=16 rides the coarse streamed path (S=2048)")
def _small_block_coarse():
    from deepspeed_tpu.ops.sparse_attention import (
        FixedSparsityConfig, block_sparse_attention,
        block_sparse_attention_reference)
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    H, S = 2, 2048
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=4)
    layout = cfg.make_layout(S)
    assert bs._pick_coarse_block(np.asarray(layout), 16,
                                 has_am=False) is not None, \
        "cost model declined to coarsen a block=16 layout"
    q, k, v = _qkv(1, H, S, 32, seed=9)
    _close(block_sparse_attention(q, k, v, layout),
           block_sparse_attention_reference(q, k, v, layout), msg="fwd")


def main():
    import jax
    backend = jax.default_backend()
    print(f"# backend: {backend}", flush=True)
    if backend != "tpu" and "--allow-cpu" not in sys.argv:
        # a green interpret-mode run is NOT hardware evidence — refuse
        # rather than record a false on-chip parity sweep (the unit
        # suite already covers interpret mode)
        print("# NOT on TPU — refusing to produce 'hardware evidence' "
              "from interpret mode (pass --allow-cpu to smoke-test the "
              "harness itself)", flush=True)
        sys.exit(3)
    from deepspeed_tpu.utils.platform import enable_compile_cache
    enable_compile_cache(None)
    failed = 0
    for name, fn in CHECKS:
        try:
            fn()
            print(f"PASS  {name}", flush=True)
        except Exception:
            failed += 1
            print(f"FAIL  {name}", flush=True)
            traceback.print_exc()
    print(f"# {len(CHECKS) - failed}/{len(CHECKS)} kernel checks passed",
          flush=True)
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
