"""Attention block-size autotune sweep (VERDICT r2 #6, r3 #6).

TPU-native analog of the reference's GemmTest autotuner
(/root/reference/csrc/includes/gemm_test.h:27): instead of per-GEMM
algorithm search at engine construction, this offline harness times
kernel block combinations per shape class on the REAL chip and writes
the winners to ``deepspeed_tpu/ops/attention/block_table.json``,
consulted at trace time by ``flash._pick_blocks`` (kind="flash": keys
seq_q/seq_k/d/stream/gqa), ``flash.lookup_masked_blocks``
(kind="masked": keys seq_q/seq_k/d/stream, one square ``b`` — the
unified mask-parameterized kernel's dense/causal walk tile, PR 11) and
``flash.lookup_banded_blocks`` (kind="banded": keys
seq/fine_block/band_w/causal for the legacy banded sparse walk).
Unknown shapes keep the hand-measured heuristics (one logged line per
shape for the masked kernel).

Every entry is stamped with the measuring chip's ``device_kind``; the
lookups only consume same-device entries (legacy unstamped entries act
as a global fallback), so a v5p never consumes v5e-tuned blocks. On a
hardware run this tool also stamps any legacy unstamped entries with the
current device_kind — this rig has only ever measured on its one chip.

Run on hardware:  PYTHONPATH=/root/repo python tools/autotune_blocks.py
(~minutes; each combo pays one compile, amortized by the persistent
compile cache). Timing: value-fetch completion barrier + RTT
subtraction via the shared scan-amortized protocol (utils/benchtime.py).
Idempotent: shapes that already have an entry for this device_kind are
skipped (pass --force to re-measure) so a re-run in a later tunnel
window costs nothing and keeps the bench source digest stable.
"""

import argparse
import json
import os
import time


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "deepspeed_tpu", "ops", "attention",
                   "block_table.json")

# flash shape classes: (seq_q, seq_k, head_dim, gqa_group)
FLASH_SHAPES = [
    (128, 128, 64, 1),         # BERT-large seq128 (bench headline row)
    (512, 512, 64, 1),         # BERT seq512
    (1024, 1024, 64, 1),       # GPT-2 345M / 1.5B pretraining
    (2048, 2048, 64, 1),
    (8192, 8192, 64, 1),       # long-context / sparse-vs-dense row
    (16384, 16384, 64, 1),     # streamed
    (32768, 32768, 64, 1),     # streamed
    (1024, 1024, 80, 1),       # 80-dim heads (e.g. 2560/32-style configs)
    (1024, 1024, 128, 1),      # llama-family head_dim
    (2048, 2048, 128, 4),      # llama GQA (kv_heads = heads/4)
    (4096, 4096, 128, 4),
    (2048, 2048, 64, 4),       # GQA at d=64
]
CANDIDATES = (64, 128, 256, 512)

# banded sparse walk shape classes: (S, fine_block, window_blocks)
# — the bench row (S=8192, fb=128, win=3 BSLongformer) FIRST (sweep is
# incremental; a short window should land the scored shape), then its
# s16k long-context detail and the class-default fb=64 geometry
BANDED_SHAPES = [
    (8192, 128, 3),
    (16384, 128, 3),
    (8192, 64, 3),
    # the reference's OWN headline density: block 16, 48-token window
    # (~1% density -> FLOP bound ~51x vs causal-dense; at (128,128)
    # walk tiles the static waste is 8x -> ~6.4x-vs-flash potential,
    # above the 6.3x claim). Feeds the bench row's refdensity detail.
    (8192, 16, 3),
]
# each combo compiles 7 pallas kernels through the tunnel (~20-40s per
# fresh compile): keep the candidate list small — static walk_stats
# says the FLOP spread (128,128) 1.0x -> (512,512) 4.1x of bound, so
# these four bracket the overhead-vs-waste trade
BANDED_COMBOS = ((128, 128), (256, 256), (256, 512), (512, 512))


def _rtt():
    from deepspeed_tpu.utils.benchtime import measure_rtt
    return measure_rtt()


def _device_kind():
    import jax
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return None


def _shape_plan(sq):
    """(batch, heads, scan_iters) per shape class: batch*heads mirrors the
    bench/model ladder's grid occupancy, scan_iters targets O(0.5-2s) of
    pure device time so the tunnel's per-dispatch latency is amortized
    away inside one dispatch."""
    if sq <= 512:
        return 8, 16, 100
    if sq <= 2048:
        return 1, 16, 40
    if sq <= 8192:
        return 1, 8, 8
    return 1, 4, 3


def time_combo(sq, sk, d, bq, bk, rtt, iters=None, heads=None, gqa=1):
    # iters/heads are debug-only overrides (smoke tests); the sweep itself
    # always lets _shape_plan pick them so winners aren't latency-noise.
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention import flash as F

    batch, h, n = _shape_plan(max(sq, sk))
    if heads is not None:
        h = heads
    if iters is not None:
        n = iters
    h = max(h, gqa)
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(jax.random.fold_in(key, 0), (batch, h, sq, d),
                          jnp.bfloat16)
    k, v = (jax.random.normal(jax.random.fold_in(key, i),
                              (batch, h // gqa, sk, d), jnp.bfloat16)
            for i in (1, 2))

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    # Shared scan-amortized protocol (utils/benchtime.py): chained grad
    # evals in ONE dispatch, RTT-noise floor with rescaling, fail —
    # never ~0 — when the floor is unreachable.
    from deepspeed_tpu.utils.benchtime import scan_grad_seconds

    # kind="flash" entries feed the LEGACY per-path kernels — pin them
    # for the measurement (the default dispatch is the masked kernel,
    # which sweeps separately through time_masked_combo)
    old_opts = F.set_attention_options(kernel="flash")
    F._FORCE_BLOCKS = (bq, bk)
    try:
        sec, _n = scan_grad_seconds(grad_fn, (q, k, v), rtt, start_len=n,
                                    max_len=n * 4096)
        # normalize to the old (1, 8, S) work unit so tables stay comparable
        return sec * 8.0 / (batch * h)
    finally:
        F._FORCE_BLOCKS = None
        F._OPTIONS = old_opts


def time_masked_combo(sq, sk, d, b, rtt, iters=None, gqa=1):
    """One dense/causal grad eval through the UNIFIED masked kernel at
    a forced square walk tile ``b`` (kind="masked" table entries)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention import flash as F
    from deepspeed_tpu.utils.benchtime import scan_grad_seconds

    batch, h, n = _shape_plan(max(sq, sk))
    if iters is not None:
        n = iters
    h = max(h, gqa)
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(jax.random.fold_in(key, 0), (batch, h, sq, d),
                          jnp.bfloat16)
    k, v = (jax.random.normal(jax.random.fold_in(key, i),
                              (batch, h // gqa, sk, d), jnp.bfloat16)
            for i in (1, 2))

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    # pin the unified kernel (a DSTPU_ATTENTION_KERNEL A/B export must
    # not abort the sweep — time_combo pins "flash" the same way)
    old_opts = F.set_attention_options(kernel="masked")
    F._FORCE_BLOCKS = (b, b)
    F._DENSE_MASK_CACHE.clear()
    try:
        sec, _n = scan_grad_seconds(jax.grad(loss, argnums=(0, 1, 2)),
                                    (q, k, v), rtt, start_len=n,
                                    max_len=n * 4096)
        return sec * 8.0 / (batch * h)
    finally:
        F._FORCE_BLOCKS = None
        F._OPTIONS = old_opts
        F._DENSE_MASK_CACHE.clear()


def time_banded_combo(S, fb, win, bq, bk, rtt, iters=None):
    """One banded-walk grad eval at the bench row's geometry."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import banded
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        BSLongformerSparsityConfig)
    from deepspeed_tpu.utils.benchtime import scan_grad_seconds

    H = 16 if S <= 8192 else 8
    _, _, n = _shape_plan(S)
    if iters is not None:
        n = iters
    cfg = BSLongformerSparsityConfig(num_heads=H, block=fb,
                                     num_sliding_window_blocks=win)
    layout = cfg.make_layout(S)
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, H, S, 64), jnp.bfloat16)
               for i in range(3))

    def loss(q, k, v):
        return jnp.sum(bs.block_sparse_attention(q, k, v, layout)
                       .astype(jnp.float32))

    banded._FORCE_BLOCKS = (bq, bk)
    bs._FN_CACHE.clear()
    try:
        if bs.planned_kernel(layout, fb) != "banded":
            raise RuntimeError("banded path did not engage")
        # pick_blocks silently falls back to table/heuristic tiles when
        # the forced pair fails _blocks_valid — make sure the kernel we
        # are about to time actually walks (bq, bk), or the measurement
        # would be recorded under the wrong label (ADVICE r4)
        import numpy as _np
        fn = bs._sparse_attention_fn(_np.asarray(layout), fb,
                                     float(1.0 / _np.sqrt(64)),
                                     has_am=False, interpret=False)
        got = getattr(fn, "banded_blocks", None)
        if got != (bq, bk):
            raise RuntimeError(
                f"forced banded blocks did not engage: built {got}, "
                f"forced {(bq, bk)}")
        sec, _n2 = scan_grad_seconds(jax.grad(loss, argnums=(0, 1, 2)),
                                     (q, k, v), rtt, start_len=n,
                                     max_len=n * 4096)
        return sec * 8.0 / H
    finally:
        banded._FORCE_BLOCKS = None
        bs._FN_CACHE.clear()


def _entry_key(r):
    """Merge identity: shape class + measuring device."""
    if r.get("kind") == "banded":
        shape = ("banded", r["seq"], r["fine_block"], r.get("band_w"),
                 bool(r.get("causal", False)))
    elif r.get("kind") == "masked":
        shape = ("masked", r["seq_q"], r["seq_k"], r["d"],
                 bool(r.get("stream")))
    else:
        shape = ("flash", r["seq_q"], r["seq_k"], r["d"],
                 bool(r.get("stream")), r.get("gqa", 1))
    return shape + (r.get("device_kind"),)


def _merge_write(out_path, rows, backend, device_kind):
    """Merge-write the table keyed by shape class + device: entries
    measured in THIS run replace same-shape-same-device entries, every
    other existing entry survives — a sweep that dies mid-ladder (tunnel
    drop) must never erase the shapes a previous window already paid
    for. On hardware, legacy unstamped entries get stamped with the
    current device_kind (see module docstring)."""
    if backend != "tpu":
        return
    existing = []
    try:
        with open(out_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        pass
    if device_kind:
        for r in existing:
            r.setdefault("device_kind", device_kind)
    merged = {}
    for r in existing:
        try:
            merged[_entry_key(r)] = r
        except KeyError:
            continue                      # malformed row: drop
    for r in rows:
        merged[_entry_key(r)] = r
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(sorted(merged.values(), key=lambda r: json.dumps(
            _entry_key(r), default=str)), f, indent=1)
    os.replace(tmp, out_path)


def _covered(existing, key_wo_device, device_kind):
    for r in existing:
        try:
            k = _entry_key(r)
        except KeyError:
            continue
        if "ms" not in r:
            # seeded/unmeasured placeholder (e.g. the masked entries
            # shipped from the flash square winners): it serves lookups
            # as a fallback but must never stop the sweep from actually
            # MEASURING the shape
            continue
        if k[:-1] == key_wo_device and k[-1] in (device_kind, None):
            return True
    return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--iters", type=int, default=None,
                    help="override the per-shape scan length (debug only; "
                         "default: _shape_plan governs)")
    ap.add_argument("--force", action="store_true",
                    help="re-measure shapes already covered for this "
                         "device_kind")
    ap.add_argument("--stall-timeout", type=int, default=1200,
                    help="seconds without a completed combo before the "
                         "watchdog flushes measured shapes and exits (a "
                         "dead-tunnel fetch hangs in C++ where signals "
                         "never run; cf. bench.py run_child)")
    args = ap.parse_args()

    # Arm the watchdog BEFORE any device touch: jax backend init and the
    # rtt probe themselves hang on a dead tunnel, inside C++ where
    # signal handlers never run, and a watchdog started after them would
    # never start at all.
    rows = []
    backend = [None]
    kind_box = [None]
    last_beat = [time.monotonic()]

    def _watchdog():
        while True:
            time.sleep(30)
            if time.monotonic() - last_beat[0] > args.stall_timeout:
                print(f"# WATCHDOG: no combo finished in "
                      f"{args.stall_timeout}s - flushing "
                      f"{len(rows)} shapes and exiting", flush=True)
                _merge_write(args.out, rows, backend[0], kind_box[0])
                os._exit(3)

    import threading
    threading.Thread(target=_watchdog, daemon=True).start()

    import jax
    from deepspeed_tpu.ops.attention import flash as F
    from deepspeed_tpu.utils.platform import enable_compile_cache
    enable_compile_cache(None)   # shared per-user default dir
    backend[0] = jax.default_backend()
    kind_box[0] = device_kind = _device_kind()
    print(f"# backend: {backend[0]} device_kind: {device_kind} "
          "(results are only meaningful on tpu)")
    rtt = _rtt()
    print(f"# rtt: {rtt*1e3:.2f} ms")
    last_beat[0] = time.monotonic()

    existing = []
    try:
        with open(args.out) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        pass
    # stamp legacy entries even if every shape below is already covered
    if backend[0] == "tpu":
        _merge_write(args.out, [], backend[0], device_kind)

    # ---- banded sparse walk first: it feeds the scored bench row ----
    for S, fb, win in BANDED_SHAPES:
        key_wo = ("banded", S, fb, win // 2, False)
        if not args.force and _covered(existing, key_wo, device_kind):
            print(f"# banded S={S} fb={fb} already covered - skip")
            continue
        results = {}
        for bq, bk in BANDED_COMBOS:
            if S % bq or S % bk:
                continue
            try:
                dt = time_banded_combo(S, fb, win, bq, bk, rtt,
                                       iters=args.iters)
                results[(bq, bk)] = dt
                print(f"banded S={S} fb={fb} bq={bq} bk={bk}: "
                      f"{dt*1e3:.2f} ms", flush=True)
            except Exception as e:
                print(f"banded S={S} fb={fb} bq={bq} bk={bk}: "
                      f"FAILED {type(e).__name__}", flush=True)
            last_beat[0] = time.monotonic()
        if not results:
            continue
        (bq, bk), dt = min(results.items(), key=lambda kv: kv[1])
        print(f"--> best banded (S={S}, fb={fb}): bq={bq} bk={bk} "
              f"{dt*1e3:.2f} ms", flush=True)
        rows.append({"kind": "banded", "seq": S, "fine_block": fb,
                     "band_w": win // 2, "causal": False,
                     "bq": bq, "bk": bk, "ms": round(dt * 1e3, 3),
                     "backend": backend[0], "device_kind": device_kind})
        # incremental: each finished shape lands immediately, so a later
        # tunnel drop costs only the in-flight shape
        _merge_write(args.out, rows, backend[0], device_kind)

    # ---- masked (unified-kernel) dense/causal shape classes: the
    # DEFAULT training path sweeps before the legacy flash oracle ----
    for sq, sk, d, gqa in FLASH_SHAPES:
        stream = F._use_stream(sq, sk)
        key_wo = ("masked", sq, sk, d, stream)
        if gqa != 1:
            continue          # the masked table is GQA-agnostic (square
            # walk tiles; kv delivery is the same row select)
        if not args.force and _covered(existing, key_wo, device_kind):
            print(f"# masked ({sq},{sk},{d}) already covered - skip")
            continue
        results = {}
        for b in CANDIDATES:
            if sq % b or sk % b or (stream and b % 128):
                continue
            try:
                dt = time_masked_combo(sq, sk, d, b, rtt,
                                       iters=args.iters)
                results[b] = dt
                print(f"masked S=({sq},{sk}) d={d} stream={stream} "
                      f"b={b}: {dt*1e3:.2f} ms", flush=True)
            except Exception as e:
                print(f"masked S=({sq},{sk}) d={d} b={b}: "
                      f"FAILED {type(e).__name__}", flush=True)
            last_beat[0] = time.monotonic()
        if not results:
            continue
        b, dt = min(results.items(), key=lambda kv: kv[1])
        print(f"--> best masked ({sq},{sk},{d}): b={b} "
              f"{dt*1e3:.2f} ms", flush=True)
        rows.append({"kind": "masked", "seq_q": sq, "seq_k": sk, "d": d,
                     "stream": stream, "b": b, "ms": round(dt * 1e3, 3),
                     "backend": backend[0], "device_kind": device_kind})
        _merge_write(args.out, rows, backend[0], device_kind)

    # ---- flash shape classes (legacy oracle kernels) ----
    for sq, sk, d, gqa in FLASH_SHAPES:
        stream = F._use_stream(sq, sk)
        key_wo = ("flash", sq, sk, d, stream, gqa)
        if not args.force and _covered(existing, key_wo, device_kind):
            print(f"# flash ({sq},{sk},{d},gqa{gqa}) already covered - "
                  "skip")
            continue
        combos = [
            (bq, bk) for bq in CANDIDATES for bk in CANDIDATES
            if sq % bq == 0 and sk % bk == 0
            # streamed tiles put the block width in the DMA lane dim
            and (not stream or (bq % 128 == 0 and bk % 128 == 0))
        ]
        results = {}
        for bq, bk in combos:
            try:
                dt = time_combo(sq, sk, d, bq, bk, rtt, iters=args.iters,
                                gqa=gqa)
                results[(bq, bk)] = dt
                print(f"S=({sq},{sk}) d={d} gqa={gqa} stream={stream} "
                      f"bq={bq} bk={bk}: {dt*1e3:.2f} ms", flush=True)
            except Exception as e:  # combo may not compile (VMEM, Mosaic)
                print(f"S=({sq},{sk}) d={d} gqa={gqa} bq={bq} bk={bk}: "
                      f"FAILED {type(e).__name__}", flush=True)
            last_beat[0] = time.monotonic()
        if not results:
            continue
        (bq, bk), dt = min(results.items(), key=lambda kv: kv[1])
        default = F._pick_blocks(sq, sk)   # heuristic, table not loaded
        print(f"--> best ({sq},{sk},{d},gqa{gqa}): bq={bq} bk={bk} "
              f"{dt*1e3:.2f} ms (heuristic would pick {default})",
              flush=True)
        rows.append({"seq_q": sq, "seq_k": sk, "d": d, "stream": stream,
                     "gqa": gqa, "bq": bq, "bk": bk,
                     "ms": round(dt * 1e3, 3), "backend": backend[0],
                     "device_kind": device_kind})
        _merge_write(args.out, rows, backend[0], device_kind)

    if backend[0] != "tpu":
        print("# not on TPU - NOT writing the table")
        return
    _merge_write(args.out, rows, backend[0], device_kind)
    print(f"# wrote/merged {len(rows)} entries into {args.out}")


if __name__ == "__main__":
    main()
