"""Flash-attention block-size autotune sweep (VERDICT r2 #6).

TPU-native analog of the reference's GemmTest autotuner
(/root/reference/csrc/includes/gemm_test.h:27): instead of per-GEMM
algorithm search at engine construction, this offline harness times the
Pallas flash kernel's (block_q, block_k) combinations per shape class
(seq_q, seq_k, head_dim, stream) on the REAL chip and writes the winners
to ``deepspeed_tpu/ops/attention/block_table.json``, which
``flash._pick_blocks`` consults at trace time (unknown shapes keep the
hand-measured heuristic).

Run on hardware:  PYTHONPATH=/root/repo python tools/autotune_blocks.py
(~minutes; each combo pays one compile). Timing: value-fetch completion
barrier + RTT subtraction, min-of-3 windows (the device tunnel adds
large variable latency — see bench.py).
"""

import argparse
import json
import os
import time


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "deepspeed_tpu", "ops", "attention",
                   "block_table.json")

# the bench/model ladder's attention shapes (seq_q, seq_k, head_dim)
SHAPES = [
    (128, 128, 64),        # BERT-large seq128 (bench headline row)
    (512, 512, 64),        # BERT seq512
    (1024, 1024, 64),      # GPT-2 345M / 1.5B pretraining
    (2048, 2048, 64),
    (8192, 8192, 64),      # long-context / sparse-vs-dense row
    (16384, 16384, 64),    # streamed
    (32768, 32768, 64),    # streamed
    (1024, 1024, 80),      # 80-dim heads (e.g. 2560/32-style configs)
]
CANDIDATES = (64, 128, 256, 512)


def _rtt():
    from deepspeed_tpu.utils.benchtime import measure_rtt
    return measure_rtt()


def _shape_plan(sq):
    """(batch, heads, scan_iters) per shape class: batch*heads mirrors the
    bench/model ladder's grid occupancy, scan_iters targets O(0.5-2s) of
    pure device time so the tunnel's per-dispatch latency is amortized
    away inside one dispatch."""
    if sq <= 512:
        return 8, 16, 100
    if sq <= 2048:
        return 1, 16, 40
    if sq <= 8192:
        return 1, 8, 8
    return 1, 4, 3


def time_combo(sq, sk, d, bq, bk, rtt, iters=None, heads=None):
    # iters/heads are debug-only overrides (smoke tests); the sweep itself
    # always lets _shape_plan pick them so winners aren't latency-noise.
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.attention import flash as F

    batch, h, n = _shape_plan(max(sq, sk))
    if heads is not None:
        h = heads
    if iters is not None:
        n = iters
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (batch, h, s, d), jnp.bfloat16)
               for i, s in enumerate((sq, sk, sk)))

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    # Shared scan-amortized protocol (utils/benchtime.py): chained grad
    # evals in ONE dispatch, RTT-noise floor with rescaling, fail —
    # never ~0 — when the floor is unreachable.
    from deepspeed_tpu.utils.benchtime import scan_grad_seconds

    F._FORCE_BLOCKS = (bq, bk)
    try:
        sec, _n = scan_grad_seconds(grad_fn, (q, k, v), rtt, start_len=n,
                                    max_len=n * 4096)
        # normalize to the old (1, 8, S) work unit so tables stay comparable
        return sec * 8.0 / (batch * h)
    finally:
        F._FORCE_BLOCKS = None


def _merge_write(out_path, rows, backend):
    """Merge-write the table keyed by shape class: entries measured in THIS
    run replace same-shape entries, every other existing entry survives —
    a sweep that dies mid-ladder (tunnel drop) must never erase the shapes
    a previous window already paid for."""
    if backend != "tpu":
        return
    existing = []
    try:
        with open(out_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        pass
    key = lambda r: (r["seq_q"], r["seq_k"], r["d"], bool(r.get("stream")))
    merged = {key(r): r for r in existing}
    merged.update({key(r): r for r in rows})
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(sorted(merged.values(),
                         key=lambda r: (r["seq_q"], r["seq_k"], r["d"])),
                  f, indent=1)
    os.replace(tmp, out_path)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--iters", type=int, default=None,
                    help="override the per-shape scan length (debug only; "
                         "default: _shape_plan governs)")
    ap.add_argument("--stall-timeout", type=int, default=1200,
                    help="seconds without a completed combo before the "
                         "watchdog flushes measured shapes and exits (a "
                         "dead-tunnel fetch hangs in C++ where signals "
                         "never run; cf. bench.py run_child)")
    args = ap.parse_args()

    # Arm the watchdog BEFORE any device touch: jax backend init and the
    # rtt probe themselves hang on a dead tunnel, inside C++ where
    # signal handlers never run, and a watchdog started after them would
    # never start at all.
    rows = []
    backend = [None]
    last_beat = [time.monotonic()]

    def _watchdog():
        while True:
            time.sleep(30)
            if time.monotonic() - last_beat[0] > args.stall_timeout:
                print(f"# WATCHDOG: no combo finished in "
                      f"{args.stall_timeout}s - flushing "
                      f"{len(rows)} shapes and exiting", flush=True)
                _merge_write(args.out, rows, backend[0])
                os._exit(3)

    import threading
    threading.Thread(target=_watchdog, daemon=True).start()

    import jax
    from deepspeed_tpu.ops.attention import flash as F
    from deepspeed_tpu.utils.platform import enable_compile_cache
    enable_compile_cache(None)   # shared per-user default dir
    backend[0] = jax.default_backend()
    print(f"# backend: {backend[0]} (results are only meaningful on tpu)")
    rtt = _rtt()
    print(f"# rtt: {rtt*1e3:.2f} ms")
    last_beat[0] = time.monotonic()

    for sq, sk, d in SHAPES:
        stream = F._use_stream(sq, sk)
        combos = [
            (bq, bk) for bq in CANDIDATES for bk in CANDIDATES
            if sq % bq == 0 and sk % bk == 0
            # streamed tiles put the block width in the DMA lane dim
            and (not stream or (bq % 128 == 0 and bk % 128 == 0))
        ]
        results = {}
        for bq, bk in combos:
            try:
                dt = time_combo(sq, sk, d, bq, bk, rtt, iters=args.iters)
                results[(bq, bk)] = dt
                print(f"S=({sq},{sk}) d={d} stream={stream} "
                      f"bq={bq} bk={bk}: {dt*1e3:.2f} ms", flush=True)
            except Exception as e:  # combo may not compile (VMEM, Mosaic)
                print(f"S=({sq},{sk}) d={d} bq={bq} bk={bk}: "
                      f"FAILED {type(e).__name__}", flush=True)
            last_beat[0] = time.monotonic()
        if not results:
            continue
        (bq, bk), dt = min(results.items(), key=lambda kv: kv[1])
        default = F._pick_blocks(sq, sk)   # heuristic, table not loaded
        print(f"--> best ({sq},{sk},{d}): bq={bq} bk={bk} "
              f"{dt*1e3:.2f} ms (heuristic would pick {default})",
              flush=True)
        rows.append({"seq_q": sq, "seq_k": sk, "d": d, "stream": stream,
                     "bq": bq, "bk": bk, "ms": round(dt * 1e3, 3),
                     "backend": backend[0]})
        # incremental: each finished shape lands immediately, so a later
        # tunnel drop costs only the in-flight shape
        _merge_write(args.out, rows, backend[0])

    if backend[0] != "tpu":
        print("# not on TPU - NOT writing the table")
        return
    _merge_write(args.out, rows, backend[0])
    print(f"# wrote/merged {len(rows)} entries into {args.out}")


if __name__ == "__main__":
    main()
