"""V=2 vs V=4 interleave: the single-chip-measurable half (VERDICT r4
weak #5).

The 3D flagship's interleave choice trades three terms
(docs/pipeline.md): per-device stage memory (compiler-analyzed in
test_flagship_memory.py), collective-permute traffic (pinned statically
— 2 ppermutes per tick, tile-sized, test_hlo_collectives.py — and
linear in V), and the COMPUTE cost of finer virtual-stage granularity:
V=4 runs 6-layer stage blocks where V=2 runs 12-layer blocks, so the
compiled tick body XLA fuses/overlaps across is half as deep.

Only that last term needs hardware, and it needs just ONE chip: grad
time of lax.scan(12-layer block, length=1) vs lax.scan(6-layer block,
length=2) at the flagship block shape — identical total FLOPs,
identical weights, the only difference is the tick granularity, which
is exactly how the 1F1B executor structures the work
(runtime/pipe/spmd.py: one scan step per tick). The measured ratio
plus the static permute count completes the interleave trade with
real numbers (record in docs/pipeline.md).

Run on hardware:
  PYTHONPATH=/root/repo python tools/ab_interleave.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.platform import enable_compile_cache
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_block,
                                       init_gpt2_params)


def main():
    enable_compile_cache(None)
    # flagship block shape (GPT-2 1.5B: hidden 1600, 20 heads), seq and
    # micro-batch from the 3D bench config; 12 layers = one device's
    # stage depth at pipe=2 x V=2 for 48 layers
    H, SEQ, MB, DEPTH12 = 1600, 1024, 4, 12
    cfg = GPT2Config(vocab_size=64, max_position_embeddings=SEQ,
                     hidden_size=H, num_layers=DEPTH12, num_heads=20,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    p12 = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    layers = [p12[f"h_{i}"] for i in range(DEPTH12)]

    def stacked_blocks(nb, depth):
        """Pytree with leaves (nb, depth, ...) from the same 12 layers."""
        rows = []
        for b in range(nb):
            blk = layers[b * depth:(b + 1) * depth]
            rows.append(jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *blk))
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)

    def make_loss(nb, depth):
        def loss(stacked, x):
            def tick(carry, blk):
                for i in range(depth):
                    lp = jax.tree_util.tree_map(lambda a: a[i], blk)
                    carry = gpt2_block(lp, cfg, carry, None, True,
                                       jnp.bfloat16, None, None)
                return carry, ()
            out, _ = jax.lax.scan(tick, x, stacked)
            return jnp.sum(out.astype(jnp.float32))
        return loss

    from deepspeed_tpu.utils.benchtime import measure_rtt, scan_grad_seconds
    rtt = measure_rtt()
    print(f"rtt: {rtt * 1e3:.1f} ms", flush=True)
    x0 = jax.random.normal(jax.random.PRNGKey(9), (MB, SEQ, H),
                           jnp.bfloat16)

    times = {}
    for V, (nb, depth) in ((2, (1, 12)), (4, (2, 6))):
        stacked = stacked_blocks(nb, depth)
        # scan_grad_seconds feeds back per positional ARRAY arg — pass
        # the param pytree as flattened leaves
        leaves, treedef = jax.tree_util.tree_flatten(stacked)
        loss = make_loss(nb, depth)

        def loss_flat(*args, _treedef=treedef, _loss=loss):
            *ls, x = args
            return _loss(jax.tree_util.tree_unflatten(_treedef, ls), x)

        grad_fn = jax.grad(loss_flat,
                           argnums=tuple(range(len(leaves) + 1)))
        try:
            sec, n = scan_grad_seconds(grad_fn, (*leaves, x0), rtt,
                                       start_len=8)
        except Exception as e:
            print(f"V={V}: FAILED {type(e).__name__}: {e}", flush=True)
            continue
        times[V] = sec
        print(f"V={V} (scan of {nb} x {depth}-layer tick): "
              f"{sec * 1e3:.2f} ms/12-layer grad ({n}-chained)",
              flush=True)

    if 2 in times and 4 in times:
        ratio = times[4] / times[2]
        print(f"\ncompute overhead of V=4 granularity: {ratio:.3f}x "
              f"(+{(ratio - 1) * 100:.1f}% per device-stage)", flush=True)
        print("permute side (static audit): 2 ppermutes/tick, "
              "tile-sized; V=4 runs 2x the ticks -> 2x permute traffic "
              "(test_hlo_collectives.py, docs/pipeline.md)", flush=True)


if __name__ == "__main__":
    main()
