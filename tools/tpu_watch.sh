#!/bin/bash
# Tunnel watcher: probe the TPU every PERIOD seconds; the moment two
# consecutive probes succeed, run the full hardware round
# (tools/on_tpu_up.sh: autotune sweep + bench ladder) exactly once.
#   PYTHONPATH=/root/repo:/root/.axon_site nohup bash tools/tpu_watch.sh &
# Log: /tmp/tpu_watch.log (probe history), /tmp/tpu_round/ (round output).
set -u
PERIOD=${PERIOD:-600}
LOG=/tmp/tpu_watch.log
cd "$(dirname "$0")/.."

probe() {
  timeout 120 python -c "
import jax, numpy as np, jax.numpy as jnp
x = jnp.ones((8,8), jnp.bfloat16); np.asarray(x @ x); print('alive')
" >/dev/null 2>&1
}

while true; do
  if probe; then
    echo "$(date -u +%FT%TZ) probe ok (1/2)" >> "$LOG"
    sleep 30
    if probe; then
      echo "$(date -u +%FT%TZ) probe ok (2/2) — starting hardware round" >> "$LOG"
      bash tools/on_tpu_up.sh >> "$LOG" 2>&1
      rc=$?
      # the round is only DONE when all 5 bench rows are real (the
      # round-5 ladder adds bert_onebit); a tunnel death mid-round
      # re-arms the watcher (completed rows resume from the partial
      # file, so a retry only re-pays the failed metrics)
      # NB grep -c prints the 0 itself on no-match (and exits 1) — an
      # `|| echo 0` here would yield the two-line "0\n0" and break -eq
      rows=$(grep -c '"metric"' /tmp/tpu_round/bench.jsonl 2>/dev/null)
      errs=$(grep -c '"unit": "error"' /tmp/tpu_round/bench.jsonl 2>/dev/null)
      rows=${rows:-0}; errs=${errs:-0}
      if [ "$rc" -eq 0 ] && [ "$rows" -ge 5 ] && [ "$errs" -eq 0 ]; then
        echo "$(date -u +%FT%TZ) hardware round COMPLETE ($rows rows)" >> "$LOG"
        exit 0
      fi
      echo "$(date -u +%FT%TZ) round incomplete (rc=$rc rows=$rows errs=$errs) — re-arming" >> "$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) probe dead" >> "$LOG"
  fi
  sleep "$PERIOD"
done
